"""Chaos engine: deterministic fault injection + crash-restore-verify.

Covers (1) the injection core (seeded schedules, pattern/ctx matching,
recoverable retries), (2) checkpoint integrity (CRC32 manifest, torn
writes detected, fallback to the previous complete checkpoint), (3) the
crash-restore-verify harness against the fault-free oracle across the
mesh session engine (paged spill under forced eviction), the tumbling
mesh window engine and the async-fire/dispatch-ahead pipeline path, and
(4) the cluster restart path (task crash -> RestartStrategy -> restore).

The LAST test asserts every fault point in the CANONICAL inventory
(``flink_tpu.chaos.KNOWN_FAULT_POINTS`` — one source of truth, shared
with flint's REG01 registry check; NOTES_r7.md documents each row) was
injected at least once across this suite — the tier-1 guarantee that no
injection site silently goes stale.
"""

import os

import numpy as np
import pytest

from flink_tpu.chaos import KNOWN_FAULT_POINTS
from flink_tpu.core.records import RecordBatch
from flink_tpu.chaos import injection as chaos
from flink_tpu.chaos.harness import (
    ChaosDivergenceError,
    run_crash_restore_verify,
)
from flink_tpu.chaos.injection import FaultPlan, FaultRule, InjectedFault

GAP = 100

#: fault points injected so far across this suite (reachability ledger;
#: asserted by the final test against chaos.KNOWN_FAULT_POINTS)
REACHED = {}


def _note_reached(injected):
    for k, v in injected.items():
        REACHED[k] = REACHED.get(k, 0) + v


# --------------------------------------------------------------- injection


class TestInjectionCore:
    def test_disarmed_is_noop(self):
        assert not chaos.armed()
        chaos.fault_point("anything.at.all", shard=3)
        assert chaos.payload_action("anything.at.all") is None
        assert chaos.run_recoverable("x", lambda: 41) == 41

    def test_nth_hit_fires_once(self):
        plan = FaultPlan(rules=[FaultRule(pattern="a.b", nth=3)])
        with chaos.chaos_active(plan, seed=0) as c:
            chaos.fault_point("a.b")
            chaos.fault_point("a.b")
            with pytest.raises(InjectedFault):
                chaos.fault_point("a.b")
            chaos.fault_point("a.b")  # max_injections=1: spent
            assert c.faults_injected == {"a.b": 1}
            assert c.points_hit["a.b"] == 4

    def test_every_schedule_and_unlimited(self):
        plan = FaultPlan(rules=[
            FaultRule(pattern="p.*", every=2, kind="delay",
                      delay_ms=0, max_injections=0)])
        with chaos.chaos_active(plan, seed=0) as c:
            for _ in range(6):
                chaos.fault_point("p.q")
            assert c.faults_injected["p.q"] == 3

    def test_where_filter_pins_context(self):
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.bucket_send", nth=1,
                      where={"shard": 2})])
        with chaos.chaos_active(plan, seed=0) as c:
            chaos.fault_point("shuffle.bucket_send", shard=0)
            chaos.fault_point("shuffle.bucket_send", shard=1)
            with pytest.raises(InjectedFault):
                chaos.fault_point("shuffle.bucket_send", shard=2)
            assert c.faults_injected == {"shuffle.bucket_send": 1}

    def test_prob_schedule_is_seed_deterministic(self):
        def run(seed):
            plan = FaultPlan(rules=[
                FaultRule(pattern="r.*", prob=0.3, kind="delay",
                          delay_ms=0, max_injections=0)])
            with chaos.chaos_active(plan, seed=seed) as c:
                for _ in range(200):
                    chaos.fault_point("r.s")
                return c.faults_injected.get("r.s", 0)

        a, b = run(42), run(42)
        assert a == b and 20 < a < 100  # same seed => identical draws
        assert run(43) != a or run(44) != a  # not constant across seeds

    def test_arming_twice_fails(self):
        plan = FaultPlan(rules=[FaultRule(pattern="task.batch", nth=1)])
        with chaos.chaos_active(plan, seed=0):
            with pytest.raises(RuntimeError, match="already armed"):
                chaos.arm(plan, 0)
        assert not chaos.armed()

    def test_rule_validation(self):
        with pytest.raises(ValueError, match="no schedule"):
            FaultRule(pattern="task.batch")
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultRule(pattern="task.batch", nth=1, kind="explode")

    def test_recoverable_retry_then_recover(self):
        plan = FaultPlan(rules=[
            FaultRule(pattern="io.read", nth=1, recoverable=True)])
        with chaos.chaos_active(plan, seed=0) as c:
            calls = []

            def attempt():
                calls.append(1)
                chaos.fault_point("io.read")
                return "ok"

            assert chaos.run_recoverable("io.read", attempt) == "ok"
            assert len(calls) == 2
            assert c.retries == 1 and c.recoveries == 1

    def test_recoverable_budget_exhausts(self):
        plan = FaultPlan(rules=[
            FaultRule(pattern="io.read", every=1, recoverable=True,
                      max_injections=0)],
            retry_max_attempts=3)
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(InjectedFault):
                chaos.run_recoverable(
                    "io.read",
                    lambda: chaos.fault_point("io.read"))
            # max_attempts=3 failures => 2 retries, then give up
            assert c.retries == 2 and c.recoveries == 0

    def test_nonrecoverable_fault_skips_retry(self):
        plan = FaultPlan(rules=[FaultRule(pattern="io.read", nth=1)])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(InjectedFault):
                chaos.run_recoverable(
                    "io.read",
                    lambda: chaos.fault_point("io.read"))
            assert c.retries == 0

    def test_from_spec_and_describe(self):
        plan = FaultPlan.from_spec([
            {"pattern": "a.*", "nth": 2},
            {"pattern": "b", "prob": 0.5, "kind": "delay"},
        ])
        assert len(plan.rules) == 2
        assert any("nth=2" in line for line in plan.describe())

    def test_chaos_metrics_ride_the_job_group(self):
        from flink_tpu.metrics import MetricRegistry

        plan = FaultPlan(rules=[FaultRule(pattern="m.n", nth=1,
                                          kind="delay", delay_ms=0)])
        reg = MetricRegistry()
        with chaos.chaos_active(plan, seed=0):
            chaos.register_chaos_metrics(reg.root_group("job", "j"))
            chaos.fault_point("m.n")
            snap = reg.snapshot()
            assert snap["job.j.chaos.faults_injected"] == 1
            assert snap["job.j.chaos.points_hit"] == 1


# ----------------------------------------------------- checkpoint integrity


class TestCheckpointIntegrity:
    def _write(self, root, cid, n=64):
        from flink_tpu.checkpoint.storage import CheckpointStorage

        st = CheckpointStorage(root)
        rng = np.random.default_rng(cid)
        st.write_checkpoint(cid, "job", {"op": {
            "key_id": np.arange(n, dtype=np.int64),
            "namespace": np.arange(n, dtype=np.int64),
            "leaf_0": rng.random(n).astype(np.float32),
            "host_meta": {"positions": [cid, 1, 2]},
        }})
        return st

    def test_manifest_carries_crcs_and_roundtrips(self, tmp_path):
        from flink_tpu.checkpoint.storage import (
            read_manifest,
            read_snapshot_dir,
        )

        st = self._write(str(tmp_path), 1)
        m = read_manifest(st._dir(1))
        assert m["file_crcs"] and all(
            isinstance(v, int) for v in m["file_crcs"].values())
        state = read_snapshot_dir(st._dir(1))
        assert len(state["op"]["key_id"]) == 64

    def test_truncated_npz_detected_with_clear_error(self, tmp_path):
        from flink_tpu.checkpoint.storage import (
            CheckpointCorruptedError,
            read_snapshot_dir,
        )

        st = self._write(str(tmp_path), 1)
        npz = os.path.join(st._dir(1), "op-op.npz")
        with open(npz, "r+b") as f:
            f.truncate(os.path.getsize(npz) // 2)
        with pytest.raises(CheckpointCorruptedError,
                           match="op-op.npz.*CRC32"):
            read_snapshot_dir(st._dir(1))

    def test_single_bitflip_detected(self, tmp_path):
        from flink_tpu.checkpoint.storage import (
            CheckpointCorruptedError,
            read_snapshot_dir,
        )

        st = self._write(str(tmp_path), 1)
        pkl = os.path.join(st._dir(1), "op-op.meta.pkl")
        size = os.path.getsize(pkl)
        with open(pkl, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0x01]))
        with pytest.raises(CheckpointCorruptedError, match="corrupt"):
            read_snapshot_dir(st._dir(1))

    def test_missing_file_detected(self, tmp_path):
        from flink_tpu.checkpoint.storage import (
            CheckpointCorruptedError,
            read_snapshot_dir,
        )

        st = self._write(str(tmp_path), 1)
        os.remove(os.path.join(st._dir(1), "op-op.npz"))
        with pytest.raises(CheckpointCorruptedError, match="missing"):
            read_snapshot_dir(st._dir(1))

    def test_latest_checkpoint_falls_back_past_corruption(self,
                                                          tmp_path):
        """Truncate one npz in chk-3, flip one byte in chk-2: the
        verified newest-complete id must fall back to chk-1 (the
        harness's restore source)."""
        root = str(tmp_path)
        st = self._write(root, 1)
        self._write(root, 2)
        self._write(root, 3)
        npz3 = os.path.join(st._dir(3), "op-op.npz")
        with open(npz3, "r+b") as f:
            f.truncate(os.path.getsize(npz3) // 2)
        npz2 = os.path.join(st._dir(2), "op-op.npz")
        with open(npz2, "r+b") as f:
            f.seek(5)
            f.write(b"\xff")
        assert st.latest_checkpoint_id() == 3  # unverified: newest dir
        assert st.latest_checkpoint_id(verify=True) == 1

    def test_manifestless_dir_never_counts(self, tmp_path):
        st = self._write(str(tmp_path), 1)
        os.makedirs(os.path.join(str(tmp_path), "chk-9"))
        assert st.latest_checkpoint_id() == 1
        assert st.latest_checkpoint_id(verify=True) == 1

    def test_torn_write_fault_is_detectable(self, tmp_path):
        """An injected torn write (rename durable, bytes not) must
        leave a checkpoint that READS as corrupt, not as state."""
        from flink_tpu.checkpoint.storage import (
            CheckpointCorruptedError,
            CheckpointStorage,
            read_snapshot_dir,
        )

        plan = FaultPlan(rules=[
            FaultRule(pattern="checkpoint.write.torn", nth=1,
                      kind="drop")])
        with chaos.chaos_active(plan, seed=0) as c:
            st = CheckpointStorage(str(tmp_path))
            st.write_checkpoint(1, "job", {"op": {
                "key_id": np.arange(512, dtype=np.int64)}})
            assert c.faults_injected["checkpoint.write.torn"] == 1
            _note_reached(c.faults_injected)
        with pytest.raises(CheckpointCorruptedError):
            read_snapshot_dir(st._dir(1))
        assert st.latest_checkpoint_id(verify=True) is None

    def test_torn_point_rejects_raise_kind(self, tmp_path):
        """A raise-kind rule on checkpoint.write.torn must NOT fire:
        the point sits AFTER the atomic rename, so raising there would
        model a crash of a checkpoint that is in fact durable — the
        harness would discard a committed epoch and report a false
        exactly-once violation. Tear kinds only."""
        from flink_tpu.checkpoint.storage import (
            CheckpointStorage,
            read_snapshot_dir,
        )

        plan = FaultPlan(rules=[
            FaultRule(pattern="checkpoint.write.torn", nth=1)])
        with chaos.chaos_active(plan, seed=0) as c:
            st = CheckpointStorage(str(tmp_path))
            st.write_checkpoint(1, "job", {"op": {
                "key_id": np.arange(8, dtype=np.int64)}})
            assert c.faults_injected == {}
        # and the checkpoint is intact (no tear happened either)
        assert len(read_snapshot_dir(st._dir(1))["op"]["key_id"]) == 8

    def test_recoverable_write_and_read_faults_retry(self, tmp_path):
        from flink_tpu.checkpoint.storage import (
            CheckpointStorage,
            read_snapshot_dir,
        )

        plan = FaultPlan(rules=[
            FaultRule(pattern="checkpoint.write", nth=1,
                      recoverable=True),
            FaultRule(pattern="checkpoint.read", nth=1,
                      recoverable=True),
        ])
        with chaos.chaos_active(plan, seed=0) as c:
            st = CheckpointStorage(str(tmp_path))
            st.write_checkpoint(1, "job", {"op": {
                "key_id": np.arange(8, dtype=np.int64)}})
            state = read_snapshot_dir(st._dir(1))
            assert len(state["op"]["key_id"]) == 8
            assert c.retries == 2 and c.recoveries == 2
            assert c.faults_injected["checkpoint.write"] == 1
            assert c.faults_injected["checkpoint.read"] == 1
            _note_reached(c.faults_injected)


# ------------------------------------------------------------ shuffle layer


class TestShuffleBucketFaults:
    def _bucket(self, n=64, shards=4):
        rng = np.random.default_rng(3)
        shard_of = rng.integers(0, shards, n)
        cols = [rng.integers(0, 100, n).astype(np.int32),
                rng.random(n).astype(np.float32)]
        return shard_of, cols

    def test_drop_empties_one_shard_bucket(self):
        from flink_tpu.parallel.shuffle import bucket_by_shard

        shard_of, cols = self._bucket()
        base_counts, base_blocked = bucket_by_shard(
            shard_of, 4, cols, fills=[0, 0.0])
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.bucket_send", nth=1, kind="drop",
                      where={"shard": 2})])
        with chaos.chaos_active(plan, seed=0) as c:
            counts, blocked = bucket_by_shard(
                shard_of, 4, cols, fills=[0, 0.0])
            assert counts[2] == 0 and base_counts[2] > 0
            assert (blocked[0][2] == 0).all()  # refilled with fill
            np.testing.assert_array_equal(blocked[0][1],
                                          base_blocked[0][1])
            _note_reached(c.faults_injected)

    def test_duplicate_replays_one_shard_bucket(self):
        from flink_tpu.parallel.shuffle import bucket_by_shard

        shard_of, cols = self._bucket()
        base_counts, _ = bucket_by_shard(
            shard_of, 4, cols, fills=[0, 0.0])
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.bucket_send", nth=1,
                      kind="duplicate", where={"shard": 1})])
        with chaos.chaos_active(plan, seed=0) as c:
            counts, blocked = bucket_by_shard(
                shard_of, 4, cols, fills=[0, 0.0])
            cbase = int(base_counts[1])
            assert counts[1] == 2 * cbase
            np.testing.assert_array_equal(
                blocked[1][1][:cbase], blocked[1][1][cbase:2 * cbase])
            _note_reached(c.faults_injected)

    def test_disarmed_output_is_identical(self):
        from flink_tpu.parallel.shuffle import bucket_by_shard

        shard_of, cols = self._bucket()
        c1, b1, o1 = bucket_by_shard(shard_of, 4, cols, fills=[0, 0.0],
                                     want_order=True)
        c2, b2, o2 = bucket_by_shard(shard_of, 4, cols, fills=[0, 0.0],
                                     want_order=True)
        np.testing.assert_array_equal(c1, c2)
        np.testing.assert_array_equal(o1, o2)
        for x, y in zip(b1, b2):
            np.testing.assert_array_equal(x, y)


class TestDeviceExchangeFaults:
    """The device data plane's fault point, at its REAL sites: payload
    kinds (drop/duplicate) apply in ``stage_device_exchange`` before the
    flat columns go up, and raise/delay fire at the engines'
    post-dispatch site — a crash lands mid-batch with the fused
    exchange+scatter already on the device queue."""

    def _flat(self, n=64, shards=4):
        rng = np.random.default_rng(5)
        shard_of = rng.integers(0, shards, n)
        cols = [rng.integers(1, 100, n).astype(np.int32),
                rng.random(n).astype(np.float32)]
        return shard_of, cols

    def test_drop_routes_shard_lanes_to_padding(self):
        from flink_tpu.parallel.shuffle import stage_device_exchange

        shard_of, cols = self._flat()
        dst0, _, _ = stage_device_exchange(shard_of, 4, cols,
                                           fills=[0, 0.0])
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.device_exchange", nth=1,
                      kind="drop", where={"shard": 2})])
        with chaos.chaos_active(plan, seed=0) as c:
            dst, staged, _ = stage_device_exchange(shard_of, 4, cols,
                                                   fills=[0, 0.0])
            n = len(shard_of)
            # the dropped shard's lanes re-route to the padding
            # destination (they vanish before the collective); every
            # other lane is untouched
            assert (dst0[:n] == 2).sum() > 0
            assert not (dst[:n] == 2).any()
            assert ((dst[:n] == 4) == (shard_of == 2)).all()
            np.testing.assert_array_equal(staged[0][:n], cols[0])
            _note_reached(c.faults_injected)

    def test_duplicate_replays_shard_records(self):
        from flink_tpu.parallel.shuffle import stage_device_exchange

        shard_of, cols = self._flat()
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.device_exchange", nth=1,
                      kind="duplicate", where={"shard": 1})])
        with chaos.chaos_active(plan, seed=0) as c:
            dst, staged, _ = stage_device_exchange(shard_of, 4, cols,
                                                   fills=[0, 0.0])
            n = len(shard_of)
            c1 = int((shard_of == 1).sum())
            assert c1 > 0
            # the duplicated rows ride as extra real lanes after the
            # original batch
            assert (dst[n:n + c1] == 1).all()
            np.testing.assert_array_equal(
                staged[1][n:n + c1], cols[1][shard_of == 1])
            _note_reached(c.faults_injected)

    def test_raise_fires_after_fused_dispatch(self, eight_device_mesh):
        """An engine in device mode crashes AT the post-dispatch site:
        process_batch raises with the exchange+scatter already
        dispatched (no fence pushed)."""
        from tests.test_sessions import keyed_batch

        make = _make_session_engine(eight_device_mesh,
                                    shuffle_mode="device")
        eng = make()
        assert eng.shuffle_mode == "device"
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.device_exchange", nth=1)])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(InjectedFault):
                eng.process_batch(keyed_batch(
                    [1, 2, 3], [1.0, 2.0, 3.0], [0, 10, 20]))
            assert c.faults_injected.get(
                "shuffle.device_exchange", 0) == 1
            _note_reached(c.faults_injected)

    def test_device_mode_crash_restore_matches_oracle(
            self, eight_device_mesh, tmp_path):
        """The satellite scenario: shuffle.mode=device, crash mid-batch
        after the fused dispatch, restore from the latest complete
        checkpoint, replay — committed output oracle-identical, and the
        run is seed-deterministic."""
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.device_exchange", nth=5)])

        def run(tag):
            return run_crash_restore_verify(
                _make_session_engine(eight_device_mesh,
                                     shuffle_mode="device"),
                _make_session_oracle(),
                _session_steps(seed=47), plan, seed=9,
                ckpt_root=str(tmp_path / f"ckpt-{tag}"),
                checkpoint_every=2)

        r1 = run("a")
        assert not r1.diverged and r1.windows > 0
        assert r1.crashes == 1 and r1.restores == 1
        assert r1.faults_injected.get("shuffle.device_exchange", 0) == 1
        r2 = run("b")
        assert r2.signature() == r1.signature()
        _note_reached(r1.faults_injected)

    def test_device_negative_control_drop_diverges(
            self, eight_device_mesh, tmp_path):
        """A dropped shard on the DEVICE data plane must diverge from
        the oracle — the same loss-detection proof the host path's
        negative control gives."""
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.device_exchange", nth=4,
                      kind="drop")])
        r = run_crash_restore_verify(
            _make_session_engine(eight_device_mesh,
                                 shuffle_mode="device"),
            _make_session_oracle(),
            _session_steps(seed=53), plan, seed=5,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2,
            check=False)
        assert r.diverged and r.crashes == 0
        assert r.faults_injected.get("shuffle.device_exchange", 0) == 1
        _note_reached(r.faults_injected)


# -------------------------------------------------------- restart satellites


class TestRestartStrategySatellites:
    def test_jitter_bounds_and_seed_determinism(self):
        from flink_tpu.cluster.restart_strategies import (
            ExponentialDelayRestartStrategy,
        )

        def backoffs(seed):
            s = ExponentialDelayRestartStrategy(
                initial_ms=1000, max_ms=60_000, multiplier=2.0,
                max_attempts=10, jitter_factor=0.25, seed=seed)
            out = []
            for _ in range(5):
                s.notify_failure()
                out.append(s.backoff_ms())
            return out

        a, b = backoffs(7), backoffs(7)
        assert a == b  # seeded jitter is deterministic
        base = 1000
        for got in a:
            assert 0.75 * base <= got <= 1.25 * base
            base = min(base * 2, 60_000)

    def test_backoff_resets_after_quiet_period(self):
        from flink_tpu.cluster.restart_strategies import (
            ExponentialDelayRestartStrategy,
        )

        now = [0.0]
        s = ExponentialDelayRestartStrategy(
            initial_ms=100, max_ms=60_000, multiplier=2.0,
            max_attempts=3, reset_backoff_threshold_ms=10_000,
            clock=lambda: now[0])
        for _ in range(3):
            s.notify_failure()
        assert s.backoff_ms() == 400
        assert not s.can_restart()  # budget spent
        now[0] = 11.0  # 11 s of healthy running
        s.notify_failure()
        assert s.backoff_ms() == 100  # backoff reset...
        assert s.can_restart()  # ...and the attempt budget too

    def test_no_reset_within_quiet_period(self):
        from flink_tpu.cluster.restart_strategies import (
            ExponentialDelayRestartStrategy,
        )

        now = [0.0]
        s = ExponentialDelayRestartStrategy(
            initial_ms=100, multiplier=2.0, max_attempts=10,
            reset_backoff_threshold_ms=10_000, clock=lambda: now[0])
        s.notify_failure()
        now[0] = 5.0  # inside the threshold
        s.notify_failure()
        assert s.backoff_ms() == 200

    def test_from_config_honors_exponential_options(self):
        from flink_tpu.cluster.restart_strategies import (
            restart_strategy_from_config,
        )
        from flink_tpu.core.config import Configuration

        s = restart_strategy_from_config(Configuration({
            "restart-strategy.type": "exponential-delay",
            "restart-strategy.delay-ms": 50,
            "restart-strategy.max-attempts": 7,
            "restart-strategy.exponential-delay.max-backoff-ms": 400,
            "restart-strategy.exponential-delay.backoff-multiplier": 3.0,
            "restart-strategy.exponential-delay.jitter-factor": 0.1,
            "restart-strategy.exponential-delay."
            "reset-backoff-threshold-ms": 9000,
        }))
        assert s.initial_ms == 50 and s.max_attempts == 7
        assert s.max_ms == 400 and s.multiplier == 3.0
        assert s.jitter_factor == 0.1
        assert s.reset_backoff_threshold_ms == 9000
        # the ceiling is actually enforced: 50 -> 150 -> 400 (capped)
        for _ in range(4):
            s.notify_failure()
        assert s._current == 400

    def test_from_config_honors_failure_rate_interval(self):
        from flink_tpu.cluster.restart_strategies import (
            restart_strategy_from_config,
        )
        from flink_tpu.core.config import Configuration

        s = restart_strategy_from_config(Configuration({
            "restart-strategy.type": "failure-rate",
            "restart-strategy.max-attempts": 5,
            "restart-strategy.failure-rate."
            "failure-rate-interval-ms": 1234,
        }))
        assert s.interval_ms == 1234 and s.max_failures == 5

    def test_failure_rate_interval_expires_failures(self):
        from flink_tpu.cluster.restart_strategies import (
            FailureRateRestartStrategy,
        )

        now = [0.0]
        s = FailureRateRestartStrategy(
            max_failures=2, interval_ms=1000, clock=lambda: now[0])
        s.notify_failure()
        s.notify_failure()
        assert not s.can_restart()
        now[0] = 2.0  # both failures age out of the window
        s.notify_failure()
        assert s.can_restart()


# ------------------------------------------------- crash-restore-verify


def _session_steps(num_keys=6000, n_steps=8, per_step=1500, seed=17):
    """Live session set far beyond the 1024-slot/shard budget: paged
    eviction + reload are genuinely on the path (same shape as
    tests/test_mesh_paged_spill)."""
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        out.append((keys, vals, ts, (s - 1) * 80))
    return out


def _make_session_engine(mesh, dispatch_ahead=2, shuffle_mode="host"):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    # shuffle_mode="host" pins the EXPLICIT fallback data plane for the
    # long-standing scenarios, keeping shuffle.bucket_send/_prep
    # semantics and the host-path negative control exercised; the
    # device data plane's scenarios live in TestDeviceExchangeFaults
    return lambda: MeshSessionEngine(
        GAP, SumAggregate("v"), mesh, capacity_per_shard=1 << 14,
        max_device_slots=1024, max_dispatch_ahead=dispatch_ahead,
        shuffle_mode=shuffle_mode)


def _make_session_oracle():
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.sessions import SessionWindower

    return lambda: SessionWindower(GAP, SumAggregate("v"),
                                   capacity=1 << 15)


class TestCrashRestoreVerify:
    def test_mesh_sessions_paged_forced_eviction(self, eight_device_mesh,
                                                 tmp_path):
        """The acceptance scenario: mesh session engine with
        spill_layout='pages' under forced eviction; crashes at the
        dispatch fence, in a page reload and in a session fire; one
        torn checkpoint write; deferred (recoverable) compaction.
        Committed output must equal the fault-free oracle exactly, and
        the run must be bit-deterministic for the same seed."""
        plan = FaultPlan(rules=[
            FaultRule(pattern="mesh.dispatch_fence", nth=5),
            FaultRule(pattern="spill.page_reload", nth=3),
            FaultRule(pattern="mesh.session_fire", nth=6),
            FaultRule(pattern="checkpoint.write.torn", nth=2,
                      kind="drop"),
            FaultRule(pattern="spill.page_compact", nth=1,
                      recoverable=True),
            # a zero-ms delay: proves the batch-level prep point is
            # live without perturbing behavior (stays deterministic)
            FaultRule(pattern="shuffle.bucket_prep", nth=3,
                      kind="delay", delay_ms=0),
        ])

        def run(tag):
            return run_crash_restore_verify(
                _make_session_engine(eight_device_mesh),
                _make_session_oracle(),
                _session_steps(), plan, seed=7,
                ckpt_root=str(tmp_path / f"ckpt-{tag}"),
                checkpoint_every=2)

        r1 = run("a")
        assert not r1.diverged
        assert r1.crashes == 3 and r1.restores == 3
        assert r1.corrupt_checkpoints_skipped >= 1
        for point in ("mesh.dispatch_fence", "spill.page_reload",
                      "mesh.session_fire", "checkpoint.write.torn",
                      "spill.page_compact"):
            assert r1.faults_injected.get(point, 0) >= 1, point
        assert r1.recoveries >= 1  # the deferred compaction
        # determinism: same (plan, seed, steps) => identical signature
        r2 = run("b")
        assert r1.signature() == r2.signature()
        _note_reached(r1.faults_injected)

    def test_tumbling_mesh_engine(self, eight_device_mesh, tmp_path):
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows
        from flink_tpu.windowing.windower import SliceSharedWindower

        def make_engine():
            return MeshWindowEngine(
                TumblingEventTimeWindows.of(200), SumAggregate("v"),
                eight_device_mesh, capacity_per_shard=1 << 14)

        def make_oracle():
            return SliceSharedWindower(
                TumblingEventTimeWindows.of(200), SumAggregate("v"),
                capacity=1 << 15)

        plan = FaultPlan(rules=[
            FaultRule(pattern="mesh.window_fire", nth=2),
            FaultRule(pattern="mesh.dispatch_fence", nth=5),
            FaultRule(pattern="checkpoint.write.torn", nth=3,
                      kind="corrupt"),
        ])
        r = run_crash_restore_verify(
            make_engine, make_oracle,
            _session_steps(num_keys=800, per_step=1200), plan, seed=11,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2)
        assert not r.diverged and r.windows > 0
        assert r.crashes == 2 and r.restores == 2
        assert r.faults_injected.get("mesh.window_fire", 0) == 1
        assert r.corrupt_checkpoints_skipped >= 1
        _note_reached(r.faults_injected)

    def test_dispatch_ahead_async_fire_pipeline(self, eight_device_mesh,
                                                tmp_path):
        """dispatch-ahead 3 + async fires: crashes land mid-pipeline
        (batches in flight past the fence) and in the coalesced
        harvest; exactly-once must still hold."""
        plan = FaultPlan(rules=[
            FaultRule(pattern="harvest.pending_fire", nth=3),
            FaultRule(pattern="mesh.dispatch_fence", nth=8),
        ])
        r = run_crash_restore_verify(
            _make_session_engine(eight_device_mesh, dispatch_ahead=3),
            _make_session_oracle(),
            _session_steps(seed=23), plan, seed=5,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2,
            async_fires=True)
        assert not r.diverged
        assert r.crashes == 2 and r.restores == 2
        assert r.faults_injected.get("harvest.pending_fire", 0) == 1
        _note_reached(r.faults_injected)

    def test_harness_catches_lossy_shuffle(self, eight_device_mesh,
                                           tmp_path):
        """The negative control: a genuinely lossy fault (a dropped
        shard bucket, never crashed over) MUST diverge — proving the
        oracle diff actually detects data loss rather than vacuously
        passing."""
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.bucket_send", nth=4,
                      kind="drop")])
        r = run_crash_restore_verify(
            _make_session_engine(eight_device_mesh),
            _make_session_oracle(),
            _session_steps(seed=31), plan, seed=3,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2,
            check=False)
        assert r.diverged and r.crashes == 0
        assert r.faults_injected.get("shuffle.bucket_send", 0) == 1
        _note_reached(r.faults_injected)
        with pytest.raises(ChaosDivergenceError):
            run_crash_restore_verify(
                _make_session_engine(eight_device_mesh),
                _make_session_oracle(),
                _session_steps(seed=31), plan, seed=3,
                ckpt_root=str(tmp_path / "ckpt2"), checkpoint_every=2)

    def test_cold_restart_before_first_checkpoint(self,
                                                  eight_device_mesh,
                                                  tmp_path):
        """A crash before any checkpoint exists restarts from scratch
        (source position 0) and still matches the oracle."""
        plan = FaultPlan(rules=[
            FaultRule(pattern="mesh.dispatch_fence", nth=1)])
        r = run_crash_restore_verify(
            _make_session_engine(eight_device_mesh),
            _make_session_oracle(),
            _session_steps(n_steps=4, seed=41), plan, seed=2,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2)
        assert not r.diverged
        assert r.cold_restarts == 1 and r.restores == 0
        _note_reached(r.faults_injected)


# ------------------------------------------------------------ cluster layer


class TestClusterRestartPath:
    def test_task_crash_restarts_and_finishes(self, tmp_path):
        """An injected task crash consumes restart budget, the job
        restores from its checkpoint and FINISHES — the minicluster
        form of the harness loop (reference: recovery ITCases)."""
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
        from flink_tpu.connectors.sinks import JsonLinesFileSink
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 2,
            "heartbeat.interval-ms": 100,
        }))
        try:
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 256,
                "state.checkpoints.dir": str(tmp_path / "ckpt"),
                "execution.checkpointing.every-n-source-batches": 2,
                "restart-strategy.max-attempts": 3,
                "restart-strategy.delay-ms": 10,
            }))
            rows = [{"k": i % 5, "v": 1, "ts": i * 10}
                    for i in range(5000)]
            sink = JsonLinesFileSink(str(tmp_path / "out.jsonl"))
            env.from_collection(rows, timestamp_field="ts") \
                .map(lambda b: b, name="chaosmap") \
                .key_by("k") \
                .window(TumblingEventTimeWindows.of(1000)) \
                .sum("v").sink_to(sink)
            plan = FaultPlan(rules=[
                FaultRule(pattern="task.batch", nth=12,
                          where={"op": "chaosmap"})])
            with chaos.chaos_active(plan, seed=0) as c:
                client = cluster.submit(env, "chaos-task-crash")
                st = client.wait(timeout=120)
                assert st["status"] == FINISHED, st
                assert st["attempt"] == 1  # exactly one restart
                assert c.faults_injected.get("task.batch", 0) == 1
                _note_reached(c.faults_injected)
        finally:
            cluster.shutdown()

    def test_subtask_crash_fails_stage_parallel_attempt(self):
        """The stage-parallel execution path: an injected subtask crash
        propagates through the coordinator as the attempt failure the
        cluster failover would consume."""
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "execution.stage-parallelism": 2,
        }))
        src = DataGenSource(total_records=8000, num_keys=64,
                            events_per_second_of_eventtime=10_000,
                            seed=5)
        ds = env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        ds.key_by("key").window(TumblingEventTimeWindows.of(1000)) \
            .sum("value").sink_to(CollectSink())
        plan = FaultPlan(rules=[
            FaultRule(pattern="task.subtask_batch", nth=3)])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(InjectedFault):
                env.execute("chaos-subtask-crash")
            assert c.faults_injected.get("task.subtask_batch", 0) == 1
            _note_reached(c.faults_injected)


# ---------------------------------------------------------- reachability


class TestRescaleHandoffPoint:
    """The autoscaler's live-migration fault point, injected at its real
    production site (MeshSpillSupport.reshard) so the canonical
    inventory's reachability ledger covers it in THIS suite too (the
    full crash-restore-verify exercise lives in tests/test_autoscale.py)."""

    def test_handoff_drain_crash_at_real_site(self):
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.aggregates import SumAggregate

        from tests.test_sessions import keyed_batch

        eng = MeshSessionEngine(GAP, SumAggregate("v"), make_mesh(2),
                                capacity_per_shard=1024)
        eng.process_batch(keyed_batch([1, 2, 3], [1.0, 2.0, 3.0],
                                      [0, 10, 20]))
        plan = FaultPlan(rules=[
            FaultRule(pattern="rescale.handoff", nth=1,
                      where={"stage": "drain"})])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(InjectedFault):
                eng.reshard(4)
            assert c.faults_injected.get("rescale.handoff", 0) == 1
            _note_reached(c.faults_injected)
        # reshard is not exception-atomic: the engine is dead here; the
        # recovery path (restore at the new parallelism) is proven by
        # tests/test_autoscale.py's chaos crash test


class TestRebalanceHandoffPoint:
    """The skew rebalancer's fault point, injected at its real site
    (MeshSpillSupport.reassign_key_groups — a key-group MOVE at
    unchanged P) so the canonical inventory's reachability ledger
    covers it in THIS suite too (the crash-at-commit crash-restore-
    verify exercise lives in tests/test_autoscale.py)."""

    def test_rebalance_commit_crash_at_real_site(self):
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.aggregates import SumAggregate

        from tests.test_sessions import keyed_batch

        eng = MeshSessionEngine(GAP, SumAggregate("v"), make_mesh(2),
                                capacity_per_shard=1024)
        eng.process_batch(keyed_batch([1, 2, 3], [1.0, 2.0, 3.0],
                                      [0, 10, 20]))
        cur = eng.key_group_assignment
        moved = cur.move(
            np.arange(cur.first, cur.first + cur.span // 2), 1)
        plan = FaultPlan(rules=[
            FaultRule(pattern="rebalance.handoff", nth=1,
                      where={"stage": "commit"})])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(InjectedFault):
                eng.reassign_key_groups(moved)
            assert c.faults_injected.get("rebalance.handoff", 0) == 1
            _note_reached(c.faults_injected)
        # commit crashed with the hot range's rows lifted: the engine
        # is dead; recovery restores a contiguous engine and re-applies
        # the move on replay (proven in tests/test_autoscale.py)


class TestServingLookupPoint:
    """The serving plane's fault point, injected at its real site (the
    batched queryable-state lookup wrapped in run_recoverable): a
    transient fault retries in place — lookups are read-only, so a
    retry cannot corrupt engine state (the full two-job serving-burst
    exercise lives in tests/test_tenancy.py)."""

    def test_serving_lookup_retries_at_real_site(self, tmp_path):
        from flink_tpu.chaos.harness import run_crash_restore_verify_multi
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.sessions import SessionWindower

        def mk_mesh():
            return MeshSessionEngine(GAP, SumAggregate("v"),
                                     make_mesh(2),
                                     capacity_per_shard=1024)

        def mk_oracle():
            return SessionWindower(GAP, SumAggregate("v"))

        rng = np.random.default_rng(0)
        steps = []
        for i in range(4):
            ks = rng.integers(0, 50, 128)
            steps.append((ks, np.ones(128, dtype=np.float32),
                          i * 300 + np.sort(rng.integers(0, 200, 128)),
                          i * 300 - 2 * GAP))
        plan = FaultPlan(rules=[
            FaultRule(pattern="serving.lookup", nth=1,
                      recoverable=True)])
        reports = run_crash_restore_verify_multi(
            make_engines={"j": mk_mesh}, make_oracles={"j": mk_oracle},
            steps_by_job={"j": steps}, plan=plan, seed=3,
            ckpt_root=str(tmp_path), serve_keys={"j": [1, 2, 3]})
        r = reports["j"]
        assert r.faults_injected.get("serving.lookup", 0) >= 1
        assert r.retries >= 1 and r.recoveries >= 1
        assert r.crashes == 0 and not r.diverged
        _note_reached(r.faults_injected)


class TestReplicaPublishPoint:
    """``serving.replica_publish``, injected at its real site — INSIDE
    a boundary publish, before the seal swap. The crash-restore shape:
    readers keep serving the intact sealed generation through the torn
    publish, the restored engine republishes, and lookups never observe
    a torn replica (the snapshot-isolation-under-fault pin; the full
    scenario with checkpoint restore lives in
    tests/test_serving_replica.py::TestReplicaChaos)."""

    def test_replica_publish_injected_at_real_site(self):
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.tenancy.replica import WindowReplicaAdapter
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )

        eng = MeshWindowEngine(
            TumblingEventTimeWindows(1000), SumAggregate("v"),
            make_mesh(2), capacity_per_shard=1024, max_parallelism=128)
        plane = eng.arm_replica()
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))

        def step(t):
            eng.process_batch(RecordBatch({
                "__key_id__": np.arange(16, dtype=np.int64),
                "__ts__": np.full(16, t, dtype=np.int64),
                "v": np.ones(16, dtype=np.float32),
            }))

        step(100)
        eng.on_watermark(50)  # first publish seals generation 1
        before, gen = ad.lookup_batch([3])
        plan = FaultPlan(rules=[
            FaultRule(pattern="serving.replica_publish", nth=1)])
        with chaos.chaos_active(plan, seed=0) as c:
            step(600)
            with pytest.raises(InjectedFault):
                eng.on_watermark(550)
            assert c.faults_injected.get("serving.replica_publish",
                                         0) == 1
            _note_reached(c.faults_injected)
        # torn publish: the sealed generation is untouched
        again, gen2 = ad.lookup_batch([3])
        assert gen2 == gen and again == before
        # the engine recovers at its next boundary (the publish is
        # re-derivable: dirty marks and metadata survived the raise)
        out = eng.on_watermark(550)
        fresh, gen3 = ad.lookup_batch([3])
        assert gen3 > gen
        assert fresh == eng.query_batch(np.asarray([3],
                                                   dtype=np.int64))


class TestServingCacheProbePoint:
    """``serving.cache_probe``, injected at its real site — the batched
    hot-row probe in ``ServingPlane.lookup_batch``. A ``drop`` kind
    makes the probe fall to the MISS path (the system-level shape of a
    torn native read): the request still answers, bit-identical,
    resolved against the sealed replica instead of the cache. A
    ``raise`` kind surfaces to the client as the crash path."""

    def _serving(self):
        import queue as _q

        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.tenancy.replica import WindowReplicaAdapter
        from flink_tpu.tenancy.serving import ServingPlane
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )

        eng = MeshWindowEngine(
            TumblingEventTimeWindows(1000), SumAggregate("v"),
            make_mesh(2), capacity_per_shard=1024, max_parallelism=128)
        plane = eng.arm_replica()
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        serving = ServingPlane(workers=1)
        serving.bind_job("j", _q.Queue())
        serving.bind_replica("j", "op", ad)
        eng.process_batch(RecordBatch({
            "__key_id__": np.arange(16, dtype=np.int64),
            "__ts__": np.full(16, 100, dtype=np.int64),
            "v": np.ones(16, dtype=np.float32),
        }))
        eng.on_watermark(50)  # publish + harvest-prime the cache
        return eng, serving

    def test_drop_kind_falls_to_miss_path_bit_identical(self):
        eng, serving = self._serving()
        keys = list(range(8))
        try:
            want = serving.lookup_batch("j", "op", keys)
            hits_before = serving.hot_cache.hits
            assert hits_before > 0  # primed: the probe actually served
            plan = FaultPlan(rules=[
                FaultRule(pattern="serving.cache_probe", kind="drop",
                          every=1)])
            with chaos.chaos_active(plan, seed=0) as c:
                got = serving.lookup_batch("j", "op", keys)
                assert c.faults_injected.get("serving.cache_probe",
                                             0) >= 1
                _note_reached(c.faults_injected)
            # the dropped probe NEVER serves a mixed row — the whole
            # batch re-resolved against the sealed replica, bit-equal
            assert got == want
        finally:
            serving.shutdown_workers()

    def test_raise_kind_surfaces_to_client(self):
        eng, serving = self._serving()
        try:
            plan = FaultPlan(rules=[
                FaultRule(pattern="serving.cache_probe", nth=1)])
            with chaos.chaos_active(plan, seed=0) as c:
                with pytest.raises(InjectedFault):
                    serving.lookup_batch("j", "op", [1, 2, 3])
                assert c.faults_injected.get("serving.cache_probe",
                                             0) == 1
                _note_reached(c.faults_injected)
            # disarmed again: the probe path is intact
            assert serving.lookup_batch("j", "op", [1])[0] == \
                eng.query_batch(np.asarray([1], dtype=np.int64))[0]
        finally:
            serving.shutdown_workers()


class TestServingFrontendPoint:
    """``serving.frontend``, injected at its real site — the owner-side
    dispatch in ``FrontendPool.lookup_batch``. The ``drop`` kind KILLS
    the chosen frontend process for real (death mid-burst): the
    in-flight lookup must fail over to a live sibling and the surviving
    results stay bit-identical to the dict oracle (the owner's own
    lookup path); owner and siblings are unharmed. ``raise`` surfaces
    to the client as the crash path."""

    def _serving_shm(self, tmp_path):
        import queue as _q

        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.tenancy.replica import WindowReplicaAdapter
        from flink_tpu.tenancy.serving import ServingPlane
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )

        eng = MeshWindowEngine(
            TumblingEventTimeWindows(1000), SumAggregate("v"),
            make_mesh(2), capacity_per_shard=1024, max_parallelism=128)
        plane = eng.arm_replica()
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        serving = ServingPlane(workers=1,
                               shm_dir=str(tmp_path / "shm"))
        serving.bind_job("j", _q.Queue())
        serving.bind_replica("j", "op", ad)
        eng.process_batch(RecordBatch({
            "__key_id__": np.arange(16, dtype=np.int64),
            "__ts__": np.full(16, 100, dtype=np.int64),
            "v": np.ones(16, dtype=np.float32),
        }))
        eng.on_watermark(50)  # publish + harvest-prime the shm cache
        return eng, serving

    @pytest.mark.skipif(
        not __import__("flink_tpu.native", fromlist=["x"])
        .hotcache_available(),
        reason="native hotcache unavailable")
    def test_drop_kind_kills_frontend_failover_bit_identical(
            self, tmp_path):
        from flink_tpu.tenancy.frontend import FrontendPool

        eng, serving = self._serving_shm(tmp_path)
        pool = None
        keys = list(range(8))
        try:
            want = serving.lookup_batch("j", "op", keys)  # dict oracle
            pool = FrontendPool(serving, n_frontends=2)
            assert pool.lookup_batch("j", "op", keys) == want
            plan = FaultPlan(rules=[
                FaultRule(pattern="serving.frontend", kind="drop",
                          nth=1)])
            with chaos.chaos_active(plan, seed=0) as c:
                got = pool.lookup_batch("j", "op", keys)
                assert c.faults_injected.get("serving.frontend",
                                             0) >= 1
                _note_reached(c.faults_injected)
            # the killed frontend's in-flight lookup failed over to the
            # sibling, bit-identical to the oracle
            assert got == want
            assert pool.failovers >= 1
            assert len(pool.live_frontends()) == 1
            # owner and sibling unharmed: both paths still serve
            assert pool.lookup_batch("j", "op", keys) == want
            assert serving.lookup_batch("j", "op", keys) == want
        finally:
            if pool is not None:
                pool.close()
            serving.shutdown_workers()
            serving.hot_cache.close()

    @pytest.mark.skipif(
        not __import__("flink_tpu.native", fromlist=["x"])
        .hotcache_available(),
        reason="native hotcache unavailable")
    def test_raise_kind_surfaces_to_client(self, tmp_path):
        from flink_tpu.tenancy.frontend import FrontendPool

        eng, serving = self._serving_shm(tmp_path)
        pool = None
        try:
            pool = FrontendPool(serving, n_frontends=1)
            plan = FaultPlan(rules=[
                FaultRule(pattern="serving.frontend", nth=1)])
            with chaos.chaos_active(plan, seed=0) as c:
                with pytest.raises(InjectedFault):
                    pool.lookup_batch("j", "op", [1, 2, 3])
                assert c.faults_injected.get("serving.frontend",
                                             0) == 1
                _note_reached(c.faults_injected)
            # disarmed again: the frontend path is intact
            assert pool.lookup_batch("j", "op", [1]) == \
                serving.lookup_batch("j", "op", [1])
        finally:
            if pool is not None:
                pool.close()
            serving.shutdown_workers()
            serving.hot_cache.close()


class TestWatchdogPoints:
    """The partial-failover fault points, injected at their real sites:
    ``device.lost`` fires inside the watchdog's batch-boundary probe on
    the mesh engine's ingest path, and ``watchdog.deadline`` (a
    delay-kind injection — a slow device, not an exception) stretches a
    deadline-tracked device section past its budget until the next
    boundary declares the shard dead. The full recovery protocol lives
    in tests/test_shard_failover.py."""

    def _engine_with_watchdog(self, deadline_ms=0.0, max_misses=3):
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.runtime.watchdog import DeviceWatchdog
        from flink_tpu.windowing.aggregates import SumAggregate

        eng = MeshSessionEngine(GAP, SumAggregate("v"), make_mesh(2),
                                capacity_per_shard=1024)
        eng.attach_watchdog(DeviceWatchdog(
            eng.P, deadline_ms=deadline_ms, max_misses=max_misses))
        return eng

    def test_device_lost_declares_shard_dead_at_real_site(self):
        from flink_tpu.runtime.watchdog import ShardFailedError

        from tests.test_sessions import keyed_batch

        eng = self._engine_with_watchdog()
        plan = FaultPlan(rules=[
            FaultRule(pattern="device.lost", nth=1,
                      where={"shard": 1})])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(ShardFailedError) as ei:
                eng.process_batch(keyed_batch([1, 2, 3],
                                              [1.0, 2.0, 3.0],
                                              [0, 10, 20]))
            assert ei.value.shard == 1
            assert 1 in eng._watchdog.quarantined
            assert c.faults_injected.get("device.lost", 0) == 1
            _note_reached(c.faults_injected)

    def test_deadline_delay_escalates_at_the_boundary(self):
        from flink_tpu.runtime.watchdog import MeshStalledError

        from tests.test_sessions import keyed_batch

        # every deadline-tracked section sleeps 20 ms against a 1 ms
        # deadline: timeout -> retry (miss streak) -> escalated at the
        # next batch boundary once the miss budget is spent. The
        # engine's sections are whole-mesh (SPMD), so the uniform
        # streak carries no shard attribution and escalates as a
        # MESH STALL (whole-job restart), never a false shard death
        eng = self._engine_with_watchdog(deadline_ms=1.0, max_misses=2)
        plan = FaultPlan(rules=[
            FaultRule(pattern="watchdog.deadline", every=1,
                      kind="delay", delay_ms=20, max_injections=0)])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(MeshStalledError):
                for i in range(8):
                    eng.process_batch(keyed_batch(
                        [1, 2, 3], [1.0, 2.0, 3.0],
                        [i * 10, i * 10 + 1, i * 10 + 2]))
            assert eng._watchdog.deadline_misses >= 2
            assert not eng._watchdog.quarantined
            assert c.faults_injected.get("watchdog.deadline", 0) >= 2
            _note_reached(c.faults_injected)


class TestPodFaultPoints:
    """The pod-scale fault points at their real sites: ``host.lost``
    fires inside the watchdog's boundary probe once per live HOST (the
    process-granular death the multi-process chaos scenario injects),
    and ``exchange.dcn_send`` models a lossy DCN link in the two-level
    exchange staging — drop/duplicate per CROSS-host (src, dst) bucket.
    The full host-failover protocol lives in
    tests/test_host_failover.py."""

    def _pod_engine(self, watchdog=True):
        from flink_tpu.parallel.mesh import HostTopology, make_mesh
        from flink_tpu.parallel.sharded_sessions import (
            MeshSessionEngine,
        )
        from flink_tpu.runtime.watchdog import DeviceWatchdog
        from flink_tpu.windowing.aggregates import SumAggregate

        eng = MeshSessionEngine(GAP, SumAggregate("v"), make_mesh(4),
                                capacity_per_shard=1024,
                                host_topology=HostTopology(2, 2))
        if watchdog:
            eng.attach_watchdog(DeviceWatchdog(eng.P))
        return eng

    def test_host_lost_declares_whole_host_at_real_site(self):
        from flink_tpu.runtime.watchdog import HostFailedError

        from tests.test_sessions import keyed_batch

        eng = self._pod_engine()
        plan = FaultPlan(rules=[
            FaultRule(pattern="host.lost", nth=1,
                      where={"host": 1})])
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(HostFailedError) as ei:
                eng.process_batch(keyed_batch([1, 2, 3],
                                              [1.0, 2.0, 3.0],
                                              [0, 10, 20]))
            assert ei.value.host == 1
            # the whole host's slice quarantines in one declaration
            assert ei.value.shards == (2, 3)
            assert eng._watchdog.quarantined == {2, 3}
            assert eng._watchdog.hosts_declared_dead == 1
            assert c.faults_injected.get("host.lost", 0) == 1
            _note_reached(c.faults_injected)

    def test_dcn_send_drop_loses_the_cross_host_bucket(self):
        from flink_tpu.parallel.exchange2 import (
            stage_two_level_exchange,
        )
        from flink_tpu.parallel.mesh import HostTopology

        topo = HostTopology(2, 2)
        # records in chunk 0 (source host 0) destined to shards 2 and 3
        # (host 1) — the (0 -> 1) DCN bucket
        shards = np.array([2, 3, 0, 2], dtype=np.int64)
        slots = np.arange(1, 5, dtype=np.int32)
        plan = FaultPlan(rules=[
            FaultRule(pattern="exchange.dcn_send", nth=1, kind="drop",
                      where={"src_host": 0, "dst_host": 1})])
        with chaos.chaos_active(plan, seed=0) as c:
            dst, (s_col,), w1, w2 = stage_two_level_exchange(
                shards, topo, columns=[slots], fills=[0])
            assert c.faults_injected.get("exchange.dcn_send", 0) == 1
            _note_reached(c.faults_injected)
        # the cross-host rows re-routed to the padding destination
        # (they vanish before the stage-1 collective); the intra-host
        # row survives
        np.testing.assert_array_equal(dst[:4], [4, 4, 0, 4])

    def test_dcn_send_duplicate_replays_the_bucket(self):
        from flink_tpu.parallel.exchange2 import (
            stage_two_level_exchange,
        )
        from flink_tpu.parallel.mesh import HostTopology

        topo = HostTopology(2, 2)
        shards = np.array([2, 3, 0], dtype=np.int64)
        slots = np.arange(1, 4, dtype=np.int32)
        plan = FaultPlan(rules=[
            FaultRule(pattern="exchange.dcn_send", nth=1,
                      kind="duplicate",
                      where={"src_host": 0, "dst_host": 1})])
        with chaos.chaos_active(plan, seed=0) as c:
            dst, (s_col,), w1, w2 = stage_two_level_exchange(
                shards, topo, columns=[slots], fills=[0])
            assert c.faults_injected.get("exchange.dcn_send", 0) == 1
        # the (0 -> 1) bucket's rows replay at the tail
        np.testing.assert_array_equal(dst[:5], [2, 3, 0, 2, 3])
        np.testing.assert_array_equal(s_col[:5], [1, 2, 3, 1, 2])


class _IntervalJoinHarnessEngine:
    """Adapts the device interval-join engine to the crash-restore
    harness protocol: each step batch splits by row parity into the
    two inputs (values carry the row's own timestamp, so every joined
    pair lands in a unique ``(key, lts, rts)`` upsert cell — a lost or
    duplicated pair changes the committed cells, never hides)."""

    def __init__(self, backend="device", shards=2, **kw):
        from flink_tpu.joins import MeshIntervalJoinEngine

        if backend == "device":
            from flink_tpu.parallel.mesh import make_mesh

            self.eng = MeshIntervalJoinEngine(
                -60, 60, mesh=make_mesh(shards), **kw)
        else:
            self.eng = MeshIntervalJoinEngine(
                -60, 60, backend="host", num_shards=shards, **kw)
        self._buf = []

    @property
    def P(self):
        return self.eng.P

    def reshard(self, n):
        return self.eng.reshard(n)

    def process_batch(self, batch):
        left = np.arange(len(batch)) % 2 == 0
        self._buf += self.eng.process_batch(batch.filter(left), 0)
        self._buf += self.eng.process_batch(batch.filter(~left), 1)

    def on_watermark(self, wm, async_ok=False):
        from flink_tpu.core.records import (
            KEY_ID_FIELD,
            TIMESTAMP_FIELD,
            RecordBatch,
        )
        from flink_tpu.windowing.windower import (
            WINDOW_END_FIELD,
            WINDOW_START_FIELD,
        )

        out = []
        for b in self._buf:
            lts = np.asarray(b["v_l"], dtype=np.int64)
            rts = np.asarray(b["v_r"], dtype=np.int64)
            out.append(RecordBatch({
                KEY_ID_FIELD: b[KEY_ID_FIELD],
                WINDOW_START_FIELD: lts,
                WINDOW_END_FIELD: rts + 1,
                TIMESTAMP_FIELD: b[TIMESTAMP_FIELD],
                "val": np.asarray(b["v_l"])
                + np.asarray(b["v_r"]),
            }))
        self._buf = []
        self.eng.on_watermark(int(wm))
        return out

    def snapshot(self):
        return self.eng.snapshot()

    def restore(self, snap):
        self.eng.restore(snap)
        self._buf = []


def _join_steps(n_steps=6, n=96, keys=24, seed=4):
    """Harness steps whose values ARE the row timestamps. Event time
    OVERLAPS across steps (step stride 60 < in-step span 96, band
    +-60), so buffered rows of one step match probes of later steps —
    a row lost at INGEST (after the arriving batch's own probe) still
    changes the committed cells."""
    rng = np.random.default_rng(seed)
    steps = []
    for i in range(n_steps):
        ks = rng.integers(0, keys, n)
        ts = i * 60 + np.arange(n, dtype=np.int64)
        steps.append((ks, ts.astype(np.float32), ts, i * 60 - 300))
    return steps


class TestJoinExchangePoint:
    """The two-input data plane's fault point at its real site
    (JoinEngineBase._ingest): a raise crashes mid-batch with the join
    put on the device queue — crash-restore must stay oracle-identical
    — and a DROPPED side bucket must DIVERGE (the negative control:
    the harness catches genuine loss in the join plane)."""

    def test_join_job_crash_restore_oracle_identical(self, tmp_path):
        # nth=7 = step 3's left ingest: past the first checkpoint, so
        # the recovery is a genuine RESTORE (not a cold restart)
        plan = FaultPlan(rules=[
            FaultRule(pattern="join.exchange", nth=7)])
        report = run_crash_restore_verify(
            make_engine=lambda: _IntervalJoinHarnessEngine("device"),
            make_oracle=lambda: _IntervalJoinHarnessEngine("host"),
            steps=_join_steps(), plan=plan, seed=7,
            ckpt_root=str(tmp_path))
        assert report.crashes >= 1 and report.restores >= 1
        assert report.faults_injected.get("join.exchange", 0) >= 1
        assert not report.diverged
        assert report.windows > 0
        _note_reached(report.faults_injected)

    def test_join_job_crash_restore_is_deterministic(self, tmp_path):
        plan = FaultPlan(rules=[
            FaultRule(pattern="join.exchange", nth=7)])
        sigs = []
        for i in range(2):
            r = run_crash_restore_verify(
                make_engine=lambda: _IntervalJoinHarnessEngine(
                    "device"),
                make_oracle=lambda: _IntervalJoinHarnessEngine(
                    "host"),
                steps=_join_steps(), plan=plan, seed=7,
                ckpt_root=str(tmp_path / f"run{i}"))
            sigs.append(r.signature())
        assert sigs[0] == sigs[1]

    def test_dropped_side_bucket_diverges(self, tmp_path):
        # negative control: one shard's bucket of ONE side vanishes in
        # flight — its pairs never form and the diff MUST catch it
        plan = FaultPlan(rules=[
            FaultRule(pattern="join.exchange", nth=2, kind="drop",
                      where={"side": 1})])
        report = run_crash_restore_verify(
            make_engine=lambda: _IntervalJoinHarnessEngine("device"),
            make_oracle=lambda: _IntervalJoinHarnessEngine("host"),
            steps=_join_steps(), plan=plan, seed=7,
            ckpt_root=str(tmp_path), check=False)
        assert report.faults_injected.get("join.exchange", 0) >= 1
        assert report.diverged, (
            "a dropped join-side bucket produced identical output — "
            "the harness cannot catch join data-plane loss")
        _note_reached(report.faults_injected)

    def test_payload_injection_at_real_site(self):
        from flink_tpu.core.records import (
            KEY_ID_FIELD,
            TIMESTAMP_FIELD,
            RecordBatch,
        )
        from flink_tpu.joins import MeshIntervalJoinEngine

        eng = MeshIntervalJoinEngine(-60, 60, backend="host",
                                     num_shards=2)
        b = RecordBatch({
            KEY_ID_FIELD: np.arange(32, dtype=np.int64),
            "v": np.ones(32, dtype=np.float32),
            TIMESTAMP_FIELD: np.arange(32, dtype=np.int64)})
        plan = FaultPlan(rules=[
            FaultRule(pattern="join.exchange", nth=1,
                      kind="duplicate", where={"shard": 0})])
        with chaos.chaos_active(plan, seed=0) as c:
            eng.process_batch(b, 0)
            assert c.faults_injected.get("join.exchange", 0) == 1
            _note_reached(c.faults_injected)
        # shard 0's rows were replayed in flight: more rows buffered
        # than sent on that shard
        assert sum(len(m) for m in eng.sides[0].meta) > 32


class _TemporalJoinHarnessEngine:
    """Temporal-join adapter: odd rows are versions, even rows probe;
    matches emit at the watermark with the left time as the cell."""

    def __init__(self, backend="device", shards=2, **kw):
        from flink_tpu.joins import MeshTemporalJoinEngine

        if backend == "device":
            from flink_tpu.parallel.mesh import make_mesh

            self.eng = MeshTemporalJoinEngine(
                mesh=make_mesh(shards), **kw)
        else:
            self.eng = MeshTemporalJoinEngine(
                backend="host", num_shards=shards, **kw)

    @property
    def P(self):
        return self.eng.P

    def process_batch(self, batch):
        left = np.arange(len(batch)) % 2 == 0
        self.eng.process_batch(batch.filter(~left), 1)
        self.eng.process_batch(batch.filter(left), 0)

    def on_watermark(self, wm, async_ok=False):
        from flink_tpu.core.records import (
            KEY_ID_FIELD,
            TIMESTAMP_FIELD,
            RecordBatch,
        )
        from flink_tpu.windowing.windower import (
            WINDOW_END_FIELD,
            WINDOW_START_FIELD,
        )

        out = []
        for b in self.eng.on_watermark(int(wm)):
            lts = np.asarray(b[TIMESTAMP_FIELD], dtype=np.int64)
            out.append(RecordBatch({
                KEY_ID_FIELD: b[KEY_ID_FIELD],
                WINDOW_START_FIELD: lts,
                WINDOW_END_FIELD: lts + 1,
                TIMESTAMP_FIELD: lts,
                "val": np.asarray(b["v_l"]) + np.asarray(b["v_r"]),
            }))
        return out

    def snapshot(self):
        return self.eng.snapshot()

    def restore(self, snap):
        self.eng.restore(snap)


class TestJoinVersionedLookupPoint:
    """The versioned-plane lookup fault point at its real site (the
    temporal engine's watermark probe): a crash there happens with the
    pending left buffer intact, so restore + replay stays
    oracle-identical."""

    def test_crash_at_versioned_lookup_restores_identical(
            self, tmp_path):
        plan = FaultPlan(rules=[
            FaultRule(pattern="join.versioned_lookup", nth=2)])
        report = run_crash_restore_verify(
            make_engine=lambda: _TemporalJoinHarnessEngine("device"),
            make_oracle=lambda: _TemporalJoinHarnessEngine("host"),
            steps=_join_steps(seed=5), plan=plan, seed=9,
            ckpt_root=str(tmp_path))
        assert report.crashes >= 1 and report.restores >= 1
        assert report.faults_injected.get(
            "join.versioned_lookup", 0) >= 1
        assert not report.diverged
        assert report.windows > 0
        _note_reached(report.faults_injected)


class _CepHarnessEngine:
    """CEP adapter for the crash-restore harness: the pattern is a
    2-stage strict sequence over the value stream (``v%3==0`` then
    ``v%3==1``, within 120), SKIP_PAST_LAST_EVENT — device-eligible.
    Each emitted match maps to the harness upsert cell
    ``(key, start_ts, end_ts+1)`` with the stage counts as the value,
    so a lost/duplicated event changes which matches form — it can
    shift a cell, drop a cell, or change a count, never hide."""

    def __init__(self, backend="device", shards=2):
        from flink_tpu.cep.mesh_engine import MeshCepEngine
        from flink_tpu.cep.pattern import (
            AfterMatchSkipStrategy,
            Pattern,
        )

        pat = (Pattern.begin(
                "a", skip=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
               .where(lambda b: np.asarray(b["v"]) % 3 == 0)
               .next("b")
               .where(lambda b: np.asarray(b["v"]) % 3 == 1)
               .within(120))
        if backend == "device":
            from flink_tpu.parallel.mesh import make_mesh

            self.eng = MeshCepEngine(pat, mesh=make_mesh(shards),
                                     capacity_per_shard=256,
                                     backend="device")
        else:
            self.eng = MeshCepEngine(pat, num_shards=shards,
                                     backend="host",
                                     shuffle_mode="host")

    @property
    def P(self):
        return self.eng.P

    def reshard(self, n):
        return self.eng.reshard(n)

    def process_batch(self, batch):
        self.eng.process_batch(batch)

    def on_watermark(self, wm, async_ok=False):
        from flink_tpu.core.records import (
            KEY_ID_FIELD,
            TIMESTAMP_FIELD,
            RecordBatch,
        )
        from flink_tpu.windowing.windower import (
            WINDOW_END_FIELD,
            WINDOW_START_FIELD,
        )

        out = []
        for b in self.eng.on_watermark(int(wm)):
            rows = b.to_rows()
            out.append(RecordBatch({
                KEY_ID_FIELD: np.asarray(
                    [r["key"] for r in rows], dtype=np.int64),
                WINDOW_START_FIELD: np.asarray(
                    [r["start_ts"] for r in rows], dtype=np.int64),
                WINDOW_END_FIELD: np.asarray(
                    [r["end_ts"] + 1 for r in rows], dtype=np.int64),
                TIMESTAMP_FIELD: np.asarray(b.timestamps,
                                            dtype=np.int64),
                "val": np.asarray(
                    [r["a_count"] * 10 + r["b_count"] for r in rows],
                    dtype=np.float64),
            }))
        return out

    def snapshot(self):
        return self.eng.snapshot()

    def restore(self, snap):
        self.eng.restore(snap)


def _cep_steps(n_steps=6, n=96, keys=24, seed=13):
    """Value stream for the CEP pattern: small integers so the 0-mod-3
    -> 1-mod-3 sequence occurs often per key; timestamps advance 60
    per step with in-step spread, watermark trails one step."""
    rng = np.random.default_rng(seed)
    steps = []
    for i in range(n_steps):
        ks = rng.integers(0, keys, n)
        vs = rng.integers(0, 9, n).astype(np.float32)
        ts = i * 60 + np.sort(rng.integers(0, 60, n)).astype(np.int64)
        steps.append((ks, vs, ts, i * 60 - 30))
    return steps


class TestCepAdvancePoint:
    """The CEP data plane's fault points at their real sites: a raise
    at ``cep.advance`` (post-dispatch, ingest) crashes mid-batch with
    the pending scatter already on the device queue — crash-restore
    must stay oracle-identical — and a DROPPED device-exchange bucket
    must DIVERGE (the negative control: the harness catches genuine
    loss in the CEP pending plane)."""

    def test_cep_crash_restore_oracle_identical(self, tmp_path):
        # nth=4 = step 4's ingest: past the first checkpoint, so the
        # recovery is a genuine RESTORE (not a cold restart)
        plan = FaultPlan(rules=[
            FaultRule(pattern="cep.advance", nth=4)])
        report = run_crash_restore_verify(
            make_engine=lambda: _CepHarnessEngine("device"),
            make_oracle=lambda: _CepHarnessEngine("host"),
            steps=_cep_steps(), plan=plan, seed=11,
            ckpt_root=str(tmp_path))
        assert report.crashes >= 1 and report.restores >= 1
        assert report.faults_injected.get("cep.advance", 0) >= 1
        assert not report.diverged
        assert report.windows > 0
        _note_reached(report.faults_injected)

    def test_cep_crash_restore_is_deterministic(self, tmp_path):
        plan = FaultPlan(rules=[
            FaultRule(pattern="cep.advance", nth=4)])
        sigs = []
        for i in range(2):
            r = run_crash_restore_verify(
                make_engine=lambda: _CepHarnessEngine("device"),
                make_oracle=lambda: _CepHarnessEngine("host"),
                steps=_cep_steps(), plan=plan, seed=11,
                ckpt_root=str(tmp_path / f"run{i}"))
            sigs.append(r.signature())
        assert sigs[0] == sigs[1]

    def test_dropped_cep_exchange_diverges(self, tmp_path):
        # negative control: one shard's staged CEP columns vanish in
        # flight (re-routed to the padding destination) — the device
        # pending rows keep hits=0 while the host mirror retains the
        # real events, so those matches never fire and the diff MUST
        # catch it
        plan = FaultPlan(rules=[
            FaultRule(pattern="shuffle.device_exchange", nth=2,
                      kind="drop")])
        report = run_crash_restore_verify(
            make_engine=lambda: _CepHarnessEngine("device"),
            make_oracle=lambda: _CepHarnessEngine("host"),
            steps=_cep_steps(), plan=plan, seed=11,
            ckpt_root=str(tmp_path), check=False)
        assert report.faults_injected.get(
            "shuffle.device_exchange", 0) >= 1
        assert report.diverged, (
            "a dropped CEP exchange bucket produced identical output "
            "— the harness cannot catch CEP data-plane loss")
        _note_reached(report.faults_injected)

    def test_advance_injection_at_real_site(self):
        from flink_tpu.core.records import (
            KEY_ID_FIELD,
            RecordBatch,
        )

        eng = _CepHarnessEngine("device").eng
        b = RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.arange(32, dtype=np.int64),
             "v": np.ones(32, dtype=np.float32)},
            timestamps=np.arange(32, dtype=np.int64))
        plan = FaultPlan(rules=[
            FaultRule(pattern="cep.advance", nth=1, kind="delay",
                      delay_ms=1)])
        with chaos.chaos_active(plan, seed=0) as c:
            eng.process_batch(b)
            assert c.faults_injected.get("cep.advance", 0) == 1
            _note_reached(c.faults_injected)
        # the batch survived the delay: pending mirrors hold the rows
        assert sum(len(sh.p_key) for sh in eng._st) == 32


class TestCepMatchFirePoint:
    """``cep.match_fire`` at its real site (after the match-store
    write, before the watermark commits): a crash there lands with
    matches already on the device match planes but the pending rows
    unconsumed — restore + replay must re-fire them identically."""

    def test_crash_at_match_fire_restores_identical(self, tmp_path):
        plan = FaultPlan(rules=[
            FaultRule(pattern="cep.match_fire", nth=3)])
        report = run_crash_restore_verify(
            make_engine=lambda: _CepHarnessEngine("device"),
            make_oracle=lambda: _CepHarnessEngine("host"),
            steps=_cep_steps(seed=29), plan=plan, seed=17,
            ckpt_root=str(tmp_path))
        assert report.crashes >= 1 and report.restores >= 1
        assert report.faults_injected.get("cep.match_fire", 0) >= 1
        assert not report.diverged
        assert report.windows > 0
        _note_reached(report.faults_injected)

    def test_fire_injection_at_real_site(self):
        eng = _CepHarnessEngine("device").eng
        plan = FaultPlan(rules=[
            FaultRule(pattern="cep.match_fire", nth=1, kind="delay",
                      delay_ms=1)])
        with chaos.chaos_active(plan, seed=0) as c:
            eng.on_watermark(10)
            assert c.faults_injected.get("cep.match_fire", 0) == 1
            _note_reached(c.faults_injected)


class TestZZFaultPointReachability:
    """Must run LAST in this file (pytest preserves definition order):
    every fault point of the CANONICAL inventory was injected somewhere
    above."""

    def test_every_fault_point_injected_at_least_once(self):
        from flink_tpu.native import hotcache_available

        known = list(KNOWN_FAULT_POINTS)
        if not hotcache_available():
            # the frontend-pool dispatch site cannot be built without
            # the native shm plane (FrontendPool refuses) — its tests
            # skip above, so the point is unreachable by construction
            known.remove("serving.frontend")
        missing = [p for p in known
                   if REACHED.get(p, 0) < 1]
        assert not missing, (
            f"fault points never injected across the suite: {missing} "
            f"(reached: {REACHED}) — an injection site moved or a "
            "schedule went stale; update chaos.KNOWN_FAULT_POINTS, "
            "tests/test_chaos.py and NOTES_r7.md together")
