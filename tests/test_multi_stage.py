"""Multi-stage DAGs in the stage-parallel executor.

reference parity targets: DefaultExecutionGraph runs ANY DAG at any
per-vertex parallelism (flink-runtime/.../executiongraph/
DefaultExecutionGraph.java, Execution.java:572 deploy()): chains of keyed
exchanges (agg -> re-key -> agg), side outputs across the exchange
(OutputTag routing in OperatorChain), diamonds (one source fanning out to
a windowed branch and a join — Nexmark Q7's exact shape), and the
mesh x stage composition (a keyed subtask opening its engine over a
private sub-mesh)."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _env(stage_parallelism, extra=None):
    conf = {
        "execution.micro-batch.size": 1000,
        "state.slot-table.capacity": 8192,
    }
    if stage_parallelism:
        conf["execution.stage-parallelism"] = stage_parallelism
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def _two_stage_pipeline(env, sink, total=30_000, keys=300,
                        fail_after=None):
    """Stage 1: per-key 1 s window sums; stage 2: re-key the fired rows
    by window_start and sum the sums — a chain of two keyed exchanges."""
    src = DataGenSource(total_records=total, num_keys=keys,
                        events_per_second_of_eventtime=10_000, seed=5)
    ds = env.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
    if fail_after is not None:
        from tests.test_checkpointing import FailingMap

        ds = ds.map(FailingMap(fail_after), name="failmap")
    (ds.key_by("key").window(TumblingEventTimeWindows.of(1000))
       .sum("value")
       .key_by("window_start").window(TumblingEventTimeWindows.of(1000))
       .sum("sum_value")
       .sink_to(sink))


def _stage2_rows(sink):
    return {(r["window_start"], r["window_end"]):
            round(r["sum_sum_value"], 2)
            for r in sink.result().to_rows()}


class TestTwoExchangePipeline:
    def test_plan_has_two_stages(self):
        from flink_tpu.cluster.stage_executor import plan_stages

        env = _env(0)
        sink = CollectSink()
        _two_stage_pipeline(env, sink, total=100, keys=5)
        plan = plan_stages(env.get_stream_graph())
        assert len(plan.stages) == 2
        assert plan.stages[0].out_key_field == "window_start"
        assert plan.stages[0].outputs[0].target_stage == 1
        assert not plan.stages[1].outputs
        assert plan.stages[1].chain[-1].kind == "sink"

    def test_matches_single_slot(self):
        env0 = _env(0)
        s0 = CollectSink()
        _two_stage_pipeline(env0, s0)
        env0.execute("single")
        expected = _stage2_rows(s0)

        env = _env(4, {"execution.source-parallelism": 2})
        sink = CollectSink()
        _two_stage_pipeline(env, sink)
        result = env.execute("staged")
        assert result.metrics["keyed_stages"] == 2
        assert len(result.metrics["per_stage_records_in"]) == 2
        got = _stage2_rows(sink)
        assert set(got) == set(expected)
        for k in expected:
            assert got[k] == pytest.approx(expected[k], rel=1e-4), k

    def test_crash_restore_matches_clean_run(self, tmp_path):
        ckpt = str(tmp_path / "ckpts")
        env0 = _env(0)
        s0 = CollectSink()
        _two_stage_pipeline(env0, s0)
        env0.execute("clean")
        expected = _stage2_rows(s0)

        conf = {"state.checkpoints.dir": ckpt,
                "execution.checkpointing.every-n-source-batches": 5}
        env1 = _env(4, conf)
        s1 = CollectSink()
        _two_stage_pipeline(env1, s1, fail_after=20_000)
        with pytest.raises(RuntimeError, match="injected failure"):
            env1.execute("crashing")
        from flink_tpu.checkpoint.storage import CheckpointStorage

        assert CheckpointStorage(ckpt).latest_checkpoint_id() is not None

        env2 = _env(4, conf)
        s2 = CollectSink()
        src = DataGenSource(total_records=30_000, num_keys=300,
                            events_per_second_of_eventtime=10_000, seed=5)
        ds = env2.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        ds = ds.map(lambda b: b, name="failmap")
        (ds.key_by("key").window(TumblingEventTimeWindows.of(1000))
           .sum("value")
           .key_by("window_start")
           .window(TumblingEventTimeWindows.of(1000))
           .sum("sum_value").sink_to(s2))
        env2.execute("restored", restore_from=ckpt)
        got = _stage2_rows(s1)
        got.update(_stage2_rows(s2))
        assert set(got) == set(expected)
        for k in expected:
            assert got[k] == pytest.approx(expected[k], rel=1e-3), k


class TestSideOutputAcrossExchange:
    def test_side_output_from_keyed_stage(self):
        """A process fn chained after the keyed window splits its output:
        main rows to one sink, tagged rows to a side sink — both running
        inside the keyed subtasks (OutputTag routing across the
        exchange)."""
        from flink_tpu.runtime.process import OutputTag, ProcessFunction

        BIG = OutputTag("big")

        class SplitBig(ProcessFunction):
            def process_batch(self, batch, ctx):
                big = batch["sum_value"] > 50.0
                ctx.output(BIG, batch.filter(big))
                ctx.collect(batch.filter(~big))

        def build(env, main_sink, side_sink):
            src = DataGenSource(total_records=20_000, num_keys=100,
                                events_per_second_of_eventtime=10_000,
                                seed=5)
            m = (env.from_source(
                    src,
                    WatermarkStrategy.for_bounded_out_of_orderness(0))
                 .key_by("key")
                 .window(TumblingEventTimeWindows.of(1000))
                 .sum("value")
                 .process(SplitBig()))
            m.sink_to(main_sink)
            m.get_side_output(BIG).sink_to(side_sink)

        env0 = _env(0)
        m0, s0 = CollectSink(), CollectSink()
        build(env0, m0, s0)
        env0.execute("single")

        env = _env(4, {"execution.source-parallelism": 2})
        m1, s1 = CollectSink(), CollectSink()
        build(env, m1, s1)
        env.execute("staged")

        def rows(sink):
            return {(int(r["key"]), int(r["window_start"])):
                    float(r["sum_value"])
                    for r in sink.result().to_rows()}

        for got, want in ((rows(m1), rows(m0)), (rows(s1), rows(s0))):
            assert set(got) == set(want)
            assert len(got) > 0
            for k in want:
                assert got[k] == pytest.approx(want[k], rel=1e-4), k


class TestQ7Diamond:
    """build_q7 itself (not a stand-in): one source fans out to the
    const-key windowed MAX branch AND the window join — a diamond with a
    join fed by a source branch and an upstream keyed stage."""

    def _rows(self, sink):
        return sorted((int(r["window_end"]), int(r["auction"]),
                       round(float(r["price"]), 3))
                      for r in sink.result().to_rows())

    def test_q7_stage_parallel_matches_single_slot_and_oracle(self):
        from flink_tpu.benchmarks.nexmark import (
            BidSource,
            build_q7,
            oracle_q7,
        )

        def run(conf):
            env = StreamExecutionEnvironment(Configuration(conf))
            sink = CollectSink()
            src = BidSource(total_records=30_000, num_auctions=50,
                            events_per_second_of_eventtime=10_000)
            build_q7(env, src, size_ms=2_000).sink_to(sink)
            env.execute("q7")
            return sink

        base = {"execution.micro-batch.size": 1000}
        single = self._rows(run(base))
        staged = self._rows(run({**base,
                                 "execution.stage-parallelism": 4,
                                 "execution.source-parallelism": 2}))
        assert staged == single
        assert len(staged) > 0

        # oracle cross-check on the raw stream
        src = BidSource(total_records=30_000, num_auctions=50,
                        events_per_second_of_eventtime=10_000)
        src.open(0, 1)
        bids = []
        while True:
            b = src.poll_batch(10_000)
            if b is None:
                break
            bids += list(zip(b.columns["auction"].tolist(),
                             b.columns["bidder"].tolist(),
                             b.columns["price"].tolist(),
                             b.timestamps.tolist()))
        oracle = oracle_q7(bids, 2_000)
        got_by_window = {}
        for we, auction, price in staged:
            got_by_window.setdefault(we, set()).add(auction)
        # only COMPLETE windows fire (the stream ends mid-window)
        for we in got_by_window:
            price, pairs = oracle[we]
            assert got_by_window[we] == {a for a, _ in pairs}, we


class TestMeshByStage:
    """execution.stage-mesh-devices: each keyed subtask opens its window
    engine over a private sub-mesh, sharding WITHIN its key-group range
    (subtask expansion x SPMD — the composition the executor docstring
    promises)."""

    def _pipeline(self, env, sink):
        src = DataGenSource(total_records=30_000, num_keys=300,
                            events_per_second_of_eventtime=10_000, seed=5)
        (env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
           .key_by("key").window(TumblingEventTimeWindows.of(1000))
           .sum("value").sink_to(sink))

    def _rows(self, sink):
        return {(r["key"], r["window_start"]): round(r["sum_value"], 2)
                for r in sink.result().to_rows()}

    def test_two_subtasks_by_four_devices_matches_single_slot(self):
        env0 = _env(0)
        s0 = CollectSink()
        self._pipeline(env0, s0)
        env0.execute("single")
        expected = self._rows(s0)

        env = _env(2, {"execution.stage-mesh-devices": 4})
        sink = CollectSink()
        self._pipeline(env, sink)
        env.execute("mesh-stage")
        got = self._rows(sink)
        assert set(got) == set(expected)
        for k in expected:
            assert got[k] == pytest.approx(expected[k], rel=1e-3), k

    def test_crash_restore(self, tmp_path):
        ckpt = str(tmp_path / "ckpts")
        env0 = _env(0)
        s0 = CollectSink()
        self._pipeline(env0, s0)
        env0.execute("clean")
        expected = self._rows(s0)

        conf = {"execution.stage-mesh-devices": 4,
                "state.checkpoints.dir": ckpt,
                "execution.checkpointing.every-n-source-batches": 5}
        env1 = _env(2, conf)
        s1 = CollectSink()
        src = DataGenSource(total_records=30_000, num_keys=300,
                            events_per_second_of_eventtime=10_000, seed=5)
        from tests.test_checkpointing import FailingMap

        (env1.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
           .map(FailingMap(20_000), name="failmap")
           .key_by("key").window(TumblingEventTimeWindows.of(1000))
           .sum("value").sink_to(s1))
        with pytest.raises(RuntimeError, match="injected failure"):
            env1.execute("crashing")

        env2 = _env(2, conf)
        s2 = CollectSink()
        src2 = DataGenSource(total_records=30_000, num_keys=300,
                             events_per_second_of_eventtime=10_000, seed=5)
        (env2.from_source(
            src2, WatermarkStrategy.for_bounded_out_of_orderness(0))
           .map(lambda b: b, name="failmap")
           .key_by("key").window(TumblingEventTimeWindows.of(1000))
           .sum("value").sink_to(s2))
        env2.execute("restored", restore_from=ckpt)
        got = self._rows(s1)
        got.update(self._rows(s2))
        assert set(got) == set(expected)
        for k in expected:
            assert got[k] == pytest.approx(expected[k], rel=1e-3), k


class TestBidSourceSplits:
    def test_parallel_splits_union_to_single_stream(self):
        from flink_tpu.benchmarks.nexmark import BidSource

        def collect(par):
            rows = []
            for i in range(par):
                s = BidSource(total_records=10_000, num_auctions=50,
                              events_per_second_of_eventtime=10_000)
                s.open(i, par)
                while True:
                    b = s.poll_batch(3_000)
                    if b is None:
                        break
                    rows += list(zip(
                        b.columns["auction"].tolist(),
                        np.round(b.columns["price"], 4).tolist(),
                        b.timestamps.tolist()))
            return sorted(rows)

        assert collect(1) == collect(2) == collect(4)
