"""Partial failover: shard-granular checkpoints, the device watchdog,
and bounded replay — lose one shard, not the job.

Covers (1) the DeviceWatchdog policy (deadline misses, declare-dead at
batch boundaries, quarantine/rebind), (2) ShardedCheckpointStorage
(per-range units, torn-unit fallback to an older checkpoint's unit,
torn-aware retention), (3) the engines' shard-loss surgery
(``lose_shard`` + ``restore_key_groups`` + metadata merge), and (4) the
end-to-end ``run_shard_loss_verify`` claim: a ``device.lost`` fault
killing 1 of N shards mid-stream (paged spill armed, forced eviction)
restores ONLY that shard's key groups, replays ONLY that range's
records (bounded by ~events/shards), and commits output bit-identical
to the fault-free single-device oracle — seed-deterministic.

Satellites pinned here too: torn-aware flat-checkpoint retention,
the global retry budget, restore-path metrics through the job metric
tree, graceful native-plane degradation, and the arbiter's dead-shard
budget.
"""

import os

import numpy as np
import pytest

from flink_tpu.chaos import injection as chaos
from flink_tpu.chaos.harness import run_shard_loss_verify
from flink_tpu.chaos.injection import (
    FaultPlan,
    FaultRule,
    RetryBudgetExhaustedError,
)
from flink_tpu.runtime.watchdog import (
    DeviceWatchdog,
    MeshStalledError,
    ShardFailedError,
)

GAP = 100


def _steps(n_steps=8, per_step=800, num_keys=3000, seed=17):
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        out.append((keys, vals, ts, (s - 1) * 80))
    return out


def _mk_session_engine(shards=4, slots=1024):
    from flink_tpu.parallel.mesh import make_mesh
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    return MeshSessionEngine(
        GAP, SumAggregate("v"), make_mesh(shards),
        capacity_per_shard=1 << 14, max_device_slots=slots,
        max_dispatch_ahead=2)


def _mk_session_oracle():
    from flink_tpu.windowing.aggregates import SumAggregate
    from flink_tpu.windowing.sessions import SessionWindower

    return SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)


# ---------------------------------------------------------------- watchdog


class TestDeviceWatchdog:
    def test_in_deadline_sections_heartbeat_and_reset_misses(self):
        t = [0.0]
        wd = DeviceWatchdog(2, deadline_ms=10, max_misses=2,
                            clock=lambda: t[0])
        with wd.section("op", shard=0):
            t[0] += 0.005  # 5 ms < 10 ms deadline
        assert wd.deadline_misses == 0
        assert wd.sections_timed == 1
        wd.boundary_probe()  # no raise

    def test_miss_streak_declares_dead_at_boundary_only(self):
        t = [0.0]
        wd = DeviceWatchdog(2, deadline_ms=10, max_misses=2,
                            clock=lambda: t[0])
        for _ in range(2):
            with wd.section("op", shard=1):
                t[0] += 0.05  # 50 ms > deadline
        # misses recorded mid-batch, never raised there
        assert wd.deadline_misses == 2
        with pytest.raises(ShardFailedError) as ei:
            wd.boundary_probe()
        assert ei.value.shard == 1
        assert wd.quarantined == {1}
        assert wd.available(2) == 1

    def test_successful_section_resets_the_streak(self):
        t = [0.0]
        wd = DeviceWatchdog(1, deadline_ms=10, max_misses=2,
                            clock=lambda: t[0])
        with wd.section("op", shard=0):
            t[0] += 0.05
        with wd.section("op", shard=0):
            t[0] += 0.001  # healthy: streak resets
        with wd.section("op", shard=0):
            t[0] += 0.05
        wd.boundary_probe()  # 1 < max_misses: alive
        assert not wd.quarantined

    def test_whole_mesh_miss_streak_is_a_mesh_stall_not_shard_0(self):
        # SPMD sections charge every shard: a uniform streak carries NO
        # shard attribution — quarantining shard 0 would evacuate a
        # healthy device; the honest escalation is a whole-job failure
        t = [0.0]
        wd = DeviceWatchdog(3, deadline_ms=10, max_misses=1,
                            clock=lambda: t[0])
        with wd.section("op"):  # shard=-1
            t[0] += 0.05
        with pytest.raises(MeshStalledError):
            wd.boundary_probe()
        assert not wd.quarantined  # nobody was falsely declared dead

    def test_attributed_subset_miss_still_declares_that_shard(self):
        t = [0.0]
        wd = DeviceWatchdog(3, deadline_ms=10, max_misses=1,
                            clock=lambda: t[0])
        with wd.section("op", shard=2):
            t[0] += 0.05
        with pytest.raises(ShardFailedError) as ei:
            wd.boundary_probe()
        assert ei.value.shard == 2 and wd.quarantined == {2}

    def test_quarantined_device_ids_dedupe_across_watchdogs(self):
        t = [0.0]
        wd_a = DeviceWatchdog(2, deadline_ms=10, max_misses=1,
                              clock=lambda: t[0], device_ids=[5, 9])
        wd_b = DeviceWatchdog(2, deadline_ms=10, max_misses=1,
                              clock=lambda: t[0], device_ids=[5, 9])
        for wd in (wd_a, wd_b):
            with wd.section("op", shard=1):
                t[0] += 0.05
            with pytest.raises(ShardFailedError):
                wd.boundary_probe()
        # both tenants quarantined the SAME physical device
        assert wd_a.quarantined_devices | wd_b.quarantined_devices \
            == {9}

    def test_rebind_keeps_cumulative_counters(self):
        t = [0.0]
        wd = DeviceWatchdog(4, deadline_ms=10, max_misses=1,
                            clock=lambda: t[0])
        with wd.section("op", shard=2):
            t[0] += 0.05
        with pytest.raises(ShardFailedError):
            wd.boundary_probe()
        assert wd.declared_dead == 1
        wd.rebind(3)
        assert wd.num_shards == 3 and not wd.quarantined
        assert wd.declared_dead == 1  # history survives

    def test_metrics_registration(self):
        from flink_tpu.metrics import MetricRegistry

        registry = MetricRegistry()
        g = registry.root_group("job", "j")
        wd = DeviceWatchdog(2, deadline_ms=0)
        wd.register_metrics(g)
        snap = registry.snapshot()
        assert snap["job.j.watchdog.shards_quarantined"] == 0
        assert "job.j.watchdog.heartbeat_age_s" in snap


# ---------------------------------------------------- sharded checkpoints


class TestShardedCheckpointStorage:
    def _units(self, val):
        return {
            (0, 63): {"table": {"x": np.asarray([val])},
                      "next_sid": 5},
            (64, 127): {"table": {"x": np.asarray([val + 1])},
                        "next_sid": 5},
        }

    def test_roundtrip_units_and_positions(self, tmp_path):
        from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage

        st = ShardedCheckpointStorage(str(tmp_path))
        st.write_checkpoint(1, "j", self._units(10),
                            positions={(0, 63): 2, (64, 127): 2})
        assert st.latest_checkpoint_id() == 1
        assert st.unit_ranges(1) == [(0, 63), (64, 127)]
        state, pos = st.read_unit(1, (0, 63))
        assert pos == 2 and int(state["table"]["x"][0]) == 10

    def test_torn_unit_falls_back_to_that_ranges_older_unit(
            self, tmp_path):
        from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage

        st = ShardedCheckpointStorage(str(tmp_path))
        st.write_checkpoint(1, "j", self._units(10),
                            positions={(0, 63): 2, (64, 127): 2})
        st.write_checkpoint(2, "j", self._units(20),
                            positions={(0, 63): 4, (64, 127): 4})
        # tear chk-2's (0, 63) unit: flip a byte in a payload file
        unit = os.path.join(str(tmp_path), "chk-2", "shard-0-63")
        victim = next(os.path.join(unit, n) for n in os.listdir(unit)
                      if n != "manifest.json")
        with open(victim, "r+b") as f:
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        # the torn RANGE falls back to chk-1; the sibling stays on chk-2
        cid, states, pos = st.latest_units_for_groups(range(0, 64))
        assert cid == 1 and pos == 2
        assert int(states[0]["table"]["x"][0]) == 10
        cid2, states2, pos2 = st.latest_units_for_groups(range(64, 128))
        assert cid2 == 2 and pos2 == 4
        newest, units, skipped = st.read_all_units_with_fallback()
        assert newest == 2 and skipped == 1
        by_range = {r: (s, p) for r, s, p in units}
        assert by_range[(0, 63)][1] == 2      # fell back
        assert by_range[(64, 127)][1] == 4    # newest

    def test_retention_never_strands_below_a_torn_newest(self, tmp_path):
        from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage

        st = ShardedCheckpointStorage(str(tmp_path))
        for cid in (1, 2, 3):
            st.write_checkpoint(cid, "j", self._units(cid * 10),
                                positions={(0, 63): cid * 2,
                                           (64, 127): cid * 2})
        # tear the NEWEST checkpoint's unit
        unit = os.path.join(str(tmp_path), "chk-3", "shard-0-63")
        victim = next(os.path.join(unit, n) for n in os.listdir(unit)
                      if n != "manifest.json")
        with open(victim, "r+b") as f:
            f.truncate(4)
        st.retain(1)
        # chk-2 (the newest that VERIFIES) must survive; chk-1 may go
        assert (3 in st.checkpoint_ids()
                and 2 in st.checkpoint_ids())
        assert st.latest_units_for_groups(range(0, 64)) is not None


class TestFlatRetentionTornAware:
    def test_torn_newest_never_strands_zero_restorable(self, tmp_path):
        from flink_tpu.checkpoint.storage import CheckpointStorage

        st = CheckpointStorage(str(tmp_path))
        for cid in (1, 2, 3):
            st.write_checkpoint(cid, "j",
                                {"op": {"x": np.asarray([cid])}})
        # tear chk-3 (truncate a payload file under its manifest CRC)
        d = os.path.join(str(tmp_path), "chk-3")
        victim = next(os.path.join(d, n) for n in os.listdir(d)
                      if n != "manifest.json")
        with open(victim, "r+b") as f:
            f.truncate(4)
        st.retain(1)
        # the fallback chain below the torn newest survives: chk-2 is
        # the newest COMPLETE checkpoint and must not be GC'd
        assert st.latest_checkpoint_id(verify=True) == 2
        assert os.path.isdir(os.path.join(str(tmp_path), "chk-2"))

    def test_delta_anchor_with_corrupt_base_never_strands(
            self, tmp_path):
        from flink_tpu.checkpoint.storage import CheckpointStorage

        st = CheckpointStorage(str(tmp_path))
        st.write_checkpoint(1, "j", {"op": {"x": np.asarray([1])}})
        st.write_checkpoint(2, "j", {"op": {"x": np.asarray([2])}})
        st.write_checkpoint(
            3, "j", {"op": {"x": np.asarray([3])}},
            extra={"incremental": True, "base": 2})
        # corrupt the delta's BASE: chk-3 alone verifies, but the
        # restorable artifact (its chain) does not — anchoring it would
        # let GC delete chk-1, the only complete snapshot left
        d = os.path.join(str(tmp_path), "chk-2")
        victim = next(os.path.join(d, n) for n in os.listdir(d)
                      if n != "manifest.json")
        with open(victim, "r+b") as f:
            b = f.read(1)
            f.seek(0)
            f.write(bytes([b[0] ^ 0xFF]))
        st.retain(1)
        assert os.path.isdir(os.path.join(str(tmp_path), "chk-1"))
        assert st.latest_checkpoint_id(verify=True) in (1, 3)

    def test_healthy_retention_still_prunes(self, tmp_path):
        from flink_tpu.checkpoint.storage import CheckpointStorage

        st = CheckpointStorage(str(tmp_path))
        for cid in (1, 2, 3):
            st.write_checkpoint(cid, "j",
                                {"op": {"x": np.asarray([cid])}})
        st.retain(2)
        assert not os.path.isdir(os.path.join(str(tmp_path), "chk-1"))
        assert st.latest_checkpoint_id(verify=True) == 3


# --------------------------------------------------- engine shard surgery


class TestEngineShardSurgery:
    def test_shard_key_groups_invert_the_routing_formula(self):
        from flink_tpu.parallel.shuffle import shard_records

        eng = _mk_session_engine(shards=4)
        ranges = eng.shard_key_groups()
        assert len(ranges) == 4
        assert ranges[0][0] == 0 and ranges[-1][1] == \
            eng.max_parallelism - 1
        keys = np.arange(5000, dtype=np.int64)
        shards = shard_records(keys, eng.P, eng.max_parallelism,
                               eng.key_group_range)
        from flink_tpu.state.keygroups import assign_key_groups

        kg = assign_key_groups(keys, eng.max_parallelism)
        for p, (g0, g1) in enumerate(ranges):
            sel = shards == p
            assert kg[sel].min() >= g0 and kg[sel].max() <= g1

    def test_lose_shard_keeps_survivors_and_drops_the_range(self):
        from tests.test_sessions import keyed_batch

        eng = _mk_session_engine(shards=4)
        keys = np.arange(0, 2000, dtype=np.int64)
        eng.process_batch(keyed_batch(
            keys, np.ones(len(keys), dtype=np.float32),
            np.zeros(len(keys), dtype=np.int64)))
        g0, g1 = eng.lose_shard(1)
        assert eng.P == 3
        from flink_tpu.state.keygroups import assign_key_groups

        # the dead range's sessions are gone from the metadata; the
        # survivors' sessions are intact
        live_groups = {
            int(g) for k in eng.meta.sessions.keys()
            for g in assign_key_groups(np.asarray([k]),
                                       eng.max_parallelism)}
        assert not any(g0 <= g <= g1 for g in live_groups)
        assert live_groups  # survivors kept
        assert eng.last_shard_loss["dead_shard"] == 1

    def test_snapshot_sharded_units_union_to_full_snapshot(self):
        from tests.test_sessions import keyed_batch

        eng = _mk_session_engine(shards=4)
        keys = np.arange(0, 2000, dtype=np.int64)
        eng.process_batch(keyed_batch(
            keys, np.ones(len(keys), dtype=np.float32),
            np.zeros(len(keys), dtype=np.int64)))
        full = eng.snapshot(mode="savepoint")
        units = eng.snapshot_sharded(mode="savepoint")
        assert set(units) == set(
            (g0, g1) for g0, g1 in eng.shard_key_groups())
        merged = eng.merge_unit_snapshots(list(units.values()))
        # same rows (order may differ per unit split): compare sorted
        def rows(t):
            return sorted(zip(np.asarray(t["key_id"]).tolist(),
                              np.asarray(t["namespace"]).tolist(),
                              np.asarray(t["leaf_0"]).tolist()))

        assert rows(merged["table"]) == rows(full["table"])
        assert merged["next_sid"] == full["next_sid"]
        assert len(merged["sessions"]) == len(full["sessions"])


# ---------------------------------------------------- end-to-end failover


class TestRunShardLossVerify:
    def _plan_loss_mid_stream(self, shard=1, nth=11):
        return FaultPlan(rules=[
            FaultRule(pattern="device.lost", nth=nth,
                      where={"shard": shard})])

    def test_session_engine_partial_failover_oracle_identical(
            self, tmp_path):
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(),
            self._plan_loss_mid_stream(), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.shards_lost == 1
        assert report.shard_restores == 1
        # bounded replay: only the dead range's records, only since its
        # unit's position — about events/(shards * steps) per replayed
        # step, and never the whole stream
        assert 0 < report.records_replayed <= report.events // 4
        assert report.shard_loss_recovery_ms > 0

    def test_forced_eviction_stays_on_the_path(self, tmp_path):
        # the paged spill must genuinely engage (the acceptance shape)
        holder = {}

        def mk():
            holder["eng"] = _mk_session_engine(slots=1024)
            return holder["eng"]

        report = run_shard_loss_verify(
            mk, _mk_session_oracle, _steps(num_keys=6000,
                                           per_step=1500),
            self._plan_loss_mid_stream(), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert holder["eng"].spill_counters()["rows_evicted"] > 0

    def test_seed_deterministic_signature(self, tmp_path):
        sigs = []
        for i in range(2):
            r = run_shard_loss_verify(
                _mk_session_engine, _mk_session_oracle, _steps(),
                self._plan_loss_mid_stream(), seed=7,
                ckpt_root=str(tmp_path / f"c{i}"), checkpoint_every=2)
            sigs.append(r.signature())
        assert sigs[0] == sigs[1]
        assert sigs[0]["shards_lost"] == 1

    def test_torn_unit_falls_back_and_replays_further(self, tmp_path):
        # chk-3 (pos 6) shard-1 unit torn; shard 1 dies after it: the
        # range restores from chk-2@pos4 and replays [4, ...) — more
        # replay than the healthy case, still only ITS range
        plan = FaultPlan(rules=[
            FaultRule(pattern="checkpoint.write.torn", nth=10,
                      kind="drop"),
            FaultRule(pattern="device.lost", nth=15,
                      where={"shard": 1})])
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(), plan,
            seed=7, ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.shard_restores == 1
        assert report.records_replayed > 0

    def test_crash_takes_whole_job_path_with_unit_fallback(
            self, tmp_path):
        # a corrupt unit in the newest checkpoint + an engine crash:
        # whole-job restore assembles mixed-age units and gates the
        # catch-up replay; output stays oracle-identical
        plan = FaultPlan(rules=[
            FaultRule(pattern="checkpoint.write.torn", nth=10,
                      kind="corrupt"),
            FaultRule(pattern="mesh.session_fire", nth=5,
                      kind="raise")])
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(), plan,
            seed=7, ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.crashes == 1 and report.restores == 1
        assert report.corrupt_checkpoints_skipped == 1

    def test_loss_before_first_checkpoint_replays_cold(self, tmp_path):
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(),
            self._plan_loss_mid_stream(nth=1), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.shards_lost == 1
        assert report.shard_restores == 0  # nothing checkpointed yet

    def test_window_engine_partial_failover(self, tmp_path):
        # the protocol is engine-agnostic: tumbling mesh windows lose a
        # shard mid-stream; the book merge re-opens the windows the
        # restored range must re-fire
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows
        from flink_tpu.windowing.windower import SliceSharedWindower

        def mk_engine():
            return MeshWindowEngine(
                TumblingEventTimeWindows.of(100), SumAggregate("v"),
                make_mesh(4), capacity_per_shard=1 << 14)

        def mk_oracle():
            return SliceSharedWindower(
                TumblingEventTimeWindows.of(100), SumAggregate("v"),
                capacity=1 << 15)

        report = run_shard_loss_verify(
            mk_engine, mk_oracle, _steps(),
            self._plan_loss_mid_stream(), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.shards_lost == 1 and report.shard_restores == 1
        assert 0 < report.records_replayed <= report.events // 4


# ------------------------------------------------------ satellite: budget


class TestGlobalRetryBudget:
    def test_budget_exhaustion_escalates_to_real_failure(self):
        plan = FaultPlan(
            rules=[FaultRule(pattern="spill.page_reload", every=1,
                             kind="raise", recoverable=True,
                             max_injections=0)],
            retry_max_attempts=100, retry_budget_total=3)
        calls = {"n": 0}

        def attempt():
            calls["n"] += 1
            chaos.fault_point("spill.page_reload", page=1)
            return "ok"

        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises(RetryBudgetExhaustedError):
                chaos.run_recoverable("spill.page_reload", attempt)
            assert c.retries == 3
            assert c.budget_exhausted == 1
            assert c.counters()["retry_budget_exhausted"] == 1

    def test_unlimited_budget_keeps_per_site_semantics(self):
        plan = FaultPlan(rules=[
            FaultRule(pattern="x", nth=1, kind="raise",
                      recoverable=True)])
        with chaos.chaos_active(plan, seed=0) as c:
            out = chaos.run_recoverable(
                "x", lambda: chaos.fault_point("x") or 41)
            assert out == 41
            assert c.retries == 1 and c.recoveries == 1
            assert c.budget_exhausted == 0

    def test_budget_counts_across_sites(self):
        plan = FaultPlan(
            rules=[FaultRule(pattern="*", every=1, kind="raise",
                             recoverable=True, max_injections=0)],
            retry_budget_total=2)
        with chaos.chaos_active(plan, seed=0) as c:
            with pytest.raises((RetryBudgetExhaustedError,
                                chaos.InjectedFault)):
                chaos.run_recoverable(
                    "a.one", lambda: chaos.fault_point("a.one"))
                chaos.run_recoverable(
                    "a.two", lambda: chaos.fault_point("a.two"))
            assert c.budget_exhausted >= 0  # escalation is budgeted
            assert c.retries <= 2

    def test_budget_gauge_in_chaos_metric_group(self):
        from flink_tpu.metrics import MetricRegistry

        plan = FaultPlan(rules=[
            FaultRule(pattern="spill.page_reload", nth=1)],
            retry_budget_total=1)
        registry = MetricRegistry()
        g = registry.root_group("job", "j")
        with chaos.chaos_active(plan, seed=0):
            chaos.register_chaos_metrics(g)
            snap = registry.snapshot()
            assert snap["job.j.chaos.retry_budget_exhausted"] == 0


# ----------------------------------------------- satellite: restore metrics


class TestRestorePathMetrics:
    def test_harness_counters_surface_through_metric_tree(
            self, tmp_path):
        from flink_tpu.metrics import MetricRegistry

        registry = MetricRegistry()
        group = registry.root_group("job", "shard-loss")
        plan = FaultPlan(rules=[
            FaultRule(pattern="device.lost", nth=11,
                      where={"shard": 1})])
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(), plan,
            seed=7, ckpt_root=str(tmp_path / "c"), checkpoint_every=2,
            metric_group=group)
        snap = registry.snapshot()
        assert snap["job.shard-loss.chaos.shard_restores"] == \
            report.shard_restores == 1
        assert snap["job.shard-loss.chaos.records_replayed"] == \
            report.records_replayed > 0
        assert snap["job.shard-loss.chaos.restores"] == report.restores
        assert snap["job.shard-loss.chaos.corrupt_checkpoints_skipped"] \
            == report.corrupt_checkpoints_skipped

    def test_crash_restore_verify_also_registers(self, tmp_path):
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.metrics import MetricRegistry

        registry = MetricRegistry()
        group = registry.root_group("job", "crv")
        plan = FaultPlan(rules=[
            FaultRule(pattern="mesh.session_fire", nth=2)])
        report = run_crash_restore_verify(
            lambda: _mk_session_engine(shards=2), _mk_session_oracle,
            _steps(n_steps=6, per_step=300, num_keys=500), plan,
            seed=3, ckpt_root=str(tmp_path / "c"), checkpoint_every=2,
            metric_group=group)
        snap = registry.snapshot()
        assert snap["job.crv.chaos.restores"] == report.restores >= 1


# ------------------------------------------- satellite: native degradation


class TestNativePlaneDegradation:
    def test_build_failure_falls_back_loudly_with_identical_output(
            self, monkeypatch):
        import flink_tpu.native as native
        import flink_tpu.windowing.session_meta as sm
        from flink_tpu.windowing.session_meta import (
            SessionIntervalSet,
            make_session_meta,
        )

        from tests.test_sessions import keyed_batch

        # baseline: an engine on whatever plane the container selects
        eng_ref = _mk_session_engine(shards=2)
        # forced build failure: the loader reports unavailable while
        # native was NOT explicitly disabled
        native.reset_fallbacks_for_testing()
        monkeypatch.setattr(native, "sessions_available", lambda: False)
        monkeypatch.setattr(native, "native_disabled", lambda: False)
        monkeypatch.delenv("FLINK_TPU_NATIVE_SESSIONS", raising=False)
        with pytest.warns(RuntimeWarning, match="degraded to Python"):
            meta = make_session_meta(GAP, 0)
        assert type(meta) is SessionIntervalSet
        assert native.native_fallbacks() >= 1
        # output identity: the degraded engine's fires equal the
        # reference engine's row for row
        eng_fb = _mk_session_engine(shards=2)
        assert type(eng_fb.meta) is SessionIntervalSet
        keys = np.arange(0, 400, dtype=np.int64)
        vals = np.ones(400, dtype=np.float32)
        ts = np.arange(400, dtype=np.int64) % 50
        for eng in (eng_ref, eng_fb):
            eng.process_batch(keyed_batch(keys, vals, ts))
        fired_ref = eng_ref.on_watermark(1 << 60)
        fired_fb = eng_fb.on_watermark(1 << 60)
        rows_ref = sorted(tuple(sorted(r.items()))
                          for b in fired_ref for r in b.to_rows())
        rows_fb = sorted(tuple(sorted(r.items()))
                         for b in fired_fb for r in b.to_rows())
        assert rows_ref == rows_fb
        native.reset_fallbacks_for_testing()

    def test_runtime_sweep_failure_degrades_once_not_crash(self):
        from flink_tpu.windowing.session_meta import (
            NativePlaneError,
            SessionIntervalSet,
        )

        from tests.test_sessions import keyed_batch

        import flink_tpu.native as native

        native.reset_fallbacks_for_testing()
        eng = _mk_session_engine(shards=2)
        oracle = _mk_session_oracle()
        # wrap the CURRENT meta so its next absorb raises like a failed
        # C sweep AFTER partially registering the batch's sessions —
        # the engine must degrade to the Python plane and finish the
        # batch, not crash it
        inner = eng.meta
        real_absorb = inner.absorb_batch_ex
        state = {"armed": True}

        def failing_absorb(keys, ts, want_fresh=True):
            if state["armed"]:
                state["armed"] = False
                real_absorb(keys[: len(keys) // 2],
                            ts[: len(ts) // 2], want_fresh=want_fresh)
                raise NativePlaneError("injected sweep failure")
            return real_absorb(keys, ts, want_fresh=want_fresh)

        inner.absorb_batch_ex = failing_absorb
        steps = _steps(n_steps=4, per_step=300, num_keys=500)
        eng_fired = []
        with pytest.warns(RuntimeWarning, match="degraded"):
            for keys, vals, ts, wm in steps:
                eng.process_batch(keyed_batch(keys, vals, ts))
                eng_fired.extend(eng.on_watermark(int(wm)))
        assert type(eng.meta) is SessionIntervalSet
        assert native.native_fallbacks() >= 1
        # output correctness: the fired windows equal the oracle's
        from flink_tpu.core.records import KEY_ID_FIELD
        from flink_tpu.windowing.windower import (
            WINDOW_END_FIELD,
            WINDOW_START_FIELD,
        )

        def fold(fired, out):
            for b in fired:
                for r in b.to_rows():
                    out[(int(r[KEY_ID_FIELD]),
                         int(r[WINDOW_START_FIELD]),
                         int(r[WINDOW_END_FIELD]))] = float(r["sum_v"])

        expected = {}
        got = {}
        for keys, vals, ts, wm in steps:
            oracle.process_batch(keyed_batch(keys, vals, ts))
            fold(oracle.on_watermark(int(wm)), expected)
        fold(oracle.on_watermark(1 << 60), expected)
        fold(eng_fired, got)
        fold(eng.on_watermark(1 << 60), got)
        assert set(got) == set(expected)
        for k in expected:
            assert got[k] == pytest.approx(expected[k], rel=1e-4)
        native.reset_fallbacks_for_testing()


# --------------------------------------------- satellite: arbiter budget


class TestArbiterDeadShardBudget:
    def test_dead_shards_shrink_the_divided_budget(self):
        from flink_tpu.tenancy.arbiter import JobDemand, ShardArbiter

        demands = [
            JobDemand(job="a", current_shards=4, backlog=1000),
            JobDemand(job="b", current_shards=4, backlog=1000),
        ]
        arb = ShardArbiter(total_shards=8, cooldown_ticks=0)
        healthy = arb.decide(demands)
        assert sum(healthy.values()) == 8
        arb2 = ShardArbiter(total_shards=8, cooldown_ticks=0)
        degraded = arb2.decide(demands, dead_shards=2)
        assert sum(degraded.values()) <= 6


# --------------------------------------------------- executor integration


class TestExecutorWatchdogWiring:
    def test_watchdog_enabled_attaches_and_registers_gauges(self):
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        config = Configuration({
            "watchdog.enabled": True,
            "watchdog.deadline-ms": 10_000,
            "parallelism.default": 2,
        })
        env = StreamExecutionEnvironment(config)
        sink = CollectSink()
        rows = [{"k": i % 7, "v": 1, "ts": i * 10} for i in range(300)]
        env.from_collection(rows, timestamp_field="ts") \
            .key_by("k").window(TumblingEventTimeWindows.of(500)) \
            .sum("v").sink_to(sink)
        result = env.execute("wd-job")
        snap = result.registry.snapshot()
        assert "job.wd-job.watchdog.shards_quarantined" in snap
        assert snap["job.wd-job.watchdog.sections_timed"] > 0
        assert snap["job.wd-job.watchdog.deadline_misses"] == 0
        assert sink.batches  # the job genuinely ran on the mesh path
