"""Mesh-sharded execution tests on the 8-virtual-device CPU mesh — the
MiniCluster analog for multi-chip behavior (SURVEY.md §4 tier 3)."""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.shuffle import (
    bucket_by_shard,
    make_all_to_all_repartition,
    make_global_combine,
    shard_records,
)
from flink_tpu.parallel.sharded_windower import MeshWindowEngine
from flink_tpu.windowing.aggregates import (
    AvgAggregate,
    CountAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.windowing.windower import SliceSharedWindower


def keyed_batch(keys, values, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(values, dtype=np.float32)},
        timestamps=ts)


def fired_to_dict(batches, fields):
    out = {}
    for b in batches:
        for row in b.to_rows():
            out[(row[KEY_ID_FIELD], row["window_start"], row["window_end"])] = \
                tuple(row[f] for f in fields)
    return out


class TestShuffle:
    def test_shard_records_matches_keygroup_formula(self):
        keys = np.arange(1000, dtype=np.int64)
        shards = shard_records(keys, 8, 128)
        assert shards.min() >= 0 and shards.max() < 8
        counts = np.bincount(shards, minlength=8)
        assert counts.min() > 0  # all shards get work

    def test_bucket_by_shard_roundtrip(self):
        rng = np.random.default_rng(0)
        shards = rng.integers(0, 4, 100)
        vals = rng.random(100).astype(np.float32)
        counts, (block,), order = bucket_by_shard(
            shards, 4, columns=[vals], fills=[0.0], min_bucket=16,
            want_order=True)
        assert counts.sum() == 100
        for p in range(4):
            got = np.sort(block[p, :counts[p]])
            want = np.sort(vals[shards == p])
            np.testing.assert_allclose(got, want)

    def test_all_to_all_repartition(self, eight_device_mesh):
        import jax.numpy as jnp

        mesh = eight_device_mesh
        Pn = 8
        x = np.arange(Pn * Pn * 4, dtype=np.float32).reshape(Pn, Pn, 4)
        repart = make_all_to_all_repartition(mesh)
        y = np.asarray(repart(jnp.asarray(x)))
        # block [src, dst] moves to [dst, src]
        np.testing.assert_allclose(y, x.transpose(1, 0, 2))

    def test_global_combine_psum(self, eight_device_mesh):
        import jax.numpy as jnp

        combine = make_global_combine(eight_device_mesh, "sum")
        partials = np.ones((8, 5), dtype=np.float32) * np.arange(
            8, dtype=np.float32)[:, None]
        out = np.asarray(combine(jnp.asarray(partials)))
        np.testing.assert_allclose(out, np.full(5, 28.0))

    def test_global_combine_max(self, eight_device_mesh):
        import jax.numpy as jnp

        combine = make_global_combine(eight_device_mesh, "max")
        partials = np.arange(8, dtype=np.float32)[:, None] * np.ones(
            (8, 3), dtype=np.float32)
        out = np.asarray(combine(jnp.asarray(partials)))
        np.testing.assert_allclose(out, np.full(3, 7.0))


class TestMeshWindowEngine:
    def _run_both(self, assigner, agg_factory, events, wm_steps, mesh):
        """Run single-device and mesh engines on the same stream; compare."""
        single = SliceSharedWindower(assigner, agg_factory(), capacity=1 << 14)
        sharded = MeshWindowEngine(assigner, agg_factory(), mesh,
                                   capacity_per_shard=1 << 12)
        fired_s, fired_m = [], []
        i = 0
        for keys, vals, ts, wm in wm_steps:
            b = keyed_batch(keys, vals, ts)
            single.process_batch(b)
            sharded.process_batch(b)
            fired_s.extend(single.on_watermark(wm))
            fired_m.extend(sharded.on_watermark(wm))
        return fired_s, fired_m

    def test_matches_single_device(self, eight_device_mesh):
        rng = np.random.default_rng(3)
        assigner = SlidingEventTimeWindows.of(400, 200)
        steps = []
        for s in range(6):
            n = 500
            keys = rng.integers(0, 100, n).astype(np.int64)
            vals = rng.random(n).astype(np.float32)
            ts = rng.integers(s * 300, s * 300 + 500, n).astype(np.int64)
            steps.append((keys, vals, ts, s * 300))
        steps.append((np.array([0], dtype=np.int64),
                      np.array([0.0], dtype=np.float32),
                      np.array([steps[-1][3] + 1000], dtype=np.int64), 10**9))
        fired_s, fired_m = self._run_both(
            assigner, lambda: SumAggregate("v"), None, steps,
            eight_device_mesh)
        ds = fired_to_dict(fired_s, ["sum_v"])
        dm = fired_to_dict(fired_m, ["sum_v"])
        assert set(ds) == set(dm)
        for k in ds:
            assert ds[k][0] == pytest.approx(dm[k][0], rel=1e-4)

    def test_multi_agg_on_mesh(self, eight_device_mesh):
        assigner = TumblingEventTimeWindows.of(100)
        eng = MeshWindowEngine(
            assigner,
            MultiAggregate([CountAggregate(), AvgAggregate("v")]),
            eight_device_mesh, capacity_per_shard=1 << 12)
        keys = np.arange(64, dtype=np.int64)
        eng.process_batch(keyed_batch(
            np.repeat(keys, 2), np.tile([1.0, 3.0], 64),
            np.full(128, 50, dtype=np.int64)))
        fired = eng.on_watermark(99)
        d = fired_to_dict(fired, ["count", "avg_v"])
        assert len(d) == 64
        for k, (cnt, avg) in d.items():
            assert cnt == 2
            assert avg == pytest.approx(2.0)

    def test_snapshot_restore_rescale(self, eight_device_mesh):
        """State written on an 8-shard mesh restores onto a 4-shard mesh —
        the key-group rescale contract."""
        import jax

        assigner = TumblingEventTimeWindows.of(1000)
        eng8 = MeshWindowEngine(assigner, SumAggregate("v"),
                                eight_device_mesh, capacity_per_shard=1 << 12)
        rng = np.random.default_rng(5)
        keys = rng.integers(0, 500, 2000).astype(np.int64)
        vals = rng.random(2000).astype(np.float32)
        ts = np.full(2000, 100, dtype=np.int64)
        eng8.process_batch(keyed_batch(keys, vals, ts))
        snap = eng8.snapshot()

        mesh4 = make_mesh(4)
        eng4 = MeshWindowEngine(assigner, SumAggregate("v"), mesh4,
                                capacity_per_shard=1 << 12)
        eng4.restore(snap)
        fired = eng4.on_watermark(999)
        got = fired_to_dict(fired, ["sum_v"])

        oracle = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            kk = (k, 0, 1000)
            oracle[kk] = oracle.get(kk, 0.0) + v
        assert set(got) == set(oracle)
        for k in oracle:
            assert got[k][0] == pytest.approx(oracle[k], rel=1e-4)

    def test_state_locality_no_cross_shard_keys(self, eight_device_mesh):
        """Each key's state must live on exactly one shard."""
        eng = MeshWindowEngine(TumblingEventTimeWindows.of(100),
                               CountAggregate(), eight_device_mesh,
                               capacity_per_shard=1 << 12)
        keys = np.arange(200, dtype=np.int64)
        eng.process_batch(keyed_batch(
            keys, np.ones(200, dtype=np.float32),
            np.full(200, 10, dtype=np.int64)))
        seen = {}
        for p, idx in enumerate(eng.indexes):
            for k in idx.slot_key[idx.used_slots()].tolist():
                assert k not in seen, f"key {k} on shards {seen[k]} and {p}"
                seen[k] = p


class TestSkewGrowth:
    def test_hot_shard_grows_instead_of_failing(self, eight_device_mesh):
        """Key concentration beyond capacity_per_shard grows the table
        (SURVEY hard-part (e)) — previously a hard SlotTableFullError."""
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.windowing.aggregates import CountAggregate
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        eng = MeshWindowEngine(
            TumblingEventTimeWindows.of(1000), CountAggregate(),
            eight_device_mesh, capacity_per_shard=1024)
        n = 10_000  # ~1250 keys/shard on average > 1024 capacity
        keys = np.arange(n, dtype=np.int64)
        eng.process_batch(RecordBatch.from_pydict(
            {"__key_id__": keys}, timestamps=np.zeros(n, dtype=np.int64)))
        fired = eng.on_watermark(1 << 40)
        total = sum(int(b["count"].sum()) for b in fired)
        assert total == n
        assert eng.capacity > 1024, "table must have grown"

    def test_session_shard_growth(self, eight_device_mesh):
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.aggregates import CountAggregate

        eng = MeshSessionEngine(50, CountAggregate(), eight_device_mesh,
                                capacity_per_shard=1024)
        n = 10_000
        keys = np.arange(n, dtype=np.int64)
        eng.process_batch(RecordBatch.from_pydict(
            {"__key_id__": keys}, timestamps=np.zeros(n, dtype=np.int64)))
        fired = eng.on_watermark(1 << 40)
        total = sum(int(b["count"].sum()) for b in fired)
        assert total == n
        assert eng.capacity > 1024

    def test_grown_table_restores_into_fresh_engine(self, eight_device_mesh):
        """A checkpoint of a GROWN table must restore into an engine at the
        original configured capacity (restore triggers the same growth)."""
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.windowing.aggregates import CountAggregate
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        n = 10_000
        keys = np.arange(n, dtype=np.int64)
        a = MeshWindowEngine(
            TumblingEventTimeWindows.of(1000), CountAggregate(),
            eight_device_mesh, capacity_per_shard=1024)
        a.process_batch(RecordBatch.from_pydict(
            {"__key_id__": keys}, timestamps=np.zeros(n, dtype=np.int64)))
        snap = a.snapshot()
        b = MeshWindowEngine(
            TumblingEventTimeWindows.of(1000), CountAggregate(),
            eight_device_mesh, capacity_per_shard=1024)
        b.restore(snap)
        fired = b.on_watermark(1 << 40)
        assert sum(int(bb["count"].sum()) for bb in fired) == n
