"""Pane/ring window-state layout (state/pane_table.py + PaneWindower):
equivalence with the slot layout, cross-layout snapshot restore, slice-
granular deltas, fused top-k fires, and layout selection."""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import (
    CountAggregate,
    MinAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import (
    CumulativeEventTimeWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.windowing.fire_projectors import TopKFireProjector
from flink_tpu.windowing.windower import PaneWindower, SliceSharedWindower


def _events(n=6000, keys=250, seed=13, rate=1000):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, keys, n).astype(np.int64)
    ts = (np.arange(n, dtype=np.int64) * 1000) // rate
    vs = (rng.random(n) * 10).astype(np.float32)
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: ks, "v": vs}, timestamps=ts)


def _drive(w, batch, wm_step=700):
    """Feed in chunks with advancing watermarks, then flush."""
    fired = []
    n = len(batch)
    step = 1000
    for i in range(0, n, step):
        chunk = batch.slice(i, min(i + step, n))
        w.process_batch(chunk)
        fired.extend(w.on_watermark(int(chunk.timestamps.max()) - wm_step))
    fired.extend(w.on_watermark(1 << 60))
    return fired


def _as_dict(fired, fields):
    out = {}
    for b in fired:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"], r["window_end"])] = \
                tuple(round(float(r[f]), 3) for f in fields)
    return out


def _assert_windows_close(got, want):
    """Same windows, same values to f32 reassociation tolerance: the
    pane pre-aggregation folds sums in record order while the slot
    layout merges per-slice partials, so float results agree to ~1 ulp,
    not bitwise (the conftest assert_windows_approx_equal rationale)."""
    assert set(got) == set(want)
    for k, vals in want.items():
        assert got[k] == pytest.approx(vals, rel=1e-4, abs=1e-2), k


AGG = lambda: MultiAggregate(  # noqa: E731
    [SumAggregate("v", output="s"), CountAggregate(output="n"),
     MinAggregate("v", output="lo")])


class TestPaneEquivalence:
    @pytest.mark.parametrize("assigner_factory", [
        lambda: TumblingEventTimeWindows.of(1000),
        lambda: SlidingEventTimeWindows.of(2000, 500),
        lambda: CumulativeEventTimeWindows(4000, 1000),
    ])
    def test_matches_slot_layout(self, assigner_factory):
        batch = _events()
        pane = PaneWindower(assigner_factory(), AGG(), capacity=4096)
        slot = SliceSharedWindower(assigner_factory(), AGG(),
                                   capacity=4096)
        got = _as_dict(_drive(pane, batch), ("s", "n", "lo"))
        want = _as_dict(_drive(slot, batch), ("s", "n", "lo"))
        _assert_windows_close(got, want)

    def test_fused_topk_fire(self):
        batch = _events()
        pane = PaneWindower(
            SlidingEventTimeWindows.of(2000, 500), CountAggregate(),
            capacity=4096, fire_projector=TopKFireProjector("count", k=8))
        plain = PaneWindower(SlidingEventTimeWindows.of(2000, 500),
                             CountAggregate(), capacity=4096)
        out_p = _drive(pane, batch)
        out_f = _drive(plain, batch)
        assert len(out_p) == len(out_f)
        for bp, bf in zip(out_p, out_f):
            want = np.sort(bf["count"])[::-1][: len(bp)]
            np.testing.assert_array_equal(np.sort(bp["count"])[::-1], want)

    def test_sum_zero_still_emitted(self):
        """Presence, not value, decides emission: a key whose window sum is
        exactly 0.0 must still fire (identity != absence)."""
        pane = PaneWindower(TumblingEventTimeWindows.of(1000),
                            SumAggregate("v", output="s"), capacity=1024)
        b = RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.asarray([5, 5], dtype=np.int64),
             "v": np.asarray([2.5, -2.5], dtype=np.float32)},
            timestamps=[100, 200])
        pane.process_batch(b)
        fired = pane.on_watermark(1 << 60)
        rows = [r for bb in fired for r in bb.to_rows()]
        assert len(rows) == 1 and rows[0]["s"] == 0.0


class TestPaneSnapshots:
    def _halves(self):
        batch = _events(n=3000, keys=120)
        return batch.slice(0, 1500), batch.slice(1500, 3000), batch

    @pytest.mark.parametrize("src,dst", [
        (PaneWindower, PaneWindower),
        (PaneWindower, SliceSharedWindower),
        (SliceSharedWindower, PaneWindower),
    ])
    def test_cross_layout_restore(self, src, dst):
        a_half, b_half, full = self._halves()
        assigner = lambda: SlidingEventTimeWindows.of(2000, 500)  # noqa
        one = src(assigner(), AGG(), capacity=4096)
        one.process_batch(a_half)
        snap = one.snapshot()
        two = dst(assigner(), AGG(), capacity=4096)
        two.restore(snap)
        two.process_batch(b_half)
        got = _as_dict(two.on_watermark(1 << 60), ("s", "n", "lo"))
        oracle = SliceSharedWindower(assigner(), AGG(), capacity=4096)
        oracle.process_batch(full)
        want = _as_dict(oracle.on_watermark(1 << 60), ("s", "n", "lo"))
        _assert_windows_close(got, want)

    def test_delta_covers_only_dirty_slices(self):
        pane = PaneWindower(TumblingEventTimeWindows.of(1000),
                            CountAggregate(), capacity=1024)
        b1 = RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.arange(10, dtype=np.int64)},
            timestamps=np.full(10, 500))
        pane.process_batch(b1)
        pane.snapshot()  # full base; slice 1000 sealed from now on
        b2 = RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.arange(5, dtype=np.int64)},
            timestamps=np.full(5, 1500))
        pane.process_batch(b2)
        delta = pane.snapshot(mode="delta")["table"]
        # only the NEW slice's rows ride the delta — the sealed slice
        # stays in the base (the slice IS the incremental unit)
        assert set(np.unique(delta["namespace"]).tolist()) == {2000}
        assert len(delta["key_id"]) == 5

    def test_freed_slices_leave_tombstones(self):
        pane = PaneWindower(TumblingEventTimeWindows.of(1000),
                            CountAggregate(), capacity=1024)
        b = RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.arange(4, dtype=np.int64)},
            timestamps=np.full(4, 500))
        pane.process_batch(b)
        pane.snapshot()
        pane.on_watermark(1 << 40)  # fire + expire slice 1000
        delta = pane.snapshot(mode="delta")["table"]
        assert 1000 in np.asarray(delta["freed_namespaces"]).tolist()

    def test_query_windows(self):
        pane = PaneWindower(SlidingEventTimeWindows.of(2000, 1000),
                            AGG(), capacity=1024)
        b = RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.asarray([7, 7, 9], dtype=np.int64),
             "v": np.asarray([1.0, 3.0, 8.0], dtype=np.float32)},
            timestamps=[100, 1200, 300])
        pane.process_batch(b)
        got = pane.query_windows(7)
        assert got[2000] == {"s": pytest.approx(4.0), "n": 2,
                             "lo": pytest.approx(1.0)}
        assert got[3000] == {"s": pytest.approx(3.0), "n": 1,
                             "lo": pytest.approx(3.0)}
        assert pane.query_windows(12345) == {}


class TestCompaction:
    def test_key_churn_compacts_dead_columns(self):
        """Departed keys' columns are reclaimed once they dominate — the
        table must not grow without bound under key churn."""
        from flink_tpu.state.pane_table import PaneTable

        pane = PaneWindower(TumblingEventTimeWindows.of(1000),
                            CountAggregate(), capacity=8192)
        pane.table._COMPACT_MIN_KEYS = 512  # shrink the trigger for CI
        # waves of fresh keys; old waves expire with their windows
        for wave in range(8):
            ks = np.arange(wave * 300, wave * 300 + 300, dtype=np.int64)
            b = RecordBatch.from_pydict(
                {KEY_ID_FIELD: ks},
                timestamps=np.full(300, wave * 1000 + 500))
            pane.process_batch(b)
            pane.on_watermark(wave * 1000 + 999)
        # 2400 distinct keys seen; compaction keeps the high-water bounded
        # near the live set instead of the total ever-seen count
        assert pane.table.used_cols < 1200, pane.table.used_cols
        # and correctness survives compaction: one more window fires right
        ks = np.asarray([7_000, 7_001], dtype=np.int64)
        pane.process_batch(RecordBatch.from_pydict(
            {KEY_ID_FIELD: ks}, timestamps=np.full(2, 9_500)))
        rows = [r for b2 in pane.on_watermark(1 << 60)
                for r in b2.to_rows()]
        assert {r[KEY_ID_FIELD] for r in rows} == {7_000, 7_001}
        assert all(r["count"] == 1 for r in rows)


class TestLayoutSelection:
    def test_spill_falls_back_to_slots(self, tmp_path):
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )

        op = WindowAggOperator(
            TumblingEventTimeWindows.of(1000), CountAggregate(), "k",
            spill={"max_device_slots": 2048,
                   "spill_dir": str(tmp_path / "sp")})
        op.open(OperatorContext(0, 1, 128))
        assert type(op.windower).__name__ == "SliceSharedWindower"

    def test_explicit_panes_with_spill_rejected(self, tmp_path):
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )

        op = WindowAggOperator(
            TumblingEventTimeWindows.of(1000), CountAggregate(), "k",
            spill={"max_device_slots": 2048}, window_layout="panes")
        with pytest.raises(ValueError, match="no spill tier"):
            op.open(OperatorContext(0, 1, 128))

    def test_auto_resolves_to_slots_until_measured(self):
        """'auto' stays on the measured incumbent; explicit 'panes' opts
        into the pane layout (flip once TPU numbers land)."""
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )

        op = WindowAggOperator(
            TumblingEventTimeWindows.of(1000), CountAggregate(), "k")
        op.open(OperatorContext(0, 1, 128))
        assert type(op.windower).__name__ == "SliceSharedWindower"
        op2 = WindowAggOperator(
            TumblingEventTimeWindows.of(1000), CountAggregate(), "k",
            window_layout="panes")
        op2.open(OperatorContext(0, 1, 128))
        assert type(op2.windower).__name__ == "PaneWindower"
