"""Paged spill layout (spill_layout="pages") — session-shaped state
(one row per namespace, millions of namespaces) under a device budget.

reference: RocksDBKeyedStateBackend.java — block-granular storage under
a small memory budget; the unit of movement is an eviction cohort, not
one namespace.
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.state.slot_table import SlotTable, SlotTableFullError
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.sessions import SessionWindower


def mk(capacity=2048, **kw):
    return SlotTable(SumAggregate("v"), capacity=capacity,
                     max_device_slots=capacity, spill_layout="pages",
                     track_namespaces=False, **kw)


def put(t, keys, sids, vals, chunk=1024):
    """Feed in sub-budget chunks (one batch's working set must fit the
    device — the irreducible contract of a bounded table)."""
    keys = np.asarray(keys, dtype=np.int64)
    sids = np.asarray(sids, dtype=np.int64)
    vals = np.asarray(vals, dtype=np.float32)
    slots = None
    for a in range(0, len(keys), chunk):
        slots = t.lookup_or_insert(keys[a:a + chunk], sids[a:a + chunk])
        t.scatter(slots, (vals[a:a + chunk],))
    return slots


class TestPagedSlotTable:
    def test_eviction_and_transparent_reload(self):
        t = mk()
        # 8k session rows >> 2047 device slots: cold cohorts page out
        n = 8192
        keys = np.arange(1, n + 1, dtype=np.int64)
        sids = np.arange(1, n + 1, dtype=np.int64)
        for a in range(0, n, 1024):
            put(t, keys[a:a + 1024], sids[a:a + 1024],
                np.full(1024, 2.0))
        assert len(t.spill) > 0
        # touching early (spilled) rows reloads them with values intact
        slots = t.lookup_or_insert(keys[:64], sids[:64])
        t.scatter(slots, (np.ones(64, dtype=np.float32),))
        q = t.query(int(keys[0]), namespace=int(sids[0]))
        assert q[int(sids[0])]["sum_v"] == 3.0

    def test_snapshot_covers_all_tiers_and_restores(self):
        t = mk()
        n = 6000
        keys = np.arange(1, n + 1, dtype=np.int64)
        sids = keys * 7
        put(t, keys, sids, np.full(n, 1.5))
        snap = t.snapshot()
        assert len(snap["key_id"]) == n  # resident + paged rows

        t2 = mk()
        t2.restore(snap)
        # every row readable after restore (reload by page)
        for k in (1, 2999, 5999):
            q = t2.query(k, namespace=k * 7)
            assert q[k * 7]["sum_v"] == 1.5

    def test_free_rows_drops_sessions_everywhere(self):
        t = mk()
        n = 6000
        keys = np.arange(1, n + 1, dtype=np.int64)
        sids = keys + 10
        put(t, keys, sids, np.full(n, 1.0))
        # free a resident chunk (the most recent rows stay resident)
        slots = t.lookup_or_insert(keys[-100:], sids[-100:])
        t.free_rows(slots, sids[-100:])
        # free spilled sessions: the oldest rows paged out; dropping
        # them tombstones their page rows (no rewrite)
        spilled_mask = t._spilled_mask(sids[:100])
        assert spilled_mask.any()
        dead = sids[:100][spilled_mask]
        t._drop_spilled_sessions(dead)
        snap = t.snapshot()
        got = set(int(x) for x in snap["namespace"])
        assert not (set(dead.tolist()) & got)
        assert not (set(int(s) for s in sids[-100:]) & got)

    def test_reload_leaves_remainder_unrewritten(self):
        """Lazy tombstones: reloading a subset extracts exactly the
        requested rows by index — the page's sibling rows are NOT
        rewritten (rows_split_on_reload stays 0) and stay readable."""
        t = mk()
        n = 6000
        keys = np.arange(1, n + 1, dtype=np.int64)
        sids = keys
        put(t, keys, sids, np.full(n, 4.0))
        pages_before = len(t.spill)
        assert pages_before > 0
        # request ONE old session: only its row leaves its page
        t.lookup_or_insert(keys[:1], sids[:1])
        c = t.spill_counters()
        assert c["rows_reloaded"] == 1
        assert c["rows_split_on_reload"] == 0, \
            "reload must not rewrite the cohort remainder"
        assert c["rows_compacted"] == 0, \
            "one tombstone is far below the compaction threshold"
        assert len(t.spill) == pages_before  # nothing re-bundled
        # and the sibling rows are still intact
        q = t.query(2, namespace=2)
        assert q[2]["sum_v"] == 4.0

    def test_compaction_only_after_dead_fraction_threshold(self):
        """A page compacts (rewrites its live remainder) only once its
        dead fraction crosses the threshold; a fully-dead page drops
        without any rewrite."""
        t = mk()
        n = 6000
        keys = np.arange(1, n + 1, dtype=np.int64)
        put(t, keys, keys, np.full(n, 1.0))
        pmap = t._pmap
        # pick the largest spilled page and reload just under half of
        # its rows, one chunk at a time: never compacts
        page = max(pmap.page_rows, key=pmap.page_rows.get)
        page_sids = np.sort(pmap.sp_ns[pmap.sp_page == page])
        rows = len(page_sids)
        assert rows >= 64
        just_under = page_sids[: rows // 2]  # dead fraction <= 0.5
        for a in range(0, len(just_under), 32):
            chunk = just_under[a:a + 32]
            t.lookup_or_insert(chunk, chunk)
        assert t.spill_counters()["rows_compacted"] == 0
        assert int(pmap.page_rows[page]) == rows, \
            "page must keep its tombstones until the threshold"
        # one more chunk pushes the dead fraction over the threshold:
        # the page rewrites with ONLY its live rows
        over = page_sids[rows // 2: rows // 2 + 32]
        t.lookup_or_insert(over, over)
        c = t.spill_counters()
        live = rows - len(just_under) - len(over)
        assert c["rows_compacted"] == live
        assert c["rows_split_on_reload"] == 0
        assert page not in pmap.page_rows  # old page gone
        # the compacted copy still answers queries
        survivor = int(page_sids[-1])
        q = t.query(survivor, namespace=survivor)
        assert q[survivor]["sum_v"] == 1.0

    def test_budget_exhaustion_raises(self):
        t = mk(capacity=1024)
        keys = np.arange(1, 1200, dtype=np.int64)
        with pytest.raises(SlotTableFullError):
            put(t, keys, keys, np.ones(len(keys)))

    def test_incremental_delta_covers_dirty_page_rows(self):
        t = mk()
        n = 4000
        keys = np.arange(1, n + 1, dtype=np.int64)
        put(t, keys, keys, np.full(n, 1.0))  # dirty rows page out
        delta = t.snapshot_delta()
        got = {(int(k), int(ns)): float(v) for k, ns, v in zip(
            delta["key_id"], delta["namespace"], delta["leaf_0"])}
        # every row (resident or paged) was dirty since job start
        assert len(got) == n
        assert got[(1, 1)] == 1.0


def _sessions_run(spill):
    w = SessionWindower(2000, SumAggregate("value", np.float64),
                        capacity=1 << 12, spill=spill)
    rng = np.random.default_rng(5)
    outs = []
    wm = 0
    for i in range(12):
        B = 4096
        ts = np.sort(rng.integers(wm + 1, wm + 40_000, size=B))
        keys = rng.integers(0, 200_000, size=B)
        b = RecordBatch({KEY_ID_FIELD: keys.astype(np.int64),
                         "value": np.ones(B),
                         TIMESTAMP_FIELD: ts.astype(np.int64)})
        w.process_batch(b)
        wm += 40_000
        outs.extend(w.on_watermark(wm))
    outs.extend(w.on_watermark(1 << 60))
    rows = {}
    for o in outs:
        for k, s, v in zip(o[KEY_ID_FIELD].tolist(),
                           o["window_start"].tolist(),
                           o["sum_value"].tolist()):
            rows[(int(k), int(s))] = rows.get((int(k), int(s)), 0) + v
    return rows


def test_session_windower_paged_equals_unbounded():
    """Sessions through the paged spill tier == sessions with no budget,
    at a live set far beyond the device slots."""
    bounded = _sessions_run({"max_device_slots": 1 << 12})
    unbounded = _sessions_run(None)
    assert bounded == unbounded


def test_session_windower_explicit_namespaces_layout_still_works():
    """An explicit spill_layout='namespaces' keeps the registry-driven
    eviction path functional (track_namespaces must stay on for it)."""
    bounded = _sessions_run({"max_device_slots": 1 << 12,
                             "spill_layout": "namespaces"})
    unbounded = _sessions_run(None)
    assert bounded == unbounded
