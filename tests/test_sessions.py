"""Session-window semantics vs a brute-force oracle (the reference tests
sessions in WindowOperatorTest with MergingWindowSet; same role here)."""

import collections

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import CountAggregate, SumAggregate
from flink_tpu.windowing.sessions import SessionWindower


def keyed_batch(keys, values, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(values, dtype=np.float32)},
        timestamps=ts)


def oracle_sessions(events, gap):
    """events: (key, value, ts) -> {(key, start, end): sum} after full flush."""
    by_key = collections.defaultdict(list)
    for k, v, t in events:
        by_key[k].append((t, v))
    out = {}
    for k, evs in by_key.items():
        evs.sort()
        cur = []
        for t, v in evs:
            if cur and t - cur[-1][0] > gap:
                out[(k, cur[0][0], cur[-1][0] + gap)] = sum(x[1] for x in cur)
                cur = []
            cur.append((t, v))
        if cur:
            out[(k, cur[0][0], cur[-1][0] + gap)] = sum(x[1] for x in cur)
    return out


def fired_to_dict(batches, field="sum_v"):
    out = {}
    for b in batches:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"], r["window_end"])] = r[field]
    return out


class TestSessionBasics:
    def test_single_session(self):
        w = SessionWindower(gap=100, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 1, 1], [1, 2, 3], [0, 50, 120]))
        assert w.on_watermark(218) == []  # session end 220, not yet
        fired = fired_to_dict(w.on_watermark(219))
        assert fired == {(1, 0, 220): 6.0}

    def test_pathological_timestamp_span_takes_lexsort_fallback(self):
        """A batch whose timestamp span exceeds the packed-sort bits
        (sentinel/corrupt timestamps) must fall back to lexsort, not
        crash sessionization with a negative shift."""
        w = SessionWindower(gap=100, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 2], [1.0, 2.0],
                                    [-(1 << 62), 1 << 61]))
        fired = fired_to_dict(w.on_watermark(1 << 62))
        assert fired == {(1, -(1 << 62), -(1 << 62) + 100): 1.0,
                         (2, 1 << 61, (1 << 61) + 100): 2.0}

    def test_gap_splits_sessions(self):
        w = SessionWindower(gap=10, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 1], [1, 2], [0, 100]))
        fired = fired_to_dict(w.on_watermark(10**6))
        assert fired == {(1, 0, 10): 1.0, (1, 100, 110): 2.0}

    def test_cross_batch_merge(self):
        w = SessionWindower(gap=50, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1], [1.0], [0]))
        w.process_batch(keyed_batch([1], [2.0], [40]))   # extends session
        w.process_batch(keyed_batch([1], [4.0], [200]))  # new session
        fired = fired_to_dict(w.on_watermark(10**6))
        assert fired == {(1, 0, 90): 3.0, (1, 200, 250): 4.0}

    def test_bridge_merges_two_sessions(self):
        """A late-ish record bridging two existing sessions merges them —
        the MergingWindowSet case."""
        w = SessionWindower(gap=20, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1], [1.0], [0]))
        w.process_batch(keyed_batch([1], [2.0], [100]))
        # sessions: [0,20), [100,120); bridge at 15..95 chain
        w.process_batch(keyed_batch([1, 1, 1, 1, 1],
                                    [0.5, 0.5, 0.5, 0.5, 0.5],
                                    [20, 40, 60, 80, 95]))
        fired = fired_to_dict(w.on_watermark(10**6))
        assert fired == {(1, 0, 120): pytest.approx(5.5)}

    def test_fire_frees_state(self):
        w = SessionWindower(gap=10, agg=CountAggregate(), capacity=1024)
        w.process_batch(keyed_batch([1, 2, 3], [1, 1, 1], [0, 0, 0]))
        assert w.table.num_used == 3
        w.on_watermark(10**6)
        assert w.table.num_used == 0
        assert not w.sessions

    def test_late_record_dropped(self):
        w = SessionWindower(gap=10, agg=CountAggregate(), capacity=1024)
        w.process_batch(keyed_batch([1], [1], [100]))
        w.on_watermark(200)
        w.process_batch(keyed_batch([1], [1], [50]))  # 50+10-1 < 200
        assert w.late_records_dropped == 1


class TestSessionOracle:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_random_against_oracle(self, seed):
        rng = np.random.default_rng(seed)
        gap = 30
        w = SessionWindower(gap=gap, agg=SumAggregate("v"), capacity=1 << 14)
        events = []
        for step in range(8):
            n = 300
            keys = rng.integers(0, 25, n).astype(np.int64)
            vals = rng.random(n).astype(np.float32)
            ts = rng.integers(step * 200, step * 200 + 400, n).astype(np.int64)
            for e in zip(keys.tolist(), vals.tolist(), ts.tolist()):
                events.append(e)
            w.process_batch(keyed_batch(keys, vals, ts))
            # watermark stays behind max ts so nothing is dropped as late
        fired = fired_to_dict(w.on_watermark(10**9))
        oracle = oracle_sessions(events, gap)
        assert set(fired) == set(oracle)
        for k in oracle:
            assert fired[k] == pytest.approx(oracle[k], rel=1e-4), k

    def test_high_cardinality(self):
        rng = np.random.default_rng(9)
        w = SessionWindower(gap=1000, agg=CountAggregate(), capacity=1 << 15)
        n = 20000
        keys = rng.integers(0, 10000, n).astype(np.int64)
        ts = rng.integers(0, 5000, n).astype(np.int64)
        w.process_batch(keyed_batch(keys, np.ones(n, np.float32), ts))
        fired = w.on_watermark(10**9)
        total = sum(int(b["count"].sum()) for b in fired)
        assert total == n


class TestSessionSnapshot:
    def test_snapshot_restore(self):
        gap = 50
        w = SessionWindower(gap=gap, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1, 2], [1.0, 2.0], [0, 10]))
        snap = w.snapshot()
        w2 = SessionWindower(gap=gap, agg=SumAggregate("v"), capacity=1024)
        w2.restore(snap)
        w2.process_batch(keyed_batch([1], [3.0], [40]))  # extends key 1
        fired = fired_to_dict(w2.on_watermark(10**9))
        assert fired == {(1, 0, 90): 4.0, (2, 10, 60): 2.0}


class TestSessionAPI:
    def test_datastream_session_windows(self):
        from flink_tpu import StreamExecutionEnvironment
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        env = StreamExecutionEnvironment()
        rows = [
            {"key": "a", "v": 1.0, "t": 0},
            {"key": "a", "v": 2.0, "t": 900},
            {"key": "b", "v": 5.0, "t": 100},
            {"key": "a", "v": 4.0, "t": 5000},
        ]
        result = (
            env.from_collection(rows, timestamp_field="t")
            .key_by("key")
            .window(EventTimeSessionWindows.with_gap(1000))
            .sum("v")
            .execute_and_collect()
        )
        got = {(r["key"], r["window_start"], r["window_end"]): r["sum_v"]
               for r in result.to_rows()}
        assert got == {
            ("a", 0, 1900): 3.0,
            ("b", 100, 1100): 5.0,
            ("a", 5000, 6000): 4.0,
        }


class TestOutOfOrderMerge:
    def test_out_of_order_record_merges_into_live_session(self):
        """Regression (review find): a record older than the watermark that
        merges into a LIVE session must be accepted, not dropped."""
        w = SessionWindower(gap=50, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1], [1.0], [100]))  # session [100,150)
        assert w.on_watermark(120) == []                  # still open
        w.process_batch(keyed_batch([1], [2.0], [60]))    # merges -> [60,150)
        assert w.late_records_dropped == 0
        fired = fired_to_dict(w.on_watermark(10**6))
        assert fired == {(1, 60, 150): 3.0}

    def test_stale_new_session_still_dropped(self):
        w = SessionWindower(gap=50, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1], [1.0], [1000]))
        w.on_watermark(2000)  # fires [1000,1050)
        w.process_batch(keyed_batch([1], [2.0], [100]))  # stale, no live sess
        assert w.late_records_dropped == 1
        assert fired_to_dict(w.on_watermark(10**6)) == {}


class TestEmptyStateCheckpoint:
    def test_restore_after_quiescent_checkpoint(self):
        """Regression (review find): snapshot taken when all windows fired
        and state is empty must restore cleanly (codec prunes empty dicts)."""
        import pickle

        w = SessionWindower(gap=10, agg=SumAggregate("v"), capacity=1024)
        w.process_batch(keyed_batch([1], [1.0], [0]))
        w.on_watermark(10**6)
        snap = w.snapshot()
        # simulate the checkpoint codec's empty-dict pruning
        pruned = {k: v for k, v in snap.items()
                  if not (isinstance(v, dict) and not v)}
        w2 = SessionWindower(gap=10, agg=SumAggregate("v"), capacity=1024)
        w2.restore(pruned)
        w2.process_batch(keyed_batch([2], [2.0], [10**6 + 100]))
        fired = fired_to_dict(w2.on_watermark(10**9))
        assert list(fired.values()) == [2.0]
