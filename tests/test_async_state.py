"""Async keyed state (State V2 analog) — ordering, coalescing, device
path, checkpoint drain.

reference contract: runtime/asyncprocessing/AsyncExecutionController.java
(same-key ops serialize in submission order via KeyAccountingUnit;
different-key ops batch into one executor call; everything drains before a
snapshot) and runtime/state/v2/ (StateFuture-returning handles).
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.process import ProcessFunction, ProcessOperator
from flink_tpu.state.async_state import (
    AsyncExecutionController,
    DeviceValueState,
    DeviceValueStateDescriptor,
    make_async_view,
)
from flink_tpu.state.keyed_state import (
    KeyedStateStore,
    MapStateDescriptor,
    ReducingStateDescriptor,
    ValueStateDescriptor,
)


def _aec_and_state(desc=None):
    aec = AsyncExecutionController()
    store = KeyedStateStore(64)
    desc = desc or ValueStateDescriptor("v", np.int64, 0)
    return aec, make_async_view(aec, store.get_state(desc)), store


# -- ordering ---------------------------------------------------------------


def test_same_key_ops_serialize_in_submission_order():
    aec, st, _ = _aec_and_state()
    st.put([1], 5)
    f1 = st.get([1])
    st.put([1], 7)
    f2 = st.get([1])
    assert f1.value() == [5]      # sees the first put, not the second
    assert f2.value() == [7]
    # four ops on one key cannot coalesce: four waves
    assert aec.stats["waves"] == 4


def test_read_before_write_sees_old_value():
    aec, st, _ = _aec_and_state()
    st.put([3], 10)
    aec.drain()
    f_old = st.get([3])
    st.put([3], 20)
    f_new = st.get([3])
    assert f_old.value() == [10]
    assert f_new.value() == [20]


def test_cross_key_gets_coalesce_into_one_kernel():
    aec, st, _ = _aec_and_state()
    futs = [st.get([k, k + 100]) for k in range(10)]  # 10 disjoint gets
    aec.drain()
    assert aec.stats["ops"] == 10
    assert aec.stats["waves"] == 1
    assert aec.stats["kernel_calls"] == 1             # ONE batched gather
    assert all(np.array_equal(f.value(), [0, 0]) for f in futs)


def test_cross_key_puts_coalesce_then_gets_read_them():
    aec, st, _ = _aec_and_state()
    for k in range(8):
        st.put([k], k * 11)
    futs = [st.get([k]) for k in range(8)]
    aec.drain()
    # wave 1: all puts (one scatter); wave 2: all gets (one gather)
    assert aec.stats["waves"] == 2
    assert aec.stats["kernel_calls"] == 2
    assert [int(f.value()[0]) for f in futs] == [k * 11 for k in range(8)]


def test_same_kind_writes_to_same_key_merge_last_wins():
    aec, st, _ = _aec_and_state()
    st.put([5], 1)
    st.put([5], 2)   # same kind, same key: merges, submission order holds
    f = st.get([5])
    assert f.value() == [2]
    assert aec.stats["waves"] == 2  # puts merged into one wave


def test_reducing_adds_accumulate_across_coalesced_ops():
    desc = ReducingStateDescriptor("r", np.add, np.int64, 0)
    aec, st, _ = _aec_and_state(desc)
    for _ in range(5):
        st.add([7], 3)           # same key, same kind: one wave, in order
    f = st.get([7])
    assert f.value() == [15]
    assert aec.stats["waves"] == 2


def test_put_then_add_same_key_do_not_commute_so_split_waves():
    desc = ReducingStateDescriptor("r", np.add, np.int64, 0)
    aec, st, _ = _aec_and_state(desc)
    st.put([2], 100)
    st.add([2], 1)
    assert st.get([2]).value() == [101]
    assert aec.stats["waves"] >= 3


# -- futures ----------------------------------------------------------------


def test_value_forces_drain_lazily():
    aec, st, _ = _aec_and_state()
    f = st.get([1])
    assert not f.done and aec.pending == 1
    assert np.array_equal(f.value(), [0])
    assert f.done and aec.pending == 0


def test_then_chains_and_may_submit_more_ops():
    aec, st, _ = _aec_and_state()
    st.put([1], 41)
    # callback issues a follow-up write; drain loops until empty
    st.get([1]).then(lambda v: st.put([2], int(v[0]) + 1))
    aec.drain()
    assert st.get([2]).value() == [42]


def test_then_on_done_future_runs_immediately():
    aec, st, _ = _aec_and_state()
    f = st.get([1])
    aec.drain()
    seen = []
    f.then(lambda v: seen.append(int(v[0])))
    assert seen == [0]


# -- map state --------------------------------------------------------------


def test_async_map_state_orders_and_reads():
    aec = AsyncExecutionController()
    store = KeyedStateStore(64)
    st = make_async_view(aec, store.get_state(MapStateDescriptor("m")))
    st.put([1, 2], ["a", "a"], [10, 20])
    f = st.get([1, 2, 3], ["a", "a", "a"], default=-1)
    assert f.value() == [10, 20, -1]


# -- equality: async == sync on a random op sequence ------------------------


def test_async_matches_sync_on_random_sequence():
    rng = np.random.default_rng(7)
    aec = AsyncExecutionController()
    store_a, store_s = KeyedStateStore(256), KeyedStateStore(256)
    desc = ValueStateDescriptor("v", np.float64, 0.0)
    a = make_async_view(aec, store_a.get_state(desc))
    s = store_s.get_state(desc)
    futs = []
    for _ in range(200):
        keys = rng.integers(0, 30, size=rng.integers(1, 6))
        if rng.random() < 0.5:
            vals = rng.normal(size=len(keys))
            a.put(keys, vals)
            s.put(keys, vals)
        else:
            futs.append((a.get(keys), s.get(keys).copy()))
    aec.drain()
    for fa, expect in futs:
        np.testing.assert_allclose(fa.value(), expect)


# -- device path ------------------------------------------------------------


def test_device_value_state_matches_host_and_defers_transfer():
    aec = AsyncExecutionController()
    store = KeyedStateStore(128)
    dd = DeviceValueStateDescriptor("dv", np.float32, 0.0)
    dv = make_async_view(aec, store.get_state(dd))
    assert isinstance(store.get_state(dd), DeviceValueState)
    dv.put(np.arange(16), np.arange(16, dtype=np.float32) * 2)
    f = dv.get(np.arange(16))
    aec.drain()
    # completed, but the result may still be a device array: value()
    # materializes it
    assert f.done
    np.testing.assert_allclose(f.value(), np.arange(16) * 2.0)


def test_device_state_checkpoint_restore_roundtrip():
    store = KeyedStateStore(64)
    dd = DeviceValueStateDescriptor("dv", np.int64, 0)
    st = store.get_state(dd)
    st.put(np.array([3, 5, 9]), np.array([30, 50, 90]))
    snap = store.snapshot()

    store2 = KeyedStateStore(64)
    store2.restore(snap)
    st2 = store2.get_state(dd)
    assert isinstance(st2, DeviceValueState)
    np.testing.assert_array_equal(
        st2.get(np.array([3, 5, 9])), [30, 50, 90])


def test_device_state_grows_with_index():
    store = KeyedStateStore(8)
    dd = DeviceValueStateDescriptor("dv", np.int64, -1)
    st = store.get_state(dd)
    keys = np.arange(100)
    st.put(keys, keys * 3)
    np.testing.assert_array_equal(st.get(keys), keys * 3)


def test_device_state_rejects_ttl():
    from flink_tpu.state.ttl import StateTtlConfig

    store = KeyedStateStore(8)
    dd = DeviceValueStateDescriptor(
        "dv", np.int64, 0, ttl=StateTtlConfig(1000))
    with pytest.raises(ValueError, match="TTL"):
        store.get_state(dd)


# -- operator integration ---------------------------------------------------


class _AsyncCounter(ProcessFunction):
    """Counts per key with async state; emits nothing until on_timer."""

    def open(self, ctx):
        self.desc = ReducingStateDescriptor("n", np.add, np.int64, 0)

    def process_batch(self, batch, ctx):
        st = ctx.async_state(self.desc)
        keys = batch[KEY_ID_FIELD]
        st.add(keys, np.ones(len(keys), dtype=np.int64))
        ctx.timer_service().register_event_time_timers(
            keys, np.full(len(keys), 100))

    def on_timer(self, key_ids, timestamps, ctx):
        counts = ctx.async_state(self.desc).get(key_ids)
        ctx.collect(RecordBatch({
            KEY_ID_FIELD: key_ids,
            TIMESTAMP_FIELD: timestamps,
            "count": counts.value(),
        }))


def _batch(keys, ts=0):
    keys = np.asarray(keys, dtype=np.int64)
    return RecordBatch({
        KEY_ID_FIELD: keys,
        TIMESTAMP_FIELD: np.full(len(keys), ts, dtype=np.int64),
    })


def test_process_operator_async_state_end_to_end():
    op = ProcessOperator(_AsyncCounter(), keyed=True)
    op.open(None)
    op.process_batch(_batch([1, 2, 1, 1, 2, 3]))
    op.process_batch(_batch([1, 3]))
    outs = op.process_watermark(200)
    assert len(outs) == 1
    got = dict(zip(outs[0][KEY_ID_FIELD].tolist(),
                   outs[0]["count"].tolist()))
    assert got == {1: 4, 2: 2, 3: 2}
    # invocation boundaries drained everything
    assert op.aec.pending == 0


def test_snapshot_drains_pending_async_ops():
    op = ProcessOperator(_AsyncCounter(), keyed=True)
    op.open(None)
    op.process_batch(_batch([5, 5, 6]))
    # simulate ops submitted but not yet drained (mid-invocation barrier)
    st = op._ctx().async_state(
        ReducingStateDescriptor("n", np.add, np.int64, 0))
    st.add(np.array([5]), np.array([10]))
    assert op.aec.pending == 1
    snap = op.snapshot_state()
    assert op.aec.pending == 0  # drained before capture

    op2 = ProcessOperator(_AsyncCounter(), keyed=True)
    op2.open(None)
    op2.restore_state(snap)
    st2 = op2._ctx().async_state(
        ReducingStateDescriptor("n", np.add, np.int64, 0))
    assert st2.get(np.array([5])).value() == [12]  # 2 adds + the 10
