"""The pod-scale data plane: process-spanning mesh construction and the
two-level ICI/DCN exchange (parallel/exchange2.py).

Everything here runs SINGLE-process on a 2x4 (and 2x2) VIRTUAL topology
over 8 CPU devices — the exchange programs only see the (hosts, local)
factorization, so plain tier-1 exercises the exact program family the
multi-process smoke dispatches across real process boundaries. The
contracts pinned:

- ``make_mesh`` REFUSES to silently truncate (`num_devices` beyond the
  available devices used to return a smaller mesh, silently re-routing
  key groups),
- the stable host -> key-group-range mapping (``host_key_group_ranges``)
  is contiguous, covers the space, and inverts the routing formula,
- the two-level exchange is BIT-IDENTICAL to the flat single-axis
  program AND to the host-bucketing path on identical input, for both
  mesh engines and the join exchange, under forced paged eviction
  (stream order is preserved end to end, so float folds stay bit-exact),
- per-level bucket tiers + traffic split accounting,
- a reshard / partial failover that changes the device count drops the
  stale factorization instead of programming a mesh it no longer
  covers.
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD
from flink_tpu.parallel.mesh import (
    HostTopology,
    make_mesh,
    pod_mesh_view,
)
from flink_tpu.parallel.exchange2 import (
    ExchangeTraffic,
    stage_two_level_exchange,
)
from flink_tpu.state.keygroups import (
    host_key_group_ranges,
    host_of_key_group,
    shard_key_group_ranges,
)
from flink_tpu.windowing.aggregates import SumAggregate

from tests.test_sessions import keyed_batch

GAP = 100


# ------------------------------------------------------------------ mesh


class TestMakeMesh:
    def test_oversized_request_raises_instead_of_truncating(self):
        import jax

        available = len(jax.devices())
        with pytest.raises(ValueError, match=str(available)):
            make_mesh(available + 1)

    def test_exact_and_smaller_requests_still_work(self):
        import jax

        available = len(jax.devices())
        assert make_mesh(available).devices.size == available
        assert make_mesh(2).devices.size == 2

    def test_invalid_span_rejected(self):
        with pytest.raises(ValueError, match="span"):
            make_mesh(span="pod")

    def test_topology_validation(self):
        with pytest.raises(ValueError):
            HostTopology(0, 4)
        t = HostTopology(2, 4)
        assert t.num_shards == 8
        assert t.host_of_shard(0) == 0 and t.host_of_shard(7) == 1
        assert list(t.shards_of_host(1)) == [4, 5, 6, 7]
        with pytest.raises(ValueError):
            t.shards_of_host(2)

    def test_pod_mesh_view_is_sharding_equivalent(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_tpu.parallel.mesh import (
            HOST_AXIS,
            KEY_AXIS,
            LOCAL_AXIS,
        )

        mesh = make_mesh(8)
        view = pod_mesh_view(mesh, HostTopology(2, 4))
        flat = NamedSharding(mesh, P(KEY_AXIS))
        two = NamedSharding(view, P((HOST_AXIS, LOCAL_AXIS)))
        # the whole no-copy handoff between flat and two-level programs
        assert flat.is_equivalent_to(two, 2)
        with pytest.raises(ValueError):
            pod_mesh_view(mesh, HostTopology(2, 3))

    def test_engine_rejects_noncovering_topology(self):
        from flink_tpu.parallel.sharded_sessions import (
            MeshSessionEngine,
        )

        with pytest.raises(ValueError, match="does not cover"):
            MeshSessionEngine(GAP, SumAggregate("v"), make_mesh(8),
                              host_topology=HostTopology(2, 3))


class TestHostKeyGroupRanges:
    def test_contiguous_and_covering(self):
        for mp in (128, 100, 11):
            for h, l in ((2, 4), (4, 2), (3, 2)):
                if h * l > mp:
                    continue
                ranges = host_key_group_ranges(h, l, mp)
                assert ranges[0][0] == 0
                assert ranges[-1][1] == mp - 1
                for (a0, a1), (b0, b1) in zip(ranges, ranges[1:]):
                    assert b0 == a1 + 1  # contiguous, no gap

    def test_union_of_shard_ranges(self):
        sr = shard_key_group_ranges(8, 128)
        hr = host_key_group_ranges(2, 4, 128)
        assert hr == [(sr[0][0], sr[3][1]), (sr[4][0], sr[7][1])]

    def test_host_of_key_group_matches_ranges(self):
        mp = 100
        ranges = host_key_group_ranges(2, 4, mp)
        groups = np.arange(mp, dtype=np.int64)
        owners = host_of_key_group(groups, 2, 4, mp)
        for h, (g0, g1) in enumerate(ranges):
            assert (owners[g0:g1 + 1] == h).all()


# --------------------------------------------------------------- staging


class TestTwoLevelStaging:
    def test_layout_padding_and_tiers(self):
        topo = HostTopology(2, 2)
        rng = np.random.default_rng(3)
        n = 900
        shards = rng.integers(0, 4, n).astype(np.int64)
        slots = rng.integers(1, 64, n).astype(np.int32)
        dst, (s_col,), w1, w2 = stage_two_level_exchange(
            shards, topo, columns=[slots], fills=[0])
        from flink_tpu.parallel.shuffle import exchange_chunk_size

        C = exchange_chunk_size(n, 4)
        assert len(dst) == 4 * C == len(s_col)
        np.testing.assert_array_equal(dst[:n], shards)
        assert (dst[n:] == 4).all()
        # per-level tiers: pow2, bounded by the level above
        assert w1 & (w1 - 1) == 0 and w1 <= C
        assert w2 & (w2 - 1) == 0 and w2 <= topo.local_devices * w1

    def test_traffic_split_accounting(self):
        topo = HostTopology(2, 2)
        tr = ExchangeTraffic()
        # chunk layout: C=256, so records 0..255 are chunk 0 (host 0)
        # — 3 intra (dst shards 0/1), 1 cross (dst shard 2)
        shards = np.array([0, 1, 0, 2], dtype=np.int64)
        stage_two_level_exchange(shards, topo,
                                 columns=[np.ones(4, np.int32)],
                                 fills=[0], traffic=tr)
        assert tr.rows_intra_host == 3
        assert tr.rows_cross_host == 1
        assert tr.batches == 1


# ------------------------------------------------- engine bit-identity


def _stream(num_keys=20_000, n_steps=6, per_step=5000, seed=11):
    """Live set beyond a 1024-slot/shard budget: forced paged eviction
    on the session engine. Integer values keep float sums exact so
    bit-identity across data planes is meaningful."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.integers(0, 1000, per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    steps.append((np.array([0], dtype=np.int64),
                  np.array([0.0], dtype=np.float32),
                  np.array([n_steps * 80 + 10_000], dtype=np.int64),
                  10 ** 9))
    return steps


def _run(engine, steps):
    fired = []
    for keys, vals, ts, wm in steps:
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
    return fired


def _fired_dict(batches, field="sum_v"):
    out = {}
    for b in batches:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r[field]
    return out


def _session_engine(**kw):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

    return MeshSessionEngine(gap=GAP, agg=SumAggregate("v"),
                             mesh=make_mesh(8),
                             capacity_per_shard=1 << 14,
                             max_device_slots=1024, **kw)


def _window_engine(**kw):
    from flink_tpu.parallel.sharded_windower import MeshWindowEngine
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    return MeshWindowEngine(TumblingEventTimeWindows.of(50),
                            SumAggregate("v"), make_mesh(8),
                            capacity_per_shard=1 << 14, **kw)


class TestTwoLevelBitIdentity:
    """The acceptance contract: identical input through the two-level
    program, the flat single-axis program and the host bucketing path
    produces BIT-IDENTICAL fires — stream order survives both hops."""

    def test_sessions_two_level_vs_flat_vs_host(self):
        steps = _stream()
        results = {}
        for name, kw in (
                ("flat", dict(shuffle_mode="device")),
                ("two", dict(shuffle_mode="device",
                             host_topology=HostTopology(2, 4))),
                ("host", dict(shuffle_mode="host"))):
            eng = _session_engine(**kw)
            results[name] = _fired_dict(_run(eng, steps))
            if name == "two":
                tr = eng.exchange2_traffic()
                assert tr["rows_cross_host"] > 0, \
                    "vacuous: no cross-host rows at this shape"
                assert tr["rows_intra_host"] > 0
                assert eng.spill_counters()["rows_evicted"] > 0, \
                    "vacuous: the spill tier never engaged"
        assert results["two"] == results["flat"]
        assert results["two"] == results["host"]
        assert len(results["two"]) > 1000

    def test_windows_two_level_vs_flat_vs_host(self):
        steps = _stream()
        results = {}
        for name, kw in (
                ("flat", dict(shuffle_mode="device")),
                ("two", dict(shuffle_mode="device",
                             host_topology=HostTopology(2, 4))),
                ("host", dict(shuffle_mode="host"))):
            results[name] = _fired_dict(_run(_window_engine(**kw),
                                             steps))
        assert results["two"] == results["flat"]
        assert results["two"] == results["host"]

    def test_windows_valued_two_level_path(self):
        """Two-phase partial batches (the valued exchange variant)
        through the two-level program == flat."""
        from flink_tpu.runtime.local_agg import PARTIAL_LEAF_PREFIX

        steps = _stream(per_step=3000, n_steps=4)

        def run_valued(**kw):
            eng = _window_engine(**kw)
            fired = []
            for keys, vals, ts, wm in steps:
                b = keyed_batch(keys, vals, ts)
                pb = b.with_column(PARTIAL_LEAF_PREFIX + "0", vals)
                eng.process_batch(pb)
                fired.extend(eng.on_watermark(wm))
            return _fired_dict(fired)

        flat = run_valued(shuffle_mode="device")
        two = run_valued(shuffle_mode="device",
                         host_topology=HostTopology(2, 4))
        assert two == flat

    def test_single_host_topology_keeps_flat_fast_path(self):
        eng = _session_engine(shuffle_mode="device",
                              host_topology=HostTopology(1, 8))
        assert not eng._two_level_active()
        steps = _stream(per_step=1000, n_steps=3)
        flat = _fired_dict(_run(_session_engine(), steps))
        one = _fired_dict(_run(eng, steps))
        assert one == flat
        assert eng.exchange2_traffic()["exchange2_batches"] == 0

    def test_reshard_drops_stale_topology(self):
        eng = _session_engine(shuffle_mode="device",
                              host_topology=HostTopology(2, 4))
        steps = _stream(per_step=1000, n_steps=3)
        oracle = _fired_dict(_run(_session_engine(), steps))
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 2:
                eng.reshard(4)
                assert eng.host_topology is None, \
                    "a 2x4 factorization cannot describe 4 shards"
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        assert _fired_dict(fired) == oracle


class TestJoinTwoLevel:
    def _join_steps(self, n_steps=5, per_step=600, seed=5):
        from flink_tpu.core.records import (
            TIMESTAMP_FIELD,
            RecordBatch,
        )

        rng = np.random.default_rng(seed)
        steps = []
        for s in range(n_steps):
            keys = rng.integers(0, 500, per_step).astype(np.int64)
            ts = rng.integers(s * 50, s * 50 + 45,
                              per_step).astype(np.int64)
            vals = rng.integers(0, 100, per_step).astype(np.float32)
            steps.append((RecordBatch({
                KEY_ID_FIELD: keys, "v": vals,
                TIMESTAMP_FIELD: ts}), (s - 1) * 50))
        return steps

    def _run_join(self, topology):
        from flink_tpu.joins import MeshIntervalJoinEngine

        eng = MeshIntervalJoinEngine(
            -40, 40, mesh=make_mesh(8), capacity_per_shard=4096,
            host_topology=topology)
        pairs = []
        for b, wm in self._join_steps():
            left = np.arange(len(b)) % 2 == 0
            eng.process_batch(b.filter(left), 0)
            eng.process_batch(b.filter(~left), 1)
            out = eng.on_watermark(wm)
            for ob in out:
                pairs.extend(tuple(sorted(r.items()))
                             for r in ob.to_rows())
        return eng, pairs

    def test_interval_join_two_level_bit_identical(self):
        _, flat = self._run_join(None)
        eng, two = self._run_join(HostTopology(2, 4))
        assert two == flat  # values AND emission order
        tr = eng.exchange2_traffic()
        assert tr["rows_cross_host"] > 0

    def test_join_rejects_host_backend_topology(self):
        from flink_tpu.joins import MeshIntervalJoinEngine

        with pytest.raises(ValueError, match="device backend"):
            MeshIntervalJoinEngine(-40, 40, backend="host",
                                   num_shards=8,
                                   host_topology=HostTopology(2, 4))


class TestOperatorWiring:
    def test_ctx_host_topology_reaches_the_engine(self):
        """shuffle.hosts (an int host count through OperatorContext)
        factors the engine's mesh into the (hosts, local) topology;
        a count that cannot factor the mesh falls back flat."""
        import jax

        from flink_tpu.runtime.operators import (
            OperatorContext,
            SessionWindowAggOperator,
        )

        par = min(8, len(jax.devices()))
        op = SessionWindowAggOperator(gap=GAP, agg=SumAggregate("v"),
                                      key_field="k")
        op.open(OperatorContext(parallelism=par, host_topology=2))
        t = op.windower.host_topology
        assert t is not None and t.num_hosts == 2
        assert t.num_shards == op.windower.P
        # a non-factoring declaration keeps the flat exchange
        op2 = SessionWindowAggOperator(gap=GAP, agg=SumAggregate("v"),
                                       key_field="k")
        op2.open(OperatorContext(parallelism=par, host_topology=5))
        assert op2.windower.host_topology is None

    def test_executor_config_arms_the_two_level_exchange(self):
        """An end-to-end job with shuffle.hosts=2 produces output
        identical to the flat run — the config plumbs through the
        local executor into the engine."""
        from flink_tpu import (
            Configuration,
            StreamExecutionEnvironment,
        )
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )

        rng = np.random.default_rng(3)
        n = 4000
        rows = [{"k": int(k), "v": float(v), "t": int(t)}
                for k, v, t in zip(
                    rng.integers(0, 500, n),
                    rng.integers(0, 100, n),
                    rng.integers(0, 400, n))]

        def run(hosts):
            conf = {"parallelism.default": 8}
            if hosts:
                conf["shuffle.hosts"] = hosts
            env = StreamExecutionEnvironment(Configuration(conf))
            result = (
                env.from_collection(rows, timestamp_field="t")
                .key_by("k")
                .window(TumblingEventTimeWindows.of(100))
                .aggregate(SumAggregate("v"))
                .execute_and_collect()
            )
            return sorted((r["k"], r["window_start"], r["sum_v"])
                          for r in result.to_rows())

        assert run(2) == run(0)


class TestPodDataPlane:
    """The DCN record router (parallel/pod.py) in its single-process
    tier-1 mode: same program family the multi-process smoke dispatches
    across real process boundaries."""

    def test_routes_to_owner_in_stream_order(self):
        from flink_tpu.parallel.pod import PodDataPlane
        from flink_tpu.state.keygroups import (
            assign_key_groups,
            host_of_key_group,
        )

        topo = HostTopology(2, 4)
        plane = PodDataPlane(
            topo, dtypes=[np.int64, np.int64, np.float32],
            mesh=make_mesh(8))
        rng = np.random.default_rng(0)
        n = 3000
        keys = rng.integers(0, 1 << 62, n)  # full-width identities
        ts = rng.integers(0, 1000, n)
        vals = rng.normal(size=n).astype(np.float32)
        owners = host_of_key_group(
            assign_key_groups(keys, 128), 2, 4, 128)
        arrivals = plane.exchange(owners, [keys, ts, vals])
        total = 0
        for h in (0, 1):
            k2, t2, v2 = arrivals[h]
            total += len(k2)
            sel = owners == h
            # exact rows, exact global stream order, int64 bit-exact
            # through the x32 lane-pair split
            np.testing.assert_array_equal(k2, keys[sel])
            np.testing.assert_array_equal(t2, ts[sel])
            np.testing.assert_array_equal(v2, vals[sel])
        assert total == n
        assert plane.rows_cross_host > 0
        assert plane.rows_intra_host > 0

    def test_deterministic_chunk_bound_skips_the_collective(self):
        from flink_tpu.parallel.pod import PodDataPlane

        topo = HostTopology(2, 2)
        plane = PodDataPlane(topo, dtypes=[np.int64],
                             mesh=make_mesh(4))
        owners = np.array([0, 1, 1, 0], dtype=np.int64)
        keys = np.arange(4, dtype=np.int64)
        arrivals = plane.exchange(owners, [keys], chunk_bound=1)
        np.testing.assert_array_equal(arrivals[0][0], [0, 3])
        np.testing.assert_array_equal(arrivals[1][0], [1, 2])


class TestProgramCaching:
    def test_rebuilt_engine_reuses_the_program_family(self):
        """Two engines with the same (mesh, topology, agg) share the
        cached two-level executables — the multi-tenant zero-recompile
        contract extends to the pod programs."""
        a = _session_engine(host_topology=HostTopology(2, 4))
        b = _session_engine(host_topology=HostTopology(2, 4))
        assert a._exchange2_steps[0] is b._exchange2_steps[0]
        assert a._exchange2_steps[1] is b._exchange2_steps[1]
