"""SQL logical optimizer (flink_tpu/table/optimizer.py) + INSERT INTO.

reference parity: Calcite rule sets (FlinkStreamRuleSets — FILTER_INTO_JOIN,
ReduceExpressionsRule) and TableEnvironment.executeSql INSERT INTO.

Pins: constant folding (arithmetic, boolean identities, BETWEEN/IN),
filter pushdown into INNER-join sides (both), LEFT-join (preserved side
only), pushdown through non-agg subqueries, the rank/Top-N guard
(ROW_NUMBER subqueries must keep their rownum filter outside), and
results unchanged by optimization (rewrites are semantics-preserving).
"""

import numpy as np
import pytest

from flink_tpu.table import sql_parser as ast
from flink_tpu.table.expressions import BinaryOp, Column, Literal
from flink_tpu.table.optimizer import (
    fold_constants,
    optimize,
    split_conjuncts,
)


def parse(sql):
    return ast.parse(sql)


class TestFolding:
    def test_arithmetic(self):
        stmt = parse("SELECT a FROM t WHERE a > 1 + 2 * 3")
        out = optimize(stmt)
        conj = split_conjuncts(out.where)
        assert conj == [BinaryOp(">", Column("a"), Literal(7))]

    def test_boolean_identities(self):
        stmt = parse("SELECT a FROM t WHERE a > 1 AND 1 = 1")
        out = optimize(stmt)
        # TRUE conjunct dissolves entirely
        assert out.where == BinaryOp(">", Column("a"), Literal(1))

    def test_between_in_fold(self):
        stmt = parse("SELECT a FROM t WHERE 5 BETWEEN 1 AND 9 AND a < 2")
        out = optimize(stmt)
        assert out.where == BinaryOp("<", Column("a"), Literal(2))


class TestJoinPushdown:
    def _joined(self, sql):
        return optimize(parse(sql))

    def test_inner_both_sides(self):
        out = self._joined(
            "SELECT b.x FROM b JOIN c ON b.k = c.k "
            "WHERE b.x > 5 AND c.y < 3 AND b.x < c.y + 100")
        assert isinstance(out.table, ast.Join)
        # one-sided conjuncts moved into SubQuery wrappers
        assert isinstance(out.table.left, ast.SubQuery)
        assert isinstance(out.table.right, ast.SubQuery)
        assert out.table.left.alias == "b"
        assert out.table.right.alias == "c"
        # the cross-side conjunct stays above
        kept = split_conjuncts(out.where)
        assert len(kept) == 1

    def test_left_join_preserved_side_only(self):
        out = self._joined(
            "SELECT b.x FROM b LEFT JOIN c ON b.k = c.k "
            "WHERE b.x > 5 AND c.y < 3")
        assert isinstance(out.table.left, ast.SubQuery)
        # null-supplying side's predicate must NOT sink below the join
        assert isinstance(out.table.right, ast.NamedTable)
        assert len(split_conjuncts(out.where)) == 1

    def test_unqualified_not_pushed(self):
        out = self._joined(
            "SELECT b.x FROM b JOIN c ON b.k = c.k WHERE x > 5")
        assert isinstance(out.table.left, ast.NamedTable)
        assert isinstance(out.table.right, ast.NamedTable)
        assert out.where is not None


class TestSubqueryPushdown:
    def test_pushed_through_projection(self):
        out = optimize(parse(
            "SELECT v FROM (SELECT a + 1 AS v FROM t) WHERE v > 10"))
        assert out.where is None
        inner = out.table.query
        # v > 10 became a + 1 > 10 inside
        assert inner.where == BinaryOp(
            ">", BinaryOp("+", Column("a"), Literal(1)), Literal(10))

    def test_rank_pattern_not_pushed(self):
        sql = ("SELECT * FROM (SELECT a, ROW_NUMBER() OVER ("
               "PARTITION BY k ORDER BY a DESC) AS rn FROM t) "
               "WHERE rn <= 3")
        out = optimize(parse(sql))
        assert out.where is not None  # stayed outside

    def test_agg_subquery_not_pushed(self):
        sql = ("SELECT s FROM (SELECT k, SUM(v) AS s FROM t GROUP BY k) "
               "WHERE s > 10")
        out = optimize(parse(sql))
        assert out.where is not None


class TestSemanticsPreserved:
    def _env(self):
        from flink_tpu import StreamExecutionEnvironment, Configuration
        from flink_tpu.table.environment import StreamTableEnvironment

        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 64}))
        return StreamTableEnvironment(env)

    def test_join_results_identical(self, monkeypatch):
        rows_l = [{"k": i % 5, "x": float(i), "t": i * 10}
                  for i in range(200)]
        rows_r = [{"k": i % 5, "y": float(i % 7), "t": i * 10}
                  for i in range(200)]

        def run(optimized):
            if not optimized:
                import flink_tpu.table.environment as te

                monkeypatch.setattr(te, "optimize", lambda s: s)
            t_env = self._env()
            t_env.create_temporary_view(
                "L", t_env.from_collection(rows_l, timestamp_field="t"))
            t_env.create_temporary_view(
                "R", t_env.from_collection(rows_r, timestamp_field="t"))
            res = t_env.execute_sql(
                "SELECT L.k, L.x, R.y FROM L JOIN R ON L.x = R.y "
                "WHERE L.x > 2 AND R.y < 5").collect()
            monkeypatch.undo()
            return sorted((r["k_l"], r["x"], r["y"]) for r in res)

        assert run(True) == run(False) and len(run(True)) > 0


class TestExplain:
    def test_explain_shows_optimized_and_physical(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.table.environment import StreamTableEnvironment

        t_env = StreamTableEnvironment(StreamExecutionEnvironment(
            Configuration({})))
        rows = [{"auction": 1, "price": 2.0, "t": 0}]
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="t"))
        text = t_env.execute_sql(
            "EXPLAIN SELECT auction, COUNT(*) AS n FROM TABLE(TUMBLE("
            "TABLE bid, DESCRIPTOR(t), INTERVAL '10' SECOND)) "
            "WHERE price > 1 AND 1 = 1 "
            "GROUP BY auction, window_start, window_end")
        assert "Optimized Logical Plan" in text
        assert "1 = 1" not in text          # folded away
        assert "(price > 1)" in text        # kept
        assert "Physical Plan" in text
        assert "HASH key=auction" in text   # the keyed exchange
        # explain_sql() works without the EXPLAIN keyword too
        text2 = t_env.explain_sql("SELECT auction FROM bid")
        assert "Optimized Logical Plan" in text2

    def test_explain_join_pushdown_visible(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.table.environment import StreamTableEnvironment

        t_env = StreamTableEnvironment(StreamExecutionEnvironment(
            Configuration({})))
        rows = [{"k": 1, "x": 2.0, "t": 0}]
        t_env.create_temporary_view(
            "L", t_env.from_collection(rows, timestamp_field="t"))
        t_env.create_temporary_view(
            "R", t_env.from_collection(rows, timestamp_field="t"))
        text = t_env.execute_sql(
            "EXPLAIN SELECT L.x FROM L JOIN R ON L.x = R.x "
            "WHERE L.x > 5")
        # the one-sided predicate sank into the left branch's subquery
        assert "JOIN" in text and "WHERE (L.x > 5)" in text


class TestCatalogStatements:
    def test_show_tables_and_describe(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.table.environment import StreamTableEnvironment

        t_env = StreamTableEnvironment(StreamExecutionEnvironment(
            Configuration({})))
        rows = [{"a": 1, "p": 2.0, "t": 0}]
        t_env.create_temporary_view(
            "bids", t_env.from_collection(rows, timestamp_field="t"))
        t_env.create_temporary_view(
            "asks", t_env.from_collection(rows, timestamp_field="t"))
        assert t_env.execute_sql("SHOW TABLES") == ["asks", "bids"]
        d = t_env.execute_sql("DESCRIBE bids")
        assert d["columns"] == ["a", "p", "t"]
        assert d["time_field"] == "t" and d["changelog"] is False
        # DESC shorthand; unknown table fails with the known list
        assert t_env.execute_sql("DESC asks")["name"] == "asks"
        from flink_tpu.table.planner import PlanError

        with pytest.raises(PlanError, match="not registered"):
            t_env.execute_sql("DESCRIBE nope")


class TestUnionAll:
    def _env(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.table.environment import StreamTableEnvironment

        return StreamTableEnvironment(StreamExecutionEnvironment(
            Configuration({"execution.micro-batch.size": 64})))

    def test_union_all_sql(self):
        t_env = self._env()
        a = [{"k": i, "v": float(i), "t": i * 10} for i in range(50)]
        b = [{"k": i + 100, "v": float(i), "t": i * 10} for i in range(30)]
        t_env.create_temporary_view(
            "A", t_env.from_collection(a, timestamp_field="t"))
        t_env.create_temporary_view(
            "B", t_env.from_collection(b, timestamp_field="t"))
        rows = t_env.execute_sql(
            "SELECT k, v FROM A WHERE v > 10 UNION ALL "
            "SELECT k, v FROM B").collect()
        exp = [r for r in a if r["v"] > 10] + b
        assert sorted(r["k"] for r in rows) == sorted(r["k"] for r in exp)

    def test_union_trailing_order_limit(self):
        t_env = self._env()
        a = [{"k": i, "v": float(i), "t": i * 10} for i in range(20)]
        b = [{"k": i, "v": float(i + 100), "t": i * 10} for i in range(20)]
        t_env.create_temporary_view(
            "A", t_env.from_collection(a, timestamp_field="t"))
        t_env.create_temporary_view(
            "B", t_env.from_collection(b, timestamp_field="t"))
        rows = t_env.execute_sql(
            "SELECT v FROM A UNION ALL SELECT v FROM B "
            "ORDER BY v DESC LIMIT 3").collect()
        assert [r["v"] for r in rows] == [119.0, 118.0, 117.0]

    def test_union_distinct_rejected(self):
        from flink_tpu.table.sql_parser import SqlParseError, parse

        with pytest.raises(SqlParseError, match="UNION ALL"):
            parse("SELECT a FROM t UNION SELECT a FROM u")

    def test_mismatched_columns_rejected(self):
        from flink_tpu.table.planner import PlanError

        t_env = self._env()
        a = [{"k": 1, "v": 1.0, "t": 0}]
        t_env.create_temporary_view(
            "A", t_env.from_collection(a, timestamp_field="t"))
        with pytest.raises(PlanError, match="identical columns"):
            t_env.execute_sql(
                "SELECT k FROM A UNION ALL SELECT v FROM A").collect()

    def test_union_of_changelog_branch_rejected(self):
        from flink_tpu.table.planner import PlanError

        t_env = self._env()
        a = [{"k": i % 3, "v": float(i), "t": i * 10} for i in range(30)]
        t_env.create_temporary_view(
            "A", t_env.from_collection(a, timestamp_field="t"))
        with pytest.raises(PlanError, match="changelog"):
            t_env.execute_sql(
                "SELECT k, SUM(v) AS s FROM A GROUP BY k UNION ALL "
                "SELECT k, SUM(v) AS s FROM A GROUP BY k").collect()

    def test_subquery_order_limit_rejected(self):
        from flink_tpu.table.planner import PlanError

        t_env = self._env()
        a = [{"k": i, "v": float(i), "t": i * 10} for i in range(20)]
        t_env.create_temporary_view(
            "A", t_env.from_collection(a, timestamp_field="t"))
        with pytest.raises(PlanError, match="outermost"):
            t_env.execute_sql(
                "SELECT k FROM (SELECT k FROM A ORDER BY k LIMIT 3)"
            ).collect()

    def test_mixed_time_branches_rejected(self):
        t_env = self._env()
        a = [{"k": 1, "v": 1.0, "t": 0}]
        b = [{"k": 2, "v": 2.0}]
        t_env.create_temporary_view(
            "A", t_env.from_collection(a, timestamp_field="t"))
        t_env.create_temporary_view(
            "B", t_env.from_collection(b), columns=["k", "v"])
        # the union's runtime guard names the cause (plan-time can't see
        # it: projections legitimately drop the time-field marker while
        # the timestamp column still rides along)
        with pytest.raises(Exception, match="event time"):
            t_env.execute_sql(
                "SELECT k, v FROM A UNION ALL SELECT k, v FROM B"
            ).collect()

    def test_fluent_union_all(self):
        t_env = self._env()
        a = [{"k": i, "v": float(i), "t": i * 10} for i in range(10)]
        b = [{"k": i + 50, "v": float(i), "t": i * 10} for i in range(10)]
        ta = t_env.from_collection(a, timestamp_field="t")
        tb = t_env.from_collection(b, timestamp_field="t")
        rows = ta.union_all(tb).execute().collect()
        assert sorted(r["k"] for r in rows) == sorted(
            [r["k"] for r in a] + [r["k"] for r in b])


class TestInsertInto:
    def test_insert_into_sink(self):
        from flink_tpu.connectors.sinks import CollectSink

        t_env = self._env() if hasattr(self, "_env") else None
        from flink_tpu import StreamExecutionEnvironment, Configuration
        from flink_tpu.table.environment import StreamTableEnvironment

        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 64}))
        t_env = StreamTableEnvironment(env)
        rows = [{"k": i % 3, "v": float(i), "t": i * 10}
                for i in range(100)]
        t_env.create_temporary_view(
            "src", t_env.from_collection(rows, timestamp_field="t"))
        sink = CollectSink()
        t_env.create_sink_table("out", sink, columns=["k", "doubled"])
        t_env.execute_sql(
            "INSERT INTO out SELECT k, v * 2 AS doubled FROM src "
            "WHERE v > 50")
        got = sink.result().to_rows()
        exp = [(r["k"], r["v"] * 2) for r in rows if r["v"] > 50]
        assert sorted((g["k"], g["doubled"]) for g in got) == sorted(exp)

    def test_updating_query_into_append_sink_rejected(self):
        from flink_tpu import StreamExecutionEnvironment, Configuration
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.table.environment import StreamTableEnvironment
        from flink_tpu.table.planner import PlanError

        t_env = StreamTableEnvironment(
            StreamExecutionEnvironment(Configuration({})))
        rows = [{"k": i % 3, "v": float(i), "t": i * 10}
                for i in range(30)]
        t_env.create_temporary_view(
            "src", t_env.from_collection(rows, timestamp_field="t"))
        t_env.create_sink_table("out", CollectSink(), columns=["k", "s"])
        with pytest.raises(PlanError, match="append-only"):
            t_env.execute_sql(
                "INSERT INTO out SELECT k, SUM(v) AS s FROM src "
                "GROUP BY k")

    def test_updating_query_into_changelog_sink(self):
        from flink_tpu import StreamExecutionEnvironment, Configuration
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.core.records import ROWKIND_FIELD
        from flink_tpu.table.environment import StreamTableEnvironment

        class ChangelogSink(CollectSink):
            supports_changelog = True

        t_env = StreamTableEnvironment(StreamExecutionEnvironment(
            Configuration({"execution.micro-batch.size": 8})))
        rows = [{"k": i % 3, "v": float(i), "t": i * 10}
                for i in range(30)]
        t_env.create_temporary_view(
            "src", t_env.from_collection(rows, timestamp_field="t"))
        sink = ChangelogSink()
        t_env.create_sink_table("out", sink, columns=["k", "s"])
        t_env.execute_sql(
            "INSERT INTO out SELECT k, SUM(v) AS s FROM src GROUP BY k")
        batch = sink.result()
        # the row-kind column must survive so the consumer can apply
        # retractions; folding the changelog gives the true final sums
        assert ROWKIND_FIELD in batch.columns
        final = {}
        for r in batch.to_rows():
            final[r["k"]] = r["s"]
        exp = {}
        for r in rows:
            exp[r["k"]] = exp.get(r["k"], 0.0) + r["v"]
        assert {k: round(v, 3) for k, v in final.items()} == \
            {k: round(v, 3) for k, v in exp.items()}

    def test_unregistered_target_fails(self):
        from flink_tpu import StreamExecutionEnvironment, Configuration
        from flink_tpu.table.environment import StreamTableEnvironment
        from flink_tpu.table.planner import PlanError

        t_env = StreamTableEnvironment(
            StreamExecutionEnvironment(Configuration({})))
        rows = [{"k": 1, "v": 1.0, "t": 0}]
        t_env.create_temporary_view(
            "src", t_env.from_collection(rows, timestamp_field="t"))
        with pytest.raises(PlanError, match="not a registered sink"):
            t_env.execute_sql("INSERT INTO nowhere SELECT k FROM src")
