import numpy as np

from flink_tpu.state.slot_table import SlotTable, unique_pairs
from flink_tpu.windowing.aggregates import (
    AvgAggregate,
    CountAggregate,
    MaxAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.core.records import RecordBatch


def make_batch(keys, values, ts=None):
    cols = {"v": np.asarray(values, dtype=np.float32)}
    b = RecordBatch.from_pydict(cols, timestamps=ts)
    return b


def test_unique_pairs():
    k = np.array([1, 2, 1, 1, 2], dtype=np.int64)
    n = np.array([10, 10, 10, 20, 10], dtype=np.int64)
    uk, un, inv = unique_pairs(k, n)
    assert len(uk) == 3
    pairs = set(zip(uk.tolist(), un.tolist()))
    assert pairs == {(1, 10), (2, 10), (1, 20)}
    # inverse maps each record to its pair
    for i in range(5):
        assert (uk[inv[i]], un[inv[i]]) == (k[i], n[i])


def test_scatter_and_fire_sum():
    agg = SumAggregate("v")
    t = SlotTable(agg, capacity=1024)
    keys = np.array([7, 8, 7, 9], dtype=np.int64)
    ns = np.array([100, 100, 100, 100], dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    assert slots[0] == slots[2]
    assert slots.min() >= 1  # slot 0 reserved
    t.scatter(slots, agg.map_input(make_batch(keys, [1, 2, 3, 4])))
    s = t.slots_for_namespace(100)
    res = t.fire(s[:, None])
    by_key = dict(zip(t.keys_of_slots(s).tolist(), res["sum_v"].tolist()))
    assert by_key == {7: 4.0, 8: 2.0, 9: 4.0}


def test_free_namespaces_resets_and_reuses():
    agg = SumAggregate("v")
    t = SlotTable(agg, capacity=1024)
    keys = np.array([1, 2], dtype=np.int64)
    ns = np.array([5, 5], dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    t.scatter(slots, (np.array([10.0, 20.0], dtype=np.float32),))
    t.free_namespaces([5])
    assert t.num_used == 0
    # reused slots must start from identity
    slots2 = t.lookup_or_insert(keys, ns)
    t.scatter(slots2, (np.array([1.0, 1.0], dtype=np.float32),))
    res = t.fire(t.slots_for_namespace(5)[:, None])
    assert sorted(res["sum_v"].tolist()) == [1.0, 1.0]


def test_growth():
    agg = CountAggregate()
    t = SlotTable(agg, capacity=1024)
    keys = np.arange(5000, dtype=np.int64)
    ns = np.zeros(5000, dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    assert t.capacity >= 5000
    assert len(np.unique(slots)) == 5000
    t.scatter(slots, agg.map_input(RecordBatch.from_pydict({"x": np.zeros(5000)})))
    res = t.fire(t.slots_for_namespace(0)[:, None])
    assert res["count"].sum() == 5000


def test_multi_aggregate():
    agg = MultiAggregate([SumAggregate("v"), MaxAggregate("v"), AvgAggregate("v"),
                          CountAggregate()])
    t = SlotTable(agg, capacity=1024)
    keys = np.array([1, 1, 2], dtype=np.int64)
    ns = np.array([0, 0, 0], dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    b = make_batch(keys, [3.0, 5.0, 7.0])
    t.scatter(slots, agg.map_input(b))
    s = t.slots_for_namespace(0)
    res = t.fire(s[:, None])
    by_key = {k: i for i, k in enumerate(t.keys_of_slots(s).tolist())}
    assert res["sum_v"][by_key[1]] == 8.0
    assert res["max_v"][by_key[1]] == 5.0
    assert res["avg_v"][by_key[1]] == 4.0
    assert res["count"][by_key[2]] == 1


def test_snapshot_restore_roundtrip():
    agg = SumAggregate("v")
    t = SlotTable(agg, capacity=1024)
    keys = np.array([1, 2, 3], dtype=np.int64)
    ns = np.array([100, 100, 200], dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    t.scatter(slots, (np.array([1.0, 2.0, 3.0], dtype=np.float32),))
    snap = t.snapshot()

    t2 = SlotTable(agg, capacity=1024)
    t2.restore(snap)
    s = t2.slots_for_namespace(100)
    res = t2.fire(s[:, None])
    by_key = dict(zip(t2.keys_of_slots(s).tolist(), res["sum_v"].tolist()))
    assert by_key == {1: 1.0, 2: 2.0}


def test_snapshot_restore_key_group_filter():
    from flink_tpu.state.keygroups import assign_key_groups

    agg = SumAggregate("v")
    t = SlotTable(agg, capacity=1024, max_parallelism=16)
    keys = np.arange(100, dtype=np.int64)
    ns = np.zeros(100, dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    t.scatter(slots, (np.ones(100, dtype=np.float32),))
    snap = t.snapshot()

    owned = set(range(0, 8))
    t2 = SlotTable(agg, capacity=1024, max_parallelism=16)
    t2.restore(snap, key_group_filter=owned)
    groups = assign_key_groups(keys, 16)
    expected = int((np.isin(groups, list(owned))).sum())
    assert t2.num_used == expected


def test_const_leaf_keeps_slot0_identity():
    """COUNT's const-1 input must not pollute the reserved identity slot 0:
    padded scatter lanes target slot 0, and fire matrices read slot 0 for
    missing slices — it must stay at the identity element."""
    import jax.numpy as jnp

    agg = MultiAggregate([CountAggregate(), SumAggregate("v")])
    t = SlotTable(agg, capacity=1024)
    keys = np.array([7, 8, 7], dtype=np.int64)
    ns = np.array([100, 100, 100], dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    # scatter pads to a 256 bucket -> 253 padded lanes target slot 0
    t.scatter(slots, agg.map_input(make_batch(keys, [1.0, 2.0, 3.0])))
    assert int(np.asarray(t.accs[0])[0]) == 0  # count leaf identity
    assert float(np.asarray(t.accs[1])[0]) == 0.0
    # fire with a missing-slice column (slot 0) must not inflate counts
    s = t.slots_for_namespace(100)
    matrix = np.zeros((len(s), 2), dtype=np.int32)
    matrix[:, 0] = s
    res = t.fire(matrix)
    by_key = dict(zip(t.keys_of_slots(s).tolist(), res["count"].tolist()))
    assert by_key == {7: 2, 8: 1}


def test_avg_aggregate_const_count():
    agg = AvgAggregate("v")
    t = SlotTable(agg, capacity=1024)
    keys = np.array([1, 1, 2], dtype=np.int64)
    ns = np.array([5, 5, 5], dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    t.scatter(slots, agg.map_input(make_batch(keys, [2.0, 4.0, 10.0])))
    s = t.slots_for_namespace(5)
    res = t.fire(s[:, None])
    by_key = dict(zip(t.keys_of_slots(s).tolist(), res["avg_v"].tolist()))
    assert by_key == {1: 3.0, 2: 10.0}


def test_monotonic_fire_bucket_reuses_shape():
    agg = SumAggregate("v")
    t = SlotTable(agg, capacity=4096)
    keys = np.arange(1, 201, dtype=np.int64)
    ns = np.full(200, 1, dtype=np.int64)
    slots = t.lookup_or_insert(keys, ns)
    t.scatter(slots, (np.ones(200, dtype=np.float32),))
    t.fire(slots[:, None])            # bucket -> 256
    assert t._fire_bucket == 256
    small = t.fire(slots[:3][:, None])  # smaller fire reuses the 256 bucket
    assert t._fire_bucket == 256
    assert len(small["sum_v"]) == 3
