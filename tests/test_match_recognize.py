"""MATCH_RECOGNIZE + CEP greedy()/iterative conditions.

reference: StreamExecMatch (flink-table-planner) lowering row patterns
onto flink-cep; Pattern.greedy() (Quantifier.greedy +
NFACompiler.updateWithGreedyCondition); IterativeCondition.filter(ctx).
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.cep import KeyNFA, Pattern
from flink_tpu.core.records import RecordBatch
from flink_tpu.table.environment import StreamTableEnvironment


def _advance_all(pattern, events):
    nfa = KeyNFA(pattern)
    out = []
    for ts, row in events:
        hits = [bool(st.evaluate(RecordBatch.from_pydict(
            {k: [v] for k, v in row.items()}))[0])
            for st in pattern.stages]
        for m in nfa.advance(row, ts, hits):
            out.append({name: [nfa.event(i)["v"] for i in idxs]
                        for name, idxs in m.events_by_stage.items()})
    return out


def _ev(*vs):
    return [(i * 10, {"v": v}) for i, v in enumerate(vs)]


def is_a(b):
    return np.char.startswith(np.asarray(b["v"], dtype=str), "a")


def is_b(b):
    return np.char.startswith(np.asarray(b["v"], dtype=str), "b")


def is_c(b):
    return np.char.startswith(np.asarray(b["v"], dtype=str), "c")


class TestGreedy:
    def test_greedy_emits_only_the_maximal_loop(self):
        """a b+ c (relaxed next via followedBy): non-greedy emits every
        prefix combination; greedy only the maximal one."""
        base = Pattern.begin("A").where(is_a) \
            .followed_by("B").where(is_b).one_or_more() \
            .followed_by("C").where(is_c)
        got = _advance_all(base, _ev("a", "b1", "b2", "c"))
        assert len(got) == 2
        assert {"A": ["a"], "B": ["b1"], "C": ["c"]} in got
        assert {"A": ["a"], "B": ["b1", "b2"], "C": ["c"]} in got
        greedy = Pattern.begin("A").where(is_a) \
            .followed_by("B").where(is_b).one_or_more().greedy() \
            .followed_by("C").where(is_c)
        assert _advance_all(greedy, _ev("a", "b1", "b2", "c")) == [
            {"A": ["a"], "B": ["b1", "b2"], "C": ["c"]},
        ]

    def test_greedy_claims_overlapping_event(self):
        """When an event matches BOTH the greedy loop and the next stage,
        the loop consumes it (reference: the greedy condition guards the
        next state's take/ignore edges with not(loop condition))."""
        def is_bc(b):
            return is_b(b) | is_c(b)

        greedy = Pattern.begin("A").where(is_a) \
            .followed_by("B").where(is_bc).one_or_more().greedy() \
            .followed_by("C").where(is_c)
        # c1 matches the loop too -> consumed by B; no C left -> no match
        assert _advance_all(greedy, _ev("a", "b1", "c1")) == []
        # a non-overlapping terminator still completes maximally
        def is_d(b):
            return np.char.startswith(np.asarray(b["v"], dtype=str), "d")

        greedy2 = Pattern.begin("A").where(is_a) \
            .followed_by("B").where(is_bc).one_or_more().greedy() \
            .followed_by("D").where(is_d)
        assert _advance_all(greedy2, _ev("a", "b1", "c1", "d")) == [
            {"A": ["a"], "B": ["b1", "c1"], "D": ["d"]},
        ]

    def test_greedy_requires_a_loop(self):
        with pytest.raises(ValueError, match="greedy"):
            Pattern.begin("A").where(is_a).greedy()


class TestIterativeConditions:
    def test_loop_condition_sees_taken_events(self):
        """B+ where each B must exceed the previously taken B
        (reference: IterativeCondition ctx.getEventsForPattern)."""
        p = Pattern.begin("A").where(
                lambda b: np.asarray(b["x"]) == 0) \
            .followed_by("B").where(
                lambda b: np.asarray(b["x"]) > 0).one_or_more() \
            .where_iterative(
                lambda ev, ctx: (not ctx.events_for("B"))
                or ev["x"] > ctx.events_for("B")[-1]["x"]) \
            .next("C").where(lambda b: np.asarray(b["x"]) == 99)

        nfa = KeyNFA(p)
        out = []
        for i, row in enumerate([{"x": 0}, {"x": 5}, {"x": 3},
                                 {"x": 7}, {"x": 99}]):
            hits = [bool(st.evaluate(RecordBatch.from_pydict(
                {k: [v] for k, v in row.items()}))[0])
                for st in p.stages]
            for m in nfa.advance(row, i * 10, hits):
                out.append({name: [nfa.event(j)["x"] for j in idxs]
                            for name, idxs in m.events_by_stage.items()})
        # 3 is rejected (not > 5); the increasing run 5, 7 matches
        assert {"A": [0], "B": [5, 7], "C": [99]} in out

    def test_cross_stage_condition(self):
        """B's condition reads the event A matched."""
        p = Pattern.begin("A").where(
                lambda b: np.asarray(b["x"]) < 10) \
            .followed_by("B").where_iterative(
                lambda ev, ctx: ev["x"] > ctx.events_for("A")[0]["x"] * 2)

        nfa = KeyNFA(p)
        out = []
        for i, row in enumerate([{"x": 4}, {"x": 7}, {"x": 9}]):
            hits = [bool(st.evaluate(RecordBatch.from_pydict(
                {k: [v] for k, v in row.items()}))[0])
                for st in p.stages]
            for m in nfa.advance(row, i * 10, hits):
                out.append({name: [nfa.event(j)["x"] for j in idxs]
                            for name, idxs in m.events_by_stage.items()})
        # 7 < 2*4=8 rejected for A=4; 9 > 8 matches A=4; 9 <= 14 for A=7
        assert {"A": [4], "B": [9]} in out
        assert {"A": [4], "B": [7]} not in out
        assert {"A": [7], "B": [9]} not in out


def _ticks(topic, prices, syms=None):
    from flink_tpu.connectors.kafka import FakeBroker

    broker = FakeBroker.get("default")
    broker.create_topic(topic, 1)
    n = len(prices)
    ts = np.arange(n, dtype=np.int64) * 1000
    broker.append(topic, 0, RecordBatch.from_pydict(
        {"sym": np.asarray(syms if syms is not None
                           else np.zeros(n), dtype=np.int64),
         "price": np.asarray(prices, dtype=np.float64),
         "ts": ts}, timestamps=ts))
    return ts


class TestMatchRecognizeSQL:
    def _env(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 4}))
        return StreamTableEnvironment(env)

    def _ddl(self, tenv, topic):
        tenv.execute_sql(
            f"CREATE TABLE {topic} (sym BIGINT, price DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            f"WITH ('connector'='kafka', 'topic'='{topic}')")

    def test_v_shape_pattern(self):
        """The reference docs' canonical falling-then-rising query."""
        _ticks("mr1", [10, 9, 8, 7, 8, 9, 12, 11, 10, 13, 14])
        tenv = self._env()
        self._ddl(tenv, "mr1")
        rows = tenv.execute_sql("""
            SELECT sym, start_p, bottom_p, end_p FROM mr1
            MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY ts
              MEASURES FIRST(A.price) AS start_p,
                       LAST(B.price) AS bottom_p,
                       LAST(C.price) AS end_p
              AFTER MATCH SKIP PAST LAST ROW
              PATTERN (A B+ C+)
              DEFINE B AS B.price < A.price,
                     C AS C.price > B.price
            ) AS m
        """).collect()
        assert rows == [
            {"sym": 0, "start_p": 8.0, "bottom_p": 7.0, "end_p": 8.0},
            {"sym": 0, "start_p": 12.0, "bottom_p": 10.0,
             "end_p": 13.0},
        ]

    def test_partitioned_and_quantified(self):
        """Per-partition matching with an exact {n} quantifier and
        aggregate measures."""
        prices = [1, 5, 6, 2, 1, 5, 6, 7, 2]
        syms = [0, 0, 0, 0, 1, 1, 1, 1, 1]
        _ticks("mr2", prices, syms)
        tenv = self._env()
        self._ddl(tenv, "mr2")
        rows = tenv.execute_sql("""
            SELECT sym, n_up, total FROM mr2 MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY ts
              MEASURES COUNT(UP.price) AS n_up, SUM(UP.price) AS total
              AFTER MATCH SKIP PAST LAST ROW
              PATTERN (LO UP{2})
              DEFINE LO AS LO.price < 2,
                     UP AS UP.price > 4
            ) AS m
        """).collect()
        got = {(r["sym"], r["n_up"], r["total"]) for r in rows}
        assert got == {(0, 2, 11.0), (1, 2, 11.0)}

    def test_within_prunes_slow_patterns(self):
        _ticks("mr3", [1, 5, 6])  # ts: 0, 1000, 2000
        tenv = self._env()
        self._ddl(tenv, "mr3")
        rows = tenv.execute_sql("""
            SELECT sym, total FROM mr3 MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY ts
              MEASURES SUM(UP.price) AS total
              PATTERN (LO UP{2})
              WITHIN INTERVAL '1' SECOND
              DEFINE LO AS LO.price < 2, UP AS UP.price > 4
            ) AS m
        """).collect()
        assert rows == []  # the 2 s span exceeds within 1 s

    def test_reluctant_quantifier(self):
        """B+? (reluctant) emits the shortest loop; the SQL default is
        greedy (maximal)."""
        _ticks("mr4", [1, 5, 6, 9])
        tenv = self._env()
        self._ddl(tenv, "mr4")
        greedy_rows = tenv.execute_sql("""
            SELECT sym, cnt FROM mr4 MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY ts
              MEASURES COUNT(UP.price) AS cnt
              AFTER MATCH SKIP PAST LAST ROW
              PATTERN (LO UP+ HI)
              DEFINE LO AS LO.price < 2,
                     UP AS UP.price > 4 AND UP.price < 9,
                     HI AS HI.price >= 9
            ) AS m
        """).collect()
        assert [r["cnt"] for r in greedy_rows] == [2]

    def test_unknown_variable_rejected(self):
        from flink_tpu.table.environment import PlanError

        _ticks("mr5", [1, 2])
        tenv = self._env()
        self._ddl(tenv, "mr5")
        with pytest.raises(PlanError, match="unknown pattern variable"):
            tenv.execute_sql("""
                SELECT sym, x FROM mr5 MATCH_RECOGNIZE (
                  PARTITION BY sym ORDER BY ts
                  MEASURES FIRST(Z.price) AS x
                  PATTERN (A B)
                  DEFINE A AS A.price < 2
                ) AS m
            """)

    def test_order_by_must_be_rowtime(self):
        from flink_tpu.table.environment import PlanError

        _ticks("mr6", [1, 2])
        tenv = self._env()
        self._ddl(tenv, "mr6")
        with pytest.raises(PlanError, match="event-time"):
            tenv.execute_sql("""
                SELECT sym, x FROM mr6 MATCH_RECOGNIZE (
                  PARTITION BY sym ORDER BY price
                  MEASURES FIRST(A.price) AS x
                  PATTERN (A)
                  DEFINE A AS A.price < 2
                ) AS m
            """)


class TestMatchRecognizeDeviceRouting:
    """cep.mode=device routes MATCH_RECOGNIZE onto the mesh NFA engine;
    ineligible patterns fall back LOUDLY to the host operator."""

    _QUERY = """
        SELECT sym, n_up, total FROM {t} MATCH_RECOGNIZE (
          PARTITION BY sym ORDER BY ts
          MEASURES COUNT(UP.price) AS n_up, SUM(UP.price) AS total
          AFTER MATCH SKIP PAST LAST ROW
          PATTERN (LO UP{{2}})
          DEFINE LO AS LO.price < 2,
                 UP AS UP.price > 4
        ) AS m
    """

    def _env(self, mode):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 4, "cep.mode": mode}))
        return StreamTableEnvironment(env)

    def _ddl(self, tenv, topic):
        tenv.execute_sql(
            f"CREATE TABLE {topic} (sym BIGINT, price DOUBLE, "
            "ts BIGINT, WATERMARK FOR ts AS ts) "
            f"WITH ('connector'='kafka', 'topic'='{topic}')")

    def test_device_mode_plans_mesh_operator_bit_identical(self):
        from flink_tpu.cep import mesh_engine

        prices = [1, 5, 6, 2, 1, 5, 6, 7, 2]
        syms = [0, 0, 0, 0, 1, 1, 1, 1, 1]
        _ticks("mrd1", prices, syms)
        tenv = self._env("host")
        self._ddl(tenv, "mrd1")
        host_rows = tenv.execute_sql(
            self._QUERY.format(t="mrd1")).collect()

        _ticks("mrd2", prices, syms)
        tenv = self._env("device")
        self._ddl(tenv, "mrd2")
        before = mesh_engine.host_fallbacks()
        dev_rows = tenv.execute_sql(
            self._QUERY.format(t="mrd2")).collect()
        assert mesh_engine.host_fallbacks() == before  # no fallback
        key = lambda r: (r["sym"], r["n_up"], r["total"])  # noqa: E731
        assert sorted(dev_rows, key=key) == sorted(host_rows, key=key)
        assert len(dev_rows) == 2

    def test_ineligible_pattern_falls_back_loudly(self):
        from flink_tpu.cep import mesh_engine

        # B+ is greedy by SQL default -> outside the bounded-partial
        # device class -> the plan routes to the host operator and the
        # fallback counter ticks (never a job failure)
        _ticks("mrd3", [1, 5, 6, 9])
        tenv = self._env("device")
        self._ddl(tenv, "mrd3")
        before = mesh_engine.host_fallbacks()
        rows = tenv.execute_sql("""
            SELECT sym, cnt FROM mrd3 MATCH_RECOGNIZE (
              PARTITION BY sym ORDER BY ts
              MEASURES COUNT(UP.price) AS cnt
              AFTER MATCH SKIP PAST LAST ROW
              PATTERN (LO UP+ HI)
              DEFINE LO AS LO.price < 2,
                     UP AS UP.price > 4 AND UP.price < 9,
                     HI AS HI.price >= 9
            ) AS m
        """).collect()
        assert mesh_engine.host_fallbacks() == before + 1
        assert [r["cnt"] for r in rows] == [2]
