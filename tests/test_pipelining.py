"""Host/device pipelining: source pump prefetch + adaptive batch sizing.

reference model: AsyncExecutionController.java:57,364-369 (overlap state
I/O with processing), RemoteInputChannel.java:114 (credit-based bounded
in-flight), BufferDebloater.java / BufferSizeEMA.java (latency-targeted
sizing).
"""

import time

import numpy as np
import pytest

from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.debloater import BatchSizeController
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def build(env, total=30_000, num_keys=40, sink=None):
    sink = sink or CollectSink()
    (env.add_source(DataGenSource(total_records=total, num_keys=num_keys,
                                  events_per_second_of_eventtime=20_000),
                    WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key").window(TumblingEventTimeWindows.of(1000)).count()
        .sink_to(sink))
    return sink


def counts(rows):
    return {(int(r["key"]), int(r["window_start"])): int(r["count"])
            for r in rows}


class TestSourcePump:
    def test_pipelined_equals_inline(self):
        """in-flight prefetch must not change results (same batches, same
        watermarks, same windows)."""
        out = {}
        for in_flight in (0, 1, 4):
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 512,
                "execution.pipeline.in-flight-batches": in_flight,
            }))
            sink = build(env)
            env.execute()
            out[in_flight] = counts(sink.rows())
        assert out[0] == out[1] == out[4]
        assert sum(out[0].values()) == 30_000

    def test_checkpoint_positions_are_consumed_prefix(self, tmp_path):
        """With prefetch, a checkpoint must snapshot the CONSUMED source
        position, not the pump's read-ahead — restore after a crash must
        re-read prefetched-but-unprocessed batches exactly once."""
        import os

        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
        from flink_tpu.connectors.two_phase import ExactlyOnceFileSink

        out = str(tmp_path / "out")
        ck = str(tmp_path / "ck")
        flag = str(tmp_path / "crashed.flag")
        total = 20_000

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "execution.pipeline.in-flight-batches": 4,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 3,
            "restart-strategy.max-attempts": 3,
            "restart-strategy.delay-ms": 10,
        }))

        def poison_once(b, flag=flag):
            ts = b.timestamps
            if len(ts) and ts.max() > 900 and not os.path.exists(flag):
                open(flag, "w").write("x")
                raise RuntimeError("injected fault")
            return b

        (env.add_source(DataGenSource(total_records=total, num_keys=10,
                                      events_per_second_of_eventtime=10_000),
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
            .map(poison_once, name="poison")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(500))
            .count()
            .sink_to(ExactlyOnceFileSink(out)))

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            client = cluster.submit(env, "pump-2pc-job")
            st = client.wait(timeout=120)
            assert st["status"] == FINISHED
            assert st["attempt"] >= 1
        finally:
            cluster.shutdown()
        rows = ExactlyOnceFileSink.read_committed_rows(out)
        per_window = {}
        for r in rows:
            k = (int(r["key"]), int(r["window_start"]))
            assert k not in per_window, f"duplicate committed window {k}"
            per_window[k] = int(r["count"])
        assert sum(per_window.values()) == total

    def test_drain_processes_prefetched_batches(self, tmp_path):
        """stop-with-savepoint --drain: batches the pump already read must
        be processed (their source positions are consumed), or their
        records would be lost forever."""
        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
        from flink_tpu.connectors.sinks import JsonLinesFileSink

        import json

        class SlowDataGen(DataGenSource):
            def poll_batch(self, max_records):
                b = super().poll_batch(max_records)
                if b is not None:
                    time.sleep(0.002)
                return b

        total = 12_000
        out = str(tmp_path / "o.jsonl")

        def build_drain(env, out_path, source_cls=SlowDataGen):
            (env.add_source(
                source_cls(total_records=total, num_keys=5,
                           events_per_second_of_eventtime=4000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("key")
                .window(TumblingEventTimeWindows.of(500)).count()
                .sink_to(JsonLinesFileSink(out_path)))

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 128,
            "execution.pipeline.in-flight-batches": 4,
        }))
        build_drain(env, out)
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        sp = str(tmp_path / "sp")
        try:
            client = cluster.submit(env, "drain-job")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    client.stop_with_savepoint(sp, drain=True)
                    break
                except RuntimeError:
                    time.sleep(0.02)
            assert client.wait(timeout=30)["status"] == FINISHED
        finally:
            cluster.shutdown()

        with open(out) as f:
            part1 = counts([json.loads(l) for l in f if l.strip()])
        emitted1 = sum(part1.values())
        assert 0 < emitted1 < total  # genuinely stopped mid-flight

        # drain's no-loss property: every record the source HANDED OUT up
        # to the saved position must be in the flushed output. If the pump's
        # prefetched batches had been dropped, the position (which advanced
        # past them) would exceed the flushed count.
        from flink_tpu.checkpoint.storage import read_snapshot_dir

        states = read_snapshot_dir(sp)
        src_state = next(s for s in states.values() if "source" in s)
        assert src_state["source"]["emitted"] == emitted1


class TestBatchSizeController:
    def test_converges_to_latency_budget(self):
        """At a steady observed rate R, the size converges to about
        R * target * headroom, power-of-two rounded, within bounds."""
        c = BatchSizeController(initial=1 << 17, min_size=256,
                                max_size=1 << 17, target_latency_ms=100)
        # steady 1M records/s: budget 100ms, headroom 0.5 -> ~50k -> 2^15
        for _ in range(30):
            c.observe(c.size, c.size / 1_000_000)
        assert c.size == 1 << 15

    def test_shrinks_under_slow_processing(self):
        c = BatchSizeController(initial=1 << 16, min_size=256,
                                max_size=1 << 16, target_latency_ms=20)
        # 100k records/s: 20ms budget -> ~1k records -> clamps near 2^9
        for _ in range(30):
            c.observe(c.size, c.size / 100_000)
        assert c.size <= 1 << 10
        assert c.size >= 256

    def test_never_leaves_bounds_and_is_power_of_two(self):
        c = BatchSizeController(initial=4096, min_size=512,
                                max_size=8192, target_latency_ms=50)
        rng = np.random.default_rng(0)
        for _ in range(200):
            c.observe(int(rng.integers(1, 10_000)),
                      float(rng.random() * 0.1 + 1e-4))
            assert 512 <= c.size <= 8192
            assert c.size & (c.size - 1) == 0

    def test_adaptive_job_end_to_end(self):
        """A job with a latency target adapts its batch size online and
        still produces exact results."""
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1 << 16,
            "execution.micro-batch.latency-target-ms": 5,
        }))
        sink = build(env, total=60_000)
        result = env.execute()
        assert "effective_batch_size" in result.metrics
        # with a 5ms budget on this workload the initial 64k batch cannot
        # survive: the controller must have shrunk it
        assert result.metrics["effective_batch_size"] < (1 << 16)
        assert sum(counts(sink.rows()).values()) == 60_000
