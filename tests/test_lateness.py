"""Allowed-lateness semantics: retention, late re-firing, no state leaks
(reference: WindowOperator allowedLateness + cleanup timers)."""

import numpy as np

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import (
    CumulativeEventTimeWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.windowing.windower import SliceSharedWindower


def kb(keys, values, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(values, dtype=np.float32)},
        timestamps=ts)


def fired(batches):
    out = {}
    for b in batches:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"], r["window_end"])] = r["sum_v"]
    return out


class TestAllowedLateness:
    def test_late_record_refires_window(self):
        w = SliceSharedWindower(TumblingEventTimeWindows.of(100),
                                SumAggregate("v"), capacity=1024,
                                allowed_lateness=50)
        w.process_batch(kb([1], [1.0], [10]))
        first = fired(w.on_watermark(99))
        assert first == {(1, 0, 100): 1.0}
        # late record within lateness -> updated (re-fired) result
        w.process_batch(kb([1], [2.0], [20]))
        refired = fired(w.on_watermark(120))
        assert refired == {(1, 0, 100): 3.0}
        # past retention (99 + 50) -> dropped
        w.process_batch(kb([1], [4.0], [30]))
        assert w.late_records_dropped == 0
        w.on_watermark(149)  # window cleanup at 99+50=149
        w.process_batch(kb([1], [8.0], [40]))
        assert w.late_records_dropped == 1
        assert fired(w.on_watermark(10**6)) == {}

    def test_zero_lateness_drops_immediately(self):
        w = SliceSharedWindower(TumblingEventTimeWindows.of(100),
                                SumAggregate("v"), capacity=1024)
        w.process_batch(kb([1], [1.0], [10]))
        w.on_watermark(99)
        w.process_batch(kb([1], [2.0], [20]))
        assert w.late_records_dropped == 1
        assert w.table.num_used == 0  # nothing retained

    def test_no_state_leak_with_lateness(self):
        """Slices must be freed once retention passes (the leak the review
        found: records admitted by lateness into slices whose windows all
        fired must not pin slots forever)."""
        w = SliceSharedWindower(SlidingEventTimeWindows.of(200, 100),
                                SumAggregate("v"), capacity=1024,
                                allowed_lateness=100)
        for step in range(20):
            t = step * 100
            w.process_batch(kb([1, 2], [1.0, 1.0], [t + 10, t + 20]))
            w.on_watermark(t + 50)
        w.on_watermark(20 * 100 + 1000)
        assert w.table.num_used == 0
        assert not w.book._slice_last_window

    def test_cumulate_no_leak(self):
        """Cumulate's last_window_ends must be exact or slices leak."""
        a = CumulativeEventTimeWindows(max_size_ms=300, step_ms=100)
        # vectorized last window end must agree with the scalar path
        ses = np.array([100, 200, 300, 400, 600], dtype=np.int64)
        want = [a.window_ends_for_slice(int(s))[-1] for s in ses]
        got = a.last_window_ends(ses).tolist()
        assert got == want
        w = SliceSharedWindower(a, SumAggregate("v"), capacity=1024,
                                allowed_lateness=50)
        for step in range(10):
            t = step * 100
            w.process_batch(kb([1], [1.0], [t + 10]))
            w.on_watermark(t)
        w.on_watermark(10**6)
        assert w.table.num_used == 0

    def test_sliding_last_window_ends_vectorized_matches_scalar(self):
        for size, slide in [(300, 100), (500, 200), (1000, 300), (100, 100)]:
            a = SlidingEventTimeWindows.of(size, slide)
            ses = np.arange(1, 30) * a.slice_width
            want = [a.window_ends_for_slice(int(s))[-1] for s in ses]
            got = a.last_window_ends(ses).tolist()
            assert got == want, (size, slide)


class TestSessionLateness:
    def test_session_lateness_allows_new_session(self):
        from flink_tpu.windowing.sessions import SessionWindower

        w = SessionWindower(gap=50, agg=SumAggregate("v"), capacity=1024,
                            allowed_lateness=100)
        w.process_batch(kb([1], [1.0], [0]))
        w.on_watermark(200)
        # within lateness: accepted as a new session
        w.process_batch(kb([1], [2.0], [160]))
        assert w.late_records_dropped == 0
        # beyond lateness: dropped
        w.process_batch(kb([1], [4.0], [40]))
        assert w.late_records_dropped == 1
