"""ML model functions (reference: flink-models + MLPredictRunner /
AsyncMLPredictRunner + CREATE MODEL DDL + SQL ML_PREDICT)."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.ml import (
    FunctionModel,
    JaxModel,
    MLPredictOperator,
    RemoteModel,
)
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.table.environment import StreamTableEnvironment

#: a module-level factory for the CREATE MODEL 'python' provider
def doubler_model():
    return FunctionModel(
        lambda ins: {"doubled": ins["x"] * 2},
        input_names=["x"], output_names=["doubled"])


def _rows(n=20):
    return [{"price": float(i), "qty": i % 5, "ts": i * 100}
            for i in range(n)]


def make_tenv(**conf):
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 7, **conf}))
    return StreamTableEnvironment(env), env


class TestModels:
    def test_jax_model_batched_inference(self):
        import jax.numpy as jnp

        # a tiny linear model: y = x @ w + b, jitted, sticky-padded
        params = {"w": jnp.asarray([[2.0], [1.0]]), "b": jnp.asarray(0.5)}
        model = JaxModel(
            lambda p, x: (x @ p["w"])[:, 0] + p["b"],
            params, input_names=["x"], output_names=["y"])
        for n in (5, 9, 6):  # varying batch sizes share one executable
            x = np.arange(2 * n, dtype=np.float32).reshape(n, 2)
            out = model.predict({"x": x})
            np.testing.assert_allclose(
                out["y"], x @ np.array([[2.0], [1.0]])[:, 0] + 0.5,
                rtol=1e-5)

    def test_operator_appends_outputs(self):
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.runtime.operators import OperatorContext

        op = MLPredictOperator(doubler_model(), input_fields=["price"])
        op.open(OperatorContext())
        batch = RecordBatch.from_pydict(
            {"price": np.arange(4, dtype=np.float32)})
        out = op.process_batch(batch)[0]
        np.testing.assert_array_equal(out["doubled"],
                                      np.arange(4, dtype=np.float32) * 2)
        assert "price" in out.columns  # inputs preserved

    def test_descriptor_arity_checked(self):
        with pytest.raises(ValueError, match="expects 1 inputs"):
            MLPredictOperator(doubler_model(),
                              input_fields=["a", "b"])


class TestDataStreamApi:
    @pytest.mark.parametrize("asynchronous", [False, True])
    def test_ml_predict_in_pipeline(self, asynchronous):
        t_env, env = make_tenv()
        sink = CollectSink()
        env.from_source(
            __import__("flink_tpu.connectors.sources",
                       fromlist=["CollectionSource"])
            .CollectionSource.of_rows(_rows(), batch_size=7),
            WatermarkStrategy.for_monotonous_timestamps()
            .with_timestamp_field("ts")) \
            .ml_predict(doubler_model(), input_fields=["price"],
                        asynchronous=asynchronous) \
            .sink_to(sink)
        env.execute("ml")
        rows = sink.result().to_rows()
        assert len(rows) == 20
        assert all(r["doubled"] == r["price"] * 2 for r in rows)

    def test_remote_model_async_bounded(self):
        """RemoteModel through the async runner: calls overlap but results
        stay ordered."""
        import time

        calls = []

        def client(inputs):
            calls.append(len(inputs["x"]))
            time.sleep(0.01)
            return {"score": inputs["x"] + 1}

        model = RemoteModel(client, input_names=["x"],
                            output_names=["score"])
        t_env, env = make_tenv()
        sink = CollectSink()
        from flink_tpu.connectors.sources import CollectionSource

        env.from_source(
            CollectionSource.of_rows(
                [{"price": float(i)} for i in range(30)], batch_size=5),
            WatermarkStrategy.for_monotonous_timestamps()) \
            .ml_predict(model, input_fields=["price"],
                        asynchronous=True, capacity=3) \
            .sink_to(sink)
        env.execute("remote")
        rows = sink.result().to_rows()
        assert [r["score"] for r in rows] == [float(i) + 1
                                              for i in range(30)]
        assert sum(calls) == 30


class TestSqlMlPredict:
    def test_ml_predict_tvf(self):
        t_env, env = make_tenv()
        t_env.create_temporary_view(
            "orders", t_env.from_collection(_rows(), timestamp_field="ts"))
        t_env.create_temporary_model("scorer", doubler_model())
        out = t_env.execute_sql(
            "SELECT price, doubled FROM ML_PREDICT(TABLE orders, "
            "MODEL scorer, DESCRIPTOR(price)) WHERE doubled > 10"
        ).collect()
        assert len(out) == 14  # price > 5
        assert all(r["doubled"] == r["price"] * 2 for r in out)

    def test_ml_predict_feeds_aggregate(self):
        t_env, env = make_tenv()
        t_env.create_temporary_view(
            "orders", t_env.from_collection(_rows(), timestamp_field="ts"))
        t_env.create_temporary_model("scorer", doubler_model())
        out = t_env.execute_sql(
            "SELECT qty, SUM(doubled) AS s FROM ML_PREDICT("
            "TABLE orders, MODEL scorer, DESCRIPTOR(price)) "
            "GROUP BY qty").collect()
        got = {r["qty"]: r["s"] for r in out}
        want = {}
        for r in _rows():
            want[r["qty"]] = want.get(r["qty"], 0.0) + r["price"] * 2
        assert got == want

    def test_create_model_ddl(self):
        t_env, env = make_tenv()
        t_env.create_temporary_view(
            "orders", t_env.from_collection(_rows(), timestamp_field="ts"))
        t_env.execute_sql(
            "CREATE MODEL scorer WITH ('provider' = 'python', "
            "'entry' = 'tests.test_ml_predict:doubler_model')")
        out = t_env.execute_sql(
            "SELECT doubled FROM ML_PREDICT(TABLE orders, MODEL scorer, "
            "DESCRIPTOR(price))").collect()
        assert len(out) == 20

    def test_unknown_model_precise_error(self):
        t_env, env = make_tenv()
        t_env.create_temporary_view(
            "orders", t_env.from_collection(_rows(), timestamp_field="ts"))
        with pytest.raises(KeyError, match="unknown model 'nope'"):
            t_env.execute_sql(
                "SELECT * FROM ML_PREDICT(TABLE orders, MODEL nope, "
                "DESCRIPTOR(price))")

    def test_unknown_provider_rejected(self):
        t_env, env = make_tenv()
        with pytest.raises(ValueError, match="unknown model provider"):
            t_env.execute_sql(
                "CREATE MODEL m WITH ('provider' = 'openai')")
