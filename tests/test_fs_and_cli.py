"""FileSystem abstraction + CLI frontend.

reference models: flink-core core/fs tests; flink-clients CliFrontend
tests (run/list/cancel/savepoint command surface).
"""

import json
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu.cli import main as cli_main
from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
from flink_tpu.connectors.sinks import JsonLinesFileSink
from flink_tpu.core.config import Configuration
from flink_tpu.core.fs import (
    InMemoryFileSystem,
    get_filesystem,
    register_filesystem,
)
from flink_tpu.core.records import RecordBatch
from flink_tpu.datastream.environment import StreamExecutionEnvironment


class TestFileSystem:
    def test_scheme_dispatch(self, tmp_path):
        fs, local = get_filesystem(str(tmp_path / "x"))
        assert local == str(tmp_path / "x")
        fs2, local2 = get_filesystem("mem://bucket/a/b")
        assert local2 == "bucket/a/b"
        with pytest.raises(ValueError, match="no filesystem"):
            get_filesystem("s3://nope/x")

    def test_memory_fs_roundtrip(self):
        fs = InMemoryFileSystem()
        with fs.open("a/b/data.bin", "wb") as f:
            f.write(b"hello")
        assert fs.exists("a/b/data.bin")
        with fs.open("a/b/data.bin", "rb") as f:
            assert f.read() == b"hello"
        with fs.open("a/b/data.bin", "ab") as f:
            f.write(b" world")
        with fs.open("a/b/data.bin", "rb") as f:
            assert f.read() == b"hello world"
        assert fs.listdir("a") == ["b"]
        fs.rename("a/b/data.bin", "a/b/renamed.bin")
        assert not fs.exists("a/b/data.bin")
        fs.delete("a", recursive=True)
        assert not fs.exists("a/b/renamed.bin")

    def test_memory_fs_flush_makes_writes_visible(self):
        """flush() must publish to the store (local-FS visibility
        semantics): write-then-flush patterns (JsonLinesFileSink) may never
        reach close()."""
        fs = InMemoryFileSystem()
        w = fs.open("a/log.jsonl", "wb")
        w.write(b"row1\n")
        w.flush()
        with fs.open("a/log.jsonl", "rb") as r:
            assert r.read() == b"row1\n"  # visible without close
        w.write(b"row2\n")
        w.flush()
        with fs.open("a/log.jsonl", "rb") as r:
            assert r.read() == b"row1\nrow2\n"
        w.close()

    def test_sink_writes_through_mem_scheme(self):
        sink = JsonLinesFileSink("mem://out/rows.jsonl")
        sink.open()
        sink.write(RecordBatch.from_pydict(
            {"k": np.array([1, 2]), "v": np.array([0.5, 1.5])}))
        sink.close()
        rows = JsonLinesFileSink.read_rows("mem://out/rows.jsonl")
        assert len(rows) == 2 and rows[0]["k"] == 1


PIPELINE = """
import numpy as np
from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.connectors.sinks import JsonLinesFileSink
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows
import sys

env = StreamExecutionEnvironment()
(env.add_source(DataGenSource(total_records=2000, num_keys=5,
                              events_per_second_of_eventtime=2000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
 .key_by("key").window(TumblingEventTimeWindows.of(500)).count()
 .sink_to(JsonLinesFileSink(sys.argv[1])))
r = env.execute("cli-job")
print("BATCH", env.batch_size)
"""


class TestCli:
    def test_run_with_dynamic_props(self, tmp_path, capsys):
        import os

        script = tmp_path / "pipe.py"
        script.write_text(PIPELINE)
        out = str(tmp_path / "out.jsonl")
        rc = cli_main(["run", str(script), out,
                       "-D", "execution.micro-batch.size=123"])
        assert rc == 0
        captured = capsys.readouterr()
        assert "BATCH 123" in captured.out  # -D reached the environment
        rows = JsonLinesFileSink.read_rows(out)
        assert sum(int(r["count"]) for r in rows) == 2000
        # `run` restores the ambient environment after the script
        assert "FLINK_TPU_DYNAMIC_PROPS" not in os.environ


    def test_rest_actions(self, tmp_path, capsys):
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        class Slow(DataGenSource):
            def poll_batch(self, n):
                b = super().poll_batch(n)
                if b is not None:
                    time.sleep(0.01)
                return b

        cluster = MiniCluster(Configuration({"rest.port": 0}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 256}))
            (env.add_source(Slow(total_records=50_000, num_keys=5,
                                 events_per_second_of_eventtime=5000),
                            WatermarkStrategy.for_bounded_out_of_orderness(0))
             .key_by("key").window(TumblingEventTimeWindows.of(1000)).count()
             .sink_to(JsonLinesFileSink(str(tmp_path / "o.jsonl"))))
            client = cluster.submit(env, "rest-job")
            rest = f"127.0.0.1:{cluster.rest_port}"

            # list + info via CLI
            assert cli_main(["list", "--rest", rest]) == 0
            assert client.job_id in capsys.readouterr().out
            assert cli_main(["info", client.job_id, "--rest", rest]) == 0
            capsys.readouterr()  # drain before parsing savepoint output

            # savepoint via CLI (retry until RUNNING)
            sp = str(tmp_path / "sp")
            deadline = time.monotonic() + 10
            ok = False
            while time.monotonic() < deadline:
                try:
                    rc = cli_main(["savepoint", client.job_id, sp,
                                   "--rest", rest])
                    ok = rc == 0
                    break
                except Exception:
                    time.sleep(0.05)
            assert ok
            assert json.loads(
                capsys.readouterr().out)["savepoint"] == sp

            # cancel via CLI
            assert cli_main(["cancel", client.job_id, "--rest", rest]) == 0
            st = client.wait(timeout=20)
            assert st["status"] in ("CANCELED", "FINISHED")

            # inspect the savepoint via CLI
            assert cli_main(["inspect", sp]) == 0
            assert "keyed state" in capsys.readouterr().out
        finally:
            cluster.shutdown()
