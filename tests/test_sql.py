"""SQL layer tests: parser, window TVF aggregation, group-by, Top-N, joins.

Mirrors the reference's table-runtime test strategy (SURVEY.md §4): semantic
checks against a hand-computed oracle over small in-memory collections.
"""

import numpy as np
import pytest

from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.table import StreamTableEnvironment
from flink_tpu.table.sql_parser import (
    CreateView,
    Join,
    NamedTable,
    SelectStmt,
    SubQuery,
    WindowTVF,
    parse,
)


def _bids(rows):
    """rows: (auction, price, ts_ms)"""
    return [{"auction": a, "price": p, "ts": t} for a, p, t in rows]


def make_tenv():
    env = StreamExecutionEnvironment.get_execution_environment()
    return StreamTableEnvironment.create(env)


# ---------------------------------------------------------------- parser


class TestParser:
    def test_simple_select(self):
        stmt = parse("SELECT a, b + 1 AS c FROM t WHERE a > 2")
        assert isinstance(stmt, SelectStmt)
        assert len(stmt.items) == 2
        assert stmt.items[1].alias == "c"
        assert isinstance(stmt.table, NamedTable)
        assert stmt.where is not None

    def test_tumble_tvf(self):
        stmt = parse(
            "SELECT auction, COUNT(*) AS num, window_start, window_end "
            "FROM TABLE(TUMBLE(TABLE bid, DESCRIPTOR(ts), "
            "INTERVAL '10' SECOND)) "
            "GROUP BY auction, window_start, window_end")
        tvf = stmt.table
        assert isinstance(tvf, WindowTVF)
        assert tvf.kind == "TUMBLE"
        assert tvf.size_ms == 10_000
        assert tvf.time_col == "ts"

    def test_hop_tvf_argument_order(self):
        stmt = parse(
            "SELECT COUNT(*) FROM TABLE(HOP(TABLE bid, DESCRIPTOR(ts), "
            "INTERVAL '2' SECOND, INTERVAL '10' SECOND)) "
            "GROUP BY window_start, window_end")
        tvf = stmt.table
        assert tvf.kind == "HOP"
        assert tvf.slide_ms == 2_000
        assert tvf.size_ms == 10_000

    def test_join_and_subquery(self):
        stmt = parse(
            "SELECT * FROM (SELECT a FROM t1) x JOIN t2 ON x.a = t2.b")
        assert isinstance(stmt.table, Join)
        assert isinstance(stmt.table.left, SubQuery)

    def test_create_view(self):
        stmt = parse("CREATE VIEW v AS SELECT a FROM t")
        assert isinstance(stmt, CreateView)
        assert stmt.name == "v"

    def test_over_clause(self):
        stmt = parse(
            "SELECT auction, ROW_NUMBER() OVER (PARTITION BY window_end "
            "ORDER BY num DESC) AS rownum FROM ab")
        over = stmt.items[1].expr
        assert over.func == "ROW_NUMBER"
        assert len(over.partition_by) == 1
        assert over.order_by[0][1] is True  # descending

    def test_case_and_functions(self):
        stmt = parse("SELECT CASE WHEN a > 1 THEN 'x' ELSE 'y' END, "
                     "MOD(a, 3), CAST(a AS BIGINT) FROM t")
        assert len(stmt.items) == 3


# ------------------------------------------------------------ execution


class TestSqlExecution:
    def test_projection_and_where(self):
        t_env = make_tenv()
        table = t_env.from_collection(
            [{"a": i, "b": 10 * i} for i in range(10)])
        t_env.create_temporary_view("t", table)
        rows = t_env.execute_sql(
            "SELECT a, b * 2 AS b2 FROM t WHERE a >= 5").collect()
        assert [r["a"] for r in rows] == [5, 6, 7, 8, 9]
        assert [r["b2"] for r in rows] == [100, 120, 140, 160, 180]

    def test_tumble_count_sum(self):
        t_env = make_tenv()
        rows = _bids([(1, 10, 1_000), (1, 20, 2_000), (2, 5, 3_000),
                      (1, 7, 11_000), (2, 9, 12_000)])
        table = t_env.from_collection(rows, timestamp_field="ts")
        t_env.create_temporary_view("bid", table)
        out = t_env.execute_sql(
            "SELECT auction, COUNT(*) AS num, SUM(price) AS total, "
            "window_end FROM TABLE(TUMBLE(TABLE bid, DESCRIPTOR(ts), "
            "INTERVAL '10' SECOND)) "
            "GROUP BY auction, window_start, window_end").collect()
        got = {(r["auction"], r["window_end"]): (r["num"], r["total"])
               for r in out}
        assert got[(1, 10_000)] == (2, 30.0)
        assert got[(2, 10_000)] == (1, 5.0)
        assert got[(1, 20_000)] == (1, 7.0)
        assert got[(2, 20_000)] == (1, 9.0)

    def test_hop_window_agg(self):
        t_env = make_tenv()
        rows = _bids([(1, 1, 1_000), (1, 1, 5_000), (1, 1, 9_000)])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT auction, COUNT(*) AS num, window_end FROM "
            "TABLE(HOP(TABLE bid, DESCRIPTOR(ts), INTERVAL '5' SECOND, "
            "INTERVAL '10' SECOND)) "
            "GROUP BY auction, window_start, window_end").collect()
        got = {r["window_end"]: r["num"] for r in out}
        # HOP windows (end -> contents): 5s->{1s}, 10s->{1,5,9}, 15s->{5,9}(wait)
        assert got[5_000] == 1
        assert got[10_000] == 3
        assert got[15_000] == 2

    def test_group_by_no_window_upsert(self):
        t_env = make_tenv()
        rows = _bids([(1, 10, 1_000), (2, 20, 2_000), (1, 30, 3_000)])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT auction, SUM(price) AS total FROM bid "
            "GROUP BY auction").collect()
        got = {r["auction"]: r["total"] for r in out}
        assert got == {1: 40.0, 2: 20.0}

    def test_global_aggregate(self):
        t_env = make_tenv()
        rows = _bids([(1, 10, 1_000), (2, 20, 2_000), (1, 30, 3_000)])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT COUNT(*) AS n, MAX(price) AS top FROM bid").collect()
        assert len(out) == 1
        assert out[0]["n"] == 3
        assert out[0]["top"] == 30.0

    def test_having(self):
        t_env = make_tenv()
        rows = _bids([(1, 10, 1_000), (1, 20, 2_000), (2, 5, 3_000)])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT auction, COUNT(*) AS num, window_end FROM "
            "TABLE(TUMBLE(TABLE bid, DESCRIPTOR(ts), INTERVAL '10' SECOND)) "
            "GROUP BY auction, window_start, window_end "
            "HAVING COUNT(*) > 1").collect()
        assert len(out) == 1
        assert out[0]["auction"] == 1

    def test_top_n_hot_items_q5_pattern(self):
        """Nexmark Q5 shape: hottest auction per HOP window via Top-N."""
        t_env = make_tenv()
        rows = _bids([
            (1, 1, 1_000), (1, 1, 2_000), (2, 1, 3_000),   # w10: a1=2, a2=1
            (2, 1, 11_000), (2, 1, 12_000), (1, 1, 13_000),  # w20: a2=2, a1=1
        ])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        t_env.execute_sql(
            "CREATE VIEW AuctionBids AS "
            "SELECT auction, COUNT(*) AS num, window_start, window_end "
            "FROM TABLE(TUMBLE(TABLE bid, DESCRIPTOR(ts), "
            "INTERVAL '10' SECOND)) "
            "GROUP BY auction, window_start, window_end")
        out = t_env.execute_sql(
            "SELECT auction, num, window_end FROM ("
            "  SELECT auction, num, window_end, ROW_NUMBER() OVER ("
            "    PARTITION BY window_end ORDER BY num DESC) AS rownum"
            "  FROM AuctionBids) WHERE rownum <= 1").collect()
        got = {r["window_end"]: r["auction"] for r in out}
        assert got[10_000] == 1
        assert got[20_000] == 2

    def test_interval_join_q7_pattern(self):
        """Nexmark Q7 shape: bids joined with the per-window max price."""
        t_env = make_tenv()
        rows = _bids([(1, 10, 1_000), (2, 99, 2_000), (3, 50, 3_000),
                      (4, 80, 11_000), (5, 70, 12_000)])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        t_env.execute_sql(
            "CREATE VIEW MaxPrices AS "
            "SELECT MAX(price) AS maxprice, window_end "
            "FROM TABLE(TUMBLE(TABLE bid, DESCRIPTOR(ts), "
            "INTERVAL '10' SECOND)) GROUP BY window_start, window_end")
        out = t_env.execute_sql(
            "SELECT B.auction, B.price FROM bid B JOIN MaxPrices M "
            "ON B.price = M.maxprice AND B.ts BETWEEN "
            "M.window_end - INTERVAL '10' SECOND AND M.window_end"
        ).collect()
        got = {r["auction"]: r["price"] for r in out}
        assert 2 in got and got[2] == 99
        assert 4 in got and got[4] == 80
        assert 1 not in got and 3 not in got and 5 not in got

    def test_order_by_limit(self):
        t_env = make_tenv()
        table = t_env.from_collection(
            [{"a": i, "b": (7 * i) % 10} for i in range(10)])
        t_env.create_temporary_view("t", table)
        rows = t_env.execute_sql(
            "SELECT a, b FROM t ORDER BY b DESC LIMIT 3").collect()
        assert [r["b"] for r in rows] == [9, 8, 7]

    def test_session_window_sql(self):
        t_env = make_tenv()
        rows = _bids([(1, 1, 1_000), (1, 1, 2_000), (1, 1, 30_000)])
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT auction, COUNT(*) AS num, window_start, window_end "
            "FROM TABLE(SESSION(TABLE bid, DESCRIPTOR(ts), "
            "INTERVAL '5' SECOND)) "
            "GROUP BY auction, window_start, window_end").collect()
        nums = sorted(r["num"] for r in out)
        assert nums == [1, 2]

    def test_case_expression(self):
        t_env = make_tenv()
        table = t_env.from_collection([{"a": i} for i in range(6)])
        t_env.create_temporary_view("t", table)
        rows = t_env.execute_sql(
            "SELECT a, CASE WHEN a < 3 THEN 0 ELSE 1 END AS bucket "
            "FROM t").collect()
        assert [r["bucket"] for r in rows] == [0, 0, 0, 1, 1, 1]

    def test_distinct(self):
        t_env = make_tenv()
        table = t_env.from_collection(
            [{"a": x} for x in [1, 2, 2, 3, 3, 3]])
        t_env.create_temporary_view("t", table)
        rows = t_env.execute_sql("SELECT DISTINCT a FROM t").collect()
        assert sorted(r["a"] for r in rows) == [1, 2, 3]
