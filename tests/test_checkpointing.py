"""Checkpoint / restore end-to-end: crash mid-job, restore from the latest
checkpoint, verify exactly-once *state* semantics (window results identical
to an uninterrupted run). Mirrors the reference's recovery ITCases
(flink-tests/.../checkpointing/, recovery/)."""

import os

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.checkpoint.storage import CheckpointStorage
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


class FailingMap:
    """Raises after letting ``fail_after`` records through (fault injection,
    like throwing UDFs in the reference's recovery tests)."""

    def __init__(self, fail_after):
        self.seen = 0
        self.fail_after = fail_after
        self.armed = True

    def __call__(self, batch):
        self.seen += len(batch)
        if self.armed and self.seen > self.fail_after:
            raise RuntimeError("injected failure")
        return batch


def build_pipeline(env, sink, assigner, total=50_000, fail_after=None):
    src = DataGenSource(total_records=total, num_keys=500,
                        events_per_second_of_eventtime=10_000, seed=11)
    ds = env.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
    if fail_after is not None:
        ds = ds.map(FailingMap(fail_after), name="failmap")
    (ds.key_by("key")
       .window(assigner)
       .sum("value")
       .sink_to(sink))


def collect_results(sink):
    out = {}
    for r in sink.result().to_rows():
        # last write wins: re-fired windows overwrite (exactly-once state)
        out[(r["key"], r["window_start"], r["window_end"])] = round(
            r["sum_value"], 3)
    return out


@pytest.mark.parametrize("assigner_factory", [
    lambda: TumblingEventTimeWindows.of(1000),
    lambda: SlidingEventTimeWindows.of(2000, 1000),
    lambda: EventTimeSessionWindows.with_gap(40),
])
def test_crash_restore_matches_clean_run(tmp_path, assigner_factory):
    ckpt = str(tmp_path / "ckpts")

    # clean reference run
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1000}))
    clean_sink = CollectSink()
    build_pipeline(env, clean_sink, assigner_factory())
    env.execute("clean")
    expected = collect_results(clean_sink)
    assert expected

    # run with checkpoints + injected failure
    conf = Configuration({
        "execution.micro-batch.size": 1000,
        "state.checkpoints.dir": ckpt,
        "execution.checkpointing.every-n-source-batches": 5,
    })
    env2 = StreamExecutionEnvironment(conf)
    sink2 = CollectSink()
    build_pipeline(env2, sink2, assigner_factory(), fail_after=30_000)
    with pytest.raises(RuntimeError, match="injected failure"):
        env2.execute("crashing")
    store = CheckpointStorage(ckpt)
    assert store.latest_checkpoint_id() is not None

    # restore and finish (same graph shape, fresh operators, no fault)
    env3 = StreamExecutionEnvironment(conf)
    sink3 = CollectSink()
    src = DataGenSource(total_records=50_000, num_keys=500,
                        events_per_second_of_eventtime=10_000, seed=11)
    ds = env3.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
    ds = ds.map(lambda b: b, name="failmap")  # same stable id, benign
    (ds.key_by("key").window(assigner_factory()).sum("value").sink_to(sink3))
    env3.execute("restored", restore_from=ckpt)

    # windows fired before the checkpoint are not re-emitted after restore;
    # merge the two sinks (crashing run emitted the early windows)
    got = collect_results(sink2)
    got.update(collect_results(sink3))
    assert got == expected


def test_restore_missing_checkpoint_raises(tmp_path):
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 100}))
    sink = CollectSink()
    build_pipeline(env, sink, TumblingEventTimeWindows.of(1000), total=100)
    with pytest.raises(RuntimeError, match="no checkpoint"):
        env.execute(restore_from=str(tmp_path / "nothing"))


def test_checkpoint_retention(tmp_path):
    ckpt = str(tmp_path / "ck")
    conf = Configuration({
        "execution.micro-batch.size": 200,
        "state.checkpoints.dir": ckpt,
        "execution.checkpointing.every-n-source-batches": 2,
        "execution.checkpointing.retained": 2,
    })
    env = StreamExecutionEnvironment(conf)
    sink = CollectSink()
    build_pipeline(env, sink, TumblingEventTimeWindows.of(1000), total=10_000)
    env.execute()
    names = sorted(os.listdir(ckpt))
    assert len([n for n in names if n.startswith("chk-")]) <= 2
