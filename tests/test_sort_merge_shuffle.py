"""Sort-merge (blocking, file-backed) shuffle.

reference: io/network/partition/SortMergeResultPartition.java — one
spill file per producer partition, regions indexed by subpartition,
sequential consumer reads.
"""

import numpy as np

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.shuffle_spi import (
    END_OF_PARTITION,
    create_shuffle_service,
)
from flink_tpu.runtime.sort_merge_shuffle import SortMergeShuffleService
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _b(vals):
    return RecordBatch({"x": np.asarray(vals, dtype=np.int64)})


class TestSortMergeUnit:
    def test_roundtrip_multiple_regions_and_order(self, tmp_path):
        svc = SortMergeShuffleService(str(tmp_path), memory_budget_bytes=1)
        w = svc.create_partition("p0", 2)  # budget 1 => region per emit
        w.emit(0, _b([1, 2]))
        w.emit(1, _b([10]))
        w.emit(0, _b([3]))
        w.close()
        g0 = svc.create_gate(["p0"], 0)
        got0 = []
        while True:
            entry = g0.poll(timeout=1.0)
            assert entry is not None
            ch, item = entry
            if item is END_OF_PARTITION:
                break
            got0.extend(item["x"].tolist())
        assert got0 == [1, 2, 3]  # emission order within subpartition
        g1 = svc.create_gate(["p0"], 1)
        ch, item = g1.poll(timeout=1.0)
        assert item["x"].tolist() == [10]
        svc.close()

    def test_event_order_preserved_relative_to_data(self, tmp_path):
        svc = SortMergeShuffleService(str(tmp_path),
                                      memory_budget_bytes=1 << 20)
        w = svc.create_partition("p0", 1)
        w.emit(0, _b([1]))
        w.broadcast_event("marker")  # forces the buffered data out first
        w.emit(0, _b([2]))
        w.close()
        g = svc.create_gate(["p0"], 0)
        seq = []
        while True:
            ch, item = g.poll(timeout=1.0)
            if item is END_OF_PARTITION:
                break
            seq.append(item if isinstance(item, str)
                       else tuple(item["x"].tolist()))
        assert seq == [(1,), "marker", (2,)]
        svc.close()

    def test_consumer_before_producer_and_streaming_reads(self, tmp_path):
        svc = SortMergeShuffleService(str(tmp_path), memory_budget_bytes=1)
        g = svc.create_gate(["late"], 0)     # gate first
        assert g.poll(timeout=0.0) is None   # nothing yet, no block
        w = svc.create_partition("late", 1)
        w.emit(0, _b([7]))
        w._flush()
        # region readable BEFORE the producer finishes (hybrid property)
        ch, item = g.poll(timeout=1.0)
        assert item["x"].tolist() == [7]
        w.close()
        ch, item = g.poll(timeout=1.0)
        assert item is END_OF_PARTITION
        svc.close()

    def test_registry(self):
        svc = create_shuffle_service("sort-merge")
        assert isinstance(svc, SortMergeShuffleService)
        svc.close()


def _run_pipeline(shuffle: str, tmp_path):
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1000,
        "state.slot-table.capacity": 8192,
        "execution.stage-parallelism": 3,
        "shuffle.service": shuffle,
    }))
    sink = CollectSink()
    src = DataGenSource(total_records=30_000, num_keys=300,
                        events_per_second_of_eventtime=10_000, seed=5)
    (env.from_source(src,
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key").window(TumblingEventTimeWindows.of(1000))
        .sum("value").sink_to(sink))
    env.execute(f"sm-{shuffle}")
    b = sink.result()
    return sorted(zip(b["key"].tolist(), b["window_start"].tolist(),
                      np.round(b["sum_value"], 6).tolist()))


def test_stage_parallel_pipeline_matches_local_shuffle(tmp_path):
    """The same keyed stage-parallel job through sort-merge == through
    the pipelined local shuffle."""
    assert _run_pipeline("sort-merge", tmp_path) \
        == _run_pipeline("local", tmp_path)
