"""OVER windowed aggregations (ROWS/RANGE BETWEEN ... PRECEDING).

reference: StreamExecOverAggregate ->
RowTimeRowsBoundedPrecedingFunction / RowTimeRangeBoundedPrecedingFunction /
RowTimeRangeUnboundedPrecedingFunction in flink-table-runtime."""

import collections

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.core.records import RecordBatch
from flink_tpu.table.environment import StreamTableEnvironment


def _mk_table(tenv, topic, ks, vs, ts, parts=2):
    from flink_tpu.connectors.kafka import FakeBroker

    broker = FakeBroker.get("default")
    broker.create_topic(topic, parts)
    for p in range(parts):
        m = ks % parts == p
        broker.append(topic, p, RecordBatch.from_pydict(
            {"key": ks[m], "value": vs[m], "ts": ts[m]},
            timestamps=ts[m]))
    tenv.execute_sql(
        f"CREATE TABLE {topic} (key BIGINT, value DOUBLE, ts BIGINT, "
        "WATERMARK FOR ts AS ts) "
        f"WITH ('connector'='kafka', 'topic'='{topic}')")


def _data(n=3000, keys=25, seed=11):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, keys, n).astype(np.int64)
    vs = np.round(rng.random(n), 4)
    ts = (np.arange(n, dtype=np.int64) * 7)  # unique, ordered
    return ks, vs, ts


def _oracle(ks, vs, ts, mode, preceding, func):
    """Frames per key over (ts-sorted) rows; unique ts -> unambiguous."""
    per_key = collections.defaultdict(list)
    for k, v, t in sorted(zip(ks, vs, ts), key=lambda r: (r[0], r[2])):
        per_key[k].append((t, v))
    out = {}
    for k, rows in per_key.items():
        tss = [t for t, _ in rows]
        vals = [v for _, v in rows]
        for i in range(len(rows)):
            if preceding is None:
                lo = 0
            elif mode == "ROWS":
                lo = max(i - preceding, 0)
            else:
                lo = next(j for j in range(i + 1)
                          if tss[j] >= tss[i] - preceding)
            frame = vals[lo:i + 1]
            if func == "SUM":
                r = sum(frame)
            elif func == "COUNT":
                r = float(len(frame))
            elif func == "AVG":
                r = sum(frame) / len(frame)
            elif func == "MIN":
                r = min(frame)
            else:
                r = max(frame)
            out[(k, tss[i])] = r
    return out


def _run(sql, topic_data, conf=None):
    base = {"execution.micro-batch.size": 257}
    base.update(conf or {})
    env = StreamExecutionEnvironment(Configuration(base))
    tenv = StreamTableEnvironment(env)
    _mk_table(tenv, *topic_data)
    return tenv.execute_sql(sql).collect()


class TestOverAgg:
    @pytest.mark.parametrize("func", ["SUM", "AVG", "MIN", "MAX"])
    def test_rows_preceding(self, func):
        ks, vs, ts = _data()
        rows = _run(
            f"SELECT key, ts, {func}(value) OVER (PARTITION BY key "
            "ORDER BY ts ROWS BETWEEN 10 PRECEDING AND CURRENT ROW) "
            f"AS r FROM t_{func.lower()}",
            (f"t_{func.lower()}", ks, vs, ts))
        oracle = _oracle(ks, vs, ts, "ROWS", 10, func)
        assert len(rows) == len(ks)
        for r in rows:
            assert r["r"] == pytest.approx(
                oracle[(r["key"], r["ts"])], rel=1e-6), r

    def test_range_interval_preceding(self):
        ks, vs, ts = _data()
        rows = _run(
            "SELECT key, ts, SUM(value) OVER (PARTITION BY key "
            "ORDER BY ts RANGE BETWEEN INTERVAL '1' SECOND PRECEDING "
            "AND CURRENT ROW) AS r FROM t_range",
            ("t_range", ks, vs, ts))
        oracle = _oracle(ks, vs, ts, "RANGE", 1000, "SUM")
        for r in rows:
            assert r["r"] == pytest.approx(
                oracle[(r["key"], r["ts"])], rel=1e-6), r

    def test_unbounded_preceding_default_frame(self):
        ks, vs, ts = _data(n=1500)
        # no frame clause -> RANGE UNBOUNDED PRECEDING (SQL default)
        rows = _run(
            "SELECT key, ts, COUNT(*) OVER (PARTITION BY key "
            "ORDER BY ts) AS r FROM t_unb",
            ("t_unb", ks, vs, ts))
        oracle = _oracle(ks, vs, ts, "RANGE", None, "COUNT")
        for r in rows:
            assert r["r"] == pytest.approx(
                oracle[(r["key"], r["ts"])]), r

    def test_multiple_aggs_one_window(self):
        ks, vs, ts = _data(n=1200)
        rows = _run(
            "SELECT key, ts, "
            "SUM(value) OVER (PARTITION BY key ORDER BY ts "
            "ROWS BETWEEN 4 PRECEDING AND CURRENT ROW) AS s, "
            "COUNT(*) OVER (PARTITION BY key ORDER BY ts "
            "ROWS BETWEEN 4 PRECEDING AND CURRENT ROW) AS c "
            "FROM t_multi",
            ("t_multi", ks, vs, ts))
        o_s = _oracle(ks, vs, ts, "ROWS", 4, "SUM")
        o_c = _oracle(ks, vs, ts, "ROWS", 4, "COUNT")
        for r in rows:
            assert r["s"] == pytest.approx(
                o_s[(r["key"], r["ts"])], rel=1e-6)
            assert r["c"] == pytest.approx(o_c[(r["key"], r["ts"])])

    def test_range_peer_rows_share_frames(self):
        """SQL RANGE frames include the current row's rowtime PEERS."""
        from flink_tpu.runtime.over_agg import OverAggOperator

        op = OverAggOperator("k", [("SUM", "v", "s")], mode="RANGE",
                             preceding=10_000)

        class _Ctx:
            max_parallelism = 128

        op.open(_Ctx())
        b = RecordBatch.from_pydict(
            {"k": np.asarray([1, 1, 1]),
             "v": np.asarray([1.0, 2.0, 4.0])},
            timestamps=np.asarray([100, 100, 200]))
        op.process_batch(b)
        out = op.process_watermark(10_000)[0]
        got = dict(zip(out.timestamps.tolist(), out["s"].tolist()))
        # both ts=100 peers see 1+2; ts=200 sees all three
        assert got == {100: 3.0, 200: 7.0}
        rows_s = out["s"].tolist()
        assert rows_s[0] == rows_s[1] == 3.0

    def test_mixed_window_specs_rejected(self):
        from flink_tpu.table.environment import PlanError

        ks, vs, ts = _data(n=100)
        with pytest.raises(PlanError, match="same window"):
            _run(
                "SELECT key, "
                "SUM(value) OVER (PARTITION BY key ORDER BY ts "
                "ROWS BETWEEN 4 PRECEDING AND CURRENT ROW) AS a, "
                "SUM(value) OVER (PARTITION BY key ORDER BY ts "
                "ROWS BETWEEN 9 PRECEDING AND CURRENT ROW) AS b "
                "FROM t_mix",
                ("t_mix", ks, vs, ts))

    def test_order_by_non_time_rejected(self):
        from flink_tpu.table.environment import PlanError

        ks, vs, ts = _data(n=100)
        with pytest.raises(PlanError, match="event-time"):
            _run(
                "SELECT key, SUM(value) OVER (PARTITION BY key "
                "ORDER BY value ROWS BETWEEN 4 PRECEDING AND "
                "CURRENT ROW) AS a FROM t_ord",
                ("t_ord", ks, vs, ts))

    def test_no_time_attribute_rejected(self):
        from flink_tpu.connectors.kafka import FakeBroker
        from flink_tpu.table.environment import PlanError

        ks, vs, ts = _data(n=50)
        broker = FakeBroker.get("default")
        broker.create_topic("t_nowm", 1)
        broker.append("t_nowm", 0, RecordBatch.from_pydict(
            {"key": ks, "value": vs, "ts": ts}, timestamps=ts))
        env = StreamExecutionEnvironment(Configuration({}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE t_nowm (key BIGINT, value DOUBLE, ts BIGINT) "
            "WITH ('connector'='kafka', 'topic'='t_nowm')")
        with pytest.raises(PlanError, match="event-time"):
            tenv.execute_sql(
                "SELECT key, MAX(value) OVER (PARTITION BY key "
                "ORDER BY value) AS m FROM t_nowm")

    def test_alias_cannot_clobber_source_column(self):
        ks, vs, ts = _data(n=200)
        rows = _run(
            "SELECT value AS v, SUM(value) OVER (PARTITION BY key "
            "ORDER BY ts ROWS BETWEEN 3 PRECEDING AND CURRENT ROW) "
            "AS value FROM t_alias",
            ("t_alias", ks, vs, ts))
        src = {(k, t): v for k, v, t in zip(ks, vs, ts)}
        # v must be the SOURCE value, untouched by the alias 'value'
        got_vs = sorted(round(r["v"], 4) for r in rows)
        assert got_vs == sorted(np.round(vs, 4).tolist())

    def test_nested_over_rejected_at_plan_time(self):
        from flink_tpu.table.environment import PlanError

        ks, vs, ts = _data(n=50)
        with pytest.raises(PlanError, match="top-level"):
            _run(
                "SELECT key, SUM(value) OVER (PARTITION BY key "
                "ORDER BY ts) + 1 AS r FROM t_nest",
                ("t_nest", ks, vs, ts))

    def test_fractional_rows_frame_rejected(self):
        from flink_tpu.table.sql_parser import SqlParseError, parse

        with pytest.raises(SqlParseError, match="whole row count"):
            parse("SELECT SUM(v) OVER (PARTITION BY k ORDER BY ts "
                  "ROWS BETWEEN 2.7 PRECEDING AND CURRENT ROW) FROM t")

    def test_stage_parallel_matches_single_slot(self):
        ks, vs, ts = _data(n=3000, keys=40)
        sql = ("SELECT key, ts, SUM(value) OVER (PARTITION BY key "
               "ORDER BY ts ROWS BETWEEN 7 PRECEDING AND CURRENT ROW) "
               "AS r FROM t_sp")
        single = _run(sql, ("t_sp", ks, vs, ts))

        def rows_map(rows):
            return {(r["key"], r["ts"]): round(r["r"], 6) for r in rows}

        # fresh broker topic content persists; rerun staged on same topic
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 257,
            "execution.stage-parallelism": 4,
            "execution.source-parallelism": 1}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE t_sp (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='t_sp')")
        staged = tenv.execute_sql(sql).collect()
        assert rows_map(staged) == rows_map(single)
        assert len(staged) == len(ks)
