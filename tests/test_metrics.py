"""Metrics, reporters and trace spans.

reference test model: flink-metrics-core + runtime metric group tests
(SURVEY.md §4 tier 1 unit tests).
"""

import urllib.request

from flink_tpu.metrics import (
    Counter,
    Histogram,
    Meter,
    MetricRegistry,
    PrometheusReporter,
    TraceCollector,
)


class TestMetricTypes:
    def test_counter(self):
        c = Counter()
        c.inc()
        c.inc(5)
        c.dec()
        assert c.count == 5

    def test_histogram_quantiles(self):
        h = Histogram()
        for v in range(100):
            h.update(float(v))
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["min"] == 0.0 and snap["max"] == 99.0
        assert 45 <= snap["p50"] <= 55
        assert snap["p99"] >= 95

    def test_meter_rate(self):
        m = Meter()
        for _ in range(10):
            m.mark(100)
        assert m.count == 1000

    def test_groups_and_registry(self):
        reg = MetricRegistry()
        job = reg.root_group("job", "test")
        op = job.add_group("window_agg#3")
        c = op.counter("numRecordsIn")
        c.inc(42)
        op.gauge("currentWatermark", lambda: 123)
        snap = reg.snapshot()
        assert snap["job.test.window_agg#3.numRecordsIn"] == 42
        assert snap["job.test.window_agg#3.currentWatermark"] == 123

    def test_unregister_prefix(self):
        reg = MetricRegistry()
        reg.root_group("job", "a").counter("x").inc()
        reg.root_group("job", "b").counter("y").inc()
        reg.unregister_scope_prefix(("job", "a"))
        snap = reg.snapshot()
        assert "job.a.x" not in snap and "job.b.y" in snap


class TestPrometheusReporter:
    def test_render_text_format(self):
        reg = MetricRegistry()
        g = reg.root_group("job", "nexmark", "q5")
        g.counter("numRecordsIn").inc(7)
        h = g.histogram("fireLatency")
        h.update(1.0)
        h.update(2.0)
        rep = PrometheusReporter()
        rep.open(reg)
        text = rep.render()
        assert "# TYPE" in text
        assert "numRecordsIn" in text and " 7" in text
        assert 'quantile="0.99"' in text

    def test_http_endpoint(self):
        reg = MetricRegistry()
        reg.root_group("job", "x").counter("served").inc(3)
        rep = PrometheusReporter(port=0)
        rep.open(reg)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{rep.port}/metrics", timeout=5
            ).read().decode()
            assert "served" in body
        finally:
            rep.close()


class TestTraces:
    def test_span_collection(self):
        tc = TraceCollector()
        with tc.span("checkpoint", "checkpoint-1") as sp:
            sp.set_attribute("checkpointId", 1)
        spans = tc.spans("checkpoint")
        assert len(spans) == 1
        assert spans[0].attributes["checkpointId"] == 1
        assert spans[0].duration_ms >= 0


class TestJobMetricsWiring:
    def test_job_exposes_registry_and_spans(self, tmp_path):
        from flink_tpu.core.config import Configuration
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.datastream.environment import StreamExecutionEnvironment
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        conf = Configuration({
            "state.checkpoints.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.every-n-source-batches": 1,
        })
        env = StreamExecutionEnvironment(conf)
        sink = CollectSink()
        rows = [{"k": i % 3, "v": 1, "ts": i * 100} for i in range(100)]
        env.from_collection(rows, timestamp_field="ts") \
            .key_by("k").window(TumblingEventTimeWindows.of(1000)) \
            .sum("v").sink_to(sink)
        result = env.execute("metrics-job")
        snap = result.registry.snapshot()
        in_keys = [k for k in snap if k.endswith("numRecordsIn")]
        assert in_keys and any(snap[k] > 0 for k in in_keys)
        wm_keys = [k for k in snap if k.endswith("currentInputWatermark")]
        assert wm_keys
        assert result.traces.spans("checkpoint")
