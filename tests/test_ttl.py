"""State TTL (StateTtlConfig analog).

reference: flink-core/.../api/common/state/StateTtlConfig.java (builder,
UpdateType, StateVisibility) and flink-runtime/.../runtime/state/ttl/
TtlStateFactory.java (wrapping factory; expired reads filtered; cleanup
strategies). Here TTL is a last-access column per state with a
vectorized sweep; idle GROUP BY accumulators and upsert-materializer
keys are dropped via table.exec.state.ttl."""

import numpy as np
import pytest

from flink_tpu.state.keyed_state import (
    KeyedStateStore,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    ValueStateDescriptor,
)
from flink_tpu.state.ttl import (
    NEVER_RETURN_EXPIRED,
    ON_CREATE_AND_WRITE,
    ON_READ_AND_WRITE,
    RETURN_EXPIRED_IF_NOT_CLEANED_UP,
    StateTtlConfig,
)


class Clock:
    def __init__(self, t=0):
        self.t = t

    def __call__(self):
        return self.t


def _store(clock):
    return KeyedStateStore(capacity=1 << 10, clock=clock)


K = np.asarray([1, 2, 3], dtype=np.int64)


class TestConfig:
    def test_builder_mirrors_reference(self):
        cfg = (StateTtlConfig.new_builder(5000)
               .update_ttl_on_read_and_write()
               .return_expired_if_not_cleaned_up()
               .build())
        assert cfg.ttl_ms == 5000
        assert cfg.update_type == ON_READ_AND_WRITE
        assert cfg.visibility == RETURN_EXPIRED_IF_NOT_CLEANED_UP

    def test_validation(self):
        with pytest.raises(ValueError):
            StateTtlConfig(0)
        with pytest.raises(ValueError):
            StateTtlConfig(10, update_type="sometimes")
        with pytest.raises(ValueError):
            StateTtlConfig(10, visibility="maybe")


class TestValueState:
    def test_on_create_and_write_expires(self):
        clock = Clock()
        st = _store(clock).get_state(
            ValueStateDescriptor("v", ttl=StateTtlConfig(100)))
        st.put(K, [10.0, 20.0, 30.0])
        clock.t = 99
        assert st.get(K).tolist() == [10.0, 20.0, 30.0]
        clock.t = 101
        assert st.get(K).tolist() == [0.0, 0.0, 0.0]  # NeverReturnExpired

    def test_read_does_not_extend_by_default(self):
        clock = Clock()
        st = _store(clock).get_state(
            ValueStateDescriptor("v", ttl=StateTtlConfig(100)))
        st.put(K, [1.0, 1.0, 1.0])
        clock.t = 90
        st.get(K)  # OnCreateAndWrite: a read must NOT refresh
        clock.t = 150
        assert st.get(K).tolist() == [0.0, 0.0, 0.0]

    def test_on_read_and_write_extends(self):
        clock = Clock()
        cfg = StateTtlConfig(100, update_type=ON_READ_AND_WRITE)
        st = _store(clock).get_state(ValueStateDescriptor("v", ttl=cfg))
        st.put(K, [1.0, 2.0, 3.0])
        clock.t = 90
        st.get(K)  # refreshes lifetime to t=90
        clock.t = 150  # would be expired without the read refresh
        assert st.get(K).tolist() == [1.0, 2.0, 3.0]

    def test_write_refreshes(self):
        clock = Clock()
        st = _store(clock).get_state(
            ValueStateDescriptor("v", ttl=StateTtlConfig(100)))
        st.put(K, [1.0, 1.0, 1.0])
        clock.t = 90
        st.put(K[:1], [2.0])
        clock.t = 150
        got = st.get(K)
        assert got[0] == 2.0 and got[1] == 0.0

    def test_return_expired_if_not_cleaned_up(self):
        clock = Clock()
        cfg = StateTtlConfig(
            100, visibility=RETURN_EXPIRED_IF_NOT_CLEANED_UP)
        store = _store(clock)
        st = store.get_state(ValueStateDescriptor("v", ttl=cfg))
        st.put(K, [7.0, 7.0, 7.0])
        clock.t = 200
        assert st.get(K).tolist() == [7.0, 7.0, 7.0]  # not swept yet
        store.sweep_expired()
        assert st.get(K).tolist() == [0.0, 0.0, 0.0]

    def test_expired_read_does_not_resurrect(self):
        """Reading an expired entry under ON_READ_AND_WRITE must not
        refresh its stamp back to life."""
        clock = Clock()
        cfg = StateTtlConfig(100, update_type=ON_READ_AND_WRITE)
        st = _store(clock).get_state(ValueStateDescriptor("v", ttl=cfg))
        st.put(K, [5.0, 5.0, 5.0])
        clock.t = 150
        assert st.get(K).tolist() == [0.0, 0.0, 0.0]
        clock.t = 160
        assert st.get(K).tolist() == [0.0, 0.0, 0.0]

    def test_sweep_clears_values(self):
        clock = Clock()
        store = _store(clock)
        st = store.get_state(
            ValueStateDescriptor("v", ttl=StateTtlConfig(100)))
        st.put(K, [9.0, 9.0, 9.0])
        clock.t = 101
        assert store.sweep_expired() == 3
        clock.t = 0  # even rewinding the clock: values are gone
        assert st.get(K).tolist() == [0.0, 0.0, 0.0]

    def test_restore_honors_remaining_ttl(self):
        clock = Clock()
        store = _store(clock)
        st = store.get_state(
            ValueStateDescriptor("v", ttl=StateTtlConfig(100)))
        st.put(K, [4.0, 4.0, 4.0])
        clock.t = 60
        snap = store.snapshot()

        clock2 = Clock(60)
        store2 = _store(clock2)
        store2.restore(snap)
        st2 = store2.get_state(
            ValueStateDescriptor("v", ttl=StateTtlConfig(100)))
        assert st2.get(K).tolist() == [4.0, 4.0, 4.0]
        clock2.t = 101  # written at 0 -> expires at 100, not 160
        assert st2.get(K).tolist() == [0.0, 0.0, 0.0]


class TestReducingState:
    def test_fold_restarts_after_expiry(self):
        clock = Clock()
        st = _store(clock).get_state(ReducingStateDescriptor(
            "sum", reduce=np.add, ttl=StateTtlConfig(100)))
        st.add(K, [1.0, 1.0, 1.0])
        clock.t = 50
        st.add(K, [2.0, 2.0, 2.0])
        assert st.get(K).tolist() == [3.0, 3.0, 3.0]
        clock.t = 200  # expired (last write 50 + 100 < 200)
        st.add(K, [5.0, 5.0, 5.0])
        assert st.get(K).tolist() == [5.0, 5.0, 5.0]  # not 8.0


class TestHostStates:
    def test_list_state_ttl_and_snapshot_shrink(self):
        clock = Clock()
        store = _store(clock)
        st = store.get_state(
            ListStateDescriptor("l", ttl=StateTtlConfig(100)))
        st.add(K, [1.0, 2.0, 3.0])
        clock.t = 150
        assert st.get(1) == []  # hidden
        assert store.sweep_expired() == 3
        assert st.snapshot()["lists"] == {}  # snapshot SHRANK

    def test_list_append_after_expiry_starts_fresh(self):
        clock = Clock()
        st = _store(clock).get_state(
            ListStateDescriptor("l", ttl=StateTtlConfig(100)))
        st.add(K[:1], [1.0])
        clock.t = 200
        st.add(K[:1], [9.0])
        assert st.get(1) == [9.0]

    def test_list_keys_agree_with_get_visibility(self):
        """keys() must not list expired-but-unswept phantom keys whose
        get() already returns []."""
        clock = Clock()
        st = _store(clock).get_state(
            ListStateDescriptor("l", ttl=StateTtlConfig(100)))
        st.add(K, [1.0, 2.0, 3.0])
        assert sorted(st.keys()) == [1, 2, 3]
        clock.t = 150
        assert st.keys() == [] and st.get(1) == []

    def test_map_state_ttl(self):
        clock = Clock()
        store = _store(clock)
        st = store.get_state(
            MapStateDescriptor("m", ttl=StateTtlConfig(100)))
        st.put(1, "a", 10)
        clock.t = 90
        st.put(1, "b", 20)  # write refreshes the KEY's lifetime
        clock.t = 180
        assert st.get(1, "a") == 10
        clock.t = 300
        assert st.get(1, "a") is None
        store.sweep_expired()
        assert st.snapshot()["maps"] == {}


class TestGroupAggTtl:
    def _op(self, clock, ttl=1000):
        from flink_tpu.runtime.group_agg import GroupAggOperator
        from flink_tpu.windowing.aggregates import CountAggregate

        class Ctx:
            max_parallelism = 128
            memory_manager = None

        op = GroupAggOperator(CountAggregate(), "k", capacity=1 << 12,
                              ttl_ms=ttl, clock=clock)
        op.open(Ctx())
        return op

    def _batch(self, keys, ts=0):
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.state.keygroups import hash_keys_to_i64

        arr = np.asarray(keys, dtype=np.int64)
        b = RecordBatch.from_pydict(
            {"k": arr},
            timestamps=np.full(len(arr), ts, dtype=np.int64))
        return b.with_column("__key_id__", hash_keys_to_i64(arr))

    def test_idle_keys_dropped_and_snapshot_shrinks(self):
        clock = Clock()
        op = self._op(clock, ttl=1000)
        op.process_batch(self._batch([1, 2, 3]))
        assert op.table.num_used == 3
        clock.t = 500
        op.process_batch(self._batch([1]))  # key 1 refreshed
        clock.t = 1400  # keys 2,3 idle > 1000
        op.process_watermark(10)
        assert op.table.num_used == 1
        snap = op.snapshot_state()
        assert len(snap["table"]["key_id"]) == 1
        assert len(snap["changelog"]["key_id"]) == 1

    def test_rearrival_after_expiry_emits_insert(self):
        from flink_tpu.core.records import (
            ROWKIND_FIELD,
            ROWKIND_INSERT,
        )

        clock = Clock()
        op = self._op(clock, ttl=1000)
        first = op.process_batch(self._batch([7]))
        assert first[0][ROWKIND_FIELD].tolist() == [ROWKIND_INSERT]
        clock.t = 2000
        op.process_watermark(10)  # sweeps key 7
        out = op.process_batch(self._batch([7]))
        kinds = out[0][ROWKIND_FIELD].tolist()
        # fresh INSERT with a count restarted at 1, not an update of
        # the expired accumulator (reference idle-state semantics)
        assert kinds == [ROWKIND_INSERT]
        assert float(out[0]["count"][0]) == 1.0

    def test_restore_honors_remaining_ttl(self):
        clock = Clock()
        op = self._op(clock, ttl=1000)
        op.process_batch(self._batch([1, 2]))
        clock.t = 600
        snap = op.snapshot_state()

        clock2 = Clock(600)
        op2 = self._op(clock2, ttl=1000)
        op2.restore_state(snap)
        assert op2.table.num_used == 2
        clock2.t = 1100  # written at 0 -> expired at 1000
        op2.process_watermark(10)
        assert op2.table.num_used == 0

    def test_incremental_chain_does_not_resurrect_expired(self):
        from flink_tpu.checkpoint.storage import apply_table_delta

        clock = Clock()
        op = self._op(clock, ttl=1000)
        op.process_batch(self._batch([1, 2, 3]))
        base = op.snapshot_state()["table"]  # full base
        clock.t = 500
        op.process_batch(self._batch([1]))  # refresh key 1
        clock.t = 1400
        op.process_watermark(10)  # expire 2, 3 (1 refreshed at 500)
        delta = op.snapshot_state_delta()["table"]
        assert len(delta["tombstone_key_id"]) == 2
        merged = apply_table_delta(base, delta)
        live = op.table.keys_of_slots(op.table.index.used_slots())
        assert set(merged["key_id"].tolist()) == set(live.tolist())
        assert len(merged["key_id"]) == 1


class TestSqlWiring:
    def test_table_exec_state_ttl_reaches_operators(self, monkeypatch):
        import flink_tpu.table.planner as planner_mod
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.table.environment import StreamTableEnvironment

        created = []
        real = planner_mod.GroupAggOperator

        def spy(*a, **kw):
            op = real(*a, **kw)
            created.append(op)
            return op

        monkeypatch.setattr(planner_mod, "GroupAggOperator", spy)
        env = StreamExecutionEnvironment(Configuration({
            "table.exec.state.ttl": 60_000,
            "execution.micro-batch.size": 1024}))
        tenv = StreamTableEnvironment(env)
        ts = np.asarray([1000, 2000], dtype=np.int64)
        from flink_tpu.connectors.kafka import FakeBroker

        broker = FakeBroker.get("default")
        broker.create_topic("ttl_t", 1)
        broker.append("ttl_t", 0, RecordBatch.from_pydict(
            {"k": np.asarray([1, 1], dtype=np.int64), "ts": ts},
            timestamps=ts))
        tenv.execute_sql(
            "CREATE TABLE ttl_t (k BIGINT, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='ttl_t')")
        rows = tenv.execute_sql(
            "SELECT k, COUNT(*) AS c FROM ttl_t GROUP BY k").collect()
        assert created and created[0].ttl_ms == 60_000
        assert any(r["c"] == 2 for r in rows)


class TestUpsertMaterializerTtl:
    def test_idle_sink_keys_dropped(self):
        from flink_tpu.core.records import (
            ROWKIND_FIELD,
            ROWKIND_INSERT,
            RecordBatch,
        )
        from flink_tpu.table.upsert_materializer import (
            UpsertMaterializeOperator,
        )

        clock = Clock()

        class Ctx:
            max_parallelism = 128

        op = UpsertMaterializeOperator(["k"], ttl_ms=1000, clock=clock)
        op.open(Ctx())
        op.process_batch(RecordBatch.from_pydict({
            "k": np.asarray([1, 2]), "v": np.asarray([10.0, 20.0]),
            ROWKIND_FIELD: np.asarray(
                [ROWKIND_INSERT, ROWKIND_INSERT], dtype=np.int8)}))
        clock.t = 500
        op.process_batch(RecordBatch.from_pydict({
            "k": np.asarray([1]), "v": np.asarray([11.0]),
            ROWKIND_FIELD: np.asarray([ROWKIND_INSERT], dtype=np.int8)}))
        clock.t = 1400  # key 2 idle 1400 > 1000; key 1 idle 900
        op.process_watermark(10)
        assert set(op._rows) == {(1,)}
        assert len(op.snapshot_state()["um_keys"]) == 1
