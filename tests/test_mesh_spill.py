"""Mesh-sharded spill tier: state capacity independent of parallelism.

The budgeted [P, capacity] device table evicts cold namespaces per shard to
a host/fs SpillTier and reloads them on access — the mesh form of the
single-device SlotTable spill (reference: RocksDBKeyedStateBackend.java —
RocksDB state capacity was never bounded by memory, at any parallelism).
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.parallel.sharded_windower import MeshWindowEngine
from flink_tpu.windowing.aggregates import (
    CountAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def keyed_batch(keys, values, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(values, dtype=np.float32)},
        timestamps=ts)


def fired_to_dict(batches, fields=("sum_v",)):
    out = {}
    for b in batches:
        for row in b.to_rows():
            out[(row[KEY_ID_FIELD], row["window_start"],
                 row["window_end"])] = tuple(row[f] for f in fields)
    return out


def _steps(num_keys=600, per_step=800, n_steps=6, seed=11, span=4000):
    """A stream whose live (key, slice) working set exceeds a small
    per-shard budget: many keys across many open slices."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 1000, s * 1000 + span, per_step).astype(
            np.int64)
        steps.append((keys, vals, ts, s * 1000))
    steps.append((np.array([0], dtype=np.int64),
                  np.array([0.0], dtype=np.float32),
                  np.array([n_steps * 1000 + span + 5000], dtype=np.int64),
                  10 ** 9))
    return steps


def _run(engine, steps):
    fired = []
    for keys, vals, ts, wm in steps:
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
    return fired


class TestMeshSpill:
    def test_spilled_equals_unspilled(self, eight_device_mesh, tmp_path):
        """Forcing eviction with a tiny per-shard budget must not change
        any window result."""
        assigner = SlidingEventTimeWindows.of(2000, 1000)
        steps = _steps()
        ref = MeshWindowEngine(assigner, SumAggregate("v"),
                               eight_device_mesh,
                               capacity_per_shard=1 << 14)
        budgeted = MeshWindowEngine(
            assigner, SumAggregate("v"), eight_device_mesh,
            capacity_per_shard=1 << 14,
            max_device_slots=1024,  # floor — forces eviction per shard
            spill_dir=str(tmp_path / "spill"))
        d_ref = fired_to_dict(_run(ref, steps))
        d_bud = fired_to_dict(_run(budgeted, steps))
        assert len(d_ref) > 0
        assert set(d_ref) == set(d_bud)
        for k in d_ref:
            assert d_ref[k][0] == pytest.approx(d_bud[k][0], rel=1e-4), k
        # the budget was actually binding: something spilled at some point
        assert budgeted._touch_clock > 0

    def test_eviction_actually_happens(self, eight_device_mesh):
        assigner = TumblingEventTimeWindows.of(1000)
        eng = MeshWindowEngine(
            assigner, SumAggregate("v"), eight_device_mesh,
            capacity_per_shard=1 << 14, max_device_slots=1024)
        rng = np.random.default_rng(2)
        spilled_seen = 0
        for s in range(10):
            keys = rng.integers(0, 3000, 2000).astype(np.int64)
            vals = rng.random(2000).astype(np.float32)
            # many concurrent open windows: ts spread over 8 slices
            ts = rng.integers(s * 500, s * 500 + 8000, 2000).astype(
                np.int64)
            eng.process_batch(keyed_batch(keys, vals, ts))
            spilled_seen = max(spilled_seen,
                               sum(len(sp) for sp in eng.spills))
        assert spilled_seen > 0, "budget never became binding"
        # no shard's index exceeded the budget
        for idx in eng.indexes:
            assert idx.capacity <= 1024

    def test_multi_agg_with_spill(self, eight_device_mesh):
        assigner = SlidingEventTimeWindows.of(2000, 1000)
        steps = _steps(num_keys=400, per_step=600, n_steps=5)
        agg = lambda: MultiAggregate(  # noqa: E731
            [CountAggregate(), SumAggregate("v")])
        ref = MeshWindowEngine(assigner, agg(), eight_device_mesh,
                               capacity_per_shard=1 << 14)
        bud = MeshWindowEngine(assigner, agg(), eight_device_mesh,
                               capacity_per_shard=1 << 14,
                               max_device_slots=1024)
        d_ref = fired_to_dict(_run(ref, steps), ("count", "sum_v"))
        d_bud = fired_to_dict(_run(bud, steps), ("count", "sum_v"))
        assert set(d_ref) == set(d_bud) and len(d_ref) > 0
        for k in d_ref:
            assert d_ref[k][0] == d_bud[k][0]
            assert d_ref[k][1] == pytest.approx(d_bud[k][1], rel=1e-4)

    def test_snapshot_restore_with_spill(self, eight_device_mesh,
                                         tmp_path):
        """A snapshot taken mid-run with spilled state restores onto a
        fresh budgeted engine and finishes with the same results."""
        assigner = SlidingEventTimeWindows.of(2000, 1000)
        steps = _steps(num_keys=500, per_step=700, n_steps=6)
        cut = 3

        ref = MeshWindowEngine(assigner, SumAggregate("v"),
                               eight_device_mesh,
                               capacity_per_shard=1 << 14)
        d_ref = fired_to_dict(_run(ref, steps))

        a = MeshWindowEngine(assigner, SumAggregate("v"),
                             eight_device_mesh,
                             capacity_per_shard=1 << 14,
                             max_device_slots=1024,
                             spill_dir=str(tmp_path / "a"))
        fired = _run(a, steps[:cut])
        snap = a.snapshot()
        b = MeshWindowEngine(assigner, SumAggregate("v"),
                             eight_device_mesh,
                             capacity_per_shard=1 << 14,
                             max_device_slots=1024,
                             spill_dir=str(tmp_path / "b"))
        b.restore(snap)
        fired.extend(_run(b, steps[cut:]))
        d_got = fired_to_dict(fired)
        assert set(d_got) == set(d_ref)
        for k in d_ref:
            assert d_ref[k][0] == pytest.approx(d_got[k][0], rel=1e-4), k

    def test_budgeted_snapshot_restores_on_unbudgeted(
            self, eight_device_mesh):
        """Spilled rows are part of the logical snapshot — engines with
        and without a budget are mutually restorable."""
        assigner = TumblingEventTimeWindows.of(10_000)
        a = MeshWindowEngine(assigner, SumAggregate("v"),
                             eight_device_mesh,
                             capacity_per_shard=1 << 14,
                             max_device_slots=1024)
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 4000, 6000).astype(np.int64)
        vals = rng.random(6000).astype(np.float32)
        ts = rng.integers(0, 10_000, 6000).astype(np.int64)
        a.process_batch(keyed_batch(keys, vals, ts))
        snap = a.snapshot()
        b = MeshWindowEngine(assigner, SumAggregate("v"),
                             eight_device_mesh,
                             capacity_per_shard=1 << 14)
        b.restore(snap)
        da = {}
        for k in (10, 500, 3999):
            da[k] = b.query_windows(int(keys[k]))
        fired = b.on_watermark(10**9)
        d = fired_to_dict(fired)
        # oracle
        want = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            want[k] = want.get(k, 0.0) + v
        assert len(d) == len(want)
        for (k, _, _), (s,) in d.items():
            assert s == pytest.approx(want[k], rel=1e-4)

    def test_query_windows_sees_spilled_state(self, eight_device_mesh):
        assigner = TumblingEventTimeWindows.of(1000)
        eng = MeshWindowEngine(
            assigner, SumAggregate("v"), eight_device_mesh,
            capacity_per_shard=1 << 14, max_device_slots=1024)
        rng = np.random.default_rng(9)
        want = {}
        for s in range(8):
            keys = rng.integers(0, 2500, 1500).astype(np.int64)
            vals = rng.random(1500).astype(np.float32)
            ts = rng.integers(0, 6000, 1500).astype(np.int64)
            eng.process_batch(keyed_batch(keys, vals, ts))
            for k, v, t in zip(keys.tolist(), vals.tolist(), ts.tolist()):
                w = (t // 1000 + 1) * 1000
                want[(k, w)] = want.get((k, w), 0.0) + v
        assert sum(len(sp) for sp in eng.spills) > 0
        probe = sorted({k for k, _ in want})[:5]
        for key in probe:
            got = eng.query_windows(int(key))
            for w, cols in got.items():
                assert cols["sum_v"] == pytest.approx(
                    want[(key, w)], rel=1e-4), (key, w)


class TestMeshSessionSpill:
    """Budgeted mesh session engine: cold sessions spill per shard and
    reload for merges/fires (BASELINE row 5 — 10M-key sessions cannot be
    device-resident)."""

    def _engine(self, mesh, **kw):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.aggregates import SumAggregate

        return MeshSessionEngine(gap=100, agg=SumAggregate("v"),
                                 mesh=mesh, capacity_per_shard=1 << 14,
                                 **kw)

    def _stream(self, num_keys=3000, n_steps=6, per_step=2000, seed=21):
        rng = np.random.default_rng(seed)
        steps = []
        for s in range(n_steps):
            keys = rng.integers(0, num_keys, per_step).astype(np.int64)
            vals = rng.random(per_step).astype(np.float32)
            # sessions stay open across steps (events every < gap for a
            # key subset), others go cold and eventually fire
            ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(
                np.int64)
            steps.append((keys, vals, ts, s * 80))
        steps.append((np.array([0], dtype=np.int64),
                      np.array([0.0], dtype=np.float32),
                      np.array([n_steps * 80 + 10_000], dtype=np.int64),
                      10 ** 9))
        return steps

    def session_dict(self, batches):
        out = {}
        for b in batches:
            for r in b.to_rows():
                out[(r[KEY_ID_FIELD], r["window_start"],
                     r["window_end"])] = r["sum_v"]
        return out

    def test_budgeted_sessions_equal_unbounded(self, eight_device_mesh):
        steps = self._stream()
        ref = self._engine(eight_device_mesh)
        bud = self._engine(eight_device_mesh, max_device_slots=1024)
        f_ref, f_bud = [], []
        for keys, vals, ts, wm in steps:
            ref.process_batch(keyed_batch(keys, vals, ts))
            bud.process_batch(keyed_batch(keys, vals, ts))
            f_ref.extend(ref.on_watermark(wm))
            f_bud.extend(bud.on_watermark(wm))
        d_ref = self.session_dict(f_ref)
        d_bud = self.session_dict(f_bud)
        assert len(d_ref) > 0
        assert set(d_ref) == set(d_bud)
        for k in d_ref:
            assert d_ref[k] == pytest.approx(d_bud[k], rel=1e-4), k
        for idx in bud.indexes:
            assert idx.capacity <= 1024

    def test_session_snapshot_restore_with_spill(self, eight_device_mesh):
        steps = self._stream(num_keys=2500, n_steps=6, per_step=1500)
        cut = 3
        ref = self._engine(eight_device_mesh)
        f_ref = []
        for keys, vals, ts, wm in steps:
            ref.process_batch(keyed_batch(keys, vals, ts))
            f_ref.extend(ref.on_watermark(wm))

        a = self._engine(eight_device_mesh, max_device_slots=1024)
        fired = []
        for keys, vals, ts, wm in steps[:cut]:
            a.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(a.on_watermark(wm))
        snap = a.snapshot()
        b = self._engine(eight_device_mesh, max_device_slots=1024)
        b.restore(snap)
        for keys, vals, ts, wm in steps[cut:]:
            b.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(b.on_watermark(wm))
        d_ref = self.session_dict(f_ref)
        d_got = self.session_dict(fired)
        assert set(d_ref) == set(d_got)
        for k in d_ref:
            assert d_ref[k] == pytest.approx(d_got[k], rel=1e-4), k


class TestPublicSessionSpill:
    """BASELINE row 5 shape: high-cardinality session windows at
    parallelism 8 within a device budget, through the public API."""

    def test_high_cardinality_sessions_under_budget(self):
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.runtime.operators import SessionWindowAggOperator
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        def run(extra):
            conf = {"execution.micro-batch.size": 8192,
                    "parallelism.default": 8}
            conf.update(extra)
            env = StreamExecutionEnvironment(Configuration(conf))
            sink = CollectSink()
            # scaled-down row-5 shape: many distinct keys, sparse events
            # -> sessions go cold (spill) and fire on gap expiry
            (env.add_source(
                DataGenSource(total_records=50_000, num_keys=20_000,
                              events_per_second_of_eventtime=10_000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("key")
                .window(EventTimeSessionWindows.with_gap(500))
                .sum("value").sink_to(sink))
            env.execute()
            return sink

        engines = []
        orig_open = SessionWindowAggOperator.open

        def spy_open(self, ctx):
            orig_open(self, ctx)
            engines.append(self.windower)

        SessionWindowAggOperator.open = spy_open
        try:
            ref = run({})
            got = run({"state.slot-table.max-device-slots": 1024})
        finally:
            SessionWindowAggOperator.open = orig_open

        assert isinstance(engines[-1], MeshSessionEngine)
        assert engines[-1].max_device_slots == 1024

        def d(sink):
            return {(r["key"], r["window_start"], r["window_end"]):
                    round(r["sum_value"], 3) for r in sink.rows()}

        d_ref, d_got = d(ref), d(got)
        assert len(d_ref) > 0
        assert d_ref == d_got
        for idx in engines[-1].indexes:
            assert idx.capacity <= 1024
