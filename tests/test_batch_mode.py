"""Bounded/batch execution mode (reference: RuntimeExecutionMode.BATCH,
AdaptiveBatchScheduler, SortMergeResultPartition bulk shuffle)."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource, Source
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def _window_job(env, sink, assigner, total=20_000):
    src = DataGenSource(total_records=total, num_keys=100,
                        events_per_second_of_eventtime=10_000, seed=2)
    env.from_source(src,
                    WatermarkStrategy.for_bounded_out_of_orderness(0),
                    name="gen") \
        .key_by("key").window(assigner).sum("value").sink_to(sink)


from tests.conftest import \
    assert_windows_approx_equal as _approx_equal  # noqa: E501


def _res(sink):
    return {(r["key"], r["window_start"]): round(r["sum_value"], 3)
            for r in sink.result().to_rows()}


class TestBatchMode:
    @pytest.mark.parametrize("stage_par", [0, 4])
    def test_same_results_as_streaming(self, stage_par):
        stream_sink = CollectSink()
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000}))
        _window_job(env, stream_sink, SlidingEventTimeWindows.of(2000, 500))
        env.execute("streaming")

        batch_sink = CollectSink()
        env2 = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "execution.runtime-mode": "batch",
            "execution.stage-parallelism": stage_par}))
        _window_job(env2, batch_sink, SlidingEventTimeWindows.of(2000, 500))
        env2.execute("batch")
        _approx_equal(_res(batch_sink), _res(stream_sink))

    def test_single_fire_per_window(self):
        """In batch mode every window fires exactly once (no intermediate
        watermarks), so the sink sees exactly one row per (key, window)."""
        sink = CollectSink()
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "execution.runtime-mode": "batch"}))
        _window_job(env, sink, TumblingEventTimeWindows.of(1000))
        env.execute("batch")
        rows = sink.result().to_rows()
        keys = [(r["key"], r["window_start"]) for r in rows]
        assert len(keys) == len(set(keys))

    def test_unbounded_source_rejected(self):
        class Endless(Source):
            bounded = False

            def poll_batch(self, n):
                import numpy as np

                from flink_tpu.core.records import RecordBatch

                return RecordBatch.from_pydict(
                    {"key": np.zeros(1, dtype=np.int64),
                     "value": np.ones(1, dtype=np.float32)},
                    timestamps=[0])

        for stage_par in (0, 2):
            env = StreamExecutionEnvironment(Configuration({
                "execution.runtime-mode": "batch",
                "execution.stage-parallelism": stage_par}))
            sink = CollectSink()
            env.from_source(Endless(),
                            WatermarkStrategy.for_bounded_out_of_orderness(0)) \
                .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
                .sum("value").sink_to(sink)
            with pytest.raises(RuntimeError, match="unbounded"):
                env.execute("rejected")

    def test_adaptive_batch_parallelism(self):
        """stage-parallelism=-1 sizes the keyed stage from the source's
        estimated volume (reference: AdaptiveBatchScheduler)."""
        sink = CollectSink()
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "execution.runtime-mode": "batch",
            "execution.stage-parallelism": -1,
            "execution.batch.target-records-per-subtask": 5_000}))
        _window_job(env, sink, TumblingEventTimeWindows.of(1000),
                    total=20_000)
        result = env.execute("adaptive")
        assert result.metrics["stage_parallelism"] == 4  # ceil(20k/5k)

        # streaming mode rejects the adaptive sentinel
        env2 = StreamExecutionEnvironment(Configuration({
            "execution.stage-parallelism": -1}))
        sink2 = CollectSink()
        _window_job(env2, sink2, TumblingEventTimeWindows.of(1000))
        with pytest.raises(Exception, match="adaptive"):
            env2.execute("bad")

    @pytest.mark.parametrize("lie", [lambda: 50, lambda: 2_000_000, None])
    def test_adaptive_parallelism_is_measured_not_estimated(self, lie):
        """The keyed-stage parallelism comes from a metering pass through
        the bounded source (reference: AdaptiveBatchScheduler sizes from
        PRODUCED volume) — an estimate_records() that lies by 100x in
        either direction, or does not exist at all, changes nothing."""
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        class LyingSource(DataGenSource):
            pass

        src = LyingSource(total_records=20_000, num_keys=100,
                          events_per_second_of_eventtime=10_000, seed=3)
        if lie is None:
            # estimate_records not usable at all
            LyingSource.estimate_records = None
        else:
            LyingSource.estimate_records = staticmethod(lie)
        sink = CollectSink()
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "execution.runtime-mode": "batch",
            "execution.stage-parallelism": -1,
            "execution.batch.target-records-per-subtask": 5_000}))
        env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0)) \
            .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
            .sum("value").sink_to(sink)
        result = env.execute("adaptive-measured")
        assert result.metrics["stage_parallelism"] == 4  # ceil(20k/5k)
        assert len(sink.result()) > 0

    def test_batch_sql_group_agg_emits_finals_only(self):
        from flink_tpu.table.environment import StreamTableEnvironment

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 4,
            "execution.runtime-mode": "batch"}))
        t_env = StreamTableEnvironment(env)
        rows = [{"auction": a, "ts": i * 100}
                for i, a in enumerate([1, 2, 1, 1, 2, 3])]
        t_env.create_temporary_view(
            "bid", t_env.from_collection(rows, timestamp_field="ts"))
        table = t_env.sql_query(
            "SELECT auction, COUNT(*) AS n FROM bid GROUP BY auction")
        sink = CollectSink()
        table.to_data_stream().sink_to(sink)
        env.execute("batch-groupby")
        raw = sink.result().to_rows()
        # exactly one changelog row per group — no per-micro-batch churn
        assert len(raw) == 3
        got = {r["auction"]: r["n"] for r in raw}
        assert got == {1: 3, 2: 2, 3: 1}
