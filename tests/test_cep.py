"""CEP: pattern API, NFA semantics, keyed end-to-end matching.

Semantics mirrored from the reference's NFAITCase / CEPITCase
(flink-cep/src/test): strict vs relaxed contiguity, quantifiers, within,
after-match skip, out-of-order input via watermark buffering.
"""

import numpy as np
import pytest

from flink_tpu import Configuration, RecordBatch, StreamExecutionEnvironment
from flink_tpu.cep import CEP, AfterMatchSkipStrategy, KeyNFA, Pattern
from flink_tpu.runtime.watermarks import WatermarkStrategy


def _advance_all(pattern, events):
    """events: list of (ts, row). Returns list of matches as
    {stage: [values]} dicts using row['v'] as identity."""
    nfa = KeyNFA(pattern)
    out = []
    for ts, row in events:
        hits = [bool(st.evaluate(RecordBatch.from_pydict(
            {k: [v] for k, v in row.items()}))[0])
            for st in pattern.stages]
        for m in nfa.advance(row, ts, hits):
            out.append({name: [nfa.event_log[i]["v"] for i in idxs]
                        for name, idxs in m.events_by_stage.items()})
    return out


def _ev(*vs):
    return [(i * 10, {"v": v}) for i, v in enumerate(vs)]


def is_a(b):
    return np.char.startswith(np.asarray(b["v"], dtype=str), "a")


def is_b(b):
    return np.char.startswith(np.asarray(b["v"], dtype=str), "b")


def is_c(b):
    return np.char.startswith(np.asarray(b["v"], dtype=str), "c")


def test_strict_next_kills_on_gap():
    p = Pattern.begin("A").where(is_a).next("B").where(is_b)
    assert _advance_all(p, _ev("a1", "b1")) == [{"A": ["a1"], "B": ["b1"]}]
    # a gap between a and b breaks strict contiguity
    assert _advance_all(p, _ev("a1", "c1", "b1")) == []


def test_relaxed_followed_by_skips_gaps():
    p = Pattern.begin("A").where(is_a).followed_by("B").where(is_b)
    assert _advance_all(p, _ev("a1", "c1", "b1")) == [
        {"A": ["a1"], "B": ["b1"]}]


def test_one_or_more_emits_all_combinations():
    p = Pattern.begin("A").where(is_a).one_or_more().followed_by("B").where(is_b)
    got = _advance_all(p, _ev("a1", "a2", "b1"))
    as_sets = sorted(tuple(m["A"]) for m in got)
    assert as_sets == [("a1",), ("a1", "a2"), ("a2",)]


def test_times_exact():
    p = Pattern.begin("A").where(is_a).times(2).followed_by("B").where(is_b)
    got = _advance_all(p, _ev("a1", "a2", "a3", "b1"))
    as_sets = sorted(tuple(m["A"]) for m in got)
    # default relaxed contiguity consumes matching events: adjacent pairs
    # only ({a1,a3} needs allow_combinations — reference default semantics)
    assert as_sets == [("a1", "a2"), ("a2", "a3")]


def test_times_allow_combinations():
    p = (Pattern.begin("A").where(is_a).times(2).allow_combinations()
         .followed_by("B").where(is_b))
    got = _advance_all(p, _ev("a1", "a2", "a3", "b1"))
    as_sets = sorted(tuple(m["A"]) for m in got)
    assert as_sets == [("a1", "a2"), ("a1", "a3"), ("a2", "a3")]


def test_times_consecutive():
    p = (Pattern.begin("A").where(is_a).times(2).consecutive()
         .followed_by("B").where(is_b))
    got = _advance_all(p, _ev("a1", "c1", "a2", "a3", "b1"))
    as_sets = sorted(tuple(m["A"]) for m in got)
    assert as_sets == [("a2", "a3")]


def test_optional_middle_stage():
    p = (Pattern.begin("A").where(is_a)
         .next("B").where(is_b).optional()
         .next("C").where(is_c))
    got = _advance_all(p, _ev("a1", "b1", "c1"))
    assert {"A": ["a1"], "B": ["b1"], "C": ["c1"]} in got
    got2 = _advance_all(p, _ev("a1", "c1"))
    assert got2 == [{"A": ["a1"], "C": ["c1"]}]


def test_optional_first_stage_allows_late_start():
    p = (Pattern.begin("A").where(is_a).optional()
         .next("B").where(is_b))
    got = _advance_all(p, _ev("b1"))
    assert got == [{"B": ["b1"]}]


def test_optional_last_stage_completes_early():
    p = Pattern.begin("A").where(is_a).followed_by("B").where(is_b).optional()
    got = _advance_all(p, _ev("a1", "b1"))
    assert {"A": ["a1"]} in got and {"A": ["a1"], "B": ["b1"]} in got


def test_within_prunes_old_partials():
    p = (Pattern.begin("A").where(is_a).followed_by("B").where(is_b)
         .within(15))
    # a at ts 0, b at ts 20 -> span 20 > 15: no match
    assert _advance_all(p, _ev("a1", "c1", "b1")) == []
    # tighter spacing matches
    events = [(0, {"v": "a1"}), (10, {"v": "b1"})]
    assert _advance_all(p, events) == [{"A": ["a1"], "B": ["b1"]}]


def test_skip_past_last_event():
    p = (Pattern.begin("A").where(is_a).followed_by("B").where(is_b)
         .with_skip_strategy(AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT))
    got = _advance_all(p, _ev("a1", "a2", "b1", "b2"))
    # NO_SKIP would give a1b1, a2b1, a1b2, a2b2; skip-past keeps only the
    # first completed match and then restarts after it
    assert sorted(tuple(m["A"]) + tuple(m["B"]) for m in got) == [
        ("a1", "b1")]


def test_single_stage_loop():
    p = Pattern.begin("A").where(is_a).times(2)
    got = _advance_all(p, _ev("a1", "a2", "a3"))
    as_sets = sorted(tuple(m["A"]) for m in got)
    assert as_sets == [("a1", "a2"), ("a2", "a3")]


# ---------------------------------------------------------------- end-to-end


def test_cep_end_to_end_keyed_fraud_pattern():
    # canonical fraud detection: small charge followed by a big charge
    # within 60s, per card
    rows = []
    for i, (card, amount, ts) in enumerate([
            (1, 0.5, 0), (1, 900.0, 10_000),       # match for card 1
            (2, 0.4, 5_000), (2, 3.0, 12_000),     # no match (no big)
            (2, 0.6, 20_000), (2, 700.0, 90_000),  # too far apart -> no match
            (3, 0.9, 30_000), (3, 600.0, 80_000),  # match for card 3
    ]):
        rows.append({"card": card, "amount": amount, "ts": ts})

    p = (Pattern.begin("small").where(lambda b: b["amount"] < 1.0)
         .followed_by("big").where(lambda b: b["amount"] > 500.0)
         .within(60_000))

    env = StreamExecutionEnvironment(
        Configuration({"execution.micro-batch.size": 3}))
    s = env.from_collection(rows, timestamp_field="ts",
                            watermark_strategy=WatermarkStrategy
                            .for_bounded_out_of_orderness(0))
    out = (CEP.pattern(s.key_by("card"), p)
           .select(lambda key, m, ev: {
               "card": key,
               "small": ev["small"][0]["amount"],
               "big": ev["big"][0]["amount"]})
           .execute_and_collect())
    got = sorted(zip(out["card"].tolist(), out["big"].tolist()))
    assert got == [(1, 900.0), (3, 600.0)]


def test_cep_out_of_order_events_sorted_by_watermark():
    # b arrives before a in processing order but has later event time
    rows = [
        {"k": 1, "v": "b1", "ts": 2000},
        {"k": 1, "v": "a1", "ts": 1000},
    ]
    p = Pattern.begin("A").where(is_a).next("B").where(is_b)
    env = StreamExecutionEnvironment(
        Configuration({"execution.micro-batch.size": 10}))
    s = env.from_collection(
        rows, timestamp_field="ts",
        watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(
            5000))
    out = CEP.pattern(s.key_by("k"), p).select().execute_and_collect()
    assert len(out) == 1
    assert out["start_ts"].tolist() == [1000]
    assert out["end_ts"].tolist() == [2000]


def test_cep_operator_snapshot_restore():
    from flink_tpu.cep.operator import CepOperator
    from flink_tpu.runtime.operators import OperatorContext

    p = Pattern.begin("A").where(is_a).followed_by("B").where(is_b)
    op = CepOperator(p, "k")
    op.open(OperatorContext())
    b = RecordBatch.from_pydict(
        {"k": np.array([1, 1]), "v": np.array(["a1", "c1"], dtype=object),
         "__key_id__": np.array([1, 1], dtype=np.int64)},
        timestamps=np.array([0, 10], dtype=np.int64))
    op.process_batch(b)
    op.process_watermark(10)  # a1 absorbed into a partial
    snap = op.snapshot_state()

    op2 = CepOperator(p, "k")
    op2.open(OperatorContext())
    op2.restore_state(snap)
    b2 = RecordBatch.from_pydict(
        {"k": np.array([1]), "v": np.array(["b1"], dtype=object),
         "__key_id__": np.array([1], dtype=np.int64)},
        timestamps=np.array([20], dtype=np.int64))
    op2.process_batch(b2)
    outs = op2.process_watermark(30)
    assert len(outs) == 1 and outs[0]["A_count"].tolist() == [1]


def test_skip_past_last_event_processes_same_ts_followups():
    # a2 shares b1's timestamp; skip-past must NOT swallow it (the reference
    # discards partial matches, not future events)
    p = (Pattern.begin("A").where(is_a).followed_by("B").where(is_b)
         .with_skip_strategy(AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT))
    events = [(0, {"v": "a1"}), (10, {"v": "b1"}),
              (10, {"v": "a2"}), (20, {"v": "b2"})]
    got = _advance_all(p, events)
    assert sorted(tuple(m["A"]) + tuple(m["B"]) for m in got) == [
        ("a1", "b1"), ("a2", "b2")]


def test_event_log_compaction_bounds_memory():
    p = Pattern.begin("A").where(is_a).followed_by("B").where(is_b).within(1000)
    nfa = KeyNFA(p)
    for i in range(500):
        ts = i * 100
        nfa.advance({"v": f"a{i}"}, ts, [True, False])
        nfa.prune(ts)
    # within=1000 keeps ~11 live partials; the log must stay proportional
    assert len(nfa.partials) <= 12
    assert len(nfa.event_log) <= 12


def test_heterogeneous_optional_match_rows_share_schema():
    p = (Pattern.begin("A").where(is_a).optional().next("B").where(is_b))
    env = StreamExecutionEnvironment(
        Configuration({"execution.micro-batch.size": 10}))
    s = env.from_collection(
        [{"k": 1, "v": "a1", "ts": 0}, {"k": 1, "v": "b1", "ts": 5},
         {"k": 1, "v": "b2", "ts": 15}],
        timestamp_field="ts",
        watermark_strategy=WatermarkStrategy.for_bounded_out_of_orderness(0))
    out = CEP.pattern(s.key_by("k"), p).select().execute_and_collect()
    # matches: [a1 b1], [b1], [b2] — all rows carry A_count and B_count
    assert "A_count" in out.columns and "B_count" in out.columns
    assert sorted(zip(out["A_count"].tolist(), out["B_count"].tolist())) == [
        (0, 1), (0, 1), (1, 1)]


def test_pattern_builder_is_persistent():
    """A shared prefix must branch into independent patterns (reference:
    Pattern.next returns a new linked Pattern, never mutates the receiver)."""
    base = Pattern.begin("a").where(lambda b: b["x"] > 0)
    p1 = base.next("b")
    p2 = base.followed_by("c").within(500)
    assert [s.name for s in base.stages] == ["a"]
    assert [s.name for s in p1.stages] == ["a", "b"]
    assert [s.name for s in p2.stages] == ["a", "c"]
    assert base.within_ms is None and p1.within_ms is None
    assert p2.within_ms == 500
    # stage modifiers don't leak across branches either
    p3 = p1.times(3)
    assert p1.stages[-1].min_times == 1
    assert p3.stages[-1].min_times == 3
