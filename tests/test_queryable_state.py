"""Queryable state: live point-lookups against a running job.

reference model: flink-queryable-state ITCases (QueryableStateITCase).
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
from flink_tpu.cluster.queryable_state import QueryableStateClient
from flink_tpu.connectors.sinks import DiscardingSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import CountAggregate, SumAggregate
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


class SlowDataGen(DataGenSource):
    def poll_batch(self, max_records):
        b = super().poll_batch(max_records)
        if b is not None:
            time.sleep(0.01)
        return b


class TestSlotTableQuery:
    def test_point_query_readonly(self):
        agg = SumAggregate("v")
        t = SlotTable(agg, capacity=1024)
        keys = np.array([7, 8, 7], dtype=np.int64)
        ns = np.array([100, 100, 200], dtype=np.int64)
        slots = t.lookup_or_insert(keys, ns)
        t.scatter(slots, (np.array([1.0, 2.0, 4.0], dtype=np.float32),))
        used_before = t.num_used
        assert t.query(7) == {100: {"sum_v": 1.0}, 200: {"sum_v": 4.0}}
        assert t.query(8, namespace=100) == {100: {"sum_v": 2.0}}
        assert t.query(8, namespace=999) == {}
        assert t.query(12345) == {}  # miss never allocates
        assert t.num_used == used_before

    def test_lookup_probe_both_backends(self, monkeypatch):
        import flink_tpu.native as native_mod

        for force_py in (False, True):
            if force_py:
                monkeypatch.setenv("FLINK_TPU_NO_NATIVE", "1")
            t = SlotTable(SumAggregate("v"), capacity=1024)
            s = t.lookup_or_insert(np.array([5], dtype=np.int64),
                                   np.array([1], dtype=np.int64))
            probe = t.index.lookup(np.array([5, 6], dtype=np.int64),
                                   np.array([1, 1], dtype=np.int64))
            assert probe[0] == s[0] and probe[1] == -1
            monkeypatch.delenv("FLINK_TPU_NO_NATIVE", raising=False)


class TestQueryableStateE2E:
    def test_query_running_job_and_rest(self):
        cluster = MiniCluster(Configuration({"rest.port": 0}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 256}))
            (env.add_source(
                SlowDataGen(total_records=60_000, num_keys=8,
                            events_per_second_of_eventtime=5_000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("key")
                .window(TumblingEventTimeWindows.of(100_000))
                .count()
                .sink_to(DiscardingSink()))
            client = cluster.submit(env, "qs-job")
            qs = QueryableStateClient(cluster)
            deadline = time.monotonic() + 15
            state = {}
            while time.monotonic() < deadline:
                try:
                    state = qs.get_state(client.job_id,
                                         "window_agg(CountAggregate)", 3)
                    if state:
                        break
                except RuntimeError:
                    pass
                time.sleep(0.05)
            assert state, "no state observed while job ran"
            (ns, cols), = state.items()
            assert cols["count"] > 0
            first = cols["count"]

            # the count grows as the stream continues
            grew = False
            for _ in range(100):
                time.sleep(0.05)
                try:
                    s2 = qs.get_state(client.job_id,
                                      "window_agg(CountAggregate)", 3)
                except RuntimeError:
                    break
                if s2 and s2[ns]["count"] > first:
                    grew = True
                    break
            assert grew, "count did not grow between queries"

            # same lookup over REST
            url = (f"http://127.0.0.1:{cluster.rest_port}/jobs/"
                   f"{client.job_id}/state/window_agg(CountAggregate)?key=3")
            body = json.loads(urllib.request.urlopen(url).read())
            assert body["state"] and "count" in next(
                iter(body["state"].values()))
            client.cancel()
        finally:
            cluster.shutdown()

    def test_query_unknown_operator_fails_stage_parallel(self):
        """Regression: the stage-parallel control path silently routed an
        unknown operator name to stage 0 and answered [None]*n — "no
        such operator" and "key has no state" must stay distinct errors,
        matching the LocalExecutor path's KeyError."""
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 256,
                 "execution.stage-parallelism": 2}))
            (env.add_source(
                SlowDataGen(total_records=40_000, num_keys=4,
                            events_per_second_of_eventtime=5_000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("key")
                .window(TumblingEventTimeWindows.of(100_000))
                .count().sink_to(DiscardingSink()))
            client = cluster.submit(env, "qs-unknown-stages")
            qs = QueryableStateClient(cluster)
            deadline = time.monotonic() + 15
            matched = False
            while time.monotonic() < deadline:
                try:
                    with pytest.raises(KeyError, match="available"):
                        qs.get_state_batch(client.job_id, "nope", [3, 4])
                    matched = True
                    break
                except RuntimeError:
                    time.sleep(0.05)
            assert matched, "job never became queryable within deadline"
            client.cancel()
        finally:
            cluster.shutdown()

    def test_query_unknown_operator_fails(self):
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 256}))
            (env.add_source(
                SlowDataGen(total_records=40_000, num_keys=4,
                            events_per_second_of_eventtime=5_000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("key")
                .window(TumblingEventTimeWindows.of(100_000))
                .count().sink_to(DiscardingSink()))
            client = cluster.submit(env, "qs-unknown")
            qs = QueryableStateClient(cluster)
            deadline = time.monotonic() + 15
            matched = False
            while time.monotonic() < deadline:
                try:
                    with pytest.raises(KeyError):
                        qs.get_state(client.job_id, "nope", 3)
                    matched = True
                    break
                except RuntimeError:
                    time.sleep(0.05)
            assert matched, "job never became queryable within deadline"
            client.cancel()
        finally:
            cluster.shutdown()


class TestClientCoalescerRetirement:
    def test_forget_job_drops_coalescers_keeps_totals(self):
        """Regression: a long-lived client querying many short-lived
        jobs grew one coalescer (+ latency reservoir) per (job,
        operator) forever; forget_job retires them with cumulative
        stats intact."""
        from flink_tpu.cluster.queryable_state import (
            QueryableStateClient,
        )

        client = QueryableStateClient(cluster=None)
        for i in range(4):
            jid = f"job-{i}"
            client._coalescer(jid, "op").note_batch(3, 1.0)
            client.forget_job(jid)
        assert len(client._pool) == 0
        s = client.stats()
        assert s["lookups_total"] == 12
        assert s["lookup_batches_total"] == 4
        assert s["avg_batch_size"] == 3.0

    def test_lookup_racing_retire_folds_into_retained_totals(self):
        """Regression: a lookup that already held the coalescer when
        forget_job/unbind_job retired it recorded its counts on the
        orphaned object — silently dropped from cumulative stats. A
        retired coalescer now redirects post-retirement counts into the
        pool's retained totals."""
        from flink_tpu.cluster.queryable_state import (
            QueryableStateClient,
        )

        client = QueryableStateClient(cluster=None)
        co = client._coalescer("job", "op")
        co.note_batch(2, 1.0)
        client.forget_job("job")        # retires + folds: 2 lookups
        co.note_batch(3, 1.0)           # in-flight rider lands late
        assert len(client._pool) == 0   # not resurrected
        s = client.stats()
        assert s["lookups_total"] == 5  # nothing dropped
        assert s["lookup_batches_total"] == 2

    def test_explicit_batch_recorded_in_client_stats(self):
        """Regression: get_state_batch bypassed the coalescer counters,
        so a client doing only explicit batches (the documented
        high-QPS shape) reported zero 'amortization evidence'."""
        import types

        from flink_tpu.cluster.queryable_state import (
            QueryableStateClient,
        )

        gw = types.SimpleNamespace(
            query_state_batch=lambda j, o, keys, ns: [{}] * len(keys))
        cluster = types.SimpleNamespace(dispatcher_gateway=lambda: gw)
        client = QueryableStateClient(cluster)
        client.get_state_batch("j", "op", [1, 2, 3])
        s = client.stats()
        assert s["lookups_total"] == 3
        assert s["lookup_batches_total"] == 1
        assert s["avg_batch_size"] == 3.0


class TestClientServingPlaneFastPath:
    """r19 client plumbing: against a cluster that exposes a
    ServingPlane (the tenancy session cluster), the client routes batch
    lookups through the plane — the whole key batch probes the native
    hot-row table in ONE call — instead of the RPC control plane; the
    packed form stays lazy until read."""

    def _plane_cluster(self, served):
        import types

        class _Plane:
            def lookup_batch(self, job, op, keys, namespace=None):
                served.append(("dict", job, op, list(keys)))
                return [{1: {"v": float(k)}} for k in keys]

            def lookup_batch_packed(self, job, op, keys):
                from flink_tpu.tenancy.serving import (
                    PackedLookupResult,
                )

                served.append(("packed", job, op, list(keys)))
                return PackedLookupResult.from_dicts(
                    [{1: {"v": float(k)}} for k in keys])

            def lookup(self, job, op, key, namespace=None):
                served.append(("point", job, op, key))
                return {1: {"v": float(key)}}

        def _gw():  # the RPC path must NOT be taken
            raise AssertionError("RPC gateway used despite a plane")

        return types.SimpleNamespace(serving=_Plane(),
                                     dispatcher_gateway=_gw)

    def test_batch_routes_through_plane_not_rpc(self):
        from flink_tpu.cluster.queryable_state import (
            QueryableStateClient,
        )

        served = []
        client = QueryableStateClient(self._plane_cluster(served))
        out = client.get_state_batch("j", "op", [1, 2])
        assert out == [{1: {"v": 1.0}}, {1: {"v": 2.0}}]
        assert served[0][0] == "dict"
        assert client.get_state("j", "op", 7) == {1: {"v": 7.0}}
        assert served[-1][0] == "point"
        # counters: the batch AND the point lookup both recorded
        # client-side (the plane route must not silently stop counting
        # what the legacy coalescer path counted)
        assert client.stats()["lookups_total"] == 3

    def test_packed_batch_lazy_and_bit_identical(self):
        from flink_tpu.cluster.queryable_state import (
            QueryableStateClient,
        )
        from flink_tpu.tenancy.serving import PackedLookupResult

        served = []
        client = QueryableStateClient(self._plane_cluster(served))
        res = client.get_state_batch_packed("j", "op", [3, 4, 5])
        assert isinstance(res, PackedLookupResult)
        assert len(res) == 3
        assert res[1] == {1: {"v": 4.0}}
        assert res.to_dicts() == client.get_state_batch(
            "j", "op", [3, 4, 5])
        assert res == client.get_state_batch("j", "op", [3, 4, 5])

    def test_packed_wraps_rpc_cluster(self):
        import types

        from flink_tpu.cluster.queryable_state import (
            QueryableStateClient,
        )
        from flink_tpu.tenancy.serving import PackedLookupResult

        gw = types.SimpleNamespace(
            query_state_batch=lambda j, o, keys, ns:
            [{0: {"v": 1.0}}] * len(keys))
        cluster = types.SimpleNamespace(dispatcher_gateway=lambda: gw)
        client = QueryableStateClient(cluster)
        res = client.get_state_batch_packed("j", "op", [1, 2])
        assert isinstance(res, PackedLookupResult)
        assert res.to_dicts() == [{0: {"v": 1.0}}] * 2


class TestSlidingWindowQuery:
    def test_query_composes_window_values_from_slices(self):
        """Sliding windows: a query must return true WINDOW results
        (merged across slices), not per-slice fragments."""
        from flink_tpu.state.slot_table import SlotTable
        from flink_tpu.windowing.assigners import SlidingEventTimeWindows

        assigner = SlidingEventTimeWindows.of(1000, 250)  # k = 4 slices
        agg = CountAggregate()
        t = SlotTable(agg, capacity=1024)
        # key 5 gets 3 records in slice (0,250], 2 in (250,500]
        keys = np.array([5] * 5, dtype=np.int64)
        ts = np.array([10, 20, 30, 260, 270], dtype=np.int64)
        ns = assigner.assign_slice_ends(ts)
        slots = t.lookup_or_insert(keys, ns)
        t.scatter(slots, agg.map_input(
            type("B", (), {"__len__": lambda s: 5})()))
        res = t.query_windows(5, assigner)
        # window ending 500 covers both slices -> 5; window ending 250
        # covers only the first slice -> 3
        assert res[500]["count"] == 5
        assert res[250]["count"] == 3
        # per-slice namespaces are NOT window results
        assert set(res) == {250, 500, 750, 1000, 1250}
        assert res[1250]["count"] == 2  # only the second slice reaches it
