"""End-to-end DataStream pipeline tests — the minimum slice of SURVEY.md §7
step 4: source -> key_by -> tumbling window sum -> sink, verified against a
pure-Python oracle."""

import collections

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.aggregates import MultiAggregate, CountAggregate, SumAggregate
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def make_env(**conf):
    c = Configuration(conf)
    return StreamExecutionEnvironment(c)


class TestTumblingWordCountStyle:
    def test_window_sum_matches_oracle(self):
        env = make_env()
        rows = [
            {"key": "a", "v": 1.0, "t": 100},
            {"key": "b", "v": 2.0, "t": 4900},
            {"key": "a", "v": 3.0, "t": 5100},
            {"key": "a", "v": 0.5, "t": 200},
            {"key": "b", "v": 1.5, "t": 9900},
        ]
        result = (
            env.from_collection(rows, timestamp_field="t")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(5000))
            .sum("v")
            .execute_and_collect()
        )
        got = {(r["key"], r["window_start"]): r["sum_v"]
               for r in result.to_rows()}
        assert got == {
            ("a", 0): 1.5, ("b", 0): 2.0,
            ("a", 5000): 3.0, ("b", 5000): 1.5,
        }

    def test_int_keys(self):
        env = make_env()
        rows = [{"key": k, "v": 1.0, "t": 10 * k} for k in range(100)]
        result = (
            env.from_collection(rows, timestamp_field="t")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(500))
            .count()
            .execute_and_collect()
        )
        assert int(result["count"].sum()) == 100
        assert set(result["key"].tolist()) == set(range(100))


class TestMapFilterChain:
    def test_map_filter_window(self):
        env = make_env()
        n = 1000
        rows = [{"key": i % 10, "v": float(i), "t": i} for i in range(n)]
        result = (
            env.from_collection(rows, timestamp_field="t")
            .map(lambda b: b.with_column("v", b["v"] * 2.0))
            .filter(lambda b: b["key"] < 5)
            .key_by("key")
            .window(TumblingEventTimeWindows.of(100))
            .sum("v")
            .execute_and_collect()
        )
        oracle = collections.defaultdict(float)
        for r in rows:
            if r["key"] < 5:
                oracle[(r["key"], (r["t"] // 100) * 100)] += r["v"] * 2.0
        got = {(r["key"], r["window_start"]): r["sum_v"]
               for r in result.to_rows()}
        assert set(got) == set(oracle)
        for k in oracle:
            assert got[k] == pytest.approx(oracle[k], rel=1e-5)


class TestDataGenSliding:
    def test_sliding_window_datagen(self):
        env = make_env(**{"execution.micro-batch.size": 512})
        src = DataGenSource(total_records=5000, num_keys=50,
                            events_per_second_of_eventtime=1000)
        result = (
            env.from_source(
                src,
                WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("key")
            .window(SlidingEventTimeWindows.of(2000, 1000))
            .aggregate(MultiAggregate([CountAggregate(), SumAggregate("value")]))
            .execute_and_collect()
        )
        # each record lands in exactly 2 sliding windows
        assert int(result["count"].sum()) == 2 * 5000

    def test_union(self):
        env = make_env()
        rows1 = [{"key": 1, "v": 1.0, "t": 10}]
        rows2 = [{"key": 1, "v": 2.0, "t": 20}]
        s1 = env.from_collection(rows1, timestamp_field="t")
        s2 = env.from_collection(rows2, timestamp_field="t")
        result = (
            s1.union(s2)
            .key_by("key")
            .window(TumblingEventTimeWindows.of(100))
            .sum("v")
            .execute_and_collect()
        )
        rows = result.to_rows()
        assert len(rows) == 1
        assert rows[0]["sum_v"] == 3.0


class TestWatermarkSemantics:
    def test_out_of_orderness_holds_window_open(self):
        env = make_env(**{"execution.micro-batch.size": 1})
        # with bounded lateness 100, record at t=95 arriving after t=150 is
        # NOT late (watermark at 150-101=49 < 99)
        rows = [
            {"key": 1, "v": 1.0, "t": 150},
            {"key": 1, "v": 2.0, "t": 95},
        ]
        result = (
            env.from_collection(
                rows, timestamp_field="t",
                watermark_strategy=WatermarkStrategy
                .for_bounded_out_of_orderness(100))
            .key_by("key")
            .window(TumblingEventTimeWindows.of(100))
            .sum("v")
            .execute_and_collect()
        )
        got = {r["window_start"]: r["sum_v"] for r in result.to_rows()}
        assert got == {0: 2.0, 100: 1.0}


class TestUntimedInputGuard:
    def test_event_time_window_over_untimed_input_names_the_cause(self):
        """An untimed source (e.g. a mixed union branch) reaching an
        event-time window must fail with the cause, not a KeyError deep
        in the windower."""
        env = make_env()
        timed = env.from_collection(
            [{"key": 1, "v": 1.0, "t": 0}], timestamp_field="t")
        untimed = env.from_collection([{"key": 2, "v": 2.0, "t": 5}])
        with pytest.raises(RuntimeError, match="without timestamps"):
            (timed.union(untimed).key_by("key")
             .window(TumblingEventTimeWindows.of(1000)).sum("v")
             .execute_and_collect())
