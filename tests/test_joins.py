"""Window join + interval join semantics vs pandas-free oracles."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment, Configuration
from flink_tpu.runtime.join_operators import equi_join_indices
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def test_equi_join_indices():
    L = np.array([1, 2, 3, 2], dtype=np.int64)
    R = np.array([2, 2, 4, 1], dtype=np.int64)
    li, ri = equi_join_indices(L, R)
    pairs = sorted(zip(L[li].tolist(), li.tolist(), ri.tolist()))
    # key 1: L[0] x R[3]; key 2: L[1],L[3] x R[0],R[1] -> 1 + 4 = 5 pairs
    assert len(li) == 5
    for l, r in zip(li, ri):
        assert L[l] == R[r]


def test_equi_join_empty():
    e = np.empty(0, dtype=np.int64)
    li, ri = equi_join_indices(e, np.array([1], dtype=np.int64))
    assert len(li) == 0


class TestWindowJoin:
    def test_basic_window_join(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 2}))
        orders = [
            {"user": 1, "amount": 10.0, "t": 100},
            {"user": 2, "amount": 20.0, "t": 200},
            {"user": 1, "amount": 30.0, "t": 1100},
        ]
        clicks = [
            {"user": 1, "page": 7, "t": 150},
            {"user": 1, "page": 8, "t": 250},
            {"user": 3, "page": 9, "t": 300},
        ]
        a = env.from_collection(orders, timestamp_field="t")
        b = env.from_collection(clicks, timestamp_field="t")
        result = (
            a.join(b).where("user").equal_to("user")
            .window(TumblingEventTimeWindows.of(1000))
            .apply()
            .execute_and_collect()
        )
        rows = result.to_rows()
        # window [0,1000): order(u1,10) x clicks(u1@150, u1@250) = 2 pairs
        # window [1000,2000): order(u1,30) has no clicks -> nothing
        assert len(rows) == 2
        for r in rows:
            assert r["user"] == 1
            assert r["amount"] == 10.0
            assert r["page"] in (7, 8)
            assert r["window_start"] == 0

    def test_join_no_overlap_keys(self):
        env = StreamExecutionEnvironment()
        a = env.from_collection([{"k": 1, "t": 0}], timestamp_field="t")
        b = env.from_collection([{"k": 2, "t": 0}], timestamp_field="t")
        result = (a.join(b).where("k").equal_to("k")
                  .window(TumblingEventTimeWindows.of(100))
                  .apply().execute_and_collect())
        assert len(result) == 0


class TestIntervalJoin:
    def test_interval_join_bounds(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1}))
        lefts = [{"k": 1, "lv": i, "t": i * 100} for i in range(4)]
        rights = [{"k": 1, "rv": i, "t": i * 100 + 50} for i in range(4)]
        a = env.from_collection(lefts, timestamp_field="t").key_by("k")
        b = env.from_collection(rights, timestamp_field="t").key_by("k")
        result = a.interval_join(b).between(0, 100).execute_and_collect()
        got = sorted((r["lv"], r["rv"]) for r in result.to_rows())
        # left at t=i*100 matches right r at t=r*100+50 iff
        # 0 <= (r*100+50) - i*100 <= 100  =>  r == i  (only +50 offset fits)
        assert got == [(0, 0), (1, 1), (2, 2), (3, 3)]

    def test_interval_join_asymmetric(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 10}))
        lefts = [{"k": 5, "lv": 0, "t": 1000}]
        rights = [{"k": 5, "rv": i, "t": t}
                  for i, t in enumerate([400, 800, 1200, 1700])]
        a = env.from_collection(lefts, timestamp_field="t").key_by("k")
        b = env.from_collection(rights, timestamp_field="t").key_by("k")
        # right in [t-500, t+500] -> ts 800 and 1200 (endpoints: 500..1500)
        result = a.interval_join(b).between(-500, 500).execute_and_collect()
        got = sorted(r["rv"] for r in result.to_rows())
        assert got == [1, 2]

    def test_no_duplicate_pairs(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 3}))
        lefts = [{"k": 1, "lv": i, "t": 100} for i in range(3)]
        rights = [{"k": 1, "rv": i, "t": 100} for i in range(3)]
        a = env.from_collection(lefts, timestamp_field="t").key_by("k")
        b = env.from_collection(rights, timestamp_field="t").key_by("k")
        result = a.interval_join(b).between(-10, 10).execute_and_collect()
        assert len(result) == 9  # 3x3 exactly once each
