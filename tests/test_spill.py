"""Beyond-HBM state: the spill tier (device -> host -> filesystem).

Round-1 verdict item 3: state must not be bounded by device memory. The
SlotTable becomes an HBM-bounded cache over a host/filesystem SpillTier —
cold namespaces evict wholesale, reload transparently on access, fire and
queries tolerate non-resident slices, and snapshots (full + delta) cover
all tiers.

reference model: RocksDBKeyedStateBackend (state ≫ memory),
ForStStateExecutor.java:149 (batched state movement).
"""

import numpy as np
import pytest

from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.state.keygroups import hash_keys_to_i64
from flink_tpu.state.slot_table import SlotTable, SlotTableFullError
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.windowing.windower import SliceSharedWindower


def table_rows(tbl):
    return {
        (int(k), int(n)): float(v)
        for k, n, v in zip(tbl["key_id"], tbl["namespace"], tbl["leaf_0"])
    }


def fill(table, ns, keys, vals):
    slots = table.lookup_or_insert(
        np.asarray(keys, dtype=np.int64),
        np.full(len(keys), ns, dtype=np.int64))
    table.scatter(slots, (np.asarray(vals, dtype=np.float32),))


class TestSlotTableSpill:
    def test_eviction_and_transparent_reload(self):
        t = SlotTable(SumAggregate("v"), capacity=1024,
                      max_device_slots=1024)
        keys = np.arange(1, 401, dtype=np.int64)
        # 5 namespaces x 400 keys = 2000 rows >> 1023 device slots
        for ns in range(10, 60, 10):
            fill(t, ns, keys, np.full(400, float(ns)))
        assert len(t.spill) > 0  # something actually spilled
        assert set(int(n) for n in t.namespaces) == {10, 20, 30, 40, 50}
        # writing to a spilled namespace reloads it and accumulates on top
        fill(t, 10, keys[:5], np.ones(5))
        q = t.query(int(keys[0]), namespace=10)
        assert q[10]["sum_v"] == 11.0
        # full snapshot covers every tier
        rows = table_rows(t.snapshot())
        assert len(rows) == 2000
        assert rows[(int(keys[0]), 10)] == 11.0
        assert rows[(int(keys[7]), 50)] == 50.0

    def test_budget_exhausted_with_all_protected_fails_loudly(self):
        t = SlotTable(SumAggregate("v"), capacity=1024,
                      max_device_slots=1024)
        with pytest.raises(SlotTableFullError, match="protected"):
            fill(t, 7, np.arange(1, 1500, dtype=np.int64),
                 np.ones(1499))

    def test_free_namespaces_drops_spilled_entries_with_tombstones(self):
        t = SlotTable(SumAggregate("v"), capacity=1024,
                      max_device_slots=1024)
        keys = np.arange(1, 401, dtype=np.int64)
        for ns in (10, 20, 30, 40):
            fill(t, ns, keys, np.full(400, float(ns)))
        spilled_ns = [int(n) for n in t.spill.namespaces]
        assert spilled_ns
        t.snapshot()  # establish delta base
        t.free_namespaces([spilled_ns[0]])
        assert spilled_ns[0] not in t.spill
        delta = t.snapshot_delta()
        assert spilled_ns[0] in delta["freed_namespaces"].tolist()
        assert spilled_ns[0] not in {int(n) for n in t.namespaces}

    def test_delta_includes_dirty_spilled_namespaces(self):
        from flink_tpu.checkpoint.storage import apply_table_delta

        t = SlotTable(SumAggregate("v"), capacity=1024,
                      max_device_slots=1024)
        keys = np.arange(1, 401, dtype=np.int64)
        fill(t, 10, keys, np.ones(400))
        base = t.snapshot()
        # dirty ns 10, then push it out with new namespaces
        fill(t, 10, keys[:3], np.ones(3))
        for ns in (20, 30, 40):
            fill(t, ns, keys, np.full(400, float(ns)))
        assert 10 in t.spill  # evicted while dirty
        delta = t.snapshot_delta()
        merged = table_rows(apply_table_delta(base, delta))
        full = table_rows(t.snapshot())
        assert merged == full
        assert merged[(1, 10)] == 2.0

    def test_filesystem_tier_roundtrip(self, tmp_path):
        spill_dir = str(tmp_path / "spill")
        t = SlotTable(SumAggregate("v"), capacity=1024,
                      max_device_slots=1024,
                      spill_dir=spill_dir,
                      spill_host_max_bytes=1)  # everything overflows to fs
        keys = np.arange(1, 401, dtype=np.int64)
        for ns in (10, 20, 30, 40, 50):
            fill(t, ns, keys, np.full(400, float(ns)))
        import os

        assert t.spill._fs, "nothing reached the filesystem tier"
        assert os.listdir(spill_dir)
        # reload from fs on access
        q = t.query(1, namespace=int(next(iter(t.spill._fs))))
        assert list(q.values())[0]["sum_v"] > 0
        rows = table_rows(t.snapshot())
        assert len(rows) == 2000

    def test_restore_empty_snapshot_into_bounded_table(self):
        """A checkpoint taken before any state existed must restore cleanly
        on the spill path (regression: empty-array indexing)."""
        empty = SlotTable(SumAggregate("v"), capacity=1024).snapshot()
        t = SlotTable(SumAggregate("v"), capacity=1024,
                      max_device_slots=1024)
        t.restore(empty)
        assert t.num_used == 0
        fill(t, 10, np.asarray([1, 2]), np.asarray([1.0, 2.0]))
        assert t.query(1, namespace=10)[10]["sum_v"] == 1.0

    def test_restore_lazy_loads_into_bounded_table(self):
        """A snapshot far larger than the device budget restores (rows land
        in the spill tier) and serves reads/writes correctly."""
        big = SlotTable(SumAggregate("v"), capacity=1 << 13)
        keys = np.arange(1, 2001, dtype=np.int64)
        for ns in (10, 20, 30):
            fill(big, ns, keys, np.full(2000, float(ns)))
        snap = big.snapshot()

        small = SlotTable(SumAggregate("v"), capacity=1024,
                          max_device_slots=2048)
        small.restore(snap)
        assert table_rows(small.snapshot(reset_dirty=False)) == \
            table_rows(snap)
        fill(small, 10, keys[:4], np.ones(4))
        assert small.query(1, namespace=10)[10]["sum_v"] == 11.0


class TestWindowedJobWithSpill:
    @staticmethod
    def run_job(extra, total=60_000, num_keys=3000):
        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 1024, **extra}))
        sink = CollectSink()
        (env.add_source(
            DataGenSource(total_records=total, num_keys=num_keys,
                          events_per_second_of_eventtime=10_000),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("key")
            .window(SlidingEventTimeWindows.of(4000, 1000))
            .count()
            .sink_to(sink))
        env.execute()
        return {(int(r["key"]), int(r["window_start"])): int(r["count"])
                for r in sink.rows()}

    def test_sliding_window_job_matches_oracle_under_heavy_spill(self):
        """Live state (~5 slices x 3000 keys) is several times the device
        budget; results must equal the unbounded run exactly — including
        hybrid fires where part of a window's slices are spilled."""
        unbounded = self.run_job({})
        spilled = self.run_job({"state.slot-table.max-device-slots": 4096})
        assert unbounded == spilled
        # each record lands in 4 sliding windows (size 4000 / slide 1000)
        assert sum(spilled.values()) == 4 * 60_000

    def test_checkpoint_restore_with_spill(self, tmp_path):
        """Exactly-once across failover with the spill tier active."""
        import os

        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
        from flink_tpu.connectors.two_phase import ExactlyOnceFileSink

        out = str(tmp_path / "out")
        ck = str(tmp_path / "ck")
        flag = str(tmp_path / "crashed.flag")
        total = 20_000

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2,
            "state.slot-table.max-device-slots": 2048,
            "restart-strategy.max-attempts": 3,
            "restart-strategy.delay-ms": 10,
        }))

        def poison_once(b, flag=flag):
            ts = b.timestamps
            if len(ts) and ts.max() > 900 and not os.path.exists(flag):
                open(flag, "w").write("x")
                raise RuntimeError("injected fault")
            return b

        (env.add_source(DataGenSource(total_records=total, num_keys=900,
                                      events_per_second_of_eventtime=10_000),
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
            .map(poison_once, name="poison")
            .key_by("key")
            .window(SlidingEventTimeWindows.of(2000, 500))
            .count()
            .sink_to(ExactlyOnceFileSink(out)))

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            client = cluster.submit(env, "spill-2pc-job")
            st = client.wait(timeout=120)
            assert st["status"] == FINISHED
            assert st["attempt"] >= 1
        finally:
            cluster.shutdown()
        rows = ExactlyOnceFileSink.read_committed_rows(out)
        per_window = {}
        for r in rows:
            k = (int(r["key"]), int(r["window_start"]))
            assert k not in per_window, f"duplicate committed window {k}"
            per_window[k] = int(r["count"])
        # each record lands in 4 sliding windows
        assert sum(per_window.values()) == 4 * total

    def test_session_job_with_keys_beyond_device_budget(self):
        """The BASELINE 10M-key session shape, scaled down: live sessions
        (one per key) far exceed the device slot budget; idle sessions
        spill and reload on merge/fire. Results must match the unbounded
        run exactly."""
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        def run(extra):
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 1024, **extra}))
            sink = CollectSink()
            (env.add_source(
                DataGenSource(total_records=40_000, num_keys=5_000,
                              events_per_second_of_eventtime=2_000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
                .key_by("key")
                .window(EventTimeSessionWindows.with_gap(800))
                .count()
                .sink_to(sink))
            env.execute()
            return sorted(
                (int(r["key"]), int(r["window_start"]),
                 int(r["window_end"]), int(r["count"]))
                for r in sink.rows())

        unbounded = run({})
        spilled = run({"state.slot-table.max-device-slots": 2048})
        assert unbounded == spilled
        assert sum(c for _, _, _, c in spilled) == 40_000

    def test_query_windows_spans_tiers(self):
        assigner = SlidingEventTimeWindows.of(2000, 500)
        w_spill = SliceSharedWindower(
            assigner, SumAggregate("v"), capacity=1024,
            spill={"max_device_slots": 1024})
        w_ref = SliceSharedWindower(assigner, SumAggregate("v"),
                                    capacity=1 << 13)
        rng = np.random.default_rng(5)
        n = 20_000
        keys = rng.integers(0, 900, n)
        batch = RecordBatch.from_pydict({
            "key": keys,
            "v": rng.random(n).astype(np.float32),
            TIMESTAMP_FIELD: rng.integers(0, 3000, n),
        }).with_column(KEY_ID_FIELD, hash_keys_to_i64(keys))
        w_spill.process_batch(batch)
        w_ref.process_batch(batch)
        assert len(w_spill.table.spill) > 0
        for key in (1, 57, 899):
            kid = int(hash_keys_to_i64(np.asarray([key]))[0])
            a = w_ref.query_windows(kid)
            b = w_spill.query_windows(kid)
            assert set(a) == set(b) and a
            for w in a:
                np.testing.assert_allclose(a[w]["sum_v"], b[w]["sum_v"],
                                           rtol=1e-5)
