"""Native C++ slot map vs pure-Python index parity + direct behavior."""

import numpy as np
import pytest

from flink_tpu.native import slotmap_available
from flink_tpu.state.slot_table import HostSlotIndex, NativeSlotIndex

needs_native = pytest.mark.skipif(
    not slotmap_available(), reason="native slotmap not built")


@needs_native
class TestNativeSlotIndex:
    def test_basic_insert_lookup(self):
        idx = NativeSlotIndex(1024)
        keys = np.array([5, 6, 5, 7], dtype=np.int64)
        ns = np.array([1, 1, 1, 2], dtype=np.int64)
        slots = idx.lookup_or_insert(keys, ns)
        assert slots[0] == slots[2]
        assert len({slots[0], slots[1], slots[3]}) == 3
        assert slots.min() >= 1
        assert idx.num_used == 3
        # idempotent lookup
        again = idx.lookup_or_insert(keys, ns)
        np.testing.assert_array_equal(slots, again)

    def test_metadata_views(self):
        idx = NativeSlotIndex(1024)
        slots = idx.lookup_or_insert(np.array([42], dtype=np.int64),
                                     np.array([7], dtype=np.int64))
        s = int(slots[0])
        assert idx.slot_key[s] == 42
        assert idx.slot_ns[s] == 7
        assert bool(idx.slot_used[s])

    def test_growth_rewraps_and_notifies(self):
        grows = []
        idx = NativeSlotIndex(1024, on_grow=lambda o, n: grows.append((o, n)))
        n = 5000
        idx.lookup_or_insert(np.arange(n, dtype=np.int64),
                             np.zeros(n, dtype=np.int64))
        assert idx.capacity >= n
        assert grows and grows[-1][1] == idx.capacity
        assert idx.num_used == n

    def test_not_growable_raises(self):
        idx = NativeSlotIndex(1024, growable=False, full_hint="HINT")
        with pytest.raises(RuntimeError, match="HINT"):
            idx.lookup_or_insert(np.arange(2000, dtype=np.int64),
                                 np.zeros(2000, dtype=np.int64))

    def test_free_namespaces_and_reuse(self):
        idx = NativeSlotIndex(1024)
        keys = np.arange(100, dtype=np.int64)
        ns = np.full(100, 9, dtype=np.int64)
        slots = idx.lookup_or_insert(keys, ns)
        freed = idx.free_namespaces([9])
        assert sorted(freed.tolist()) == sorted(slots.tolist())
        assert idx.num_used == 0
        # reinsert reuses freed slots
        slots2 = idx.lookup_or_insert(keys, ns)
        assert idx.num_used == 100
        assert set(slots2.tolist()) <= set(range(1, 1024))

    def test_parity_with_python_index(self):
        rng = np.random.default_rng(0)
        nat = NativeSlotIndex(1 << 12)
        py = HostSlotIndex(1 << 12)
        for step in range(10):
            n = 2000
            keys = rng.integers(0, 500, n).astype(np.int64)
            ns = rng.integers(0, 8, n).astype(np.int64)
            s_n = nat.lookup_or_insert(keys, ns)
            s_p = py.lookup_or_insert(keys, ns)
            # slot numbers may differ; the *mapping* must agree
            assert nat.num_used == py.num_used
            np.testing.assert_array_equal(nat.slot_key[s_n], keys)
            np.testing.assert_array_equal(nat.slot_ns[s_n], ns)
            np.testing.assert_array_equal(py.slot_key[s_p], keys)
            if step % 3 == 2:
                dead = int(rng.integers(0, 8))
                f_n = nat.free_namespaces([dead])
                f_p = py.free_namespaces([dead])
                assert (f_n is None) == (f_p is None)
                if f_n is not None:
                    assert len(f_n) == len(f_p)
                assert nat.num_used == py.num_used

    def test_duplicate_heavy_batch(self):
        idx = NativeSlotIndex(1024)
        keys = np.zeros(10000, dtype=np.int64)
        ns = np.zeros(10000, dtype=np.int64)
        slots = idx.lookup_or_insert(keys, ns)
        assert len(np.unique(slots)) == 1
        assert idx.num_used == 1
