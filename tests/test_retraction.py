"""Retraction / changelog semantics for unbounded GROUP BY.

reference: GroupAggFunction.java:85 emits UPDATE_BEFORE/UPDATE_AFTER pairs
(and DELETE on count-to-zero) so downstream operators compose over updating
results. The classic probe is the two-level "count of counts" aggregate,
which silently double-counts without retractions.
"""

import numpy as np
import pytest

from flink_tpu.core.records import (
    ROWKIND_DELETE,
    ROWKIND_FIELD,
    ROWKIND_INSERT,
    ROWKIND_UPDATE_AFTER,
    ROWKIND_UPDATE_BEFORE,
    RecordBatch,
)
from flink_tpu.runtime.group_agg import GroupAggOperator
from flink_tpu.windowing.aggregates import (
    AvgAggregate,
    CountAggregate,
    MaxAggregate,
    MultiAggregate,
    SumAggregate,
)


class _Ctx:
    parallelism = 1
    max_parallelism = 128


def _batch(keys, vals=None, kinds=None, ts=0):
    cols = {
        "__key_id__": np.asarray(keys, dtype=np.int64),
        "k": np.asarray(keys, dtype=np.int64),
        "__ts__": np.full(len(keys), ts, dtype=np.int64),
    }
    if vals is not None:
        cols["v"] = np.asarray(vals, dtype=np.float32)
    if kinds is not None:
        cols[ROWKIND_FIELD] = np.asarray(kinds, dtype=np.int8)
    return RecordBatch(cols)


def _rows(batches):
    out = []
    for b in batches:
        out.extend(b.to_rows())
    return out


class TestChangelogEmission:
    def test_insert_then_update_pair(self):
        op = GroupAggOperator(CountAggregate(), "k")
        op.open(_Ctx())
        out1 = _rows(op.process_batch(_batch([7])))
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out1] == \
            [(ROWKIND_INSERT, 1)]
        out2 = _rows(op.process_batch(_batch([7])))
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out2] == \
            [(ROWKIND_UPDATE_BEFORE, 1), (ROWKIND_UPDATE_AFTER, 2)]

    def test_delete_on_count_to_zero(self):
        op = GroupAggOperator(CountAggregate(), "k")
        op.open(_Ctx())
        op.process_batch(_batch([5]))
        out = _rows(op.process_batch(
            _batch([5], kinds=[ROWKIND_DELETE])))
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out] == \
            [(ROWKIND_DELETE, 1)]
        # reappearing key is a fresh INSERT
        out2 = _rows(op.process_batch(_batch([5])))
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out2] == \
            [(ROWKIND_INSERT, 1)]

    def test_upsert_mode_suppresses_update_before(self):
        op = GroupAggOperator(CountAggregate(), "k",
                              generate_update_before=False)
        op.open(_Ctx())
        op.process_batch(_batch([1]))
        out = _rows(op.process_batch(_batch([1])))
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out] == \
            [(ROWKIND_UPDATE_AFTER, 2)]

    def test_minibatch_emits_net_change_per_watermark(self):
        op = GroupAggOperator(CountAggregate(), "k",
                              emit_on_watermark_only=True)
        op.open(_Ctx())
        assert op.process_batch(_batch([3])) == []
        assert op.process_batch(_batch([3, 3])) == []
        out = _rows(op.process_watermark(100))
        # one INSERT with the net value — intermediate states skipped
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out] == \
            [(ROWKIND_INSERT, 3)]
        op.process_batch(_batch([3]))
        out2 = _rows(op.process_watermark(200))
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out2] == \
            [(ROWKIND_UPDATE_BEFORE, 3), (ROWKIND_UPDATE_AFTER, 4)]

    def test_retraction_input_folds_signed(self):
        op = GroupAggOperator(
            MultiAggregate([SumAggregate("v", output="s"),
                            CountAggregate(output="n")]), "k")
        op.open(_Ctx())
        op.process_batch(_batch([1, 1], vals=[10.0, 20.0]))
        out = _rows(op.process_batch(_batch(
            [1, 1], vals=[10.0, 15.0],
            kinds=[ROWKIND_UPDATE_BEFORE, ROWKIND_UPDATE_AFTER])))
        ua = [r for r in out if r[ROWKIND_FIELD] == ROWKIND_UPDATE_AFTER]
        assert len(ua) == 1
        assert ua[0]["s"] == pytest.approx(35.0)  # 10+20-10+15
        assert ua[0]["n"] == 2

    def test_non_retractable_agg_rejects_updates(self):
        op = GroupAggOperator(MaxAggregate("v"), "k")
        op.open(_Ctx())
        with pytest.raises(ValueError, match="retractable"):
            op.process_batch(_batch([1], vals=[5.0],
                                    kinds=[ROWKIND_UPDATE_BEFORE]))

    def test_changelog_state_survives_restore(self):
        op = GroupAggOperator(CountAggregate(), "k")
        op.open(_Ctx())
        op.process_batch(_batch([9]))
        snap = op.snapshot_state()
        op2 = GroupAggOperator(CountAggregate(), "k")
        op2.open(_Ctx())
        op2.restore_state(snap)
        out = _rows(op2.process_batch(_batch([9])))
        # restored operator knows key 9 was emitted -> UB/UA, not INSERT
        assert [(r[ROWKIND_FIELD], r["count"]) for r in out] == \
            [(ROWKIND_UPDATE_BEFORE, 1), (ROWKIND_UPDATE_AFTER, 2)]


def make_tenv():
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.table.environment import StreamTableEnvironment

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 4,  # force multi-batch updates
    }))
    return StreamTableEnvironment(env)


def _bid_rows(pairs):
    return [{"auction": a, "price": float(p), "ts": t}
            for a, p, t in pairs]


class TestTwoLevelSql:
    def test_count_of_counts(self):
        """SELECT c, COUNT(*) FROM (per-auction counts) GROUP BY c — wrong
        without retractions (stale groups keep phantom members)."""
        t_env = make_tenv()
        pairs = [(a, 1, i * 100) for i, a in enumerate(
            [1, 2, 3, 1, 2, 1, 4, 4, 4, 4])]
        t_env.create_temporary_view(
            "bid", t_env.from_collection(_bid_rows(pairs),
                                         timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT c, COUNT(*) AS n FROM "
            "(SELECT auction, COUNT(*) AS c FROM bid GROUP BY auction) "
            "GROUP BY c").collect()
        # final counts: a1=3, a2=2, a3=1, a4=4 -> c=3:1, c=2:1, c=1:1, c=4:1
        got = {r["c"]: r["n"] for r in out}
        assert got == {3: 1, 2: 1, 1: 1, 4: 1}

    def test_sum_over_updating_counts(self):
        t_env = make_tenv()
        pairs = [(a, 1, i * 100) for i, a in enumerate([1, 1, 2, 2, 2])]
        t_env.create_temporary_view(
            "bid", t_env.from_collection(_bid_rows(pairs),
                                         timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT SUM(c) AS total, AVG(c) AS mean FROM "
            "(SELECT auction, COUNT(*) AS c FROM bid GROUP BY auction)"
        ).collect()
        assert len(out) == 1
        assert out[0]["total"] == 5  # 2 + 3
        assert out[0]["mean"] == pytest.approx(2.5)

    def test_max_over_updating_input_rejected(self):
        from flink_tpu.table.planner import PlanError

        t_env = make_tenv()
        t_env.create_temporary_view(
            "bid", t_env.from_collection(
                _bid_rows([(1, 1, 0)]), timestamp_field="ts"))
        with pytest.raises(PlanError, match="retractable"):
            t_env.execute_sql(
                "SELECT MAX(c) AS m FROM "
                "(SELECT auction, COUNT(*) AS c FROM bid "
                "GROUP BY auction)")

    def test_window_over_updating_input_rejected(self):
        from flink_tpu.table.planner import PlanError

        t_env = make_tenv()
        t_env.create_temporary_view(
            "bid", t_env.from_collection(
                _bid_rows([(1, 1, 0)]), timestamp_field="ts"))
        t_env.execute_sql(
            "CREATE VIEW counts AS SELECT auction, COUNT(*) AS c "
            "FROM bid GROUP BY auction")
        with pytest.raises(PlanError, match="updating"):
            t_env.execute_sql(
                "SELECT window_end, COUNT(*) AS n FROM TABLE("
                "TUMBLE(TABLE counts, DESCRIPTOR(ts), "
                "INTERVAL '10' SECOND)) "
                "GROUP BY window_start, window_end")

    def test_single_level_unchanged(self):
        """Plain GROUP BY still materializes the same final table."""
        t_env = make_tenv()
        pairs = [(1, 10, 1000), (2, 20, 2000), (1, 30, 3000)]
        t_env.create_temporary_view(
            "bid", t_env.from_collection(_bid_rows(pairs),
                                         timestamp_field="ts"))
        out = t_env.execute_sql(
            "SELECT auction, SUM(price) AS total FROM bid "
            "GROUP BY auction").collect()
        got = {r["auction"]: r["total"] for r in out}
        assert got == {1: 40.0, 2: 20.0}
