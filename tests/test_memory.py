"""Managed device-memory accounting (flink_tpu/core/memory.py).

reference: flink-runtime/.../memory/MemoryManager.java — one managed
pool per slot; reservations fail with a breakdown, never an opaque OOM."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.memory import MemoryManager, MemoryReservationError
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


class TestPool:
    def test_reserve_release(self):
        m = MemoryManager(1000)
        m.reserve("a", 400)
        m.reserve("b", 500)
        assert m.reserved_bytes == 900
        with pytest.raises(MemoryReservationError, match="a=400"):
            m.reserve("c", 200)
        m.release("a", 400)
        m.reserve("c", 200)
        assert m.usage() == {"b": 500, "c": 200}
        assert m.release_all("b") == 500
        assert m.reserved_bytes == 200

    def test_unlimited_by_default(self):
        m = MemoryManager(0)
        m.reserve("x", 1 << 40)
        assert m.reserved_bytes == 1 << 40


def _pipeline(env, capacity=1 << 14):
    sink = CollectSink()
    src = DataGenSource(total_records=30_000, num_keys=200,
                        events_per_second_of_eventtime=10_000, seed=5)
    (env.from_source(src,
                     WatermarkStrategy.for_bounded_out_of_orderness(0))
       .key_by("key").window(TumblingEventTimeWindows.of(1000))
       .sum("value").sink_to(sink))
    return sink


class TestJobAccounting:
    def test_job_runs_inside_budget(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "state.slot-table.capacity": 4096,
            "memory.device.size": 64 * 1024 * 1024}))
        sink = _pipeline(env)
        env.execute("budgeted")
        assert len(sink.result()) > 0

    def test_over_budget_fails_with_breakdown(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "state.slot-table.capacity": 1 << 16,
            "memory.device.size": 1024}))  # absurdly small
        _pipeline(env)
        with pytest.raises(MemoryReservationError,
                           match="memory.device.size"):
            env.execute("starved")

    def test_pane_layout_accounted_too(self):
        from flink_tpu.core.records import RecordBatch  # noqa: F401

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1000,
            "state.slot-table.capacity": 1 << 16,
            "state.window-layout": "panes",
            "memory.device.size": 1024}))
        _pipeline(env)
        with pytest.raises(MemoryReservationError,
                           match="memory.device.size"):
            env.execute("panes-starved")

    def test_growth_reserves_and_dispose_releases(self):
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )
        from flink_tpu.windowing.aggregates import SumAggregate

        mm = MemoryManager(1 << 30)
        op = WindowAggOperator(TumblingEventTimeWindows.of(1000),
                               SumAggregate("v"), "key", capacity=1024)
        op.open(OperatorContext(max_parallelism=128,
                                memory_manager=mm))
        base = mm.reserved_bytes
        assert base > 0
        # force index growth past the initial capacity
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.state.keygroups import hash_keys_to_i64

        n = 5000
        b = RecordBatch.from_pydict(
            {"key": np.arange(n, dtype=np.int64),
             "v": np.ones(n)},
            timestamps=np.zeros(n, dtype=np.int64))
        b = b.with_column("__key_id__", hash_keys_to_i64(b["key"]))
        op.process_batch(b)
        assert mm.reserved_bytes > base  # grew
        op.dispose()
        assert mm.reserved_bytes == 0