"""FileSink — bucketed, rolling, exactly-once file output.

reference: flink-connector-files FileSink (BucketAssigner /
DateTimeBucketAssigner, DefaultRollingPolicy, pending -> finished part
lifecycle through the two-phase committer).
"""

import json
import os

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.filesystem import (
    ColumnBucketAssigner,
    DateTimeBucketAssigner,
    FileSink,
    RollingPolicy,
    read_committed_rows,
)
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _batch(vals, ts=None):
    return RecordBatch({
        "v": np.asarray(vals, dtype=np.int64),
        TIMESTAMP_FIELD: np.asarray(
            ts if ts is not None else [0] * len(vals), dtype=np.int64)})


class TestFileSinkUnit:
    def test_nothing_visible_before_commit(self, tmp_path):
        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="json")
        sink.open(0)
        sink.write(_batch([1, 2, 3]))
        assert read_committed_rows(d) == []          # only .inprogress
        pend = sink.prepare_commit()
        assert read_committed_rows(d) == []          # sealed, not visible
        sink.commit(pend)
        rows = [json.loads(r) for r in read_committed_rows(d)]
        assert sorted(r["v"] for r in rows) == [1, 2, 3]
        sink.commit(pend)                            # idempotent

    def test_datetime_bucketing_by_event_time(self, tmp_path):
        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="json",
                        bucket_assigner=DateTimeBucketAssigner(
                            "%Y-%m-%d--%H"))
        sink.open(0)
        hour = 3_600_000
        sink.write(_batch([1, 2, 3],
                          ts=[0, hour, hour]))       # two hour buckets
        sink.commit(sink.prepare_commit())
        buckets = sorted(os.listdir(d))
        assert buckets == ["1970-01-01--00", "1970-01-01--01"]
        rows0 = [json.loads(r) for r in read_committed_rows(
            os.path.join(d, buckets[0]))]
        assert [r["v"] for r in rows0] == [1]

    def test_column_bucketing(self, tmp_path):
        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="json",
                        bucket_assigner=ColumnBucketAssigner("v"))
        sink.open(0)
        sink.write(_batch([7, 8, 7]))
        sink.commit(sink.prepare_commit())
        assert sorted(os.listdir(d)) == ["7", "8"]

    def test_rolling_by_records_makes_multiple_parts(self, tmp_path):
        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="json",
                        rolling_policy=RollingPolicy(max_part_records=2))
        sink.open(0)
        for i in range(5):
            sink.write(_batch([i]))
        sink.commit(sink.prepare_commit())
        parts = [f for f in os.listdir(d) if not f.endswith(".inprogress")]
        assert len(parts) >= 2                       # rolled at least once
        rows = [json.loads(r) for r in read_committed_rows(d)]
        assert sorted(r["v"] for r in rows) == [0, 1, 2, 3, 4]

    def test_avro_binary_framing_roundtrips(self, tmp_path):
        """Binary rows (avro varints contain 0x0A freely) use
        length-prefixed framing — newline framing would corrupt them."""
        from flink_tpu.connectors.formats import resolve_format

        d = str(tmp_path / "out")
        # v=5 zigzag-encodes to 0x0A — the exact corruption case
        sink = FileSink(d, ["v"], fmt="avro", types=["BIGINT"])
        sink.open(0)
        sink.write(_batch([5, 7, 1000]))
        sink.commit(sink.prepare_commit())
        raw = read_committed_rows(d, binary=True)
        assert len(raw) == 3
        deser, _ = resolve_format("avro", ["v"], ["BIGINT"])
        got = deser.deserialize_batch(raw)
        assert got["v"].tolist() == [5, 7, 1000]

    def test_avro_filesource_roundtrip_default_framing(self, tmp_path):
        """FileSource must derive the length-prefix framing from the
        deserializer itself — NO explicit binary flag anywhere. A
        text-framed read of avro parts newline-splits on 0x0A payload
        bytes (v=5 zigzag-encodes to 0x0A) and silently corrupts rows."""
        from flink_tpu.connectors.filesystem import FileSource
        from flink_tpu.connectors.formats import resolve_format

        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="avro", types=["BIGINT"])
        sink.open(0)
        sink.write(_batch([5, 7, 1000]))
        sink.commit(sink.prepare_commit())
        deser, _ = resolve_format("avro", ["v"], ["BIGINT"])
        src = FileSource(d, deser)
        src.open()
        got = src.poll_batch(100)
        assert got is not None and got["v"].tolist() == [5, 7, 1000]
        assert src.poll_batch(100) is None

    def test_csv_format_through_the_seam(self, tmp_path):
        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="csv")
        sink.open(0)
        sink.write(_batch([5, 6]))
        sink.commit(sink.prepare_commit())
        assert [r.strip() for r in read_committed_rows(d)] == [b"5", b"6"]

    def test_abort_uncommitted_cleans_inprogress(self, tmp_path):
        d = str(tmp_path / "out")
        sink = FileSink(d, ["v"], fmt="json")
        sink.open(0)
        sink.write(_batch([1]))
        pend = sink.prepare_commit()
        sink.write(_batch([2]))                      # unsealed leftover
        sink2 = FileSink(d, ["v"], fmt="json")
        sink2.open(0)
        sink2.abort_uncommitted(pend)
        sink2.commit(pend)
        rows = [json.loads(r) for r in read_committed_rows(d)]
        assert [r["v"] for r in rows] == [1]         # the 2 never commits

    def test_abort_uncommitted_spares_peer_subtasks(self, tmp_path):
        """Parallel sinks share one base_path: subtask 0's restore-time
        cleanup must only touch its OWN part-0-* leftovers, never a
        peer's committable or freshly opened in-progress part."""
        d = str(tmp_path / "out")
        peer = FileSink(d, ["v"], fmt="json")
        peer.open(1)
        peer.write(_batch([10]))
        peer_pend = peer.prepare_commit()            # sealed, uncommitted
        peer.write(_batch([11]))                     # freshly open part

        own = FileSink(d, ["v"], fmt="json")
        own.open(0)
        own.write(_batch([1]))                       # own leftover

        restored = FileSink(d, ["v"], fmt="json")
        restored.open(0)
        restored.abort_uncommitted([])               # subtask 0 restores
        # own leftover cleaned, both peer files intact
        leftovers = [f for r, _, fs in os.walk(d) for f in fs
                     if f.endswith(".inprogress")]
        assert not any(f.startswith("part-0-") for f in leftovers)
        assert len([f for f in leftovers if f.startswith("part-1-")]) == 2
        peer.commit(peer_pend)                       # still committable
        rows = [json.loads(r) for r in read_committed_rows(d)]
        assert [r["v"] for r in rows] == [10]


def test_exactly_once_under_failover(tmp_path):
    """Fault mid-job, restart from checkpoint: committed bucketed output
    holds every window exactly once."""
    out = str(tmp_path / "out")
    ck = str(tmp_path / "ck")
    flag = str(tmp_path / "crashed.flag")
    total = 20_000

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 256,
        "state.checkpoints.dir": ck,
        "execution.checkpointing.every-n-source-batches": 2,
        "restart-strategy.max-attempts": 3,
        "restart-strategy.delay-ms": 10,
    }))

    def poison_once(b, flag=flag):
        ts = b.timestamps
        if len(ts) and ts.max() > 900 and not os.path.exists(flag):
            open(flag, "w").write("x")
            raise RuntimeError("injected fault")
        return b

    sink = FileSink(out, ["key", "window_start", "sum_value"], fmt="json",
                    bucket_assigner=ColumnBucketAssigner("key"))
    (env.add_source(DataGenSource(total_records=total, num_keys=10,
                                  events_per_second_of_eventtime=10_000),
                    WatermarkStrategy.for_bounded_out_of_orderness(0))
        .map(poison_once, name="poison")
        .key_by("key").window(TumblingEventTimeWindows.of(500))
        .sum("value").sink_to(sink))

    from flink_tpu.cluster.minicluster import FINISHED, MiniCluster

    cluster = MiniCluster(Configuration({"rest.port": -1}))
    try:
        client = cluster.submit(env, "file-sink-failover")
        st = client.wait(timeout=60)
        assert st["status"] == FINISHED
        assert st["attempt"] >= 1  # the fault really fired
    finally:
        cluster.shutdown()

    rows = [json.loads(r) for r in read_committed_rows(out)]
    seen = {}
    for r in rows:
        k = (r["key"], r["window_start"])
        assert k not in seen, f"window emitted twice: {k}"
        seen[k] = r["sum_value"]
    # one bucket directory per key, every window exactly once:
    # 20k records at 10k ev/s of event time = 2 s span = 4 windows of
    # 500 ms, per key
    assert sorted(os.listdir(out)) == [str(k) for k in range(10)]
    assert len(seen) == 10 * 4
    # and the committed sums cover every record exactly once
    assert sum(seen.values()) == pytest.approx(
        total * 0.5, rel=0.1)  # DataGen values ~U(0,1)
