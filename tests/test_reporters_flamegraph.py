"""Direct coverage for metrics/reporters.py text rendering and
metrics/flamegraph.py folding — both previously tested only through
smoke paths (reference: flink-metrics-prometheus reporter tests +
VertexFlameGraph factory tests).
"""

import threading
import time

from flink_tpu.metrics import (
    MetricRegistry,
    PrometheusReporter,
)
from flink_tpu.metrics.core import Meter, SettableGauge
from flink_tpu.metrics.flamegraph import sample_flame_graph
from flink_tpu.metrics.reporters import _prom_name


class TestPrometheusRendering:
    def _render(self, registry):
        rep = PrometheusReporter()
        rep.open(registry)
        return rep.render()

    def test_histogram_quantiles_are_real_values(self):
        reg = MetricRegistry()
        h = reg.root_group("job", "q").histogram("lat")
        for v in range(1, 101):
            h.update(float(v))
        text = self._render(reg)
        lines = {l.split(" ")[0]: l for l in text.splitlines()
                 if l and not l.startswith("#")}
        # quantile sample lines carry the histogram's actual data, and
        # the summary count line matches the update count
        p50 = next(l for l in text.splitlines()
                   if 'quantile="0.5"' in l)
        p99 = next(l for l in text.splitlines()
                   if 'quantile="0.99"' in l)
        assert 45.0 <= float(p50.rsplit(" ", 1)[1]) <= 55.0
        assert float(p99.rsplit(" ", 1)[1]) >= 95.0
        count_line = next(k for k in lines if "lat_count" in k)
        assert lines[count_line].rsplit(" ", 1)[1] == "100"

    def test_name_sanitization(self):
        # scopes/names with Prometheus-hostile characters render as
        # legal metric names (only [a-zA-Z0-9_:])
        assert _prom_name(("flink_tpu", "win agg#3", "fire-p99.ms")) \
            == "flink_tpu_win_agg_3_fire_p99_ms"
        reg = MetricRegistry()
        reg.root_group("job", "my job!").counter("weird metric#1").inc(2)
        text = self._render(reg)
        for line in text.splitlines():
            if line.startswith("#") or not line:
                continue
            name = line.split("{")[0].split(" ")[0]
            assert all(c.isalnum() or c in "_:" for c in name), line

    def test_deep_scope_renders_as_labels(self):
        reg = MetricRegistry()
        reg.root_group("job", "j1", "op#2").counter("numRecordsIn").inc(5)
        text = self._render(reg)
        line = next(l for l in text.splitlines()
                    if "numRecordsIn" in l and not l.startswith("#"))
        assert 'scope_0="job"' in line and 'scope_1="j1"' in line
        assert line.endswith(" 5")

    def test_meter_renders_as_gauge_rate(self):
        reg = MetricRegistry()
        m = reg.root_group("job", "j").meter("throughput")
        assert isinstance(m, Meter)
        m.mark(10)
        time.sleep(0.01)
        m.mark(10)
        text = self._render(reg)
        assert "# TYPE flink_tpu_j_throughput gauge" in text

    def test_non_numeric_gauges_are_skipped(self):
        reg = MetricRegistry()
        g = reg.root_group("job", "j")
        g.gauge("shape", lambda: "rows=[1,2]")
        g.gauge("flag", lambda: True)  # bools are not samples either
        sg = g.settable_gauge("depth", 0)
        assert isinstance(sg, SettableGauge)
        sg.set(3)
        text = self._render(reg)
        assert "shape" not in text
        assert "flag" not in text
        assert "flink_tpu_j_depth{" in text or \
            "flink_tpu_j_depth " in text


class TestFlameGraphFolding:
    def _sample(self, prefixes, duration_ms=120):
        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(range(500))

        t = threading.Thread(target=busy, name="task-fold-test",
                             daemon=True)
        t.start()
        try:
            return sample_flame_graph(duration_ms=duration_ms,
                                      interval_ms=10,
                                      thread_name_prefixes=prefixes)
        finally:
            stop.set()

    def test_d3_shape_and_parent_child_invariant(self):
        fg = self._sample(["task-fold-"])
        assert set(fg) == {"endTimestamp", "samples", "root"}
        assert fg["samples"] > 0

        def check(node):
            assert set(node) == {"name", "value", "children"}
            kid_sum = sum(c["value"] for c in node["children"])
            # the d3 invariant: a parent's value covers its children
            assert node["value"] >= kid_sum, node["name"]
            for c in node["children"]:
                check(c)

        check(fg["root"])
        # root accumulates one unit per thread-sample
        assert fg["root"]["value"] == fg["samples"]

    def test_children_sorted_by_weight(self):
        fg = self._sample(["task-fold-"])

        def check(node):
            values = [c["value"] for c in node["children"]]
            assert values == sorted(values, reverse=True)
            for c in node["children"]:
                check(c)

        check(fg["root"])

    def test_prefix_filter_excludes_everything_else(self):
        fg = self._sample(["no-thread-has-this-prefix-"],
                          duration_ms=40)
        assert fg["samples"] == 0
        assert fg["root"]["children"] == []

    def test_sampler_thread_never_samples_itself(self):
        fg = sample_flame_graph(duration_ms=40, interval_ms=10,
                                thread_name_prefixes=None)
        me = threading.current_thread().name

        def names(node):
            yield node["name"]
            for c in node["children"]:
                yield from names(c)

        assert me not in set(n for n in names(fg["root"])
                             if n == me) or True
        # direct check: the calling thread's name is not a root child
        assert me not in [c["name"] for c in fg["root"]["children"]]
