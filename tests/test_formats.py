"""Formats layer: DeserializationSchema seam + JSON/CSV formats.

reference: DeserializationSchema (flink-core serialization),
JsonRowDataDeserializationSchema (flink-formats/flink-json), discovered
from DDL via 'format' = 'json'."""

import json

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.formats import (
    JsonRowDeserializationSchema,
    JsonRowSerializationSchema,
    resolve_format,
)
from flink_tpu.connectors.kafka import FakeBroker, KafkaSource
from flink_tpu.core.records import RecordBatch
from flink_tpu.table.environment import StreamTableEnvironment


class TestJsonSchema:
    def test_deserialize_typed_columns(self):
        s = JsonRowDeserializationSchema(
            ["k", "v", "name"], ["BIGINT", "DOUBLE", "STRING"])
        b = s.deserialize_batch([
            b'{"k": 1, "v": 2.5, "name": "x"}',
            b'{"k": 2, "v": 7, "name": "y", "extra": true}',
            b'{"k": 3, "name": "z"}',  # missing v -> NaN
        ])
        assert b["k"].tolist() == [1, 2, 3]
        assert b["k"].dtype == np.int64
        assert b["v"][0] == 2.5 and np.isnan(b["v"][2])
        assert list(b["name"]) == ["x", "y", "z"]

    def test_parse_error_raises_or_skips(self):
        s = JsonRowDeserializationSchema(["k"], ["BIGINT"])
        with pytest.raises(RuntimeError, match="deserialize"):
            s.deserialize_batch([b'{"k": 1}', b"not json"])
        s2 = JsonRowDeserializationSchema(["k"], ["BIGINT"],
                                          ignore_parse_errors=True)
        b = s2.deserialize_batch([b'{"k": 1}', b"not json",
                                  b'{"k": 2}'])
        assert b["k"].tolist() == [1, 2]

    def test_type_coercion_failures_skippable(self):
        """ignore-parse-errors covers CONVERSION failures too (the
        reference's contract): one bad-typed field skips one record."""
        s = JsonRowDeserializationSchema(["k"], ["BIGINT"],
                                         ignore_parse_errors=True)
        b = s.deserialize_batch([b'{"k": 1}', b'{"k": "abc"}',
                                 b'{"k": 2}'])
        assert b["k"].tolist() == [1, 2]
        s2 = JsonRowDeserializationSchema(["k"], ["BIGINT"])
        with pytest.raises(RuntimeError, match="deserialize"):
            s2.deserialize_batch([b'{"k": "abc"}'])

    def test_broker_timestamps_survive_the_format_seam(self):
        from flink_tpu.connectors.kafka import FakeBroker, KafkaSource

        broker = FakeBroker.get("default")
        broker.create_topic("jts", 1)
        ts = np.asarray([5, 6, 7], dtype=np.int64)
        broker.append_raw("jts", 0,
                          [b'{"k": 1}', b'{"k": 2}', b'{"k": 3}'],
                          timestamps=ts)
        from flink_tpu.connectors.formats import (
            JsonRowDeserializationSchema as J,
        )

        src = KafkaSource("jts", value_format=J(["k"], ["BIGINT"]))
        src.open(0, 1)
        b = src.poll_batch(10)
        assert b.has_timestamps and b.timestamps.tolist() == [5, 6, 7]

    def test_serialize_roundtrip(self):
        ser = JsonRowSerializationSchema(["k", "v"])
        de = JsonRowDeserializationSchema(["k", "v"],
                                          ["BIGINT", "DOUBLE"])
        b = RecordBatch.from_pydict(
            {"k": np.asarray([5, 6], dtype=np.int64),
             "v": np.asarray([1.5, 2.5])})
        back = de.deserialize_batch(ser.serialize_batch(b))
        assert back["k"].tolist() == [5, 6]
        assert back["v"].tolist() == [1.5, 2.5]

    def test_unknown_format_rejected(self):
        with pytest.raises(ValueError, match="unknown format"):
            resolve_format("avro-nope", ["a"], [None])


class TestCsvSchema:
    def test_roundtrip(self):
        de, ser = resolve_format("csv", ["k", "v"],
                                 ["BIGINT", "DOUBLE"])
        b = RecordBatch.from_pydict(
            {"k": np.asarray([1, 2], dtype=np.int64),
             "v": np.asarray([0.5, 1.5])})
        back = de.deserialize_batch(ser.serialize_batch(b))
        assert back["k"].tolist() == [1, 2]
        assert back["v"].tolist() == [0.5, 1.5]


class TestJsonKafkaSQL:
    def test_json_topic_roundtrips_through_sql(self):
        """A JSON-encoded topic -> CREATE TABLE with 'format'='json' ->
        windowed SQL -> INSERT INTO a JSON sink table -> raw bytes on
        the output topic parse back to the expected aggregates."""
        broker = FakeBroker.get("default")
        broker.create_topic("jin", 2)
        rng = np.random.default_rng(8)
        n = 3000
        ks = rng.integers(0, 20, n).astype(np.int64)
        vs = np.round(rng.random(n), 6)
        ts = np.arange(n, dtype=np.int64) * 4
        for p in range(2):
            m = ks % 2 == p
            recs = [json.dumps({"key": int(k), "value": float(v),
                                "ts": int(t)}).encode()
                    for k, v, t in zip(ks[m], vs[m], ts[m])]
            broker.append_raw("jin", p, recs, timestamps=ts[m])

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 500}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE jin (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='jin', "
            "'format'='json')")
        tenv.execute_sql(
            "CREATE TABLE jout (key BIGINT, window_end BIGINT, "
            "total DOUBLE) "
            "WITH ('connector'='kafka', 'topic'='jout', "
            "'format'='json', 'sink.partitions'='2', "
            "'sink.partition-by'='key')")
        tenv.execute_sql("""
            INSERT INTO jout
            SELECT key, window_end, SUM(value) AS total
            FROM TABLE(TUMBLE(TABLE jin, DESCRIPTOR(ts),
                              INTERVAL '1' SECOND))
            GROUP BY key, window_start, window_end
        """)

        # oracle
        import collections

        oracle = collections.defaultdict(float)
        for k, v, t in zip(ks, vs, ts):
            oracle[(int(k), (int(t) // 1000 + 1) * 1000)] += float(v)

        # the output topic holds RAW JSON bytes — parse them back
        src = KafkaSource("jout")
        src.open(0, 1)
        got = {}
        raw_seen = 0
        while True:
            b = src.poll_batch(10_000)
            if b is None:
                break
            assert FakeBroker.RAW_FIELD in b.columns
            for rec in b[FakeBroker.RAW_FIELD]:
                obj = json.loads(rec)
                raw_seen += 1
                got[(obj["key"], obj["window_end"])] = obj["total"]
        assert raw_seen > 0
        assert set(got) == set(oracle)
        for k in oracle:
            assert got[k] == pytest.approx(oracle[k], rel=1e-4), k

    def test_corrupt_records_skippable_via_option(self):
        broker = FakeBroker.get("default")
        broker.create_topic("jin2", 1)
        recs = [b'{"key": 1, "value": 2.0, "ts": 0}',
                b"garbage{{{",
                b'{"key": 2, "value": 3.0, "ts": 10}']
        broker.append_raw("jin2", 0, recs)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 10}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE jin2 (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='jin2', "
            "'format'='json', 'json.ignore-parse-errors'='true')")
        rows = tenv.execute_sql(
            "SELECT key, value FROM jin2").collect()
        assert sorted(r["key"] for r in rows) == [1, 2]
