"""Device-vectorized CEP (flink_tpu/cep/mesh_engine.py): the mesh NFA
engine over per-key computation-state columns on the state plane.

The contract under test, in order of importance:

1. BIT-IDENTITY: the device engine equals the host ``CepOperator``
   oracle row for row — same values, same emission order — across
   pattern shapes (multi-stage within-window sequences under both
   after-match skip strategies, consecutive ``times`` loops), including
   under forced paged eviction (always-alive pattern, keys >> budget)
   and a mid-stream live ``reshard()``.
2. ELIGIBILITY: ``compile_device_pattern`` admits exactly the
   bounded-partial class; every disqualifier raises
   ``UnsupportedCepPattern`` (the loud-fallback cue) instead of
   silently approximating, and the ``MeshCepOperator`` wrapper falls
   back to the host NFA while ticking the fallback counter.
3. CHECKPOINTS: snapshot -> restore round-trips mid-stream;
   ``snapshot_sharded`` units merge through ``merge_unit_snapshots``
   into a DIFFERENT shard count and replay identically.
4. SERVING: the matched-pattern store answers ``query_match_batch``
   and the replica-plane adapter returns the same rows.
"""

import tempfile

import numpy as np
import pytest

from flink_tpu.cep import (
    MeshCepEngine,
    UnsupportedCepPattern,
    compile_device_pattern,
    host_fallbacks,
)
from flink_tpu.cep.pattern import AfterMatchSkipStrategy as Skip
from flink_tpu.cep.pattern import Pattern
from flink_tpu.core.records import RecordBatch
from flink_tpu.parallel.mesh import make_mesh


def seq3(skip=Skip.SKIP_PAST_LAST_EVENT, within=50):
    p = (Pattern.begin("a", skip=skip)
         .where(lambda b: np.asarray(b["v"]) % 3 == 0)
         .next("b").where(lambda b: np.asarray(b["v"]) % 3 == 1)
         .next("c").where(lambda b: np.asarray(b["v"]) % 3 == 2))
    return p.within(within) if within else p


def churn_pattern():
    """Always-alive two-stage pattern: the virtual start keeps every
    seen key's column alive forever, so residency grows without bound
    and eviction is FORCED once keys exceed the slot budget."""
    return (Pattern.begin("a", skip=Skip.SKIP_PAST_LAST_EVENT)
            .next("b").where(lambda b: np.asarray(b["v"]) == 7))


def gen_steps(seed, n_steps=10, n_keys=40, batch=256, stride=25,
              spread=30):
    rng = np.random.default_rng(seed)
    ts = 0
    steps = []
    for _ in range(n_steps):
        keys = rng.integers(0, n_keys, size=batch).astype(np.int64)
        vals = rng.integers(0, 9, size=batch).astype(np.int64)
        tss = ts + np.sort(
            rng.integers(0, spread, size=batch)).astype(np.int64)
        ts += stride
        steps.append((keys, vals, tss, ts - 5))
    return steps


def mk_batch(keys, vals, tss):
    return RecordBatch.from_pydict(
        {"k": keys, "v": vals, "__key_id__": keys}, timestamps=tss)


def run(engine, steps, hook=None):
    out = []
    for i, (keys, vals, tss, wm) in enumerate(steps):
        out.extend(engine.process_batch(mk_batch(keys, vals, tss)))
        out.extend(engine.on_watermark(wm))
        if hook:
            engine = hook(engine, i) or engine
    return out, engine


def rows_of(batches):
    """Order-preserving flatten — a reordered emission diverges even
    when the value multiset matches."""
    rows = []
    for b in batches:
        for r, t in zip(b.to_rows(),
                        np.asarray(b.timestamps).tolist()):
            rows.append((t, tuple(sorted(r.items()))))
    return rows


def host_rows(pat, steps):
    out, _ = run(MeshCepEngine(pat, key_field="k", backend="host"),
                 steps)
    return rows_of(out)


def device(pat, shards=2, capacity=256, **kw):
    return MeshCepEngine(pat, key_field="k", mesh=make_mesh(shards),
                         capacity_per_shard=capacity,
                         max_parallelism=128, **kw)


class TestBitIdentity:
    @pytest.mark.parametrize("skip", [Skip.SKIP_PAST_LAST_EVENT,
                                      Skip.NO_SKIP])
    def test_seq3_within_matches_oracle(self, skip):
        pat = seq3(skip)
        steps = gen_steps(7, n_steps=12, n_keys=17, batch=64)
        want = host_rows(pat, steps)
        got, _ = run(device(pat, shards=4), steps)
        assert want, "vacuous: oracle emitted nothing"
        assert rows_of(got) == want

    def test_times_loop_matches_oracle(self):
        pat = (Pattern.begin("a", skip=Skip.NO_SKIP)
               .where(lambda b: np.asarray(b["v"]) < 5)
               .times(2, 3).consecutive()
               .next("end")
               .where(lambda b: np.asarray(b["v"]) >= 7)
               .within(60))
        steps = gen_steps(11, n_keys=40)
        want = host_rows(pat, steps)
        got, _ = run(device(pat), steps)
        assert want
        assert rows_of(got) == want

    def test_forced_eviction_matches_oracle(self):
        """Keys >> slot budget: the spill tier MUST churn (asserted —
        a vacuous pass would cover nothing) and output stays
        bit-identical through evict/reload."""
        pat = churn_pattern()
        steps = gen_steps(11, n_keys=5000, batch=256)
        want = host_rows(pat, steps)
        with tempfile.TemporaryDirectory() as td:
            eng = device(pat, spill_dir=td)
            got, eng = run(eng, steps)
            sc = eng.spill_counters()
        assert want
        assert rows_of(got) == want
        assert sc["rows_evicted"] > 0
        assert sc["rows_reloaded"] > 0

    def test_late_rows_dropped_like_oracle(self):
        pat = seq3()
        steps = gen_steps(3, n_keys=10, batch=32)
        # replay a batch far behind the fired watermark: both backends
        # must drop it (same late-drop policy) and tick the counter
        keys, vals, tss, _ = steps[0]
        h = MeshCepEngine(pat, key_field="k", backend="host")
        d = device(pat)
        hout, _ = run(h, steps)
        dout, _ = run(d, steps)
        for e in (h, d):
            assert e.process_batch(mk_batch(keys, vals, tss)) == []
            assert e.late_dropped >= len(keys)
        assert rows_of(hout) == rows_of(dout)


class TestEligibility:
    def test_eligible_class_compiles(self):
        lay = compile_device_pattern(seq3().validate())
        assert lay.n_states >= 1
        assert lay.has_within
        assert lay.key  # stable program-cache identity
        assert compile_device_pattern(churn_pattern().validate())

    @pytest.mark.parametrize("pat", [
        # greedy loop
        (Pattern.begin("a").where(lambda b: np.asarray(b["v"]) > 0)
         .one_or_more().greedy()
         .next("b").where(lambda b: np.asarray(b["v"]) < 0)),
        # unbounded loop
        (Pattern.begin("a").where(lambda b: np.asarray(b["v"]) > 0)
         .times_or_more(2).consecutive()
         .next("b").where(lambda b: np.asarray(b["v"]) < 0)),
        # non-consecutive times
        (Pattern.begin("a").where(lambda b: np.asarray(b["v"]) > 0)
         .times(2, 3)
         .next("b").where(lambda b: np.asarray(b["v"]) < 0)),
    ])
    def test_ineligible_raises(self, pat):
        with pytest.raises(UnsupportedCepPattern):
            compile_device_pattern(pat.validate())

    def test_operator_falls_back_loudly(self):
        from flink_tpu.cep import MeshCepOperator

        pat = (Pattern.begin("a")
               .where(lambda b: np.asarray(b["v"]) > 0)
               .one_or_more().greedy()
               .next("b").where(lambda b: np.asarray(b["v"]) < 0))
        op = MeshCepOperator(pat, key_field="k")
        before = host_fallbacks()

        class _Ctx:
            parallelism = 2
            mesh = None

        op.open(_Ctx())
        assert host_fallbacks() == before + 1
        assert op.engine.backend == "host"


class TestCheckpoints:
    def test_snapshot_restore_mid_stream(self):
        pat = seq3()
        steps = gen_steps(23, n_steps=12, n_keys=300, batch=256)
        want = host_rows(pat, steps)

        def hook(e, i):
            if i == 5:
                snap = e.snapshot()
                e2 = device(pat)
                e2.restore(snap)
                return e2

        got, _ = run(device(pat), steps, hook=hook)
        assert want
        assert rows_of(got) == want

    def test_sharded_merge_into_different_shard_count(self):
        pat = seq3()
        steps = gen_steps(23, n_steps=12, n_keys=300, batch=256)
        want = host_rows(pat, steps)

        def hook(e, i):
            if i == 6:
                units = e.snapshot_sharded()
                e2 = device(pat, shards=4)
                e2.restore(e2.merge_unit_snapshots(
                    list(units.values())))
                return e2

        got, _ = run(device(pat, shards=2), steps, hook=hook)
        assert rows_of(got) == want

    def test_live_reshard_mid_stream(self):
        pat = seq3()
        steps = gen_steps(23, n_steps=12, n_keys=300, batch=256)
        want = host_rows(pat, steps)

        def hook(e, i):
            if i == 4:
                info = e.reshard(2)
                assert info["shards"] == 2
                assert info["rows_moved"] > 0
            if i == 8:
                e.reshard(8)

        got, _ = run(device(pat, shards=4), steps, hook=hook)
        assert rows_of(got) == want


class TestMatchStore:
    def test_replica_lookup_equals_live_probe(self):
        pat = (Pattern.begin("a", skip=Skip.SKIP_PAST_LAST_EVENT)
               .where(lambda b: np.asarray(b["v"]) % 3 == 0)
               .next("b")
               .where(lambda b: np.asarray(b["v"]) % 3 == 1)
               .within(50))
        eng = device(pat, match_capacity=64)
        adapter = eng.arm_match_replica()
        steps = gen_steps(3, n_steps=10, n_keys=30, batch=128)
        _, eng = run(eng, steps)
        assert eng.matches_emitted > 0
        qkeys = np.arange(30, dtype=np.int64)
        live = eng.query_match_batch(qkeys)
        rep, _gen = adapter.lookup_batch(qkeys)
        assert sum(len(r) for r in live) > 0
        for i in range(30):
            assert live[i] == rep[i]
        # retained rids are unique (FIFO store, slot-deduped)
        rids = [r["rid"] for rows in live for r in rows]
        assert len(rids) == len(set(rids))

    def test_metrics_group_registers(self):
        from flink_tpu.metrics import MetricRegistry

        eng = device(seq3())
        reg = MetricRegistry()
        eng.register_metrics(reg.root_group("job"))
        steps = gen_steps(5, n_steps=4, n_keys=20, batch=64)
        run(eng, steps)
        assert eng.matches_emitted >= 0
