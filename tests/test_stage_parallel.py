"""Stage-parallel execution (subtask expansion + shuffle SPI).

reference parity targets: ExecutionGraph parallel expansion
(DefaultExecutionGraph / Execution.deploy), KeyGroupStreamPartitioner
routing, credit-based flow control, aligned checkpoint barriers
(SingleCheckpointBarrierHandler), key-group-filtered restore."""

import collections

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import (
    EventTimeSessionWindows,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def _env(stage_parallelism, extra=None):
    conf = {
        "execution.micro-batch.size": 1000,
        "execution.stage-parallelism": stage_parallelism,
        "state.slot-table.capacity": 8192,
    }
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def _pipeline(env, sink, assigner, total=30_000, keys=300, fail_after=None):
    src = DataGenSource(total_records=total, num_keys=keys,
                        events_per_second_of_eventtime=10_000, seed=5)
    ds = env.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
    if fail_after is not None:
        from tests.test_checkpointing import FailingMap

        ds = ds.map(FailingMap(fail_after), name="failmap")
    ds.key_by("key").window(assigner).sum("value").sink_to(sink)


def _results(sink):
    out = {}
    for r in sink.result().to_rows():
        out[(r["key"], r["window_start"], r["window_end"])] = round(
            r["sum_value"], 3)
    return out


from tests.conftest import \
    assert_windows_approx_equal as _assert_windows_equal  # noqa: E501


class TestShuffleSpi:
    def test_local_credit_flow(self):
        from flink_tpu.core.records import RecordBatch
        from flink_tpu.runtime.shuffle_spi import LocalShuffleService

        svc = LocalShuffleService()
        w = svc.create_partition("p0", 2, credits_per_channel=2)
        gate0 = svc.create_gate(["p0"], 0)
        b = RecordBatch.from_pydict({"x": np.arange(4)})
        w.emit(0, b)
        w.emit(0, b)
        # third emit must block until the consumer polls (credit bound)
        import threading

        done = threading.Event()

        def third():
            w.emit(0, b)
            done.set()

        t = threading.Thread(target=third, daemon=True)
        t.start()
        assert not done.wait(0.2), "emit must block with no credit left"
        ch, item = gate0.poll(timeout=1)
        assert ch == 0 and len(item) == 4
        assert done.wait(2), "credit grant must unblock the producer"

    def test_events_ride_credit_free(self):
        from flink_tpu.runtime.shuffle_spi import (
            END_OF_PARTITION,
            LocalShuffleService,
        )

        svc = LocalShuffleService()
        w = svc.create_partition("p1", 1, credits_per_channel=1)
        gate = svc.create_gate(["p1"], 0)
        from flink_tpu.core.records import RecordBatch

        w.emit(0, RecordBatch.from_pydict({"x": np.arange(2)}))
        w.broadcast_event(77)          # watermark despite zero credit
        w.close()                      # EOP despite zero credit
        assert isinstance(gate.poll(timeout=1)[1], RecordBatch)
        assert gate.poll(timeout=1)[1] == 77
        assert gate.poll(timeout=1)[1] is END_OF_PARTITION

    def test_unknown_service_rejected(self):
        from flink_tpu.runtime.shuffle_spi import create_shuffle_service

        with pytest.raises(ValueError, match="unknown shuffle.service"):
            create_shuffle_service("netty")


class TestStageParallelJobs:
    @pytest.mark.parametrize("assigner_factory", [
        lambda: TumblingEventTimeWindows.of(1000),
        lambda: SlidingEventTimeWindows.of(2000, 500),
        lambda: EventTimeSessionWindows.with_gap(40),
    ])
    def test_matches_single_slot(self, assigner_factory):
        single_sink = CollectSink()
        env = _env(0)
        _pipeline(env, single_sink, assigner_factory())
        env.execute("single")
        expected = _results(single_sink)
        assert expected

        par_sink = CollectSink()
        env2 = _env(4)
        _pipeline(env2, par_sink, assigner_factory())
        result = env2.execute("parallel")
        assert result.metrics["stage_parallelism"] == 4
        _assert_windows_equal(_results(par_sink), expected)

    def test_records_route_by_key_group(self):
        """Every subtask processes only records of its key-group range, and
        all subtasks participate."""
        sink = CollectSink()
        env = _env(4)
        _pipeline(env, sink, TumblingEventTimeWindows.of(1000))
        result = env.execute("routing")
        per_subtask = result.metrics["subtask_records_in"]
        assert len(per_subtask) == 4
        assert all(c > 0 for c in per_subtask)
        # with the local combiner (default on) fewer rows cross the
        # exchange than were polled; every shuffled row must arrive
        assert sum(per_subtask) == result.metrics["records_shuffled"]
        assert result.metrics["records_shuffled"] < result.metrics["records"]

    def test_stateless_chain_runs_in_source_stage(self):
        sink = CollectSink()
        env = _env(3)
        src = DataGenSource(total_records=5000, num_keys=50,
                            events_per_second_of_eventtime=10_000, seed=5)
        (env.from_source(src,
                         WatermarkStrategy.for_bounded_out_of_orderness(0))
            .map(lambda b: b.with_column("value", b["value"] * 2),
                 name="double")
            .filter(lambda b: np.asarray(b["key"]) % 2 == 0, name="evens")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(1000))
            .sum("value")
            .sink_to(sink))
        env.execute("chained")
        rows = sink.rows()
        assert rows and all(r["key"] % 2 == 0 for r in rows)

    def test_unsupported_shapes_fail_by_default(self):
        """A user who asked for parallelism N must not silently get 1."""
        from flink_tpu.cluster.stage_executor import StagePlanError

        env = _env(2)
        sink = CollectSink()
        src = DataGenSource(total_records=100, num_keys=5,
                            events_per_second_of_eventtime=100)
        env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0)) \
            .map(lambda b: b).sink_to(sink)
        with pytest.raises(StagePlanError, match="stage-fallback"):
            env.execute("stateless")

    def test_unsupported_shapes_fall_back_when_opted_in(self):
        env = _env(2, extra={"execution.stage-fallback": True})
        sink = CollectSink()
        src = DataGenSource(total_records=100, num_keys=5,
                            events_per_second_of_eventtime=100)
        # no keyed exchange -> the stage planner can't expand; with the
        # opt-in the job still runs (single-slot) with a warning
        env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0)) \
            .map(lambda b: b).sink_to(sink)
        with pytest.warns(UserWarning, match="no keyed exchange"):
            env.execute("stateless")
        assert len(sink.result()) == 100


class TestStageParallelCheckpointing:
    def test_crash_restore_matches_clean_run(self, tmp_path):
        ckpt = str(tmp_path / "ckpts")
        assigner = lambda: TumblingEventTimeWindows.of(1000)  # noqa: E731

        env = _env(4)
        clean_sink = CollectSink()
        _pipeline(env, clean_sink, assigner())
        env.execute("clean")
        expected = _results(clean_sink)

        conf = {"state.checkpoints.dir": ckpt,
                "execution.checkpointing.every-n-source-batches": 5}
        env2 = _env(4, conf)
        sink2 = CollectSink()
        _pipeline(env2, sink2, assigner(), fail_after=20_000)
        with pytest.raises(RuntimeError, match="injected failure"):
            env2.execute("crashing")
        from flink_tpu.checkpoint.storage import CheckpointStorage

        assert CheckpointStorage(ckpt).latest_checkpoint_id() is not None

        env3 = _env(4, conf)
        sink3 = CollectSink()
        src = DataGenSource(total_records=30_000, num_keys=300,
                            events_per_second_of_eventtime=10_000, seed=5)
        ds = env3.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        ds = ds.map(lambda b: b, name="failmap")
        (ds.key_by("key").window(assigner()).sum("value").sink_to(sink3))
        env3.execute("restored", restore_from=ckpt)
        got = _results(sink2)
        got.update(_results(sink3))
        _assert_windows_equal(got, expected)

    def test_restore_across_subtask_counts(self, tmp_path):
        """Checkpoint at parallelism 4, restore at 2 and at single-slot —
        key-group re-assignment (reference: rescale restore)."""
        ckpt = str(tmp_path / "ckpts")
        conf = {"state.checkpoints.dir": ckpt,
                "execution.checkpointing.every-n-source-batches": 5}
        env = _env(4, conf)
        sink = CollectSink()
        _pipeline(env, sink, SlidingEventTimeWindows.of(2000, 500),
                  fail_after=20_000)
        with pytest.raises(RuntimeError, match="injected failure"):
            env.execute("crashing")

        # clean expected
        env_c = _env(0)
        sink_c = CollectSink()
        _pipeline(env_c, sink_c, SlidingEventTimeWindows.of(2000, 500))
        env_c.execute("clean")
        expected = _results(sink_c)

        for par in (2, 0):  # rescale down + single-slot restore
            # no checkpointing in the restored runs: a new checkpoint in the
            # shared dir would shadow the crash checkpoint for the next loop
            env_r = _env(par)
            sink_r = CollectSink()
            src = DataGenSource(total_records=30_000, num_keys=300,
                                events_per_second_of_eventtime=10_000,
                                seed=5)
            ds = env_r.from_source(
                src, WatermarkStrategy.for_bounded_out_of_orderness(0))
            ds = ds.map(lambda b: b, name="failmap")
            (ds.key_by("key").window(SlidingEventTimeWindows.of(2000, 500))
               .sum("value").sink_to(sink_r))
            env_r.execute(f"restored-{par}", restore_from=ckpt)
            got = _results(sink)
            got.update(_results(sink_r))
            _assert_windows_equal(got, expected)

    def test_group_agg_state_restores_across_subtask_counts(self, tmp_path):
        """GroupAgg changelog state is logical (key-indexed): snapshot at
        one subtask count restores at another with correct UB/UA kinds."""
        from flink_tpu.runtime.group_agg import GroupAggOperator
        from flink_tpu.windowing.aggregates import CountAggregate
        from flink_tpu.cluster.stage_executor import merge_subtask_states
        from flink_tpu.core.records import RecordBatch, ROWKIND_FIELD

        class _Ctx:
            parallelism = 1
            max_parallelism = 128

        def batch(keys):
            return RecordBatch.from_pydict(
                {"__key_id__": np.asarray(keys, dtype=np.int64),
                 "k": np.asarray(keys, dtype=np.int64)})

        # two "subtasks" with disjoint keys
        a, b = (GroupAggOperator(CountAggregate(), "k") for _ in range(2))
        a.open(_Ctx()); b.open(_Ctx())
        a.process_batch(batch([1, 1]))
        b.process_batch(batch([2]))
        merged = merge_subtask_states([a.snapshot_state(),
                                       b.snapshot_state()])
        c = GroupAggOperator(CountAggregate(), "k")
        c.open(_Ctx())
        c.restore_state(merged)
        out = []
        for bt in c.process_batch(batch([1, 2])):
            out.extend(bt.to_rows())
        kinds = {(r["k"], r["count"]): r[ROWKIND_FIELD] for r in out}
        # both keys were emitted pre-restore -> UB(old)+UA(new), no INSERT
        from flink_tpu.core.records import (
            ROWKIND_UPDATE_AFTER,
            ROWKIND_UPDATE_BEFORE,
        )

        assert kinds[(1, 2)] == ROWKIND_UPDATE_BEFORE
        assert kinds[(1, 3)] == ROWKIND_UPDATE_AFTER
        assert kinds[(2, 1)] == ROWKIND_UPDATE_BEFORE
        assert kinds[(2, 2)] == ROWKIND_UPDATE_AFTER


class TestStageParallelControl:
    def test_savepoint_and_stop(self, tmp_path):
        """stop-with-savepoint through the control queue, then restore."""
        import queue
        import threading

        from flink_tpu.cluster.local_executor import SavepointRequest
        from flink_tpu.cluster.stage_executor import StageParallelExecutor

        sp = str(tmp_path / "sp")
        env = _env(3)
        sink = CollectSink()

        class SlowSource(DataGenSource):
            def poll_batch(self, n):
                import time

                time.sleep(0.01)
                return super().poll_batch(n)

        src = SlowSource(total_records=200_000, num_keys=100,
                         events_per_second_of_eventtime=10_000, seed=5)
        env.from_source(src,
                        WatermarkStrategy.for_bounded_out_of_orderness(0),
                        name="gen") \
            .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
            .sum("value").sink_to(sink)
        graph = env.get_stream_graph()
        executor = StageParallelExecutor(env._effective_config())
        control: queue.Queue = queue.Queue()
        req = SavepointRequest(sp, stop=True)
        result_box = {}

        def run():
            result_box["result"] = executor.run(graph, "sp-job",
                                                control_queue=control)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        import time

        time.sleep(0.8)
        control.put(req)
        path = req.wait(timeout=60)
        t.join(timeout=60)
        assert not t.is_alive()
        assert path == result_box["result"].metrics.get("savepoint")

        # restore from the savepoint and run to completion
        env2 = _env(3)
        sink2 = CollectSink()
        src2 = DataGenSource(total_records=200_000, num_keys=100,
                             events_per_second_of_eventtime=10_000, seed=5)
        env2.from_source(src2,
                         WatermarkStrategy.for_bounded_out_of_orderness(0),
                         name="gen") \
            .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
            .sum("value").sink_to(sink2)
        env2.execute("resumed", restore_from=path)

        env_c = _env(0)
        sink_c = CollectSink()
        src_c = DataGenSource(total_records=200_000, num_keys=100,
                              events_per_second_of_eventtime=10_000, seed=5)
        env_c.from_source(src_c,
                          WatermarkStrategy.for_bounded_out_of_orderness(0)) \
            .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
            .sum("value").sink_to(sink_c)
        env_c.execute("clean")
        got = _results(sink)
        got.update(_results(sink2))
        _assert_windows_equal(got, _results(sink_c))

    def test_state_query_routed_to_owner(self):
        import queue
        import threading
        import time

        from flink_tpu.cluster.local_executor import StateQueryRequest
        from flink_tpu.cluster.stage_executor import StageParallelExecutor

        env = _env(4)
        sink = CollectSink()

        class SlowSource(DataGenSource):
            def poll_batch(self, n):
                time.sleep(0.02)
                return super().poll_batch(n)

        src = SlowSource(total_records=100_000, num_keys=20,
                         events_per_second_of_eventtime=10_000, seed=5)
        env.from_source(src,
                        WatermarkStrategy.for_bounded_out_of_orderness(0),
                        name="gen") \
            .key_by("key").window(TumblingEventTimeWindows.of(100_000),
                                  ).sum("value").sink_to(sink)
        graph = env.get_stream_graph()
        window_name = next(t.name for t in graph.nodes
                           if "window_agg" in t.name)
        executor = StageParallelExecutor(env._effective_config())
        control: queue.Queue = queue.Queue()
        box = {}

        def run():
            box["r"] = executor.run(graph, "query-job",
                                    control_queue=control)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        time.sleep(1.0)
        req = StateQueryRequest(window_name, 7)
        control.put(req)
        result = req.wait(timeout=30)
        t.join(timeout=120)
        assert result, "live window state for key 7 must be queryable"
        assert all(v.get("sum_value", 0) > 0 for v in result.values())


class TestPartitioners:
    """reference: streaming/runtime/partitioner/* — the channel-selection
    family, vectorized to batch granularity."""

    def _batch(self, keys):
        from flink_tpu.core.records import RecordBatch

        return RecordBatch.from_pydict(
            {"k": np.asarray(keys, dtype=np.int64)})

    def test_key_group_partitioner_routes_like_the_stage(self):
        from flink_tpu.runtime.shuffle_spi import KeyGroupPartitioner
        from flink_tpu.state.keygroups import (
            assign_key_groups,
            hash_keys_to_i64,
            key_group_to_operator_index,
        )

        b = self._batch(np.arange(1000))
        parts = KeyGroupPartitioner("k", 128).partition(b, 4)
        assert sum(len(p) for _, p in parts) == 1000
        for ch, p in parts:
            kid = hash_keys_to_i64(p["k"])
            g = assign_key_groups(kid, 128)
            assert (key_group_to_operator_index(g, 128, 4) == ch).all()

    def test_rebalance_round_robins_batches(self):
        from flink_tpu.runtime.shuffle_spi import RebalancePartitioner

        p = RebalancePartitioner()
        seen = [p.partition(self._batch([i]), 3)[0][0] for i in range(6)]
        assert seen == [0, 1, 2, 0, 1, 2]

    def test_broadcast_hits_every_channel(self):
        from flink_tpu.runtime.shuffle_spi import BroadcastPartitioner

        parts = BroadcastPartitioner().partition(self._batch([1, 2]), 3)
        assert [ch for ch, _ in parts] == [0, 1, 2]
        assert all(len(b) == 2 for _, b in parts)

    def test_forward_pins_the_channel(self):
        from flink_tpu.runtime.shuffle_spi import ForwardPartitioner

        assert ForwardPartitioner(2).partition(
            self._batch([1]), 4)[0][0] == 2

    def test_rescale_stays_in_the_producer_span(self):
        from flink_tpu.runtime.shuffle_spi import RescalePartitioner

        # 2 producers, 4 consumers: producer 0 -> {0,1}, producer 1 -> {2,3}
        p0 = RescalePartitioner(0, 2)
        p1 = RescalePartitioner(1, 2)
        chans0 = {p0.partition(self._batch([i]), 4)[0][0]
                  for i in range(8)}
        chans1 = {p1.partition(self._batch([i]), 4)[0][0]
                  for i in range(8)}
        assert chans0 == {0, 1} and chans1 == {2, 3}
