"""CEP negative patterns, until(), times_or_more (flink_tpu/cep).

reference parity: Pattern.notNext/notFollowedBy (NotCondition edges),
Pattern.until (loop stop condition), Pattern.timesOrMore, and the
trailing-notFollowedBy-with-within release semantics.
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.cep.operator import CEP
from flink_tpu.cep.pattern import Pattern


def run_pattern(pattern, rows, select=None):
    env = StreamExecutionEnvironment(Configuration(
        {"execution.micro-batch.size": 4}))
    ds = env.from_collection(rows, timestamp_field="t")
    stream = CEP.pattern(ds.key_by("k"), pattern).select(select)
    return stream.execute_and_collect().to_rows()


def ev(k, kind, t, amount=0.0):
    return {"k": k, "kind": kind, "t": t, "amount": amount}


def kind_is(x):
    return lambda b: np.asarray(b["kind"]) == x


class TestNotFollowedBy:
    def test_mid_pattern_kills_on_forbidden(self):
        # a -> (no c) -> b : sequence a,c,b must NOT match; a,b must
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c"))
             .followed_by("b").where(kind_is("b")))
        good = [ev(1, "a", 0), ev(1, "x", 10), ev(1, "b", 20),
                ev(1, "z", 100)]
        bad = [ev(2, "a", 0), ev(2, "c", 10), ev(2, "b", 20),
               ev(2, "z", 100)]
        out = run_pattern(p, good + bad)
        assert len(out) == 1 and out[0]["key"] == 1

    def test_trailing_requires_within(self):
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c")))
        with pytest.raises(ValueError, match="within"):
            p.validate()

    def test_trailing_releases_at_window_expiry(self):
        # a NOT followed by c within 50ms: key 1 stays clean -> match at
        # t=0+50; key 2 sees c at t=30 -> no match
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c"))
             .within(50))
        rows = [ev(1, "a", 0), ev(2, "a", 0), ev(2, "c", 30),
                ev(1, "x", 40),
                # late traffic pushes the watermark far past both windows
                ev(3, "z", 500), ev(3, "z", 600)]
        out = run_pattern(p, rows)
        assert len(out) == 1
        assert out[0]["key"] == 1 and out[0]["end_ts"] == 50

    def test_not_condition_event_can_be_next_stage(self):
        # notFollowedBy(c) then followed_by(b): an event that is b (not c)
        # satisfies the next stage even while the guard is armed
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c"))
             .followed_by("b").where(kind_is("b")))
        rows = [ev(1, "a", 0), ev(1, "b", 5), ev(1, "z", 100)]
        out = run_pattern(p, rows)
        assert len(out) == 1


class TestNotNext:
    def test_immediate_event_only(self):
        # a notNext(c) followedBy(b): c right after a kills; c LATER (after
        # an innocent event) does not
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_next("nc").where(kind_is("c"))
             .followed_by("b").where(kind_is("b")))
        killed = [ev(1, "a", 0), ev(1, "c", 10), ev(1, "b", 20),
                  ev(1, "z", 100)]
        survived = [ev(2, "a", 0), ev(2, "x", 10), ev(2, "c", 20),
                    ev(2, "b", 30), ev(2, "z", 100)]
        out = run_pattern(p, killed + survived)
        assert [r["key"] for r in out] == [2]

    def test_cannot_end_with_not_next(self):
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_next("nc").where(kind_is("c")).within(50))
        with pytest.raises(ValueError, match="notNext"):
            p.validate()


class TestUntil:
    def test_until_stops_the_loop(self):
        # oneOrMore small amounts until a big one; the big event closes
        # the loop (and is not consumed by it)
        p = (Pattern.begin("small").where(
                lambda b: np.asarray(b["amount"]) < 10)
             .one_or_more().until(lambda b: np.asarray(b["amount"]) > 100)
             .followed_by("end").where(kind_is("e")))
        rows = [ev(1, "s", 0, 1.0), ev(1, "s", 10, 2.0),
                ev(1, "big", 20, 500.0), ev(1, "s", 30, 3.0),
                ev(1, "e", 40), ev(1, "z", 200)]
        out = run_pattern(p, rows)
        # loops of size 1 and 2 formed before the until event; the post-
        # until small event must NOT extend any loop => max small_count 2
        assert out and max(r["small_count"] for r in out) == 2

    def test_until_requires_unbounded(self):
        with pytest.raises(ValueError, match="until"):
            (Pattern.begin("a").where(kind_is("a"))
             .times(2).until(lambda b: np.asarray(b["amount"]) > 1))


class TestTimesOrMore:
    def test_min_bound_unbounded_above(self):
        p = (Pattern.begin("s").where(kind_is("s")).times_or_more(3)
             .followed_by("e").where(kind_is("e")))
        rows = [ev(1, "s", 0), ev(1, "s", 10), ev(1, "s", 20),
                ev(1, "s", 30), ev(1, "e", 40), ev(1, "z", 200)]
        out = run_pattern(p, rows)
        counts = sorted(r["s_count"] for r in out)
        assert counts and counts[0] >= 3 and 4 in counts

    def test_two_takes_insufficient(self):
        p = (Pattern.begin("s").where(kind_is("s")).times_or_more(3)
             .followed_by("e").where(kind_is("e")))
        rows = [ev(1, "s", 0), ev(1, "s", 10), ev(1, "e", 20),
                ev(1, "z", 200)]
        assert run_pattern(p, rows) == []


class TestReviewRegressions:
    def test_negative_before_optional_rejected(self):
        """The skip-the-optional branch would lose the guard; the
        reference rejects the shape at validation, so do we."""
        p = (Pattern.begin("a").where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c"))
             .followed_by("b").where(kind_is("b")).optional()
             .followed_by("d").where(kind_is("d")))
        with pytest.raises(ValueError, match="optional"):
            p.validate()

    def test_until_kills_waiting_count0_partial(self):
        """until fires BEFORE the loop ever took: no later event may
        start the loop for that partial (reference: no more events are
        accepted once the stop condition fires)."""
        p = (Pattern.begin("a").where(kind_is("a"))
             .followed_by("b").where(kind_is("b"))
             .one_or_more().until(lambda b: np.asarray(b["kind"]) == "x"))
        rows = [ev(1, "a", 0), ev(1, "x", 10), ev(1, "b", 20),
                ev(1, "z", 200)]
        assert run_pattern(p, rows) == []
        # without the stop event the same trace matches
        rows2 = [ev(2, "a", 0), ev(2, "y", 10), ev(2, "b", 20),
                 ev(2, "z", 200)]
        assert len(run_pattern(p, rows2)) == 1

    def test_timeout_release_does_not_skip_past_fresh_partials(self):
        """A trailing-notFollowedBy release triggered by a later event
        must not wipe the partials that event just started (its span lies
        entirely before them)."""
        from flink_tpu.cep.pattern import AfterMatchSkipStrategy

        p = (Pattern.begin("a",
                           skip=AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT)
             .where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c"))
             .within(10))
        rows = [ev(1, "a", 1), ev(1, "a", 20), ev(1, "z", 200)]
        out = run_pattern(p, rows)
        ends = sorted(r["end_ts"] for r in out)
        assert ends == [11, 30], out  # BOTH windows release


class TestCheckpointWithNegatives:
    def test_snapshot_restore_preserves_guards(self):
        from flink_tpu.cep.nfa import KeyNFA

        p = (Pattern.begin("a").where(kind_is("a"))
             .not_followed_by("nc").where(kind_is("c"))
             .followed_by("b").where(kind_is("b"))).validate()
        nfa = KeyNFA(p)
        # a arrives; guard armed
        nfa.advance({"kind": "a"}, 0, [True, False, False])
        snap = nfa.snapshot()
        nfa2 = KeyNFA(p)
        nfa2.restore(snap)
        # forbidden c kills the restored partial
        nfa2.advance({"kind": "c"}, 10, [False, True, False])
        ms = nfa2.advance({"kind": "b"}, 20, [False, False, True])
        assert ms == []
        # sibling timeline without c still matches
        nfa3 = KeyNFA(p)
        nfa3.restore(snap)
        ms = nfa3.advance({"kind": "b"}, 20, [False, False, True])
        assert len(ms) == 1
