"""Fire-time device-side Top-N projection (fire_projectors).

The projected fire must agree with the unprojected fire + host Top-N on
every engine: single-device, spill-hybrid, and the 8-device mesh.
"""

import numpy as np
import pytest

from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.aggregates import CountAggregate, SumAggregate
from flink_tpu.windowing.assigners import SlidingEventTimeWindows
from flink_tpu.windowing.fire_projectors import TopKFireProjector
from flink_tpu.windowing.windower import SliceSharedWindower


def _bids(n=5000, keys=200, seed=7, rate=1000):
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, keys, n).astype(np.int64)
    ts = (np.arange(n, dtype=np.int64) * 1000) // rate
    vals = rng.random(n).astype(np.float32) * 10
    return RecordBatch.from_pydict(
        {"__key_id__": ks, "k": ks, "v": vals}, timestamps=ts)


def _run_windower(w, batch, wm):
    w.process_batch(batch)
    return w.on_watermark(wm)


class TestTopKProjector:
    def test_matches_unprojected_fire(self):
        batch = _bids()
        assigner = SlidingEventTimeWindows.of(2000, 500)
        plain = SliceSharedWindower(assigner, CountAggregate(), capacity=4096)
        proj = SliceSharedWindower(
            assigner, CountAggregate(), capacity=4096,
            fire_projector=TopKFireProjector("count", k=8))
        out_plain = _run_windower(plain, batch, 10_000)
        out_proj = _run_windower(proj, batch, 10_000)
        assert len(out_plain) == len(out_proj)
        for bp, bq in zip(out_plain, out_proj):
            assert len(bq) == min(8, len(bp))
            # top-8 counts of the full fire == the projected batch's counts
            want = np.sort(bp["count"])[::-1][: len(bq)]
            got = np.sort(bq["count"])[::-1]
            np.testing.assert_array_equal(want, got)
            # the projected keys must be keys achieving those counts
            kth = want[-1]
            full = {int(k): int(c)
                    for k, c in zip(bp["__key_id__"], bp["count"])}
            for k, c in zip(bq["__key_id__"], bq["count"]):
                assert full[int(k)] == int(c)
                assert c >= kth

    def test_ascending_and_sum(self):
        batch = _bids()
        assigner = SlidingEventTimeWindows.of(2000, 1000)
        plain = SliceSharedWindower(
            assigner, SumAggregate("v", output="s"), capacity=4096)
        proj = SliceSharedWindower(
            assigner, SumAggregate("v", output="s"), capacity=4096,
            fire_projector=TopKFireProjector("s", k=4, descending=False))
        out_plain = _run_windower(plain, batch, 10_000)
        out_proj = _run_windower(proj, batch, 10_000)
        for bp, bq in zip(out_plain, out_proj):
            want = np.sort(bp["s"])[: len(bq)]
            np.testing.assert_allclose(np.sort(bq["s"]), want, rtol=1e-5)

    def test_fewer_rows_than_k(self):
        batch = _bids(n=40, keys=3)
        assigner = SlidingEventTimeWindows.of(2000, 1000)
        proj = SliceSharedWindower(
            assigner, CountAggregate(), capacity=1024,
            fire_projector=TopKFireProjector("count", k=16))
        out = _run_windower(proj, batch, 10_000)
        assert out, "windows must fire"
        for b in out:
            # only real rows survive the validity mask
            assert 0 < len(b) <= 3
            assert (b["count"] > 0).all()

    def test_hybrid_spill_fire_projects_on_host(self, tmp_path):
        batch = _bids(n=4000, keys=500)
        assigner = SlidingEventTimeWindows.of(2000, 500)
        plain = SliceSharedWindower(assigner, CountAggregate(), capacity=8192)
        proj = SliceSharedWindower(
            assigner, CountAggregate(), capacity=8192,
            spill={"max_device_slots": 1024,
                   "spill_dir": str(tmp_path / "spill")},
            fire_projector=TopKFireProjector("count", k=8))
        out_plain = _run_windower(plain, batch, 10_000)
        out_proj = _run_windower(proj, batch, 10_000)
        assert len(out_plain) == len(out_proj)
        for bp, bq in zip(out_plain, out_proj):
            want = np.sort(bp["count"])[::-1][: len(bq)]
            np.testing.assert_array_equal(np.sort(bq["count"])[::-1], want)


class TestMeshProjector:
    def test_mesh_fire_projects(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine

        batch = _bids(n=8000, keys=300)
        assigner = SlidingEventTimeWindows.of(2000, 500)
        plain = MeshWindowEngine(
            assigner, CountAggregate(), eight_device_mesh,
            capacity_per_shard=4096)
        proj = MeshWindowEngine(
            assigner, CountAggregate(), eight_device_mesh,
            capacity_per_shard=4096,
            fire_projector=TopKFireProjector("count", k=8))
        out_plain = _run_windower(plain, batch, 10_000)
        out_proj = _run_windower(proj, batch, 10_000)
        assert len(out_plain) == len(out_proj)
        for bp, bq in zip(out_plain, out_proj):
            want = np.sort(bp["count"])[::-1][: len(bq)]
            np.testing.assert_array_equal(np.sort(bq["count"])[::-1], want)


class TestQ5DeviceTopK:
    def test_q5_fused_matches_oracle(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.benchmarks.nexmark import (
            BidSource, build_q5, oracle_q5)
        from flink_tpu.connectors.sinks import CollectSink

        src = BidSource(total_records=60_000, num_auctions=500,
                        events_per_second_of_eventtime=10_000, seed=3)
        ref_rows = []
        probe = BidSource(total_records=60_000, num_auctions=500,
                          events_per_second_of_eventtime=10_000, seed=3)
        while True:
            b = probe.poll_batch(8192)
            if b is None:
                break
            ref_rows.extend(zip(b["auction"].tolist(),
                                b.timestamps.tolist()))
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 8192,
            "state.slot-table.capacity": 1 << 14,
        }))
        sink = CollectSink()
        build_q5(env, src, size_ms=2000, slide_ms=500,
                 device_top_k=16).sink_to(sink)
        env.execute("q5-fused")
        oracle = oracle_q5(ref_rows, 2000, 500)
        got = {}
        for r in sink.rows():
            got.setdefault(int(r["window_end"]), set()).add(
                (int(r["auction"]), int(r["count"])))
        for w_end, (best, auctions) in oracle.items():
            if w_end not in got:
                continue  # incomplete tail windows don't fire
            want = {(a, best) for a in auctions}
            assert got[w_end] == want, f"window {w_end}"
        # every complete window fired
        last_complete = max(got) if got else 0
        fired_ends = {w for w in oracle if w <= last_complete}
        assert fired_ends <= set(got)
