"""Avro binary format: encoding round-trips, SCHEMA EVOLUTION (reader vs
writer schema resolution), and the 'format'='avro' DDL seam.

reference: flink-formats/flink-avro/.../AvroRowDataDeserializationSchema.java:1,
AvroRowDataSerializationSchema.java, AvroSchemaConverter (DDL -> schema).
"""

import json

import numpy as np
import pytest

from flink_tpu.connectors.avro import (
    AvroRowDeserializationSchema,
    AvroRowSerializationSchema,
    decode_record,
    encode_record,
    parse_schema,
    schema_from_ddl,
)
from flink_tpu.connectors.formats import resolve_format
from flink_tpu.core.records import RecordBatch

V1 = parse_schema(json.dumps({
    "type": "record", "name": "Bid", "fields": [
        {"name": "auction", "type": "int"},
        {"name": "price", "type": "double"},
        {"name": "bidder", "type": ["null", "string"], "default": None},
    ]}))

# evolved: auction promoted int->long, NEW field region with a default,
# price unchanged, bidder dropped by the reader
V2 = parse_schema(json.dumps({
    "type": "record", "name": "Bid", "fields": [
        {"name": "auction", "type": "long"},
        {"name": "price", "type": "double"},
        {"name": "region", "type": "string", "default": "emea"},
    ]}))


class TestBinaryCore:
    def test_roundtrip_primitives_and_unions(self):
        payload = encode_record(
            V1, {"auction": 7, "price": 2.5, "bidder": "alice"})
        back = decode_record(V1, V1, payload)
        assert back == {"auction": 7, "price": 2.5, "bidder": "alice"}
        payload = encode_record(
            V1, {"auction": -3, "price": 0.0, "bidder": None})
        assert decode_record(V1, V1, payload)["bidder"] is None

    def test_zigzag_edge_values(self):
        s = parse_schema('{"type":"record","name":"R","fields":'
                         '[{"name":"x","type":"long"}]}')
        for v in (0, -1, 1, 63, -64, 64, 2**40, -2**40, 2**62):
            assert decode_record(s, s, encode_record(s, {"x": v}))["x"] == v

    def test_nested_record_array_map_enum(self):
        s = parse_schema(json.dumps({
            "type": "record", "name": "Outer", "fields": [
                {"name": "tags", "type": {"type": "array",
                                          "items": "string"}},
                {"name": "attrs", "type": {"type": "map",
                                           "values": "long"}},
                {"name": "color", "type": {"type": "enum", "name": "C",
                                           "symbols": ["RED", "BLUE"]}},
                {"name": "inner", "type": {
                    "type": "record", "name": "Inner", "fields": [
                        {"name": "v", "type": "double"}]}},
            ]}))
        d = {"tags": ["a", "b"], "attrs": {"x": 1, "y": -2},
             "color": "BLUE", "inner": {"v": 1.25}}
        assert decode_record(s, s, encode_record(s, d)) == d


class TestSchemaEvolution:
    def test_reader_evolves_over_writer(self):
        """v1-encoded bytes read under the v2 schema: promotion
        int->long, added field takes its default, dropped field is
        skipped over in the byte stream."""
        payload = encode_record(
            V1, {"auction": 42, "price": 9.5, "bidder": "bob"})
        got = decode_record(V1, V2, payload)
        assert got == {"auction": 42, "price": 9.5, "region": "emea"}
        assert isinstance(got["auction"], int)

    def test_added_field_without_default_fails_loudly(self):
        v_bad = parse_schema(json.dumps({
            "type": "record", "name": "Bid", "fields": [
                {"name": "auction", "type": "int"},
                {"name": "price", "type": "double"},
                {"name": "must_have", "type": "string"},
            ]}))
        payload = encode_record(
            V1, {"auction": 1, "price": 1.0, "bidder": None})
        with pytest.raises(ValueError, match="must_have"):
            decode_record(V1, v_bad, payload)

    def test_field_matched_by_alias(self):
        v_renamed = parse_schema(json.dumps({
            "type": "record", "name": "Bid", "fields": [
                {"name": "auction_id", "aliases": ["auction"],
                 "type": "int"},
                {"name": "price", "type": "double"},
                {"name": "bidder", "type": ["null", "string"],
                 "default": None},
            ]}))
        payload = encode_record(
            V1, {"auction": 5, "price": 2.0, "bidder": None})
        assert decode_record(V1, v_renamed, payload)["auction_id"] == 5

    def test_union_promotion(self):
        w = parse_schema('{"type":"record","name":"R","fields":'
                         '[{"name":"x","type":["null","int"]}]}')
        r = parse_schema('{"type":"record","name":"R","fields":'
                         '[{"name":"x","type":["null","double"]}]}')
        payload = encode_record(w, {"x": 3})
        assert decode_record(w, r, payload)["x"] == 3.0


class TestBatchSeam:
    def test_batch_roundtrip_with_evolution(self):
        ser = AvroRowSerializationSchema(
            ["auction", "price", "bidder"], V1)
        batch = RecordBatch.from_pydict({
            "auction": np.arange(5, dtype=np.int64),
            "price": np.linspace(1, 2, 5),
            "bidder": np.asarray(["u%d" % i for i in range(5)],
                                 dtype=object)})
        raw = ser.serialize_batch(batch)
        de = AvroRowDeserializationSchema(
            ["auction", "price", "region"],
            ["BIGINT", "DOUBLE", "STRING"],
            V2, writer_schema=V1)
        out = de.deserialize_batch(raw)
        assert out["auction"].tolist() == list(range(5))
        assert list(out["region"]) == ["emea"] * 5

    def test_resolve_format_ddl_options(self):
        de, ser = resolve_format(
            "avro", ["auction", "price", "region"],
            ["BIGINT", "DOUBLE", "STRING"],
            {"avro.schema": json.dumps({
                "type": "record", "name": "Bid", "fields": [
                    {"name": "auction", "type": "long"},
                    {"name": "price", "type": "double"},
                    {"name": "region", "type": "string",
                     "default": "emea"}]}),
             "avro.writer-schema": json.dumps({
                "type": "record", "name": "Bid", "fields": [
                    {"name": "auction", "type": "int"},
                    {"name": "price", "type": "double"},
                    {"name": "bidder", "type": ["null", "string"],
                     "default": None}]})})
        payload = encode_record(
            V1, {"auction": 3, "price": 4.5, "bidder": "x"})
        out = de.deserialize_batch([payload])
        assert out["auction"].tolist() == [3]
        assert list(out["region"]) == ["emea"]

    def test_schema_derived_from_ddl_when_unspecified(self):
        de, ser = resolve_format(
            "avro", ["k", "v"], ["BIGINT", "DOUBLE"], {})
        b = RecordBatch.from_pydict({
            "k": np.asarray([1, 2], dtype=np.int64),
            "v": np.asarray([0.5, 1.5])})
        back = de.deserialize_batch(ser.serialize_batch(b))
        assert back["k"].tolist() == [1, 2]
        assert back["v"].tolist() == [0.5, 1.5]


class TestAvroKafkaSQL:
    def test_avro_topic_roundtrips_through_sql_with_evolution(self):
        """v1-encoded Avro topic read through CREATE TABLE under the v2
        reader schema ('format'='avro'), aggregated, and written back
        out as Avro — end-to-end over the connector seam."""
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.connectors.kafka import FakeBroker
        from flink_tpu.table.environment import StreamTableEnvironment

        broker = FakeBroker.get("default")
        broker.create_topic("ain", 1)
        rng = np.random.default_rng(4)
        n = 2000
        ks = rng.integers(0, 10, n)
        ts = np.arange(n, dtype=np.int64) * 4
        recs = [encode_record(V1, {"auction": int(k),
                                   "price": float(k) * 0.5,
                                   "bidder": None})
                for k in ks]
        broker.append_raw("ain", 0, recs, timestamps=ts)

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 500}))
        tenv = StreamTableEnvironment(env)
        reader = json.dumps({
            "type": "record", "name": "Bid", "fields": [
                {"name": "auction", "type": "long"},
                {"name": "price", "type": "double"},
                {"name": "region", "type": "string",
                 "default": "emea"}]})
        writer = json.dumps({
            "type": "record", "name": "Bid", "fields": [
                {"name": "auction", "type": "int"},
                {"name": "price", "type": "double"},
                {"name": "bidder", "type": ["null", "string"],
                 "default": None}]})
        tenv.execute_sql(
            "CREATE TABLE ain (auction BIGINT, price DOUBLE, "
            "region STRING) "
            "WITH ('connector'='kafka', 'topic'='ain', "
            "'format'='avro', "
            f"'avro.schema'='{reader}', "
            f"'avro.writer-schema'='{writer}')")
        from flink_tpu.connectors.sinks import CollectSink

        # evolved column materializes with its default on every row
        proj = tenv.sql_query(
            "SELECT auction, region FROM ain WHERE auction < 3")
        psink = CollectSink()
        proj.to_data_stream().sink_to(psink)
        env.execute("avro-projection")
        prows = psink.result().to_rows()
        assert prows and all(r["region"] == "emea" for r in prows)

        table = tenv.sql_query(
            "SELECT auction, COUNT(*) AS n FROM ain GROUP BY auction")
        sink = CollectSink()
        table.to_data_stream().sink_to(sink)
        env.execute("avro-sql")
        finals = {}
        for r in sink.result().to_rows():
            finals[r["auction"]] = r["n"]
        import collections

        expect = collections.Counter(int(k) for k in ks)
        assert finals == dict(expect)
