"""Fluent (programmatic) Table API — flink_tpu/table/fluent.py.

reference parity: flink-table-api-java Table/Expressions DSL
(select/where/groupBy/window/join/orderBy/fetch/distinct with Tumble/
Slide/Session group windows). Every fluent query must plan through the
SAME AST/planner as its SQL spelling — pinned by comparing each fluent
query against the equivalent SQL string.
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.table.environment import StreamTableEnvironment
from flink_tpu.table.fluent import Session, Slide, Tumble, col, count_star, lit


def _t_env():
    return StreamTableEnvironment(StreamExecutionEnvironment(
        Configuration({"execution.micro-batch.size": 128})))


def _bids(t_env, n=4000):
    rng = np.random.default_rng(3)
    rows = [{"auction": int(rng.integers(30)),
             "price": float(rng.integers(1, 100)),
             "t": i * 5} for i in range(n)]
    table = t_env.from_collection(rows, timestamp_field="t")
    t_env.create_temporary_view("bid", table)
    return table


def _sorted(rows):
    return sorted(tuple(sorted(r.items())) for r in rows)


class TestProjectionFilter:
    def test_select_where_matches_sql(self):
        t_env = _t_env()
        bids = _bids(t_env)
        fluent = (bids.where((col("price") > 50) & (col("auction") < 10))
                  .select(col("auction"),
                          (col("price") * 2).alias("double_price"))
                  .execute().collect())
        sql = t_env.execute_sql(
            "SELECT auction, price * 2 AS double_price FROM bid "
            "WHERE price > 50 AND auction < 10").collect()
        assert _sorted(fluent) == _sorted(sql) and len(fluent) > 0

    def test_distinct_and_fetch(self):
        t_env = _t_env()
        bids = _bids(t_env, 500)
        fluent = bids.select(col("auction")).distinct().execute().collect()
        sql = t_env.execute_sql(
            "SELECT DISTINCT auction FROM bid").collect()
        assert _sorted(fluent) == _sorted(sql)
        limited = (bids.select(col("auction"), col("price"))
                   .order_by(col("price").desc()).fetch(5)
                   .execute().collect())
        sql_l = t_env.execute_sql(
            "SELECT auction, price FROM bid ORDER BY price DESC "
            "LIMIT 5").collect()
        assert [r["price"] for r in limited] == [r["price"] for r in sql_l]


class TestGroupBy:
    def test_plain_group_by(self):
        t_env = _t_env()
        bids = _bids(t_env)
        fluent = (bids.group_by(col("auction"))
                  .select(col("auction"), col("price").sum().alias("total"),
                          count_star().alias("n"))
                  .execute().collect())
        sql = t_env.execute_sql(
            "SELECT auction, SUM(price) AS total, COUNT(*) AS n "
            "FROM bid GROUP BY auction").collect()
        assert _sorted(fluent) == _sorted(sql) and len(fluent) > 5


class TestGroupWindows:
    def test_tumble_matches_sql(self):
        t_env = _t_env()
        bids = _bids(t_env)
        fluent = (bids.window(Tumble.over(2000).on(col("t")).alias("w"))
                  .group_by("w", col("auction"))
                  .select(col("auction"), col("window_end"),
                          count_star().alias("bids"))
                  .execute().collect())
        sql = t_env.execute_sql(
            "SELECT auction, window_end, COUNT(*) AS bids "
            "FROM TABLE(TUMBLE(TABLE bid, DESCRIPTOR(t), "
            "INTERVAL '2' SECOND)) "
            "GROUP BY auction, window_start, window_end").collect()
        assert _sorted(fluent) == _sorted(sql) and len(fluent) > 10

    def test_slide_window(self):
        t_env = _t_env()
        bids = _bids(t_env)
        fluent = (bids.window(Slide.over(4000, 2000).on(col("t"))
                              .alias("w"))
                  .group_by("w", col("auction"))
                  .select(col("auction"), col("window_start"),
                          col("price").max().alias("top"))
                  .execute().collect())
        sql = t_env.execute_sql(
            "SELECT auction, window_start, MAX(price) AS top "
            "FROM TABLE(HOP(TABLE bid, DESCRIPTOR(t), "
            "INTERVAL '2' SECOND, INTERVAL '4' SECOND)) "
            "GROUP BY auction, window_start, window_end").collect()
        assert _sorted(fluent) == _sorted(sql)


class TestExpressions:
    def test_reflected_arithmetic(self):
        t_env = _t_env()
        bids = _bids(t_env, 200)
        rows = (bids.select(col("auction"),
                            (100 - col("price")).alias("inv"),
                            (2 * col("price")).alias("dbl"))
                .execute().collect())
        sql = t_env.execute_sql(
            "SELECT auction, 100 - price AS inv, 2 * price AS dbl "
            "FROM bid").collect()
        assert _sorted(rows) == _sorted(sql)


class TestJoin:
    def test_same_named_keys_via_qualified_cols(self):
        """col('L.k') == col('R.k') — the common join shape where both
        sides share the key column name."""
        t_env = _t_env()
        rng = np.random.default_rng(4)
        left = [{"k": int(rng.integers(6)), "x": float(i), "t": i * 7}
                for i in range(150)]
        right = [{"k": int(rng.integers(6)), "y": float(i), "t": i * 7}
                 for i in range(150)]
        lt = t_env.from_collection(left, timestamp_field="t").alias("L")
        rt = t_env.from_collection(right, timestamp_field="t").alias("R")
        t_env.create_temporary_view("L", lt)
        t_env.create_temporary_view("R", rt)
        fluent = lt.join(rt, col("L.k") == col("R.k")).execute().collect()
        sql = t_env.execute_sql(
            "SELECT * FROM L JOIN R ON L.k = R.k").collect()
        assert len(fluent) == len(sql) > 0
        assert _sorted(fluent) == _sorted(sql)
    def test_inner_join_matches_sql(self):
        t_env = _t_env()
        rng = np.random.default_rng(9)
        left = [{"k": int(rng.integers(8)), "x": float(i % 11), "t": i * 7}
                for i in range(300)]
        right = [{"k": int(rng.integers(8)), "y": float(i % 11),
                  "t": i * 7} for i in range(300)]
        lt = t_env.from_collection(left, timestamp_field="t").alias("L")
        rt = t_env.from_collection(right, timestamp_field="t").alias("R")
        t_env.create_temporary_view("L", lt)
        t_env.create_temporary_view("R", rt)
        fluent = (lt.join(rt, col("x") == col("y"))
                  .execute().collect())
        sql = t_env.execute_sql(
            "SELECT * FROM L JOIN R ON L.x = R.y").collect()
        assert len(fluent) == len(sql) > 0
        assert _sorted(fluent) == _sorted(sql)
