"""Flight recorder: the always-on span plane + its exporters.

reference test model: the reference's metric/trace reporting tests
(SURVEY §5 — spans, latency markers, the webmonitor), applied to the
per-batch recorder of flink_tpu.observe.flight_recorder.
"""

import time

import numpy as np
import pytest

from flink_tpu.observe import KNOWN_SPAN_KINDS
from flink_tpu.observe import flight_recorder as flight
from flink_tpu.observe.export import (
    breakdown_from_kind_totals,
    chrome_trace,
    register_flight_metrics,
    validate_trace_schema,
)
from flink_tpu.observe.flight_recorder import FlightRecorder


@pytest.fixture()
def rec():
    r = flight.recorder()
    r.clear()
    return r


class TestRecorder:
    def test_span_records_duration_and_attribution(self, rec):
        flight.set_job("t-job")
        flight.set_batch(41)
        with flight.span("batch.ingest", batch=42):
            time.sleep(0.002)
        got = [r for r in rec.snapshot() if r.kind == "batch.ingest"]
        assert got, "span not recorded"
        r = got[-1]
        assert r.job == "t-job"
        assert r.batch_id == 42
        assert not r.instant
        assert r.duration_s >= 0.002

    def test_ambient_context_inherited_by_nested_spans(self, rec):
        flight.set_job("ambient-job")
        flight.set_batch(7)
        flight.set_watermark(1234)
        with flight.span("fire.dispatch"):
            flight.instant("watchdog.miss", shard=3)
        miss = [r for r in rec.snapshot()
                if r.kind == "watchdog.miss"][-1]
        assert miss.job == "ambient-job"
        assert miss.batch_id == 7
        assert miss.watermark == 1234
        assert miss.shard == 3
        assert miss.instant

    def test_unknown_kind_raises(self, rec):
        with pytest.raises(KeyError):
            rec.span("no.such.kind")
        with pytest.raises(KeyError):
            rec.instant("no.such.kind")

    def test_disabled_region_records_nothing(self, rec):
        before = len(rec.snapshot())
        with flight.disabled():
            with flight.span("batch.ingest"):
                pass
            flight.instant("watchdog.miss")
        assert len(rec.snapshot()) == before

    def test_drop_oldest_bounds_memory(self):
        # private instance: fill one thread's ring past capacity — the
        # ring wraps (drop-oldest), never grows
        r = FlightRecorder(KNOWN_SPAN_KINDS)
        cap = r._ring().mask + 1
        for _ in range(cap + 100):
            r.instant("d2h.transfer")
        assert r.dropped() == 100
        assert len(r.snapshot()) == cap

    def test_kind_totals_aggregates(self, rec):
        for _ in range(5):
            with flight.span("serving.lookup"):
                pass
        stats = rec.kind_totals()["serving.lookup"]
        assert stats["count"] >= 5
        assert stats["total_s"] >= 0
        assert stats["p99_ms"] >= stats["p50_ms"] >= 0

    def test_span_contexts_are_pooled(self, rec):
        # entering/exiting spans reuses the per-thread pool — the hot
        # path must not grow an object per span
        ring = rec._ring()
        with flight.span("emit"):
            pass
        n = len(ring.pool)
        for _ in range(50):
            with flight.span("emit"):
                pass
        assert len(ring.pool) == n

    def test_registry_matches_recorder(self, rec):
        assert rec.kinds == KNOWN_SPAN_KINDS
        assert len(set(KNOWN_SPAN_KINDS)) == len(KNOWN_SPAN_KINDS)


class TestChromeExport:
    def test_pid_per_job_tid_per_shard(self, rec):
        with flight.span("batch.ingest", job="job-a", batch=1):
            pass
        with flight.span("fire.shard", job="job-b", shard=3):
            pass
        trace = chrome_trace(
            [r for r in rec.snapshot()
             if r.job in ("job-a", "job-b")], anchor=rec.anchor)
        evs = [e for e in trace["traceEvents"] if e["ph"] != "M"]
        pids = {e["pid"] for e in evs}
        assert len(pids) == 2, "one pid per job"
        shard_ev = next(e for e in evs if e["name"] == "fire.shard")
        assert shard_ev["tid"] == 4  # shard 3 -> tid 4 (0 is host)
        names = {(e["pid"], e["args"]["name"])
                 for e in trace["traceEvents"] if e["ph"] == "M"
                 and e["name"] == "process_name"}
        assert {n for _, n in names} == {"job-a", "job-b"}
        thread_names = {e["args"]["name"]
                        for e in trace["traceEvents"]
                        if e["ph"] == "M" and e["name"] == "thread_name"}
        assert "shard-3" in thread_names
        # shard-less spans ride PER-THREAD host tracks (concurrent
        # threads must not interleave complete events on one tid)
        assert any(n.startswith("host:") for n in thread_names)

    def test_instants_are_instant_events(self, rec):
        flight.instant("chaos.inject", job="job-i", shard=1)
        trace = chrome_trace(
            [r for r in rec.snapshot() if r.job == "job-i"])
        ev = next(e for e in trace["traceEvents"]
                  if e["name"] == "chaos.inject")
        assert ev["ph"] == "i"
        assert ev["args"]["shard"] == 1

    def test_schema_validation_catches_drift(self):
        good = {"traceEvents": [
            {"ph": "X", "name": "batch.ingest", "dur": 5, "ts": 0,
             "pid": 1, "tid": 0, "args": {"batch": 3}}]}
        assert validate_trace_schema(good, KNOWN_SPAN_KINDS) == []
        bad_kind = {"traceEvents": [
            {"ph": "X", "name": "not.registered", "dur": 5, "ts": 0,
             "pid": 1, "tid": 0, "args": {}}]}
        assert validate_trace_schema(bad_kind, KNOWN_SPAN_KINDS)
        no_batch = {"traceEvents": [
            {"ph": "X", "name": "batch.ingest", "dur": 5, "ts": 0,
             "pid": 1, "tid": 0, "args": {"batch": -1}}]}
        assert validate_trace_schema(no_batch, KNOWN_SPAN_KINDS)


class TestBreakdown:
    def test_host_prep_excludes_device_and_fence(self):
        kt = {
            "batch.ingest": {"total_s": 10.0},
            "device.dispatch": {"total_s": 3.0},
            "device.fence_wait": {"total_s": 2.0},
            "fire.dispatch": {"total_s": 1.5},
            "fire.harvest": {"total_s": 0.5},
        }
        b = breakdown_from_kind_totals(kt, wall_s=20.0)
        assert b["host_prep_s"] == pytest.approx(5.0)
        assert b["device_step_s"] == pytest.approx(6.5)
        assert b["harvest_s"] == pytest.approx(0.5)
        assert b["host_prep_fraction"] == pytest.approx(0.25)

    def test_empty_totals_zero_breakdown(self):
        b = breakdown_from_kind_totals({}, wall_s=1.0)
        assert b["host_prep_s"] == 0.0
        assert b["device_step_s"] == 0.0


class TestMetricExport:
    def test_flight_group_gauges_render(self, rec):
        from flink_tpu.metrics import MetricRegistry, PrometheusReporter

        with flight.span("checkpoint.write"):
            pass
        registry = MetricRegistry()
        register_flight_metrics(
            registry.root_group("job", "x"), rec)
        snap = registry.snapshot()
        assert snap["job.x.flight.checkpoint_write_count"] >= 1
        assert "job.x.flight.records_dropped" in snap
        rep = PrometheusReporter()
        rep.open(registry)
        text = rep.render()
        assert "checkpoint_write_p99_ms" in text


class TestProbeCorrelation:
    def test_compile_event_lands_in_timeline(self, rec):
        from flink_tpu.observe import recompile_sentinel as rs

        flight.install_probes()
        before = rec.kind_totals().get("xla.compile",
                                       {}).get("count", 0)
        # drive the monitoring listener directly: one "real" backend
        # compile of 12.5 ms
        rs._on_duration_event(
            "/jax/core/compile/backend_compile_duration", 0.0125)
        got = [r for r in rec.snapshot() if r.kind == "xla.compile"]
        assert got, "compile not correlated into the timeline"
        assert got[-1].duration_s == pytest.approx(0.0125, abs=1e-6)
        after = rec.kind_totals()["xla.compile"]["count"]
        assert after == before + 1

    def test_watchdog_miss_instant(self, rec):
        from flink_tpu.runtime.watchdog import DeviceWatchdog

        clock = [0.0]
        wd = DeviceWatchdog(num_shards=2, deadline_ms=1.0,
                            clock=lambda: clock[0])
        with wd.section("probe", shard=1):
            clock[0] += 0.5  # 500 ms >> the 1 ms deadline
        misses = [r for r in rec.snapshot()
                  if r.kind == "watchdog.miss"]
        assert misses and misses[-1].shard == 1

    def test_chaos_injection_instant(self, rec):
        import flink_tpu.chaos as chaos

        plan = chaos.FaultPlan(rules=[
            chaos.FaultRule("serving.lookup", nth=1)])
        with chaos.chaos_active(plan, seed=7):
            with pytest.raises(chaos.InjectedFault):
                chaos.fault_point("serving.lookup", shard=2)
        inj = [r for r in rec.snapshot() if r.kind == "chaos.inject"]
        assert inj and inj[-1].shard == 2


class TestExecutorIntegration:
    def test_job_spans_latency_markers_and_flight_metrics(self, tmp_path):
        from flink_tpu.core.config import Configuration
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        rec = flight.recorder()
        rec.clear()
        conf = Configuration({
            "state.checkpoints.dir": str(tmp_path / "ckpt"),
            "execution.checkpointing.every-n-source-batches": 1,
        })
        env = StreamExecutionEnvironment(conf)
        sink = CollectSink()
        rows = [{"k": i % 3, "v": 1, "ts": i * 100} for i in range(200)]
        env.from_collection(rows, timestamp_field="ts") \
            .key_by("k").window(TumblingEventTimeWindows.of(1000)) \
            .sum("v").sink_to(sink)
        result = env.execute("flight-job")
        kinds = {r.kind for r in rec.snapshot()
                 if r.job == "flight-job"}
        # executor lifecycle spans, attributed to THIS job
        assert {"op.process", "op.watermark", "emit",
                "checkpoint.write"} <= kinds
        snap = result.registry.snapshot()
        # latency markers: per-operator histogram + watermark lag
        marker_keys = [k for k in snap
                       if k.endswith("latency.markerLatencyMs.count")]
        assert marker_keys and any(snap[k] > 0 for k in marker_keys)
        assert any(k.endswith("latency.watermarkLagMs") for k in snap)
        # per-span-kind aggregates at the REGISTRY ROOT: the recorder
        # is process-global, so the rollups are not claimed by one job
        assert snap["flight.op_process_count"] > 0

    def test_restore_records_checkpoint_restore_span(self, tmp_path):
        from flink_tpu.core.config import Configuration
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        ckpt = tmp_path / "ckpt"
        conf = Configuration({
            "state.checkpoints.dir": str(ckpt),
            "execution.checkpointing.every-n-source-batches": 1,
        })

        def build(env):
            sink = CollectSink()
            rows = [{"k": i % 3, "v": 1, "ts": i * 100}
                    for i in range(100)]
            env.from_collection(rows, timestamp_field="ts") \
                .key_by("k").window(TumblingEventTimeWindows.of(1000)) \
                .sum("v").sink_to(sink)

        env = StreamExecutionEnvironment(conf)
        build(env)
        env.execute("restore-a")
        import os

        chks = sorted(p for p in os.listdir(ckpt)
                      if p.startswith("chk-"))
        rec = flight.recorder()
        rec.clear()
        env2 = StreamExecutionEnvironment(conf)
        build(env2)
        result = env2.execute("restore-b",
                              restore_from=str(ckpt / chks[-1]))
        assert [r for r in rec.snapshot()
                if r.kind == "checkpoint.restore"]
        assert result.traces.spans("recovery")


class TestShardedCheckpointSpans:
    def test_write_and_restore_report_spans(self, tmp_path):
        from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage
        from flink_tpu.metrics.traces import TraceCollector

        tc = TraceCollector()
        storage = ShardedCheckpointStorage(str(tmp_path), traces=tc)
        units = {
            (0, 63): {"table": {"key_id": np.arange(3)}},
            (64, 127): {"table": {"key_id": np.arange(2)}},
        }
        storage.write_checkpoint(1, "job", units,
                                 {(0, 63): 10, (64, 127): 10})
        writes = tc.spans("checkpoint")
        assert writes and writes[-1].attributes["units"] == 2
        assert writes[-1].attributes["checkpointId"] == 1
        found = storage.latest_units_for_groups(range(0, 40))
        assert found is not None and found[0] == 1
        restores = tc.spans("recovery")
        assert restores
        assert restores[-1].attributes["checkpointId"] == 1
        assert restores[-1].duration_ms >= 0

    def test_default_collector_used_when_unthreaded(self, tmp_path):
        from flink_tpu.checkpoint.sharded import ShardedCheckpointStorage
        from flink_tpu.metrics.traces import default_collector

        storage = ShardedCheckpointStorage(str(tmp_path))
        before = len(default_collector().spans("checkpoint"))
        storage.write_checkpoint(
            1, "job", {(0, 7): {"table": {}}}, {(0, 7): 0})
        assert len(default_collector().spans("checkpoint")) == before + 1
