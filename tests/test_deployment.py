"""Kubernetes deployment driver — manifests, apply/scale/teardown seam,
and the active-scaling (ResourceManagerDriver) reconcile loop.

reference: flink-kubernetes — KubernetesClusterDescriptor (JM
Deployment + Service), KubernetesResourceManagerDriver (worker pods to
match declared resources). No cluster exists in CI, so the kubectl seam
is faked — the contract under test is the manifests and the driver
protocol.
"""

import json
import subprocess
import sys

from flink_tpu.cluster.deployment import (
    ElasticScaler,
    KubernetesDeployment,
)
from flink_tpu.core.config import Configuration


class FakeKubectl:
    def __init__(self):
        self.applied = []
        self.scaled = []
        self.deleted = []

    def apply(self, manifest):
        self.applied.append(manifest)

    def scale(self, deployment, replicas):
        self.scaled.append((deployment, replicas))

    def delete(self, kind, name):
        self.deleted.append((kind, name))


def mk(**kw):
    client = FakeKubectl()
    dep = KubernetesDeployment(
        "bench", config=Configuration({"state.checkpoints.dir":
                                       "gs://ck/bench"}),
        task_executors=3, slots_per_executor=2, client=client, **kw)
    return dep, client


class TestManifests:
    def test_deploy_applies_jm_service_and_te(self):
        dep, client = mk()
        dep.deploy()
        kinds = [(m["kind"], m["metadata"]["name"]) for m in client.applied]
        assert kinds == [("Deployment", "bench-jobmanager"),
                         ("Service", "bench-jobmanager"),
                         ("Deployment", "bench-taskexecutor")]
        te = client.applied[-1]
        assert te["spec"]["replicas"] == 3
        args = te["spec"]["template"]["spec"]["containers"][0]["args"]
        # workers register with the JM service and carry the config
        assert "--jobmanager" in args
        assert args[args.index("--jobmanager") + 1] == \
            "bench-jobmanager:6123"
        assert "--slots" in args and args[args.index("--slots") + 1] == "2"
        assert "-Dstate.checkpoints.dir=gs://ck/bench" in args

    def test_tpu_workers_request_devices_and_pin_slice(self):
        dep, client = mk(tpus_per_executor=4,
                         tpu_accelerator="tpu-v5p-slice",
                         tpu_topology="2x2x1")
        te = dep.taskexecutor_manifest()
        spec = te["spec"]["template"]["spec"]
        res = spec["containers"][0]["resources"]
        assert res["requests"]["google.com/tpu"] == 4
        assert res["limits"]["google.com/tpu"] == 4
        assert spec["nodeSelector"] == {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5p-slice",
            "cloud.google.com/gke-tpu-topology": "2x2x1"}

    def test_cpu_workers_carry_no_tpu_fields(self):
        dep, _ = mk()
        spec = dep.taskexecutor_manifest()["spec"]["template"]["spec"]
        assert "nodeSelector" not in spec
        assert "resources" not in spec["containers"][0]

    def test_jm_service_exposes_rpc_and_rest(self):
        dep, _ = mk()
        svc = dep.jobmanager_manifests()[1]
        ports = {p["name"]: p["port"] for p in svc["spec"]["ports"]}
        assert ports == {"rpc": 6123, "rest": 8081}

    def test_scale_and_teardown(self):
        dep, client = mk()
        dep.scale_task_executors(7)
        assert client.scaled == [("bench-taskexecutor", 7)]
        dep.teardown()
        assert ("deployment", "bench-taskexecutor") in client.deleted
        assert ("service", "bench-jobmanager") in client.deleted


class TestElasticScaler:
    def test_scales_up_to_meet_demand(self):
        dep, client = mk()  # 3 workers x 2 slots
        demand = [(10, 6)]  # 10 slots required, 6 registered
        scaler = ElasticScaler(dep, lambda: demand[0], max_workers=8)
        assert scaler.reconcile() == 5  # ceil(10/2)
        assert client.scaled == [("bench-taskexecutor", 5)]
        # converged: demand met -> no further scaling
        demand[0] = (10, 10)
        assert scaler.reconcile() is None

    def test_scales_down_but_respects_minimum(self):
        dep, client = mk()
        scaler = ElasticScaler(dep, lambda: (0, 0), min_workers=1)
        assert scaler.reconcile() == 1
        assert client.scaled == [("bench-taskexecutor", 1)]

    def test_scale_down_never_kills_busy_workers(self):
        # 0 slots REQUIRED but 6 still IN USE across 3 workers x 2
        # slots: the floor is the busy workers, not min_workers
        dep, client = mk()
        scaler = ElasticScaler(dep, lambda: (0, 6), min_workers=1)
        assert scaler.reconcile() is None  # 3 workers already = ceil(6/2)
        assert client.scaled == []
        # one worker drains -> only then scale down
        scaler2 = ElasticScaler(dep, lambda: (0, 4), min_workers=1)
        assert scaler2.reconcile() == 2

    def test_bounded_by_max_workers(self):
        dep, client = mk()
        scaler = ElasticScaler(dep, lambda: (1000, 0), max_workers=8)
        assert scaler.reconcile() == 8


def test_cli_scale_requires_explicit_count():
    out = subprocess.run(
        [sys.executable, "-m", "flink_tpu.cli", "deploy", "scale", "prod"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 2
    assert "--task-executors" in out.stderr


def test_cli_dry_run_prints_manifests():
    out = subprocess.run(
        [sys.executable, "-m", "flink_tpu.cli", "deploy", "kubernetes",
         "demo", "--task-executors", "4", "--tpus-per-executor", "1",
         "--dry-run"],
        capture_output=True, text=True, cwd="/root/repo")
    assert out.returncode == 0, out.stderr
    # three JSON documents; the TE one carries the TPU request
    assert '"google.com/tpu": 1' in out.stdout
    assert '"name": "demo-jobmanager"' in out.stdout
    assert '"replicas": 4' in out.stdout
