"""Asynchronous window fires (flink_tpu/runtime/pending.py).

Fires are dispatched (kernel + async host copy) and harvested later by the
executor, which holds back the covering watermark until the results have
been forwarded — hiding the device-link round trip without reordering
event time. These tests pin:

- async == sync results, for projected and plain fires, both layouts;
- a window-into-window cascade stays correct (watermark holdback: the
  downstream window must not see watermark W before the upstream fires
  covered by W — otherwise it would drop them as late);
- checkpoints drain in-flight fires first (restore loses nothing);
- fires that stay pending across many loop iterations still all land
  (forced via a readiness gate).
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.runtime.pending import PendingFire
from flink_tpu.windowing.aggregates import CountAggregate, SumAggregate
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def run_q(async_fires: bool, layout: str = "slots", rows=None, top_k=None):
    env = StreamExecutionEnvironment(Configuration({
        "execution.window.async-fires": async_fires,
        "state.window-layout": layout,
        "execution.micro-batch.size": 16,
    }))
    stream = (
        env.from_collection(rows, timestamp_field="t")
        .key_by("key")
        .window(SlidingEventTimeWindows.of(200, 100))
    )
    if top_k is not None:
        from flink_tpu.windowing.fire_projectors import TopKFireProjector

        stream = stream.aggregate(CountAggregate(),
                                  fire_projector=TopKFireProjector(
                                      "count", k=top_k))
    else:
        stream = stream.sum("v")
    return stream.execute_and_collect().to_rows()


def make_rows(n=400, keys=13):
    rng = np.random.default_rng(7)
    return [{"key": int(rng.integers(keys)), "v": float(i % 5), "t": i * 2}
            for i in range(n)]


class TestAsyncEqualsSync:
    @pytest.mark.parametrize("layout", ["slots", "panes"])
    def test_plain_fire(self, layout):
        rows = make_rows()
        key = lambda r: (r["key"], r["window_start"])
        sync = {key(r): r["sum_v"] for r in run_q(False, layout, rows)}
        asy = {key(r): r["sum_v"] for r in run_q(True, layout, rows)}
        assert sync == asy and len(sync) > 10

    @pytest.mark.parametrize("layout", ["slots", "panes"])
    def test_projected_fire(self, layout):
        rows = make_rows()
        key = lambda r: (r["key"], r["window_start"])
        sync = {key(r): r["count"] for r in run_q(False, layout, rows, 4)}
        asy = {key(r): r["count"] for r in run_q(True, layout, rows, 4)}
        assert sync == asy and len(sync) > 0


class TestCascade:
    def test_window_into_window(self):
        """Upstream 100ms tumbling sums cascade into a downstream 400ms
        tumbling sum over the fired results. With eager watermarks the
        downstream would drop upstream fires as late records; holdback
        must keep them live."""
        rows = make_rows(600, keys=5)

        def run(async_fires):
            env = StreamExecutionEnvironment(Configuration({
                "execution.window.async-fires": async_fires,
                "execution.micro-batch.size": 16,
            }))
            return (
                env.from_collection(rows, timestamp_field="t")
                .key_by("key")
                .window(TumblingEventTimeWindows.of(100))
                .sum("v")
                .key_by("key")
                .window(TumblingEventTimeWindows.of(400))
                .sum("sum_v")
                .execute_and_collect()
                .to_rows()
            )

        key = lambda r: (r["key"], r["window_start"])
        sync = {key(r): r["sum_sum_v"] for r in run(False)}
        asy = {key(r): r["sum_sum_v"] for r in run(True)}
        assert sync == asy and len(sync) > 3
        # oracle: total mass is conserved through both window levels
        assert sum(asy.values()) == pytest.approx(
            sum(r["v"] for r in rows))


class TestAsyncSessions:
    def _run(self, async_fires):
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        rows = []
        rng = np.random.default_rng(11)
        t = 0
        for i in range(500):
            t += int(rng.integers(1, 60))  # gaps > 40 split sessions
            rows.append({"key": int(rng.integers(6)), "v": 1.0, "t": t})
        env = StreamExecutionEnvironment(Configuration({
            "execution.window.async-fires": async_fires,
            "execution.micro-batch.size": 32,
        }))
        result = (
            env.from_collection(rows, timestamp_field="t")
            .key_by("key")
            .window(EventTimeSessionWindows.with_gap(40))
            .sum("v")
            .execute_and_collect()
        )
        return {(r["key"], r["window_start"], r["window_end"]): r["sum_v"]
                for r in result.to_rows()}

    def test_async_equals_sync(self):
        sync, asy = self._run(False), self._run(True)
        assert sync == asy and len(sync) > 5


class TestForcedPending:
    def test_fires_stay_pending_then_land(self, monkeypatch):
        """Gate readiness so every fire stays in flight for several polls:
        results must still all be emitted (by the wait=True drain at the
        latest) and the watermark holdback must not deadlock."""
        polls = {}
        orig = PendingFire.ready

        def slow_ready(self):
            polls[id(self)] = polls.get(id(self), 0) + 1
            return polls[id(self)] > 3 and orig(self)

        monkeypatch.setattr(PendingFire, "ready", slow_ready)
        rows = make_rows()
        got = {(r["key"], r["window_start"]): r["sum_v"]
               for r in run_q(True, "slots", rows)}
        ref = {(r["key"], r["window_start"]): r["sum_v"]
               for r in run_q(False, "slots", rows)}
        assert got == ref


class TestCheckpointDrain:
    def test_checkpoint_with_inflight_fires(self, tmp_path, monkeypatch):
        """Checkpoints must drain pending fires before the cut; the
        snapshot guard raises if an executor ever snapshots with fires in
        flight. Force every fire pending so checkpoints always race one."""
        monkeypatch.setattr(PendingFire, "ready", lambda self: False)
        env = StreamExecutionEnvironment(Configuration({
            "execution.window.async-fires": True,
            "execution.micro-batch.size": 16,
            "execution.checkpointing.every-n-source-batches": 2,
            "state.checkpoints.dir": str(tmp_path / "ckpt"),
        }))
        rows = make_rows(300, keys=7)
        result = (
            env.from_collection(rows, timestamp_field="t")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(100))
            .sum("v")
            .execute_and_collect()
        )
        got = {(r["key"], r["window_start"]): r["sum_v"]
               for r in result.to_rows()}
        exp = {}
        for r in rows:
            exp_key = (r["key"], r["t"] // 100 * 100)
            exp[exp_key] = exp.get(exp_key, 0.0) + r["v"]
        assert got == {k: pytest.approx(v) for k, v in exp.items()}
