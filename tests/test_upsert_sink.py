"""SinkUpsertMaterializer + upsert-capable Kafka sink.

reference: flink-table-runtime/.../operators/sink/SinkUpsertMaterializer.java
(changelog -> last-row-wins upsert stream before the sink) and the
upsert-kafka connector (PRIMARY KEY ... NOT ENFORCED, key-partitioned
writes, consumer-side compaction giving effective exactly-once)."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.core.records import (
    ROWKIND_DELETE,
    ROWKIND_FIELD,
    ROWKIND_INSERT,
    ROWKIND_UPDATE_AFTER,
    ROWKIND_UPDATE_BEFORE,
    RecordBatch,
)
from flink_tpu.table.upsert_materializer import UpsertMaterializeOperator


class _Ctx:
    max_parallelism = 128
    operator_index = 0
    parallelism = 1


def _batch(rows):
    cols = {k: np.asarray([r[k] for r in rows])
            for k in rows[0]}
    return RecordBatch.from_pydict(cols)


class TestMaterializeOperator:
    def test_collapses_changelog_to_last_row_wins(self):
        op = UpsertMaterializeOperator(["k"])
        op.open(_Ctx())
        out = op.process_batch(_batch([
            {"k": 1, "v": 10.0, ROWKIND_FIELD: ROWKIND_INSERT},
            {"k": 1, "v": 10.0, ROWKIND_FIELD: ROWKIND_UPDATE_BEFORE},
            {"k": 1, "v": 20.0, ROWKIND_FIELD: ROWKIND_UPDATE_AFTER},
            {"k": 2, "v": 5.0, ROWKIND_FIELD: ROWKIND_INSERT},
        ]))
        assert len(out) == 1
        rows = out[0].to_rows()
        got = {r["k"]: (r["v"], r[ROWKIND_FIELD]) for r in rows}
        # one row per key, the LAST image, first emission = INSERT
        assert got == {1: (20.0, ROWKIND_INSERT),
                       2: (5.0, ROWKIND_INSERT)}

    def test_update_then_delete_emits_tombstone(self):
        op = UpsertMaterializeOperator(["k"])
        op.open(_Ctx())
        op.process_batch(_batch([
            {"k": 7, "v": 1.0, ROWKIND_FIELD: ROWKIND_INSERT}]))
        out = op.process_batch(_batch([
            {"k": 7, "v": 1.0, ROWKIND_FIELD: ROWKIND_UPDATE_BEFORE},
            {"k": 7, "v": 2.0, ROWKIND_FIELD: ROWKIND_UPDATE_AFTER}]))
        assert out[0].to_rows()[0][ROWKIND_FIELD] == ROWKIND_UPDATE_AFTER
        out = op.process_batch(_batch([
            {"k": 7, "v": 2.0, ROWKIND_FIELD: ROWKIND_DELETE}]))
        r = out[0].to_rows()[0]
        assert r[ROWKIND_FIELD] == ROWKIND_DELETE and r["v"] == 2.0
        # re-insert after delete is an INSERT again
        out = op.process_batch(_batch([
            {"k": 7, "v": 3.0, ROWKIND_FIELD: ROWKIND_INSERT}]))
        assert out[0].to_rows()[0][ROWKIND_FIELD] == ROWKIND_INSERT

    def test_unchanged_value_suppressed(self):
        op = UpsertMaterializeOperator(["k"])
        op.open(_Ctx())
        op.process_batch(_batch([
            {"k": 1, "v": 4.0, ROWKIND_FIELD: ROWKIND_INSERT}]))
        out = op.process_batch(_batch([
            {"k": 1, "v": 4.0, ROWKIND_FIELD: ROWKIND_UPDATE_AFTER}]))
        assert out == []

    def test_retraction_matches_despite_restamped_ts(self):
        """Upstream GroupAgg re-stamps -U pre-images with the CURRENT
        max_ts, so retraction matching must ignore __ts__ — otherwise a
        miss falls to drop-oldest and removes the WRONG key's image when
        two changelog keys feed one sink-key value (advisor r4, high)."""
        from flink_tpu.core.records import TIMESTAMP_FIELD

        op = UpsertMaterializeOperator(["v"])
        op.open(_Ctx())
        # two changelog keys (g=1, g=2) both currently at v=5 — with
        # sink PRIMARY KEY (v), key (5.0,) holds two images
        op.process_batch(_batch([
            {"g": 2, "v": 5.0, TIMESTAMP_FIELD: 100,
             ROWKIND_FIELD: ROWKIND_INSERT},
            {"g": 1, "v": 5.0, TIMESTAMP_FIELD: 200,
             ROWKIND_FIELD: ROWKIND_INSERT},
        ]))
        assert len(op._rows[(5.0,)]) == 2
        # g=1 retracts its v=5 image later: the -U carries the NEW
        # stamp (999), not the stored 100. It must remove g=1's image,
        # leaving g=2's as the current one.
        out = op.process_batch(_batch([
            {"g": 1, "v": 5.0, TIMESTAMP_FIELD: 999,
             ROWKIND_FIELD: ROWKIND_UPDATE_BEFORE},
            {"g": 1, "v": 6.0, TIMESTAMP_FIELD: 999,
             ROWKIND_FIELD: ROWKIND_UPDATE_AFTER},
        ]))
        remaining = op._rows[(5.0,)]
        assert len(remaining) == 1
        g_idx = op._cols.index("g")
        assert remaining[0][g_idx] == 2  # g=2's image survived
        rows = {r["v"]: r for r in out[0].to_rows()}
        assert rows[6.0][ROWKIND_FIELD] == ROWKIND_INSERT

    def test_snapshot_restore_key_group_filter(self):
        op = UpsertMaterializeOperator(["k"])
        op.open(_Ctx())
        op.process_batch(_batch([
            {"k": k, "v": float(k), ROWKIND_FIELD: ROWKIND_INSERT}
            for k in range(50)]))
        snap = op.snapshot_state()
        from flink_tpu.state.keygroups import (
            assign_key_groups,
            hash_keys_to_i64,
        )

        groups = assign_key_groups(
            hash_keys_to_i64(np.arange(50)), 128)
        keep = {int(g) for g in groups[:25]}
        op2 = UpsertMaterializeOperator(["k"])
        op2.open(_Ctx())
        op2.restore_state(snap, key_group_filter=keep)
        expect = {k for k in range(50) if int(groups[k]) in keep}
        assert {k[0] for k in op2._rows} == expect


def _compact_topic(topic, parts, key_col):
    """Consumer-side last-wins compaction (what a reader of an
    upsert-kafka topic does): per key keep the LAST row across the
    key's partition; DELETE removes the key."""
    from flink_tpu.connectors.kafka import KafkaSource

    src = KafkaSource(topic)
    src.open(0, 1)
    current = {}
    while True:
        b = src.poll_batch(10_000)
        if b is None:
            break
        kinds = (b[ROWKIND_FIELD] if ROWKIND_FIELD in b.columns
                 else np.zeros(len(b), dtype=np.int8))
        for r, kind in zip(b.to_rows(), kinds):
            if int(kind) == ROWKIND_DELETE:
                current.pop(r[key_col], None)
            else:
                current[r[key_col]] = r
    return current


class TestUpsertKafkaSQL:
    def _produce(self, topic, n, keys):
        from flink_tpu.connectors.kafka import FakeBroker

        broker = FakeBroker.get("default")
        broker.create_topic(topic, 2)
        rng = np.random.default_rng(3)
        ks = rng.integers(0, keys, n).astype(np.int64)
        vs = rng.random(n).astype(np.float64)
        ts = np.arange(n, dtype=np.int64) * 10
        for p in range(2):
            m = ks % 2 == p
            broker.append(topic, p, RecordBatch.from_pydict(
                {"key": ks[m], "value": vs[m], "ts": ts[m]},
                timestamps=ts[m]))
        return ks, vs

    def test_plain_group_by_into_upsert_kafka(self):
        """BREAD-AND-BUTTER: INSERT INTO upsert_table SELECT k, COUNT(*)
        FROM t GROUP BY k — an updating aggregate into an external
        table, retractions collapsed by the materializer."""
        from flink_tpu.table.environment import StreamTableEnvironment

        ks, _ = self._produce("ub1", n=5000, keys=40)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 500}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE ub1 (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='ub1')")
        tenv.execute_sql(
            "CREATE TABLE out_up (key BIGINT, cnt BIGINT, "
            "PRIMARY KEY (key) NOT ENFORCED) "
            "WITH ('connector'='kafka', 'topic'='out_up', "
            "'sink.partitions'='2')")
        tenv.execute_sql(
            "INSERT INTO out_up "
            "SELECT key, COUNT(*) AS cnt FROM ub1 GROUP BY key")
        import collections

        oracle = collections.Counter(ks.tolist())
        current = _compact_topic("out_up", 2, "key")
        assert {k: r["cnt"] for k, r in current.items()} == dict(oracle)
        # the topic holds upserts, never UPDATE_BEFORE pre-images
        from flink_tpu.connectors.kafka import KafkaSource

        src = KafkaSource("out_up")
        src.open(0, 1)
        while True:
            b = src.poll_batch(10_000)
            if b is None:
                break
            assert ROWKIND_FIELD in b.columns
            assert not (np.asarray(b[ROWKIND_FIELD])
                        == ROWKIND_UPDATE_BEFORE).any()

    def test_append_sink_still_rejected(self):
        from flink_tpu.table.environment import (
            PlanError,
            StreamTableEnvironment,
        )

        self._produce("ub2", n=100, keys=5)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 50}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE ub2 (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='ub2')")
        tenv.execute_sql(
            "CREATE TABLE out_append (key BIGINT, cnt BIGINT) "
            "WITH ('connector'='kafka', 'topic'='out_append')")
        with pytest.raises(PlanError, match="append-only"):
            tenv.execute_sql(
                "INSERT INTO out_append "
                "SELECT key, COUNT(*) AS cnt FROM ub2 GROUP BY key")

    def test_sink_pk_differs_from_changelog_key(self):
        """The reference's main materializer trigger: the sink PRIMARY
        KEY is NOT the changelog's key (a global aggregate written into
        a value-keyed table) — the list-based algorithm retracts stale
        pk rows, so compaction leaves exactly the final value."""
        from flink_tpu.table.environment import StreamTableEnvironment

        self._produce("ub3", n=900, keys=5)
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 100}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            "CREATE TABLE ub3 (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            "WITH ('connector'='kafka', 'topic'='ub3')")
        tenv.execute_sql(
            "CREATE TABLE out_pk (cnt BIGINT, "
            "PRIMARY KEY (cnt) NOT ENFORCED) "
            "WITH ('connector'='kafka', 'topic'='out_pk')")
        tenv.execute_sql(
            "INSERT INTO out_pk SELECT COUNT(*) AS cnt FROM ub3")
        current = _compact_topic("out_pk", 1, "cnt")
        # every intermediate count was retracted: one row, the total
        assert sorted(r["cnt"] for r in current.values()) == [900]

    def test_crash_restore_effective_exactly_once(self, tmp_path):
        """At-least-once replay + last-wins compaction = the final
        compacted view equals the clean run's (upsert-kafka's
        effective-exactly-once argument)."""
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.table.environment import StreamTableEnvironment
        from tests.test_checkpointing import FailingMap

        ckpt = str(tmp_path / "ck")

        def build(env, fail_after=None):
            tenv = StreamTableEnvironment(env)
            src = DataGenSource(total_records=8_000, num_keys=60,
                                events_per_second_of_eventtime=10_000,
                                seed=9)
            ds = env.from_source(
                src, WatermarkStrategy.for_bounded_out_of_orderness(0))
            if fail_after is not None:
                ds = ds.map(FailingMap(fail_after), name="failmap")
            else:
                ds = ds.map(lambda b: b, name="failmap")
            tenv.create_temporary_view("t", ds,
                                       columns=["key", "value"])
            tenv.execute_sql(
                "CREATE TABLE out_cr (key BIGINT, cnt BIGINT, "
                "PRIMARY KEY (key) NOT ENFORCED) "
                "WITH ('connector'='kafka', 'topic'='out_cr', "
                "'sink.partitions'='2')")
            return tenv

        # clean oracle (no kafka): batch counts
        import collections

        src = DataGenSource(total_records=8_000, num_keys=60,
                            events_per_second_of_eventtime=10_000, seed=9)
        src.open(0, 1)
        oracle = collections.Counter()
        while True:
            b = src.poll_batch(4096)
            if b is None:
                break
            oracle.update(b["key"].tolist())

        conf = {"execution.micro-batch.size": 400,
                "state.checkpoints.dir": ckpt,
                "execution.checkpointing.every-n-source-batches": 4}
        env1 = StreamExecutionEnvironment(Configuration(conf))
        tenv1 = build(env1, fail_after=5_000)
        with pytest.raises(RuntimeError, match="injected failure"):
            tenv1.execute_sql(
                "INSERT INTO out_cr "
                "SELECT key, COUNT(*) AS cnt FROM t GROUP BY key")

        import os

        env2 = StreamExecutionEnvironment(Configuration(conf))
        tenv2 = build(env2)
        os.environ["FLINK_TPU_RESTORE_FROM"] = ckpt
        try:
            tenv2.execute_sql(
                "INSERT INTO out_cr "
                "SELECT key, COUNT(*) AS cnt FROM t GROUP BY key")
        finally:
            os.environ.pop("FLINK_TPU_RESTORE_FROM", None)

        current = _compact_topic("out_cr", 2, "key")
        assert {k: r["cnt"] for k, r in current.items()} == dict(oracle)
