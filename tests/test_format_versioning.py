"""Snapshot format versioning + compression.

reference model: TypeSerializerSnapshot compatibility resolution
(flink-core typeutils) and lz4/snappy state compression (root
pom.xml:168,225).
"""

import json
import os

import numpy as np
import pytest

from flink_tpu.checkpoint.storage import (
    FORMAT_VERSION,
    read_manifest,
    read_snapshot_dir,
    register_migration,
    write_snapshot_dir,
    _MIGRATIONS,
)
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import SumAggregate


def _state():
    return {"table": {
        "key_id": np.arange(100, dtype=np.int64),
        "namespace": np.full(100, 10, dtype=np.int64),
        "key_group": np.zeros(100, dtype=np.int32),
        "leaf_0": np.random.default_rng(0).random(100).astype(np.float32),
    }}


class TestFormatVersion:
    def test_manifest_carries_current_version(self, tmp_path):
        d = write_snapshot_dir(str(tmp_path / "s"), 1, "job",
                               {"op": _state()})
        assert read_manifest(d)["format_version"] == FORMAT_VERSION

    def test_newer_version_fails_precisely(self, tmp_path):
        d = write_snapshot_dir(str(tmp_path / "s"), 1, "job",
                               {"op": _state()})
        m = read_manifest(d)
        m["format_version"] = FORMAT_VERSION + 7
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(m, f)
        with pytest.raises(RuntimeError, match="newer framework version"):
            read_snapshot_dir(d)

    def test_v1_snapshot_migrates_forward(self, tmp_path):
        """A round-1 snapshot (no version field) reads as v1 and migrates
        through the registered chain."""
        d = write_snapshot_dir(str(tmp_path / "s"), 1, "job",
                               {"op": _state()})
        m = read_manifest(d)
        del m["format_version"]
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(m, f)
        states = read_snapshot_dir(d)
        np.testing.assert_array_equal(states["op"]["table"]["key_id"],
                                      np.arange(100))

    def test_custom_migration_hook_runs(self, tmp_path):
        d = write_snapshot_dir(str(tmp_path / "s"), 1, "job",
                               {"op": _state()})
        m = read_manifest(d)
        del m["format_version"]  # pretend v1
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(m, f)
        seen = {}
        old = _MIGRATIONS[1]

        def migrate(states):
            seen["ran"] = True
            return states

        register_migration(1, migrate)
        try:
            read_snapshot_dir(d)
        finally:
            register_migration(1, old)
        assert seen.get("ran")

    def test_lossy_dtype_restore_fails_lossless_migrates(self):
        t = SlotTable(SumAggregate("v"), capacity=1024)
        good = {
            "key_id": np.asarray([1, 2], dtype=np.int64),
            "namespace": np.asarray([10, 10], dtype=np.int64),
            "key_group": np.zeros(2, dtype=np.int32),
            "leaf_0": np.asarray([1.5, 2.5], dtype=np.float64),  # castable
        }
        t.restore(good)  # value-preserving cast float64 -> float32
        assert t.query(1, namespace=10)[10]["sum_v"] == 1.5
        bad = dict(good, leaf_0=np.asarray([1.0, 1e300]))  # overflows f32
        with pytest.raises(RuntimeError, match="schema incompatible"):
            SlotTable(SumAggregate("v"), capacity=1024).restore(bad)


class TestCompression:
    def test_compressed_snapshot_reads_back_and_is_smaller(self, tmp_path):
        # highly compressible state
        state = {"table": {
            "key_id": np.arange(50_000, dtype=np.int64),
            "namespace": np.full(50_000, 10, dtype=np.int64),
            "key_group": np.zeros(50_000, dtype=np.int32),
            "leaf_0": np.ones(50_000, dtype=np.float32),
        }}
        dc = write_snapshot_dir(str(tmp_path / "c"), 1, "job",
                                {"op": state}, compress=True)
        du = write_snapshot_dir(str(tmp_path / "u"), 1, "job",
                                {"op": state}, compress=False)

        def size(d):
            return sum(e.stat().st_size for e in os.scandir(d))

        assert size(dc) < size(du) / 4
        a = read_snapshot_dir(dc)["op"]["table"]
        b = read_snapshot_dir(du)["op"]["table"]
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])

    def test_checkpoint_span_reports_state_size(self, tmp_path):
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": str(tmp_path / "ck"),
            "execution.checkpointing.every-n-source-batches": 2,
        }))
        sink = CollectSink()
        (env.add_source(DataGenSource(total_records=8_000, num_keys=20,
                                      events_per_second_of_eventtime=4_000),
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("key").window(TumblingEventTimeWindows.of(1000)).count()
            .sink_to(sink))
        result = env.execute()
        spans = result.traces.spans(scope="checkpoint")
        assert spans
        assert all(s.attributes.get("stateSizeBytes", 0) > 0
                   for s in spans)
