"""Changelog (DSTL) backend: write-ahead state log, instant checkpoints,
materialization + truncation, replay on restore.

reference model: flink-dstl FsStateChangelogWriter tests + changelog
backend ITCases.
"""

import os

import numpy as np
import pytest

from flink_tpu.checkpoint.changelog import (
    ChangelogKeyedBackend,
    ChangelogWriter,
    read_entries,
)
from flink_tpu.windowing.aggregates import SumAggregate


def scatter(backend, keys, ns, vals):
    backend.scatter(np.asarray(keys, dtype=np.int64),
                    np.asarray(ns, dtype=np.int64),
                    (np.asarray(vals, dtype=np.float32),))


def sums(backend, ns):
    s = backend.table.slots_for_namespace(ns)
    res = backend.table.fire(s[:, None])
    return dict(zip(backend.table.keys_of_slots(s).tolist(),
                    res["sum_v"].tolist()))


class TestWriter:
    def test_append_flush_read_roundtrip(self, tmp_path):
        p = str(tmp_path / "log.bin")
        w = ChangelogWriter(p)
        w.append("op", "scatter", {"x": np.arange(3)})
        w.append("op", "free", {"namespaces": [1, 2]})
        w.flush()
        entries = list(read_entries(p))
        assert [e[0] for e in entries] == [0, 1]
        assert entries[1][2] == "free"
        # sequence numbers continue across reopen
        w.close()
        w2 = ChangelogWriter(p)
        assert w2.append("op", "free", {"namespaces": []}) == 2
        w2.close()

    def test_torn_final_frame_is_ignored(self, tmp_path):
        p = str(tmp_path / "log.bin")
        w = ChangelogWriter(p)
        w.append("op", "scatter", {"x": np.arange(3)})
        w.flush()
        w.close()
        with open(p, "ab") as f:  # simulate crash mid-append
            f.write(b"FTCL\x99\x00\x00\x00\x00\x00\x00\x00partial")
        assert len(list(read_entries(p))) == 1

    def test_truncate_drops_materialized_prefix(self, tmp_path):
        p = str(tmp_path / "log.bin")
        w = ChangelogWriter(p)
        for i in range(5):
            w.append("op", "free", {"namespaces": [i]})
        w.truncate(3)
        assert [e[0] for e in read_entries(p)] == [3, 4]
        assert w.append("op", "free", {"namespaces": []}) == 5
        w.close()


class TestChangelogBackend:
    def test_checkpoint_is_offset_only_and_restores_exactly(self, tmp_path):
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        scatter(b, [1, 2, 1], [10, 10, 10], [1.0, 2.0, 3.0])
        ck = b.checkpoint()  # instant: just an offset
        scatter(b, [1], [10], [100.0])  # AFTER the checkpoint cut
        b.close()

        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        b2.restore(ck)
        assert sums(b2, 10) == {1: 4.0, 2: 2.0}  # post-cut write excluded
        b2.close()

    def test_materialize_and_subsumption_bound_replay(self, tmp_path):
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        scatter(b, [1, 2], [10, 10], [1.0, 2.0])
        mat_ck = b.materialize()
        # materialize alone discards nothing (older checkpoints stay
        # restorable); truncation follows checkpoint subsumption
        log = os.path.join(str(tmp_path / "cl"), "changelog.bin")
        assert len(list(read_entries(log))) == 1
        b.truncate_subsumed(mat_ck["changelog_seq"])
        assert list(read_entries(log)) == []  # now truncated
        scatter(b, [2, 3], [10, 10], [5.0, 7.0])
        b.free_namespaces([99])  # no-op free is still logged + replayable
        ck = b.checkpoint()
        b.close()

        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        b2.restore(ck)
        assert sums(b2, 10) == {1: 1.0, 2: 7.0, 3: 7.0}
        b2.close()

    def test_checkpoint_survives_later_materialization(self, tmp_path):
        """A checkpoint taken BEFORE a materialization must stay restorable
        until explicitly subsumed (the bug class: materialize deleting the
        replay prefix under a retained checkpoint)."""
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        scatter(b, [1], [10], [1.0])
        early_ck = b.checkpoint()
        scatter(b, [1], [10], [10.0])
        b.materialize()  # later materialization
        b.close()
        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        b2.restore(early_ck)
        assert sums(b2, 10) == {1: 1.0}
        b2.close()

    def test_truncated_checkpoint_fails_loudly(self, tmp_path):
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        scatter(b, [1], [10], [1.0])
        early_ck = b.checkpoint()
        scatter(b, [1], [10], [10.0])
        mat = b.materialize()
        b.truncate_subsumed(mat["changelog_seq"])  # early_ck now subsumed
        b.close()
        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        with pytest.raises(RuntimeError, match="not\\s+restorable"):
            b2.restore(early_ck)
        b2.close()

    def test_recovery_after_torn_tail_preserves_new_appends(self, tmp_path):
        """Crash mid-append, reopen, append more: the post-crash entries
        must be durable (the torn tail is trimmed on reopen)."""
        p = str(tmp_path / "cl" / "changelog.bin")
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        scatter(b, [1], [10], [1.0])
        b.writer.flush()
        b.close()
        with open(p, "ab") as f:
            f.write(b"FTCL" + b"\xff" * 12)  # torn frame
        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        # replay existing log into the fresh table first
        b2.restore({"changelog_seq": b2.writer.next_sequence,
                    "materialized_seq": 0})
        scatter(b2, [2], [10], [2.0])
        ck = b2.checkpoint()
        b2.close()
        b3 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        b3.restore(ck)
        assert sums(b3, 10) == {1: 1.0, 2: 2.0}
        b3.close()

    def test_free_is_replayed(self, tmp_path):
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        scatter(b, [1, 2], [10, 10], [1.0, 2.0])
        scatter(b, [1, 2], [20, 20], [3.0, 4.0])
        b.free_namespaces([10])
        ck = b.checkpoint()
        b.close()
        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        b2.restore(ck)
        assert sums(b2, 10) == {}
        assert sums(b2, 20) == {1: 3.0, 2: 4.0}
        b2.close()

    def test_restore_equals_direct_state_randomized(self, tmp_path):
        rng = np.random.default_rng(11)
        b = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        for step in range(10):
            keys = rng.integers(0, 40, 100)
            ns = rng.integers(1, 4, 100) * 10
            vals = rng.random(100)
            scatter(b, keys, ns, vals)
            if step == 4:
                b.materialize()
            if step == 7:
                b.free_namespaces([10])
        expected = {ns: sums(b, ns) for ns in (10, 20, 30)}
        ck = b.checkpoint()
        b.close()
        b2 = ChangelogKeyedBackend(SumAggregate("v"), str(tmp_path / "cl"))
        b2.restore(ck)
        for ns in (10, 20, 30):
            got = sums(b2, ns)
            assert got.keys() == expected[ns].keys()
            for k in got:
                assert abs(got[k] - expected[ns][k]) < 1e-3
        b2.close()
