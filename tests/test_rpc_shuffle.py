"""gRPC shuffle transport: record batches between task executors over real
sockets (reference: NettyShuffleEnvironment role + credit-based flow
control), including a stage-parallel job whose keyed subtasks consume
through two distinct shuffle servers."""

import queue
import threading
import time

import numpy as np
import pytest

from flink_tpu.cluster.rpc import RpcService
from flink_tpu.cluster.rpc_shuffle import RpcShuffleService
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.shuffle_spi import END_OF_PARTITION, Barrier


@pytest.fixture
def two_services():
    rpc_a, rpc_b = RpcService(), RpcService()
    yield rpc_a, rpc_b
    rpc_a.stop()
    rpc_b.stop()


class TestRpcShuffleTransport:
    def test_cross_service_batches_and_events(self, two_services):
        rpc_a, rpc_b = two_services
        # consumer lives on B; producer on A routes everything to B
        svc_b = RpcShuffleService(rpc_b, route=lambda pid, sub: None)
        svc_a = RpcShuffleService(
            rpc_a, route=lambda pid, sub: rpc_b.address)
        w = svc_a.create_partition("p", 2)
        gate0 = svc_b.create_gate(["p"], 0)
        gate1 = svc_b.create_gate(["p"], 1)
        w.emit(0, RecordBatch.from_pydict({"x": np.arange(3)}))
        w.emit(1, RecordBatch.from_pydict({"x": np.arange(5)}))
        w.broadcast_event(123)
        w.broadcast_event(Barrier(7))
        w.close()
        ch, b0 = gate0.poll(timeout=5)
        assert len(b0) == 3
        assert gate0.poll(timeout=5)[1] == 123
        assert gate0.poll(timeout=5)[1].checkpoint_id == 7
        assert gate0.poll(timeout=5)[1] is END_OF_PARTITION
        assert len(gate1.poll(timeout=5)[1]) == 5

    def test_backpressure_blocks_remote_producer(self, two_services):
        rpc_a, rpc_b = two_services
        RpcShuffleService(rpc_b, route=lambda pid, sub: None,
                          credits_per_channel=1)
        svc_a = RpcShuffleService(
            rpc_a, route=lambda pid, sub: rpc_b.address)
        w = svc_a.create_partition("bp", 1)
        b = RecordBatch.from_pydict({"x": np.arange(2)})
        w.emit(0, b)  # fills the single credit
        done = threading.Event()

        def second():
            w.emit(0, b)
            done.set()

        t = threading.Thread(target=second, daemon=True)
        t.start()
        assert not done.wait(0.3), \
            "push must block while the consumer queue is full"
        # consumer drains -> the blocked push completes
        svc_b = RpcShuffleService(rpc_b, route=lambda pid, sub: None)
        gate = svc_b.create_gate(["bp"], 0)
        assert len(gate.poll(timeout=5)[1]) == 2
        assert done.wait(5)

    def test_local_route_skips_the_socket(self):
        rpc = RpcService()
        try:
            svc = RpcShuffleService(rpc, route=lambda pid, sub: None)
            w = svc.create_partition("loc", 1)
            gate = svc.create_gate(["loc"], 0)
            w.emit(0, RecordBatch.from_pydict({"x": np.arange(4)}))
            assert len(gate.poll(timeout=2)[1]) == 4
        finally:
            rpc.stop()


class TestStageJobOverGrpcShuffle:
    def test_stage_job_spans_two_shuffle_servers(self):
        """Keyed subtasks 0..1 consume via server A, 2..3 via server B —
        the data plane crosses real gRPC sockets mid-job."""
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.cluster.stage_executor import StageParallelExecutor
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        rpc_a, rpc_b = RpcService(), RpcService()
        try:
            # producer-side service: subpartitions 0-1 -> server A (local),
            # 2-3 -> server B (remote socket)
            svc_b = RpcShuffleService(rpc_b, route=lambda pid, sub: None)

            def route(pid, sub):
                return None if sub < 2 else rpc_b.address

            svc_a = RpcShuffleService(rpc_a, route=route)

            class SplitGateService:
                """The executor-facing view: writers route via A's table;
                gates 0-1 poll A's buffers, 2-3 poll B's."""

                def create_partition(self, pid, n, credits=2):
                    return svc_a.create_partition(pid, n, credits)

                def create_gate(self, pids, sub):
                    return (svc_a if sub < 2 else svc_b).create_gate(
                        pids, sub)

                def close(self):
                    pass

            def build(env, sink):
                src = DataGenSource(total_records=20_000, num_keys=200,
                                    events_per_second_of_eventtime=10_000,
                                    seed=3)
                env.from_source(
                    src, WatermarkStrategy.for_bounded_out_of_orderness(0),
                    name="gen") \
                    .key_by("key") \
                    .window(TumblingEventTimeWindows.of(1000)) \
                    .sum("value").sink_to(sink)

            conf = Configuration({
                "execution.micro-batch.size": 1000,
                "execution.stage-parallelism": 4,
                "state.slot-table.capacity": 8192,
            })
            env = StreamExecutionEnvironment(conf)
            sink = CollectSink()
            build(env, sink)
            graph = env.get_stream_graph()
            executor = StageParallelExecutor(env._effective_config(),
                                             shuffle_service=SplitGateService())
            result = executor.run(graph, "grpc-shuffle-job")
            assert all(c > 0 for c in result.metrics["subtask_records_in"])

            # equivalence vs single-slot
            env2 = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 1000,
                "state.slot-table.capacity": 8192}))
            sink2 = CollectSink()
            build(env2, sink2)
            env2.execute("single")

            def res(s):
                return {(r["key"], r["window_start"]):
                        round(r["sum_value"], 3)
                        for r in s.result().to_rows()}

            from tests.conftest import assert_windows_approx_equal

            assert_windows_approx_equal(res(sink), res(sink2))
        finally:
            rpc_a.stop()
            rpc_b.stop()
