"""Native columnar wire codec (native/codec.cpp + flink_tpu/native/codec.py).

reference parity: compiled fast coders (pyflink coder_impl_fast.pyx) and
lz4/snappy buffer compression (root pom.xml:168) — SURVEY §2.10 items 5/7.

Pins: roundtrip fidelity for every column kind (numeric dtypes, string
object columns, arbitrary-object columns), compression actually engaging
on compressible payloads, corruption -> loud CRC failure (never silent
garbage), incompressible data falling back to stored form, and the gRPC
shuffle's encode/decode using the codec for batches.
"""

import numpy as np
import pytest

from flink_tpu.core.records import RecordBatch
from flink_tpu.native.codec import (
    codec_available,
    decode_batch,
    encode_batch,
)

pytestmark = pytest.mark.skipif(
    not codec_available(), reason="native codec unavailable")


def _roundtrip(batch):
    data = encode_batch(batch)
    out = decode_batch(data)
    assert set(out.columns) == set(batch.columns)
    return out, data


class TestRoundtrip:
    def test_numeric_dtypes(self):
        rng = np.random.default_rng(0)
        b = RecordBatch({
            "i64": rng.integers(-5, 5, 1000),
            "i32": rng.integers(0, 100, 1000).astype(np.int32),
            "f32": rng.random(1000).astype(np.float32),
            "f64": rng.random(1000),
            "u8": rng.integers(0, 255, 1000).astype(np.uint8),
            "b": rng.random(1000) > 0.5,
        })
        out, _ = _roundtrip(b)
        for name, col in b.columns.items():
            got = out[name]
            assert got.dtype == np.asarray(col).dtype, name
            np.testing.assert_array_equal(got, col)

    def test_string_and_object_columns(self):
        b = RecordBatch({
            "k": np.arange(4),
            "s": np.array(["a", "déjà", "", "x" * 500], dtype=object),
            "o": np.array([(1, 2), None, {"z": 3}, "mixed"], dtype=object),
        })
        out, _ = _roundtrip(b)
        assert list(out["s"]) == ["a", "déjà", "", "x" * 500]
        assert list(out["o"]) == [(1, 2), None, {"z": 3}, "mixed"]

    def test_empty_batch(self):
        b = RecordBatch({"x": np.empty(0, dtype=np.int64)})
        out, _ = _roundtrip(b)
        assert len(out) == 0

    def test_multidim_column(self):
        """[n, d] columns (e.g. ML embedding outputs) keep their shape."""
        rng = np.random.default_rng(3)
        emb = rng.random((40, 16)).astype(np.float32)
        b = RecordBatch({"k": np.arange(40), "emb": emb})
        out, _ = _roundtrip(b)
        assert out["emb"].shape == (40, 16)
        np.testing.assert_array_equal(out["emb"], emb)

    def test_receiver_without_codec_fails_precisely(self):
        """A node that can't load the native library must name the
        problem, not crash with AttributeError."""
        import subprocess
        import sys

        b = RecordBatch({"x": np.arange(100)})
        frame = encode_batch(b)
        r = subprocess.run(
            [sys.executable, "-c",
             "import sys, os\n"
             "os.environ['FLINK_TPU_NO_NATIVE'] = '1'\n"
             "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
             "from flink_tpu.native.codec import decode_batch\n"
             "try:\n"
             "    decode_batch(sys.stdin.buffer.read())\n"
             "except RuntimeError as e:\n"
             "    assert 'codec library is unavailable' in str(e), e\n"
             "    print('precise-error-ok')\n"],
            input=frame, capture_output=True, timeout=120)
        assert b"precise-error-ok" in r.stdout, (r.stdout, r.stderr)


class TestCompression:
    def test_compressible_shrinks(self):
        b = RecordBatch({"x": np.zeros(100_000, dtype=np.int64)})
        _, data = _roundtrip(b)
        assert len(data) < 100_000 * 8 / 10  # >10x on constant data

    def test_incompressible_stored(self):
        rng = np.random.default_rng(1)
        raw = rng.integers(0, 2**63, 50_000)
        b = RecordBatch({"x": raw})
        out, data = _roundtrip(b)
        np.testing.assert_array_equal(out["x"], raw)
        # stored form: frame ~= payload + small headers
        assert len(data) < 50_000 * 8 + 256

    def test_mixed_then_exact(self):
        rng = np.random.default_rng(2)
        vals = np.repeat(rng.integers(0, 50, 1000), 100).astype(np.int32)
        b = RecordBatch({"x": vals})
        out, data = _roundtrip(b)
        np.testing.assert_array_equal(out["x"], vals)
        assert len(data) < vals.nbytes / 2


class TestCorruption:
    def test_flipped_payload_byte_fails_crc(self):
        b = RecordBatch({"x": np.arange(10_000)})
        data = bytearray(encode_batch(b))
        data[-3] ^= 0xFF
        with pytest.raises(ValueError, match="CRC|malformed"):
            decode_batch(bytes(data))

    def test_any_byte_flip_fails_loudly(self):
        """Column metadata rides inside the CRC-protected block: flipping
        ANY byte of the frame must raise, never silently mistype a
        column."""
        b = RecordBatch({"x": np.arange(500, dtype=np.int32),
                         "y": np.ones(500)})
        data = encode_batch(b)
        rng = np.random.default_rng(9)
        for _ in range(60):
            broken = bytearray(data)
            broken[int(rng.integers(0, len(data)))] ^= 0x40
            try:
                out = decode_batch(bytes(broken))
            except Exception:
                continue
            # a flip may land on a match-offset byte that happens to point
            # at equivalent bytes of a periodic region — then the decoded
            # payload is bit-identical and the CRC passing is CORRECT. What
            # must never happen is decoding to *different* data.
            np.testing.assert_array_equal(out["x"], b["x"])
            np.testing.assert_array_equal(out["y"], b["y"])

    def test_truncated_frame_fails(self):
        b = RecordBatch({"x": np.arange(10_000)})
        data = encode_batch(b)
        with pytest.raises(ValueError):
            decode_batch(data[:len(data) - 7])


class TestShuffleIntegration:
    def test_rpc_shuffle_uses_codec(self):
        from flink_tpu.cluster.rpc_shuffle import _decode, _encode

        b = RecordBatch({"k": np.arange(100), "v": np.ones(100)})
        data = _encode(b)
        assert data[:1] == b"B"
        out = _decode(data)
        np.testing.assert_array_equal(out["k"], b["k"])
        np.testing.assert_array_equal(out["v"], b["v"])
