"""Native hot-row probe table (native/hotcache.cpp + its wrapper):
parity with the Python fallback, seeded cross-generation fuzz against
a dict oracle, deterministic torn-read coverage, packing exactness,
and the make_hot_row_cache selection knob.

The contract: :class:`NativeHotRowCache` is interface- and RESULT-
identical to :class:`HotRowCache` (the serving plane selects one at
construction, like ``make_session_meta``); a torn native read NEVER
surfaces a mixed row — it retries, then falls to the miss path.
"""

import numpy as np
import pytest

from flink_tpu.native import hotcache_available
from flink_tpu.tenancy.hot_cache import (
    HotRowCache,
    PrimeDelta,
    make_hot_row_cache,
)

native = pytest.mark.skipif(not hotcache_available(),
                            reason="native hotcache unavailable")


def _native():
    from flink_tpu.tenancy.hot_cache_native import NativeHotRowCache

    return NativeHotRowCache(max_entries=1 << 12)


def _both():
    return [_native(), HotRowCache(max_entries=1 << 12)]


def _close(c):
    if hasattr(c, "close"):
        c.close()


def _delta(entries):
    """PrimeDelta from {kid: (updates{ns: {col: v}}, removals[ns],
    flags)} — the adapters' flat shape, hand-built for tests."""
    kids = sorted(entries)
    uoff = [0]
    u_ns = []
    u_rows = []
    roff = [0]
    r_ns = []
    flags = []
    cols = None
    for kid in kids:
        ups, rem, fl = entries[kid]
        for ns, row in (ups or {}).items():
            if cols is None:
                cols = tuple(row.keys())
            u_ns.append(ns)
            u_rows.append([row[c] for c in cols])
        uoff.append(len(u_ns))
        r_ns.extend(rem)
        roff.append(len(r_ns))
        flags.append(fl)
    u_cols = []
    if cols is not None:
        mat = np.asarray(u_rows, dtype=np.float64)
        u_cols = [(c, mat[:, i]) for i, c in enumerate(cols)]
    return PrimeDelta(
        keys=np.asarray(kids, dtype=np.int64),
        uoff=np.asarray(uoff, dtype=np.int64),
        u_ns=np.asarray(u_ns, dtype=np.int64),
        u_cols=u_cols,
        roff=np.asarray(roff, dtype=np.int64),
        r_ns=np.asarray(r_ns, dtype=np.int64),
        flags=np.asarray(flags, dtype=np.uint8))


@native
class TestParity:
    """Every operation, native vs Python, result-identical."""

    def test_put_get_roundtrip_exact_types(self):
        # int64 beyond 2^53 and float64 must round-trip EXACTLY (the
        # packed entry stores raw bit patterns with a dtype tag)
        val = {100: {"a": 2 ** 53 + 1, "b": 1.0 / 3.0},
               200: {"a": -5, "b": -0.0}}
        for c in _both():
            c.put("j", "op", 7, 1, val)
            hit, got = c.get("j", "op", 7, 1, exact=False)
            assert hit
            assert got == val
            assert isinstance(got[100]["a"], int)
            assert got[100]["a"] == 2 ** 53 + 1
            assert np.float64(got[200]["b"]).view(np.int64) == \
                np.float64(-0.0).view(np.int64)
            _close(c)

    def test_exact_generation_semantics(self):
        for c in _both():
            c.put("j", "op", 1, 3, {1: {"v": 1.0}})
            assert c.get("j", "op", 1, 3, exact=True)[0]
            assert not c.get("j", "op", 1, 4, exact=True)[0]
            # presence-implies-validity mode hits whatever generation
            c.put("j", "op", 2, 3, {1: {"v": 2.0}})
            assert c.get("j", "op", 2, 99, exact=False)[0]
            _close(c)

    def test_put_never_downgrades(self):
        for c in _both():
            c.put("j", "op", 1, 5, {1: {"v": 5.0}})
            c.put("j", "op", 1, 4, {1: {"v": 4.0}})  # stale worker
            assert c.get("j", "op", 1, 5, exact=False)[1] == \
                {1: {"v": 5.0}}
            _close(c)

    def test_prime_fold_insert_remove_drop(self):
        for c in _both():
            c.put("j", "op", 1, 1, {10: {"v": 1.0}, 20: {"v": 2.0}})
            c.put("j", "op", 2, 1, {30: {"v": 3.0}})
            c.prime_batch("j", "op", 2, _delta({
                1: ({20: {"v": 9.0}, 40: {"v": 4.0}}, [10], 0),
                2: (None, [], 2),            # drop
                3: ({50: {"v": 5.0}}, [], 1),  # insert_ok
                4: ({60: {"v": 6.0}}, [], 0),  # absent, no insert
            }))
            assert c.get("j", "op", 1, 2, exact=False)[1] == \
                {20: {"v": 9.0}, 40: {"v": 4.0}}
            assert not c.get("j", "op", 2, 2, exact=False)[0]
            assert c.get("j", "op", 3, 2, exact=False)[1] == \
                {50: {"v": 5.0}}
            assert not c.get("j", "op", 4, 2, exact=False)[0]
            _close(c)

    def test_get_many_batch_shapes(self):
        for c in _both():
            for k in range(8):
                c.put("j", "op", k, 1, {k: {"v": float(k)}})
            out = [None] * 12
            misses = []
            hits = c.get_many("j", "op",
                              np.arange(12, dtype=np.int64), 1, out,
                              misses, exact=False)
            assert hits == 8
            assert [int(k) for _i, k in misses] == [8, 9, 10, 11]
            assert out[:8] == [{k: {"v": float(k)}} for k in range(8)]
            _close(c)

    def test_empty_composed_state_hits(self):
        # a key cached with an EMPTY composed dict is a HIT returning
        # {} — distinct from a miss (the key is known to have no state)
        for c in _both():
            c.put("j", "op", 5, 1, {6: {"v": 1.0}})  # schema known
            c.put("j", "op", 9, 1, {})
            hit, got = c.get("j", "op", 9, 1, exact=False)
            assert hit and got == {}
            _close(c)

    def test_non_packable_values_identical(self):
        # join-style list results cannot pack: the native plane routes
        # them through its overflow store with identical semantics
        val = [{"ts": 1, "rid": 2, "x": "obj"}]
        for c in _both():
            c.put("j", "join", 1, 1, val)
            hit, got = c.get("j", "join", 1, 1, exact=False)
            assert hit and got == val
            _close(c)

    def test_invalidate_op_and_job(self):
        for c in _both():
            c.put("a", "op1", 1, 1, {1: {"v": 1.0}})
            c.put("a", "op2", 1, 1, {1: {"v": 2.0}})
            c.put("b", "op1", 1, 1, {1: {"v": 3.0}})
            c.invalidate_op("a", "op1")
            assert not c.get("a", "op1", 1, 1, exact=False)[0]
            assert c.get("a", "op2", 1, 1, exact=False)[0]
            c.invalidate_job("a")
            assert not c.get("a", "op2", 1, 1, exact=False)[0]
            assert c.get("b", "op1", 1, 1, exact=False)[0]
            _close(c)

    def test_drop(self):
        for c in _both():
            c.put("j", "op", 1, 1, {1: {"v": 1.0}})
            c.drop("j", "op", 1)
            assert not c.get("j", "op", 1, 1, exact=False)[0]
            _close(c)

    def test_stats_shape(self):
        for c in _both():
            c.put("j", "op", 1, 1, {1: {"v": 1.0}})
            c.get("j", "op", 1, 1, exact=False)
            c.get("j", "op", 2, 1, exact=False)
            s = c.stats()
            assert s["hot_row_hits"] == 1.0
            assert s["hot_row_misses"] == 1.0
            assert s["hot_row_entries"] == 1.0
            assert 0 < s["hot_row_hit_rate"] < 1
            assert c.hit_rate() == s["hot_row_hit_rate"]
            assert len(c) == 1
            _close(c)


@native
class TestNativeSpecific:
    def test_oversize_composition_stays_a_miss(self):
        from flink_tpu.tenancy.hot_cache_native import ENTRY_CAP

        c = _native()
        big = {i: {"v": float(i)} for i in range(ENTRY_CAP + 3)}
        c.put("j", "op", 1, 1, {0: {"v": 0.0}})  # schema: packable op
        c.put("j", "op", 2, 1, big)
        # oversize rides the overflow store — still served, identically
        hit, got = c.get("j", "op", 2, 1, exact=False)
        assert hit and got == big
        _close(c)

    def test_eviction_under_pressure(self):
        from flink_tpu.tenancy.hot_cache_native import (
            NativeHotRowCache,
        )

        c = NativeHotRowCache(max_entries=64)
        for k in range(1000):
            c.put("j", "op", k, 1, {1: {"v": float(k)}})
        assert len(c) <= 2 * 64  # bounded (pow2 slots, windowed evict)
        assert c.evictions > 0
        _close(c)

    def test_torn_read_falls_to_miss_never_mixed(self):
        # freeze a key's slot stamp ODD (a write frozen mid-flight):
        # the probe must retry, count the torn read, and MISS — never
        # return a half-written row. Unfreeze: it hits again.
        from flink_tpu.native import load_hotcache

        lib = load_hotcache()
        c = _native()
        c.put("j", "op", 7, 1, {1: {"v": 1.0}})
        assert c.get("j", "op", 7, 1, exact=False)[0]
        tbl = c._tables[("j", "op")]
        assert lib.hc_debug_lock_slot(tbl.ptr, 7) == 1
        hit, got = c.get("j", "op", 7, 1, exact=False)
        assert not hit and got is None
        assert c.torn_retries > 0 and c.torn_misses > 0
        assert lib.hc_debug_unlock_slot(tbl.ptr, 7) == 1
        assert c.get("j", "op", 7, 1, exact=False) == \
            (True, {1: {"v": 1.0}})
        _close(c)

    def test_concurrent_prime_probe_never_mixed(self):
        # a writer re-priming one key with generation-consistent rows
        # while a reader hammers probes: every observed value is one of
        # the complete published states, never a mix
        import threading

        c = _native()
        states = [{1: {"a": float(g), "b": float(g)}} for g in range(50)]
        c.put("j", "op", 1, 0, states[0])
        stop = threading.Event()
        bad = []

        def reader():
            while not stop.is_set():
                hit, got = c.get("j", "op", 1, 0, exact=False)
                if hit and got[1]["a"] != got[1]["b"]:
                    bad.append(got)

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        for g in range(1, 50):
            c.prime_batch("j", "op", g, _delta({
                1: ({1: {"a": float(g), "b": float(g)}}, [], 0)}))
        stop.set()
        t.join(timeout=5)
        assert not bad, f"mixed-generation rows observed: {bad[:3]}"
        _close(c)


@native
class TestCrossGenerationFuzz:
    """Randomized interleaved prime/probe/put/drop/retire against a
    plain dict oracle, seeded — native and Python planes both tracked.
    Capacity is large enough that no eviction fires, so all three
    must agree EXACTLY at every probe."""

    def _oracle_prime(self, oracle, kid, gen, ups, rem, insert_ok):
        ent = oracle.get(kid)
        if ent is None and not insert_ok:
            return
        if ent is not None and ent[0] > gen:
            return
        val = dict(ent[1]) if ent is not None else {}
        for ns in rem:
            val.pop(ns, None)
        if ups:
            val.update(ups)
        oracle[kid] = (gen, val)

    def test_fuzz_vs_dict_oracle(self):
        rng = np.random.default_rng(1234)
        planes = _both()
        oracle = {}  # kid -> (gen, {ns: {col: val}})
        gen = 1
        KEYS = 64
        for step in range(1500):
            op = rng.integers(0, 10)
            kid = int(rng.integers(0, KEYS))
            if op < 3:  # put (worker feed), occasionally stale gen
                g = gen - int(rng.integers(0, 3))
                val = {int(ns): {"v": float(rng.random())}
                       for ns in rng.integers(0, 8,
                                              int(rng.integers(0, 4)))}
                for c in planes:
                    c.put("j", "op", kid, g, val)
                ent = oracle.get(kid)
                if ent is None or ent[0] <= g:
                    oracle[kid] = (g, val)
            elif op < 6:  # publish prime (fold) over a few keys
                gen += 1
                batch = {}
                for _ in range(int(rng.integers(1, 5))):
                    k2 = int(rng.integers(0, KEYS))
                    if k2 in batch:
                        continue  # a publish delta has ONE entry/key
                    kind = int(rng.integers(0, 4))
                    if kind == 0:  # drop
                        batch[k2] = (None, [], 2)
                        self._oracle_prime(oracle, k2, gen, None, [],
                                           False)
                        oracle.pop(k2, None)
                        continue
                    ups = {int(ns): {"v": float(rng.random())}
                           for ns in rng.integers(
                               0, 8, int(rng.integers(0, 3)))}
                    rem = [int(r) for r in rng.integers(
                        0, 8, int(rng.integers(0, 2)))]
                    insert_ok = kind == 1
                    batch[k2] = (ups, rem,
                                 1 if insert_ok else 0)
                    self._oracle_prime(oracle, k2, gen, ups, rem,
                                       insert_ok)
                for c in planes:
                    c.prime_batch("j", "op", gen, _delta(batch))
            elif op < 7:  # retire (drop)
                for c in planes:
                    c.drop("j", "op", kid)
                oracle.pop(kid, None)
            else:  # probe a batch, compare all three
                qk = rng.integers(0, KEYS, 16).astype(np.int64)
                want = [oracle.get(int(k), (None, None))[1]
                        for k in qk]
                for c in planes:
                    out = [None] * len(qk)
                    misses = []
                    c.get_many("j", "op", qk, gen, out, misses,
                               exact=False)
                    assert out == want, \
                        f"step {step}: {type(c).__name__} diverged"
                    assert sorted(i for i, _k in misses) == \
                        [i for i, w in enumerate(want) if w is None]
        for c in planes:
            _close(c)


class TestFactory:
    def test_knob_forces_python_plane(self, monkeypatch):
        monkeypatch.setenv("FLINK_TPU_NATIVE_HOTCACHE", "0")
        assert type(make_hot_row_cache(64)) is HotRowCache

    @native
    def test_selects_native_when_available(self, monkeypatch):
        from flink_tpu.tenancy.hot_cache_native import NativeHotRowCache

        monkeypatch.delenv("FLINK_TPU_NATIVE_HOTCACHE", raising=False)
        monkeypatch.delenv("FLINK_TPU_NO_NATIVE", raising=False)
        c = make_hot_row_cache(64)
        assert type(c) is NativeHotRowCache
        _close(c)

    def test_blanket_native_off(self, monkeypatch):
        monkeypatch.setenv("FLINK_TPU_NO_NATIVE", "1")
        assert type(make_hot_row_cache(64)) is HotRowCache
