"""JobGraph chaining + ExecutionGraph expansion (flink_tpu/graph/job_graph.py).

reference parity: StreamingJobGraphGenerator.isChainable/createChain,
DefaultExecutionGraph.attachJobGraph, KeyGroupRangeAssignment,
REST /jobs/:id/plan (JsonPlanGenerator).

Pins: forward one-to-one edges chain; keyed/broadcast/side edges and
fan-out break chains; parallelism mismatches break chains; ExecutionGraph
subtasks partition the key-group space exactly; plan_stages derives the
same split it used to; the REST plan endpoint serves the chained plan.
"""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.graph.job_graph import (
    BROADCAST,
    FORWARD,
    HASH,
    ExecutionGraph,
    build_job_graph,
)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _graph(env):
    return env.get_stream_graph()


def _simple_pipeline(env, sink=None, parallelism=None):
    from flink_tpu.connectors.sinks import CollectSink
    from flink_tpu.connectors.sources import DataGenSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    ds = env.add_source(
        DataGenSource(total_records=100, num_keys=10,
                      events_per_second_of_eventtime=1000),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
    ds = ds.map(lambda b: b, name="m1").map(lambda b: b, name="m2")
    win = ds.key_by("key").window(TumblingEventTimeWindows.of(1000))
    s = win.sum("value")
    if parallelism:
        s.transformation.parallelism = parallelism
    s.sink_to(sink or CollectSink())
    return env


class TestChaining:
    def test_linear_pipeline_chains_into_two_vertices(self):
        env = _simple_pipeline(StreamExecutionEnvironment(Configuration()))
        jg = build_job_graph(_graph(env), default_parallelism=1)
        assert len(jg.vertices) == 2
        assert len(jg.edges) == 1
        assert jg.edges[0].ship == HASH
        assert jg.edges[0].key_field == "key"
        # source + maps chained; keyed agg + sink chained
        names = [v.name for v in jg.vertices]
        assert "m1" in names[0] and "m2" in names[0]
        assert "sink" in names[1]

    def test_parallelism_mismatch_breaks_chain(self):
        env = StreamExecutionEnvironment(Configuration())
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        ds = env.add_source(
            DataGenSource(total_records=10, num_keys=2,
                          events_per_second_of_eventtime=100),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
        m = ds.map(lambda b: b, name="m1")
        m.transformation.parallelism = 4
        m.map(lambda b: b, name="m2").sink_to(CollectSink())
        jg = build_job_graph(_graph(env), default_parallelism=1)
        # source(1) | m1..sink(4): m2/sink INHERIT m1's parallelism and
        # chain with it; the 1->4 boundary redistributes (REBALANCE —
        # one-to-one is impossible across a parallelism change)
        from flink_tpu.graph.job_graph import REBALANCE

        assert len(jg.vertices) == 2
        assert jg.vertices[0].parallelism == 1
        assert jg.vertices[1].parallelism == 4
        assert "m2" in jg.vertices[1].name
        assert [e.ship for e in jg.edges] == [REBALANCE]

    def test_same_key_parallelism_change_reshuffles(self):
        """key_by(k) at parallelism 4 into key_by(k) at parallelism 2:
        the key-group ranges differ, so the edge must be HASH even though
        the key is unchanged."""
        env = StreamExecutionEnvironment(Configuration())
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        ds = env.add_source(
            DataGenSource(total_records=10, num_keys=2,
                          events_per_second_of_eventtime=100),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
        agg = ds.key_by("key").window(
            TumblingEventTimeWindows.of(1000)).sum("value")
        agg.transformation.parallelism = 4
        second = agg.key_by("key").window(
            TumblingEventTimeWindows.of(2000)).sum("sum_value")
        second.transformation.parallelism = 2
        second.sink_to(CollectSink())
        jg = build_job_graph(_graph(env), default_parallelism=1)
        hash_edges = [e for e in jg.edges if e.ship == HASH]
        # source->agg AND agg->second both re-shuffle
        assert len(hash_edges) == 2
        assert all(e.key_field == "key" for e in hash_edges)

    def test_plan_json_shape(self):
        env = _simple_pipeline(StreamExecutionEnvironment(Configuration()))
        plan = build_job_graph(_graph(env), default_parallelism=8).to_json()
        assert {n["id"] for n in plan["nodes"]} == {0, 1}
        keyed = [n for n in plan["nodes"] if n.get("key_field")]
        assert keyed and keyed[0]["parallelism"] == 8
        assert plan["edges"][0]["ship_strategy"] == HASH


class TestExecutionGraph:
    def test_key_groups_partition_exactly(self):
        env = _simple_pipeline(StreamExecutionEnvironment(Configuration()))
        jg = build_job_graph(_graph(env), default_parallelism=4)
        eg = ExecutionGraph(jg, max_parallelism=128)
        keyed = [ev for ev in eg.execution_vertices
                 if ev.key_group_range is not None]
        assert len(keyed) == 4
        covered = []
        for ev in keyed:
            r = ev.key_group_range
            covered.extend(range(r.start, r.end + 1))
        assert sorted(covered) == list(range(128))

    def test_subtask_naming(self):
        env = _simple_pipeline(StreamExecutionEnvironment(Configuration()))
        jg = build_job_graph(_graph(env), default_parallelism=2)
        eg = ExecutionGraph(jg, max_parallelism=16)
        keyed_v = [v for v in jg.vertices if v.key_field][0]
        subs = eg.subtasks_of(keyed_v)
        assert len(subs) == 2
        assert subs[0].name.endswith("(1/2)")


class TestPlanStagesDerivation:
    def test_supported_shape_still_plans(self):
        from flink_tpu.cluster.stage_executor import plan_stages

        env = _simple_pipeline(StreamExecutionEnvironment(Configuration()))
        plan = plan_stages(_graph(env))
        assert plan.key_field == "key"
        assert [t.name for t in plan.pre_chain] == ["m1", "m2"]
        assert plan.keyed_chain[-1].kind == "sink"

    def test_no_keyed_exchange_message_kept(self):
        from flink_tpu.cluster.stage_executor import (
            StagePlanError,
            plan_stages,
        )
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        env = StreamExecutionEnvironment(Configuration())
        env.add_source(
            DataGenSource(total_records=10, num_keys=2,
                          events_per_second_of_eventtime=100),
            WatermarkStrategy.for_bounded_out_of_orderness(0)) \
           .map(lambda b: b).sink_to(CollectSink())
        with pytest.raises(StagePlanError, match="no keyed exchange"):
            plan_stages(_graph(env))


class TestRestPlan:
    def test_plan_endpoint(self):
        import json
        import urllib.request

        from flink_tpu.cluster.minicluster import MiniCluster
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        cluster = MiniCluster(Configuration({"cluster.task-executors": 1}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 64}))
            sink = CollectSink()
            env.add_source(
                DataGenSource(total_records=5000, num_keys=10,
                              events_per_second_of_eventtime=1000),
                WatermarkStrategy.for_bounded_out_of_orderness(0)) \
               .key_by("key") \
               .window(TumblingEventTimeWindows.of(1000)) \
               .sum("value").sink_to(sink)
            client = cluster.submit(env, "plan-job")
            url = (f"http://127.0.0.1:{cluster.rest_port}"
                   f"/jobs/{client.job_id}/plan")
            body = json.loads(urllib.request.urlopen(url).read())
            assert body["job_id"] == client.job_id
            nodes = body["plan"]["nodes"]
            assert any(n.get("key_field") == "key" for n in nodes)
            assert body["plan"]["edges"][0]["ship_strategy"] == "HASH"
            client.wait(timeout=60)
        finally:
            cluster.shutdown()
