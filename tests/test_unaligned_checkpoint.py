"""Unaligned (overtaking) checkpoints under backpressure.

reference: runtime/checkpoint/channel/ChannelStateWriterImpl.java (persisting
overtaken in-flight buffers), runtime/io/network/api/CheckpointBarrier
asUnaligned + CheckpointedInputGate's priority-event path,
ExecutionCheckpointingOptions.ENABLE_UNALIGNED.

TPU re-design under test: the barrier jumps the columnar batch queue
(put_front), the overtaken batches ride the snapshot as channel state, the
keyed subtask snapshots at the FIRST barrier without alignment blocking, and
restore replays channel state through the operator before new input.
"""

import time

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink, Sink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows

from tests.conftest import \
    assert_windows_approx_equal as _assert_windows_equal  # noqa: E501


class SlowCollectSink(Sink):
    """Collects results, sleeping per write — a backpressuring consumer."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s
        self.batches = []

    def write(self, batch):
        time.sleep(self.delay_s)
        self.batches.append(batch)

    def result(self):
        return RecordBatch.concat(self.batches)


def _env(extra):
    conf = {
        "execution.micro-batch.size": 500,
        "execution.stage-parallelism": 1,
        "state.slot-table.capacity": 8192,
        "shuffle.credits-per-channel": 8,
    }
    conf.update(extra or {})
    return StreamExecutionEnvironment(Configuration(conf))


def _pipeline(env, sink, total=20_000, keys=50):
    src = DataGenSource(total_records=total, num_keys=keys,
                        events_per_second_of_eventtime=10_000, seed=11)
    ds = env.from_source(
        src, WatermarkStrategy.for_bounded_out_of_orderness(0))
    # 200 ms windows at 10k events/s of event time and 500-row batches:
    # every ~4th batch closes a window and pays the slow sink's delay,
    # so the exchange backlog (8 credits) holds multiple window fires
    ds.key_by("key").window(
        TumblingEventTimeWindows.of(200)).sum("value").sink_to(sink)


def _results(sink):
    out = {}
    for r in sink.result().to_rows():
        out[(r["key"], r["window_start"], r["window_end"])] = round(
            r["sum_value"], 3)
    return out


def _timed_checkpoints(monkeypatch):
    """Record the wall duration of every stage-executor checkpoint."""
    from flink_tpu.cluster.stage_executor import StageParallelExecutor

    durations = []
    orig = StageParallelExecutor._checkpoint

    def timed(self, *a, **k):
        t0 = time.perf_counter()
        try:
            return orig(self, *a, **k)
        finally:
            durations.append(time.perf_counter() - t0)

    monkeypatch.setattr(StageParallelExecutor, "_checkpoint", timed)
    return durations


class TestUnalignedCompletesUnderBackpressure:
    # slow: this test asserts WALL-CLOCK bounds (unaligned checkpoint
    # < 2 s and < aligned/2) around real time.sleep backpressure — the
    # assertion is inherently load-sensitive and flaked in the tier-1
    # gate since the seed whenever the CI host stalled mid-run. The
    # semantic coverage (results + restore correctness of unaligned
    # mode) lives in the fast tests below; the timing CLAIM needs a
    # quiet machine, so it runs in the slow lane only.
    @pytest.mark.slow
    def test_barrier_overtakes_backlog(self, tmp_path, monkeypatch):
        """With a slow sink and saturated credits, an unaligned checkpoint
        completes in ~one consumer step; an aligned one must wait for the
        whole in-flight backlog to drain first. Documented bound: the
        unaligned checkpoint is independent of the backlog depth."""
        durations = _timed_checkpoints(monkeypatch)
        delay = 0.25
        base = {
            "state.checkpoints.dir": str(tmp_path / "ua"),
            "execution.checkpointing.every-n-source-batches": 10,
            "execution.checkpointing.unaligned": True,
        }
        env = _env(base)
        sink = SlowCollectSink(delay)
        _pipeline(env, sink)
        env.execute("unaligned-backpressure")
        assert durations, "no checkpoint was triggered"
        ua_max = max(durations)

        durations.clear()
        aligned = dict(base)
        aligned["state.checkpoints.dir"] = str(tmp_path / "al")
        aligned["execution.checkpointing.unaligned"] = False
        env2 = _env(aligned)
        sink2 = SlowCollectSink(delay)
        _pipeline(env2, sink2)
        env2.execute("aligned-backpressure")
        assert durations
        al_max = max(durations)

        # the aligned barrier sits behind the credit-deep backlog of
        # window fires; the unaligned one overtakes it. The factor is
        # the point, the absolute bound is the regression guard.
        assert ua_max < 2.0, f"unaligned checkpoint took {ua_max:.2f}s"
        assert ua_max < al_max / 2, (
            f"overtaking gained nothing: unaligned {ua_max:.2f}s vs "
            f"aligned {al_max:.2f}s")

    def test_results_unaffected_by_unaligned_mode(self, tmp_path):
        env = _env({})
        clean = CollectSink()
        _pipeline(env, clean)
        env.execute("clean")
        expected = _results(clean)

        env2 = _env({
            "state.checkpoints.dir": str(tmp_path / "ck"),
            "execution.checkpointing.every-n-source-batches": 7,
            "execution.checkpointing.unaligned": True,
        })
        sink2 = CollectSink()
        _pipeline(env2, sink2)
        env2.execute("with-unaligned-checkpoints")
        _assert_windows_equal(_results(sink2), expected)


class TestUnalignedRestore:
    def test_crash_restore_replays_channel_state(self, tmp_path):
        """Crash after an unaligned checkpoint whose snapshot holds
        in-flight batches; restore must replay them through the operator
        (exactly-once end to end vs a clean run)."""
        ckpt = str(tmp_path / "ckpts")

        env = _env({})
        clean = CollectSink()
        _pipeline(env, clean)
        env.execute("clean")
        expected = _results(clean)

        from tests.test_checkpointing import FailingMap

        conf = {
            "state.checkpoints.dir": ckpt,
            "execution.checkpointing.every-n-source-batches": 7,
            "execution.checkpointing.unaligned": True,
        }
        env2 = _env(conf)
        sink2 = SlowCollectSink(0.05)
        src = DataGenSource(total_records=20_000, num_keys=50,
                            events_per_second_of_eventtime=10_000, seed=11)
        ds = env2.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        ds = ds.map(FailingMap(12_000), name="failmap")
        ds.key_by("key").window(
            TumblingEventTimeWindows.of(200)).sum("value").sink_to(sink2)
        with pytest.raises(RuntimeError, match="injected failure"):
            env2.execute("crashing")
        from flink_tpu.checkpoint.storage import CheckpointStorage

        assert CheckpointStorage(ckpt).latest_checkpoint_id() is not None

        env3 = _env(conf)
        sink3 = CollectSink()
        src = DataGenSource(total_records=20_000, num_keys=50,
                            events_per_second_of_eventtime=10_000, seed=11)
        ds = env3.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        ds = ds.map(lambda b: b, name="failmap")
        ds.key_by("key").window(
            TumblingEventTimeWindows.of(200)).sum("value").sink_to(sink3)
        env3.execute("restored", restore_from=ckpt)

        got = {}
        if sink2.batches:
            got.update(_results(sink2))
        got.update(_results(sink3))
        _assert_windows_equal(got, expected)


class TestTransportPrimitives:
    def test_put_front_overtakes_and_captures(self):
        from flink_tpu.runtime.shuffle_spi import (
            Barrier,
            LocalShuffleService,
        )

        svc = LocalShuffleService()
        writer = svc.create_partition("p", 1, credits_per_channel=4)
        gate = svc.create_gate(["p"], 0)
        b1 = RecordBatch.from_pydict({"x": np.arange(3)})
        b2 = RecordBatch.from_pydict({"x": np.arange(5)})
        writer.emit(0, b1)
        writer.emit(0, b2)
        bar = Barrier(7, unaligned=True)
        writer.broadcast_event(bar)
        ch, first = gate.poll(timeout=1.0)
        assert isinstance(first, Barrier) and first.checkpoint_id == 7
        captured = gate.take_inflight(0, 7)
        assert [len(b) for b in captured] == [3, 5]
        # the overtaken data still flows after the barrier
        _, nxt = gate.poll(timeout=1.0)
        assert len(nxt) == 3
        _, nxt = gate.poll(timeout=1.0)
        assert len(nxt) == 5

    def test_savepoint_barriers_stay_aligned(self):
        from flink_tpu.runtime.shuffle_spi import Barrier

        assert not Barrier(1, savepoint="/sp", unaligned=True).unaligned
