"""Filesystem table connector — SQL write + read of bucketed
exactly-once files.

reference: the filesystem table connector (readable + writable,
partitioned directories, 'format' through the schema seams).
"""

import json
import os

import numpy as np

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.filesystem import FileSource, read_committed_rows
from flink_tpu.connectors.formats import resolve_format
from flink_tpu.connectors.kafka import FakeBroker
from flink_tpu.core.records import RecordBatch
from flink_tpu.table.environment import StreamTableEnvironment


def _seed_topic(topic, n=3000, keys=20):
    broker = FakeBroker.get("default")
    broker.create_topic(topic, 1)
    rng = np.random.default_rng(8)
    ks = rng.integers(0, keys, n).astype(np.int64)
    vs = np.round(rng.random(n), 6)
    ts = np.arange(n, dtype=np.int64) * 4
    broker.append(topic, 0, RecordBatch.from_pydict(
        {"key": ks, "value": vs, "ts": ts}, timestamps=ts))
    return ks, vs, ts


def test_insert_into_filesystem_then_select_back(tmp_path):
    """SQL aggregate -> INSERT INTO a bucketed filesystem table ->
    a second job SELECTs the committed files back."""
    out = str(tmp_path / "warehouse")
    ks, vs, ts = _seed_topic("fs_in")

    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 500}))
    tenv = StreamTableEnvironment(env)
    tenv.execute_sql(
        "CREATE TABLE fs_in (key BIGINT, value DOUBLE, ts BIGINT, "
        "WATERMARK FOR ts AS ts) "
        "WITH ('connector'='kafka', 'topic'='fs_in')")
    tenv.execute_sql(
        "CREATE TABLE warehouse (key BIGINT, window_end BIGINT, "
        "total DOUBLE) "
        f"WITH ('connector'='filesystem', 'path'='{out}', "
        "'format'='json', 'sink.bucket-by'='key')")
    tenv.execute_sql("""
        INSERT INTO warehouse
        SELECT key, window_end, SUM(value) AS total
        FROM TABLE(TUMBLE(TABLE fs_in, DESCRIPTOR(ts),
                          INTERVAL '1' SECOND))
        GROUP BY key, window_start, window_end
    """)

    # bucket directories by key; only committed parts
    assert sorted(os.listdir(out)) == sorted(
        str(k) for k in set(ks.tolist()))
    rows = [json.loads(r) for r in read_committed_rows(out)]

    import collections

    oracle = collections.defaultdict(float)
    for k, v, t in zip(ks.tolist(), vs.tolist(), ts.tolist()):
        oracle[(k, (t // 1000 + 1) * 1000)] += v
    got = {(r["key"], r["window_end"]): r["total"] for r in rows}
    assert set(got) == set(oracle)
    for k in oracle:
        assert abs(got[k] - oracle[k]) < 1e-4  # f32 agg

    # a SECOND job reads the committed files back through SQL
    env2 = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 500}))
    tenv2 = StreamTableEnvironment(env2)
    tenv2.execute_sql(
        "CREATE TABLE warehouse (key BIGINT, window_end BIGINT, "
        "total DOUBLE) "
        f"WITH ('connector'='filesystem', 'path'='{out}', "
        "'format'='json')")
    back = tenv2.execute_sql(
        "SELECT key, SUM(total) AS s FROM warehouse GROUP BY key"
    ).collect()
    per_key = collections.defaultdict(float)
    for (k, _), v in oracle.items():
        per_key[k] += v
    got_back = {r["key"]: r["s"] for r in back}
    assert set(got_back) == set(per_key)
    for k, v in per_key.items():
        assert abs(got_back[k] - v) < 1e-3  # f32 agg, two passes


def test_file_source_restore_survives_directory_growth(tmp_path):
    """The checkpoint carries remaining file PATHS, so files committed
    after the snapshot neither shift the cursor (skips) nor re-emit
    consumed files (duplicates)."""
    from flink_tpu.connectors.filesystem import FileSink

    d = str(tmp_path / "out")
    sink = FileSink(d, ["v"], fmt="json")
    sink.open(0)
    for v in (1, 2):
        sink.write(RecordBatch({"v": np.array([v])}))
        sink.commit(sink.prepare_commit())  # one committed file per v

    deser, _ = resolve_format("json", ["v"], ["BIGINT"])
    src = FileSource(d, deser)
    src.open(0, 1)
    first = src.poll_batch(10)["v"].tolist()
    pos = src.snapshot_position()

    # a new file lands between snapshot and restore
    sink.write(RecordBatch({"v": np.array([99])}))
    sink.commit(sink.prepare_commit())

    src2 = FileSource(d, deser)
    src2.open(0, 1)
    src2.restore_position(pos)
    rest = []
    while (b := src2.poll_batch(10)) is not None:
        rest.extend(b["v"].tolist())
    # exactly the pre-snapshot remainder: no skip, no re-read, and the
    # post-snapshot file is NOT part of this run's split
    assert sorted(first + rest) == [1, 2]


def test_file_source_honors_max_records_and_midfile_restore(tmp_path):
    from flink_tpu.connectors.filesystem import FileSink

    d = str(tmp_path / "out")
    sink = FileSink(d, ["v"], fmt="json")
    sink.open(0)
    sink.write(RecordBatch({"v": np.arange(10)}))
    sink.commit(sink.prepare_commit())
    deser, _ = resolve_format("json", ["v"], ["BIGINT"])
    src = FileSource(d, deser)
    src.open(0, 1)
    assert src.poll_batch(4)["v"].tolist() == [0, 1, 2, 3]
    pos = src.snapshot_position()
    assert pos["row"] == 4
    src2 = FileSource(d, deser)
    src2.open(0, 1)
    src2.restore_position(pos)
    got = []
    while (b := src2.poll_batch(3)) is not None:
        assert len(b) <= 3
        got.extend(b["v"].tolist())
    assert got == [4, 5, 6, 7, 8, 9]


def test_text_framing_rejects_raw_newlines_loudly(tmp_path):
    import pytest

    from flink_tpu.connectors.filesystem import FileSink

    d = str(tmp_path / "out")
    sink = FileSink(d, ["s"], fmt="csv", types=["STRING"])
    sink.open(0)
    with pytest.raises(ValueError, match="raw newline"):
        sink.write(RecordBatch({"s": np.array(["a\nb"], dtype=object)}))


def test_file_source_reads_buckets_and_restores_position(tmp_path):
    from flink_tpu.connectors.filesystem import (
        ColumnBucketAssigner,
        FileSink,
    )

    d = str(tmp_path / "out")
    sink = FileSink(d, ["k", "v"], fmt="json",
                    bucket_assigner=ColumnBucketAssigner("k"))
    sink.open(0)
    sink.write(RecordBatch({"k": np.array([1, 2, 1]),
                            "v": np.array([10.0, 20.0, 30.0])}))
    sink.commit(sink.prepare_commit())

    deser, _ = resolve_format("json", ["k", "v"], ["BIGINT", "DOUBLE"])
    src = FileSource(d, deser)
    src.open(0, 1)
    got = []
    pos = None
    b = src.poll_batch(1 << 16)
    got.extend(zip(b["k"].tolist(), b["v"].tolist()))
    pos = src.snapshot_position()
    # restore mid-scan: a fresh source resumes at the file boundary
    src2 = FileSource(d, deser)
    src2.open(0, 1)
    src2.restore_position(pos)
    while (b := src2.poll_batch(1 << 16)) is not None:
        got.extend(zip(b["k"].tolist(), b["v"].tolist()))
    assert sorted(got) == [(1, 10.0), (1, 30.0), (2, 20.0)]
