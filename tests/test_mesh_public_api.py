"""Mesh-parallel execution through the PUBLIC API (env.execute / MiniCluster).

Round-1 verdict: the mesh engine existed but was unreachable from the
framework API. These tests pin the wiring: ``set_parallelism(N)`` /
``parallelism.default`` on a keyed window op makes ``env.execute()`` run the
MeshWindowEngine over an N-device mesh — including checkpoint/savepoint/
restore across mesh sizes and queryable state.

reference model: ExecutionJobVertex parallel expansion
(executiongraph/Execution.java:572 deploy()) + KeyGroupStreamPartitioner
routing (streaming/runtime/partitioner/KeyGroupStreamPartitioner.java:55),
tested via MiniCluster ITCases (SURVEY.md §4).
"""

import json
import os
import time

import numpy as np
import pytest

from flink_tpu.connectors.sinks import CollectSink, JsonLinesFileSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)


def build_count(env, total=40_000, num_keys=50, sink=None, window=None,
                parallelism=None, source_cls=DataGenSource):
    sink = sink if sink is not None else CollectSink()
    window = window or TumblingEventTimeWindows.of(1000)
    s = (env.add_source(source_cls(total_records=total, num_keys=num_keys,
                                   events_per_second_of_eventtime=20_000),
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
         .key_by("key").window(window).count())
    if parallelism is not None:
        s.set_parallelism(parallelism)
    s.sink_to(sink)
    return sink


def counts(rows):
    return {(int(r["key"]), int(r["window_start"])): int(r["count"])
            for r in rows}


def sliding_counts(rows):
    out = {}
    for r in rows:
        k = (int(r["key"]), int(r["window_start"]), int(r["window_end"]))
        assert k not in out
        out[k] = int(r["count"])
    return out


class TestPublicMeshExecution:
    def test_set_parallelism_runs_mesh_engine(self):
        """Explicit .set_parallelism(8) on the window op: the operator must
        actually open a MeshWindowEngine, and results must match the
        single-device run exactly."""
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.runtime.operators import WindowAggOperator

        # engine selection is observable through open()
        opened = {}
        orig_open = WindowAggOperator.open

        def spy_open(self, ctx):
            orig_open(self, ctx)
            opened[ctx.parallelism] = type(self.windower).__name__

        WindowAggOperator.open = spy_open
        try:
            env1 = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 2048}))
            s1 = build_count(env1)
            env1.execute()

            env8 = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 2048}))
            s8 = build_count(env8, parallelism=8)
            env8.execute()
        finally:
            WindowAggOperator.open = orig_open
        assert opened[1] in ("SliceSharedWindower", "PaneWindower")
        assert opened[8] == "MeshWindowEngine"
        assert counts(s1.rows()) == counts(s8.rows())

    def test_default_parallelism_config_applies_to_keyed_ops(self):
        """parallelism.default in the config reaches keyed window operators
        without any per-op call (reference: env default parallelism)."""
        env1 = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 2048}))
        s1 = build_count(env1, window=SlidingEventTimeWindows.of(2000, 500))
        env1.execute()

        env8 = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 2048,
             "parallelism.default": 8}))
        s8 = build_count(env8, window=SlidingEventTimeWindows.of(2000, 500))
        env8.execute()
        assert sliding_counts(s1.rows()) == sliding_counts(s8.rows())

    def test_nexmark_q5_through_public_api_on_mesh(self):
        """The headline query end-to-end on the 8-device mesh: results must
        equal the single-device run row for row."""
        from flink_tpu.benchmarks.nexmark import BidSource, build_q5

        def run(par):
            cfg = {"execution.micro-batch.size": 1 << 14}
            if par > 1:
                cfg["parallelism.default"] = par
            env = StreamExecutionEnvironment(Configuration(cfg))
            sink = CollectSink()
            src = BidSource(total_records=150_000, num_auctions=3_000,
                            events_per_second_of_eventtime=100_000)
            build_q5(env, src, size_ms=10_000, slide_ms=2_000).sink_to(sink)
            env.execute()
            return sorted(sorted(r.items()) for r in sink.rows())

        assert run(1) == run(8)


class TestMeshCheckpointRestore:
    def test_mesh_failover_exactly_once(self, tmp_path):
        """Fault mid-job on a parallel window op, restart from an
        INCREMENTAL checkpoint: committed output holds every window exactly
        once (the mesh engine's delta snapshots + restore under failover)."""
        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
        from flink_tpu.connectors.two_phase import ExactlyOnceFileSink

        out = str(tmp_path / "out")
        ck = str(tmp_path / "ck")
        flag = str(tmp_path / "crashed.flag")
        total = 20_000

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2,
            "execution.checkpointing.incremental": True,
            "restart-strategy.max-attempts": 3,
            "restart-strategy.delay-ms": 10,
            "parallelism.default": 4,
        }))

        def poison_once(b, flag=flag):
            ts = b.timestamps
            if len(ts) and ts.max() > 900 and not os.path.exists(flag):
                open(flag, "w").write("x")
                raise RuntimeError("injected fault")
            return b

        (env.add_source(DataGenSource(total_records=total, num_keys=10,
                                      events_per_second_of_eventtime=10_000),
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
            .map(poison_once, name="poison")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(500))
            .count()
            .sink_to(ExactlyOnceFileSink(out)))

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            client = cluster.submit(env, "mesh-2pc-job")
            st = client.wait(timeout=120)
            assert st["status"] == FINISHED
            assert st["attempt"] >= 1  # the fault really fired
        finally:
            cluster.shutdown()
        rows = ExactlyOnceFileSink.read_committed_rows(out)
        per_window = {}
        for r in rows:
            k = (int(r["key"]), int(r["window_start"]))
            assert k not in per_window, f"duplicate committed window {k}"
            per_window[k] = int(r["count"])
        assert sum(per_window.values()) == total

    def test_savepoint_rescales_across_mesh_sizes(self, tmp_path):
        """Savepoint taken at parallelism 4 resumes at parallelism 8 AND at
        parallelism 1 (single-device engine) — the logical key-group
        snapshot format is engine- and mesh-size-independent
        (reference: rescale via key-group range reassignment)."""
        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster

        class SlowDataGen(DataGenSource):
            def poll_batch(self, max_records):
                b = super().poll_batch(max_records)
                if b is not None:
                    time.sleep(0.01)
                return b

        total = 20_000
        # oracle
        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        oracle_sink = build_count(env, total=total, num_keys=20)
        env.execute()
        oracle = counts(oracle_sink.rows())

        sp = str(tmp_path / "sp")
        out1 = str(tmp_path / "part1.jsonl")
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env1 = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512,
                 "parallelism.default": 4}))
            build_count(env1, total=total, num_keys=20,
                        sink=JsonLinesFileSink(out1),
                        source_cls=SlowDataGen)
            client = cluster.submit(env1, "rescale-job")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    client.stop_with_savepoint(sp)
                    break
                except RuntimeError:
                    time.sleep(0.02)
            assert client.wait(timeout=60)["status"] == FINISHED
        finally:
            cluster.shutdown()
        with open(out1) as f:
            part1 = counts([json.loads(l) for l in f if l.strip()])
        assert len(part1) < len(oracle)  # genuinely stopped mid-flight

        for resume_par in (8, 1):
            env2 = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512,
                 "parallelism.default": resume_par}))
            sink2 = build_count(env2, total=total, num_keys=20,
                                source_cls=SlowDataGen)
            env2.execute(f"resume-{resume_par}", restore_from=sp)
            part2 = counts(sink2.rows())
            assert not (set(part1) & set(part2))
            assert {**part1, **part2} == oracle


class TestMeshQueryableState:
    def test_query_windows_matches_single_device(self):
        """Point lookups against the mesh engine compose the same window
        values as the single-device engine."""
        import jax

        from flink_tpu.core.records import (KEY_ID_FIELD, TIMESTAMP_FIELD,
            RecordBatch)
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.state.keygroups import hash_keys_to_i64
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.windower import SliceSharedWindower

        assigner = SlidingEventTimeWindows.of(2000, 500)
        rng = np.random.default_rng(7)
        n = 5_000
        keys = rng.integers(0, 40, n)
        batch = RecordBatch.from_pydict({
            "key": keys,
            "v": rng.random(n).astype(np.float32),
            TIMESTAMP_FIELD: rng.integers(0, 4000, n),
        }).with_column(KEY_ID_FIELD, hash_keys_to_i64(keys))

        single = SliceSharedWindower(assigner, SumAggregate("v"),
                                     capacity=1 << 12)
        mesh_eng = MeshWindowEngine(assigner, SumAggregate("v"),
                                    make_mesh(8),
                                    capacity_per_shard=1 << 12)
        single.process_batch(batch)
        mesh_eng.process_batch(batch)
        for key in (0, 7, 39):
            kid = int(hash_keys_to_i64(np.asarray([key]))[0])
            a = single.query_windows(kid)
            b = mesh_eng.query_windows(kid)
            assert set(a) == set(b) and len(a) > 0
            for w in a:
                np.testing.assert_allclose(a[w]["sum_v"], b[w]["sum_v"], rtol=1e-5)

    def test_query_running_parallel_job(self):
        """Queryable state through the full public path against a running
        mesh-parallel job (reference: flink-queryable-state client flow)."""
        from flink_tpu.cluster.minicluster import MiniCluster
        from flink_tpu.cluster.queryable_state import QueryableStateClient

        class SlowDataGen(DataGenSource):
            def poll_batch(self, max_records):
                b = super().poll_batch(max_records)
                if b is not None:
                    time.sleep(0.005)
                return b

        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 256,
             "parallelism.default": 4}))
        build_count(env, total=100_000, num_keys=8,
                    window=TumblingEventTimeWindows.of(10 ** 9),
                    source_cls=SlowDataGen)
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            client = cluster.submit(env, "query-job")
            qs = QueryableStateClient(cluster)
            deadline = time.monotonic() + 20
            result = None
            while time.monotonic() < deadline:
                try:
                    result = qs.get_state(client.job_id,
                                          "window_agg(CountAggregate)", 3)
                    if result:
                        break
                except RuntimeError:
                    pass
                time.sleep(0.05)
            assert result, "no queryable result while job was running"
            (window_end, cols), = result.items()
            assert cols["count"] > 0
            client.cancel()
        finally:
            cluster.shutdown()


class TestMeshDeltaSnapshots:
    def test_mesh_delta_chain_equals_full(self):
        """full + N deltas materializes to the same logical rows as a
        straight full snapshot (the mesh form of the SlotTable delta
        contract), and restores into BOTH engines."""
        from flink_tpu.checkpoint.storage import apply_table_delta
        from flink_tpu.core.records import (KEY_ID_FIELD, TIMESTAMP_FIELD,
            RecordBatch)
        from flink_tpu.parallel.mesh import make_mesh
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.state.keygroups import hash_keys_to_i64
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.windower import SliceSharedWindower

        assigner = TumblingEventTimeWindows.of(1000)
        rng = np.random.default_rng(11)

        def make_batch(lo, hi):
            n = 2_000
            keys = rng.integers(0, 30, n)
            return RecordBatch.from_pydict({
                "key": keys,
                "v": rng.random(n).astype(np.float32),
                TIMESTAMP_FIELD: rng.integers(lo, hi, n),
            }).with_column(KEY_ID_FIELD, hash_keys_to_i64(keys))

        eng = MeshWindowEngine(assigner, SumAggregate("v"), make_mesh(8),
                               capacity_per_shard=1 << 12)
        eng.process_batch(make_batch(0, 3000))
        base = eng.snapshot()["table"]
        acc = dict(base)
        for step in range(3):
            eng.process_batch(make_batch(step * 1000, step * 1000 + 4000))
            # fire + free some windows so tombstones appear in the delta
            eng.on_watermark(step * 1000)
            delta = eng.snapshot(mode="delta")["table"]
            assert bool(delta["__delta__"])
            acc = apply_table_delta(acc, delta)
        full = eng.snapshot()["table"]

        def rows(t):
            return {(int(k), int(n)): float(v) for k, n, v in
                    zip(t["key_id"], t["namespace"], t["leaf_0"])}

        assert rows(acc) == rows(full)

        # the materialized chain restores into the single-device engine too
        book_meta = {k: v for k, v in eng.snapshot().items()
                     if k != "table"}
        single = SliceSharedWindower(assigner, SumAggregate("v"),
                                     capacity=1 << 12)
        single.restore({"table": acc, **book_meta})
        mesh2 = MeshWindowEngine(assigner, SumAggregate("v"), make_mesh(4),
                                 capacity_per_shard=1 << 12)
        mesh2.restore({"table": acc, **book_meta})
        for key in range(30):
            kid = int(hash_keys_to_i64(np.asarray([key]))[0])
            a = single.query_windows(kid)
            b = mesh2.query_windows(kid)
            assert set(a) == set(b)
            for w in a:
                np.testing.assert_allclose(a[w]["sum_v"], b[w]["sum_v"], rtol=1e-5)


class TestPublicMeshSpill:
    """state.slot-table.max-device-slots at parallelism 8: the per-shard
    budget forces eviction to the spill tier; results must equal the
    unbounded run (VERDICT r2 item 2 — state capacity independent of
    parallelism, reference: RocksDBKeyedStateBackend.java)."""

    def test_budgeted_mesh_equals_unbounded(self, tmp_path):
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.runtime.operators import WindowAggOperator

        window = SlidingEventTimeWindows.of(5000, 1000)

        def run(conf_extra):
            conf = {"execution.micro-batch.size": 4096,
                    "parallelism.default": 8}
            conf.update(conf_extra)
            env = StreamExecutionEnvironment(Configuration(conf))
            sink = build_count(env, total=60_000, num_keys=4000,
                               window=window)
            env.execute()
            return sink

        ref = run({})
        engines = []
        orig_open = WindowAggOperator.open

        def spy_open(self, ctx):
            orig_open(self, ctx)
            engines.append(self.windower)

        WindowAggOperator.open = spy_open
        try:
            got = run({"state.slot-table.max-device-slots": 1024,
                       "state.spill.dir": str(tmp_path / "spill")})
        finally:
            WindowAggOperator.open = orig_open
        assert engines and isinstance(engines[0], MeshWindowEngine)
        assert engines[0].max_device_slots == 1024
        d_ref = sliding_counts(ref.rows())
        d_got = sliding_counts(got.rows())
        assert d_ref == d_got and len(d_ref) > 0
        # the budget was binding: no shard index ever exceeded it
        for idx in engines[0].indexes:
            assert idx.capacity <= 1024
