"""Control-plane tests: RPC backbone, MiniCluster lifecycle, failover, REST.

reference test model: MiniCluster-based ITCases + recovery tests
(flink-tests/.../recovery/, SURVEY.md §4 tier 3) — fault injection by
throwing in UDFs and killing TaskExecutors.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu.cluster.minicluster import (
    FAILED,
    FINISHED,
    MiniCluster,
)
from flink_tpu.cluster.restart_strategies import (
    ExponentialDelayRestartStrategy,
    FailureRateRestartStrategy,
    FixedDelayRestartStrategy,
)
from flink_tpu.cluster.rpc import RpcEndpoint, RpcException, RpcService
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


# ------------------------------------------------------------------ RPC


class EchoEndpoint(RpcEndpoint):
    def __init__(self):
        super().__init__("echo")
        self.calls = 0

    def echo(self, x):
        self.validate_main_thread()
        self.calls += 1
        return x

    def boom(self):
        raise ValueError("intentional")


class TestRpc:
    def test_roundtrip_and_main_thread(self):
        svc = RpcService()
        try:
            svc.register(EchoEndpoint())
            gw = svc.self_gateway("echo")
            assert gw.echo({"a": [1, 2, 3]}) == {"a": [1, 2, 3]}
        finally:
            svc.stop()

    def test_exception_marshalling(self):
        svc = RpcService()
        try:
            svc.register(EchoEndpoint())
            gw = svc.self_gateway("echo")
            with pytest.raises(ValueError, match="intentional"):
                gw.boom()
            with pytest.raises(RpcException):
                gw.no_such_method()
        finally:
            svc.stop()

    def test_fencing_token(self):
        svc = RpcService()
        try:
            ep = EchoEndpoint()
            ep.fencing_token = 42
            svc.register(ep)
            good = svc.self_gateway("echo", fencing_token=42)
            assert good.echo(1) == 1
            bad = svc.self_gateway("echo", fencing_token=7)
            with pytest.raises(Exception, match="fencing"):
                bad.echo(1)
        finally:
            svc.stop()


# ------------------------------------------------------- restart strategies


class TestRestartStrategies:
    def test_fixed_delay(self):
        s = FixedDelayRestartStrategy(max_attempts=2, delay_ms=5)
        assert s.can_restart()
        s.notify_failure()
        assert s.can_restart()
        s.notify_failure()
        assert not s.can_restart()

    def test_exponential(self):
        s = ExponentialDelayRestartStrategy(initial_ms=10, max_attempts=5)
        s.notify_failure()
        b1 = s.backoff_ms()
        s.notify_failure()
        assert s.backoff_ms() > b1

    def test_failure_rate(self):
        s = FailureRateRestartStrategy(max_failures=2, interval_ms=60_000)
        s.notify_failure()
        assert s.can_restart()
        s.notify_failure()
        assert not s.can_restart()


# ------------------------------------------------------------ MiniCluster


def _pipeline(env, sink, fail_at=None):
    rows = [{"k": i % 5, "v": 1, "ts": i * 10} for i in range(5000)]
    ds = env.from_collection(rows, timestamp_field="ts")
    if fail_at is not None:
        state = {"seen": 0}

        def poison(batch):
            state["seen"] += len(batch)
            if state["seen"] > fail_at:
                raise RuntimeError("injected fault")
            return batch

        ds = ds.map(poison, name="failmap")
    else:
        ds = ds.map(lambda b: b, name="failmap")
    ds.key_by("k").window(TumblingEventTimeWindows.of(1000)) \
        .sum("v").sink_to(sink)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(Configuration({
        "cluster.task-executors": 2,
        "heartbeat.interval-ms": 100,
    }))
    yield c
    c.shutdown()


class TestMiniCluster:
    def test_submit_and_finish(self, cluster):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512}))
        sink = CollectSink()
        _pipeline(env, sink)
        client = cluster.submit(env, "happy-job")
        st = client.wait(timeout=60)
        assert st["status"] == FINISHED
        result = client.result()
        assert result.metrics["records_emitted_by_sources"] == 5000
        assert result.metric_snapshot  # wire-safe registry snapshot

    def test_udf_failure_exhausts_restarts(self, cluster):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "restart-strategy.max-attempts": 2,
            "restart-strategy.delay-ms": 10,
        }))
        sink = CollectSink()
        _pipeline(env, sink, fail_at=100)
        client = cluster.submit(env, "doomed-job")
        st = client.wait(timeout=60)
        assert st["status"] == FAILED
        assert st["attempt"] == 1  # original + 1 restart = 2 attempts
        assert "injected fault" in st["error"]

    def test_failover_restores_from_checkpoint(self, cluster, tmp_path):
        """Fault once, restart, recover from checkpoint, finish with
        exactly-once totals (reference: recovery ITCases)."""
        ckpt = str(tmp_path / "ckpt")
        rows_total = 5000
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ckpt,
            "execution.checkpointing.every-n-source-batches": 2,
            "restart-strategy.max-attempts": 3,
            "restart-strategy.delay-ms": 10,
        }))
        # output must go through the filesystem: the graph (and any sink in
        # it) is serialized to the worker, so a local CollectSink object
        # would never see data
        from flink_tpu.connectors.sinks import JsonLinesFileSink

        out_path = str(tmp_path / "out.jsonl")
        sink = JsonLinesFileSink(out_path)
        # graph closures are re-deserialized per deployment attempt, so the
        # crash-once flag must live outside the process image (a file), like
        # the reference's e2e fault-injection scripts
        flag = str(tmp_path / "crashed.flag")

        rows = [{"k": i % 5, "v": 1, "ts": i * 10}
                for i in range(rows_total)]
        ds = env.from_collection(rows, timestamp_field="ts")

        def poison_once(batch, flag=flag):
            import os

            if not os.path.exists(flag) and int(batch.timestamps.max()) > 15_000:
                with open(flag, "w") as f:
                    f.write("x")
                raise RuntimeError("crash once")
            return batch

        ds.map(poison_once, name="failmap") \
            .key_by("k").window(TumblingEventTimeWindows.of(1000)) \
            .sum("v").sink_to(sink)
        client = cluster.submit(env, "phoenix-job")
        st = client.wait(timeout=120)
        assert st["status"] == FINISHED
        assert st["attempt"] >= 1
        rows_out = JsonLinesFileSink.read_rows(out_path)
        assert rows_out
        # exactly-once state: summed counts across windows equal the row
        # total (restored from checkpoint, no double counting); the
        # at-least-once file sink may hold the pre-crash attempt's
        # emissions -> dedupe per (key, window), last wins
        seen = {}
        for r in rows_out:
            seen[(r["k"], r["window_start"])] = r["sum_v"]
        assert sum(seen.values()) == rows_total

    def test_kill_task_executor_fails_over(self, cluster):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 64,
            "restart-strategy.max-attempts": 3,
            "restart-strategy.delay-ms": 10,
            "heartbeat.timeout-ms": 1000,
        }))
        sink = CollectSink()
        rows = [{"k": i % 5, "v": 1, "ts": i * 10} for i in range(200_000)]
        env.from_collection(rows, timestamp_field="ts") \
            .key_by("k").window(TumblingEventTimeWindows.of(1000)) \
            .sum("v").sink_to(sink)
        client = cluster.submit(env, "survivor-job")
        # wait until attempt 0 is actually running on some executor
        deadline = time.time() + 30
        victim = None
        exec_id = f"{client.job_id}-0"
        while time.time() < deadline and victim is None:
            for te in cluster.executors:
                if te.task_status(exec_id)["status"] == "RUNNING":
                    victim = te.endpoint_id
                    break
            time.sleep(0.02)
        if victim is not None:
            cluster.kill_task_executor(victim)
        st = client.wait(timeout=120)
        assert st["status"] == FINISHED

    def test_rest_endpoints(self, cluster):
        port = cluster.rest_port
        assert port

        def get(path):
            return json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5).read())

        overview = get("/overview")
        assert overview["taskexecutors"] >= 1
        jobs = get("/jobs")["jobs"]
        assert jobs, "previous tests should have left jobs"
        jid = jobs[0]["job_id"]
        detail = get(f"/jobs/{jid}")
        assert detail["status"]
        metrics = get(f"/jobs/{jid}/metrics")
        assert "metrics" in metrics
        execs = get("/taskexecutors")["executors"]
        assert execs


# ------------------------------------- restart-seeded slot accounting


class TestSeededSlotAccounting:
    """A surviving worker's occupied slots after a JobManager restart
    (reference: TaskExecutor registration carries a SlotReport the RM
    seeds its accounting from)."""

    def _rm(self):
        from flink_tpu.cluster.minicluster import ResourceManagerEndpoint

        return ResourceManagerEndpoint()

    def test_fresh_registration_seeds_occupied_slots(self):
        rm = self._rm()
        # JM restart: empty registry; worker reports 1 orphan on 2 slots
        rm.register_task_executor("te-1", "addr:1", 2, running_tasks=1)
        assert rm.executor_registry()["te-1"]["allocated"] == 1
        # only the one genuinely free slot is offered
        assert rm.request_slot() is not None
        assert rm.request_slot() is None

    def test_keepalive_reregistration_does_not_reseed(self):
        rm = self._rm()
        rm.register_task_executor("te-1", "addr:1", 2, running_tasks=1)
        assert rm.request_slot() is not None
        # keepalive now reports 2 running (orphan + the new task); the
        # re-registration must keep allocated=1 + seeded=1, not add more
        rm.register_task_executor("te-1", "addr:1", 2, running_tasks=2)
        assert rm.executor_registry()["te-1"]["allocated"] == 2
        assert rm.request_slot() is None

    def test_seed_drains_as_orphans_finish(self):
        rm = self._rm()
        rm.register_task_executor("te-1", "addr:1", 2, running_tasks=1)
        assert rm.request_slot() is not None  # allocated=1, seeded=1
        # within the grace window after an allocation the report may not
        # include the promised task yet — reconciliation must not drain
        rm.heartbeat_from("te-1", running_tasks=1)
        assert rm.executor_registry()["te-1"]["allocated"] == 2
        # past the grace window: report says 1 running and 1 is promised,
        # so the orphan is gone -> seed drains
        rm._executors["te-1"]["alloc_times"] = []  # promise aged out
        rm.heartbeat_from("te-1", running_tasks=1)
        assert rm.executor_registry()["te-1"]["allocated"] == 1
        rm.release_slot("te-1")
        assert rm.executor_registry()["te-1"]["allocated"] == 0
        # all capacity available again — no leak
        assert rm.request_slot() is not None
        assert rm.request_slot() is not None
        assert rm.request_slot() is None

    def test_seed_drains_under_steady_allocation_churn(self):
        """Reconciliation credits only promises YOUNGER than the grace
        window instead of suspending outright — a stale orphan seed
        drains even while allocations keep arriving (< grace apart)."""
        rm = self._rm()
        rm.register_task_executor("te-1", "addr:1", 8, running_tasks=3)
        assert rm.request_slot() is not None  # allocated=1
        assert rm.request_slot() is not None  # allocated=2 (both recent)
        # all 3 orphans finished; both fresh promises already running:
        # report = 2. Old behavior: reconciliation suspended (last alloc
        # is recent) -> seed stuck at 3. New: seed <= 2 + 2 - 2 = 2.
        rm.heartbeat_from("te-1", running_tasks=2)
        assert rm.executor_registry()["te-1"]["allocated"] == 2 + 2
        # promises age out of the grace window -> full drain
        rm._executors["te-1"]["alloc_times"] = []
        rm.heartbeat_from("te-1", running_tasks=2)
        assert rm.executor_registry()["te-1"]["allocated"] == 2

    def test_seed_never_grows_from_heartbeat(self):
        rm = self._rm()
        rm.register_task_executor("te-1", "addr:1", 4, running_tasks=1)
        rm._executors["te-1"]["alloc_times"] = []  # promise aged out
        rm.heartbeat_from("te-1", running_tasks=0)  # orphan finished
        assert rm.executor_registry()["te-1"]["allocated"] == 0
        rm.heartbeat_from("te-1", running_tasks=3)  # later load says 3
        # seeded stays 0: only registration seeds, heartbeats only drain
        assert rm.executor_registry()["te-1"]["allocated"] == 0
