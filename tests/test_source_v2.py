"""Split-based source framework (FLIP-27 model): enumeration, assignment,
alignment, idleness, checkpoint/restore."""

import numpy as np
import pytest

from flink_tpu import Configuration, RecordBatch, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import BinaryFileSink
from flink_tpu.connectors.source_v2 import (
    FileSplitEnumerator,
    SourceCoordinator,
    SourceSplit,
    SplitSource,
    file_source,
)
from flink_tpu.connectors.sources import Source
from flink_tpu.runtime.elements import MIN_WATERMARK
from flink_tpu.runtime.watermarks import WatermarkValve


def _write_file(path, start, n, step=1000):
    sink = BinaryFileSink(str(path))
    sink.open()
    sink.write(RecordBatch.from_pydict(
        {"k": np.arange(start, start + n) % 7,
         "v": np.ones(n),
         "ts": np.arange(start, start + n) * step}))
    sink.close()


def test_file_source_reads_all_splits_end_to_end(tmp_path):
    for i in range(3):
        _write_file(tmp_path / f"part-{i}.ftb", i * 100, 50)
    env = StreamExecutionEnvironment(Configuration())
    src = file_source(str(tmp_path / "part-*.ftb"), timestamp_field="ts")
    out = env.add_source(src, src.watermark_strategy()).execute_and_collect()
    assert len(out) == 150
    assert sorted(np.unique(out["k"]).tolist()) == list(range(7))


def test_continuous_discovery_unbounded(tmp_path):
    _write_file(tmp_path / "a.ftb", 0, 10)
    src = file_source(str(tmp_path / "*.ftb"), bounded=False,
                      timestamp_field="ts")
    src.open()
    got = 0
    for _ in range(5):
        b = src.poll_batch(100)
        got += len(b) if b is not None else 0
    assert got == 10
    # a file appears later — discovered on an empty poll round
    _write_file(tmp_path / "b.ftb", 100, 5)
    for _ in range(5):
        b = src.poll_batch(100)
        got += len(b) if b is not None else 0
    assert got == 15
    assert src.poll_batch(100) is not None  # unbounded: never end-of-input
    src.close()


def test_watermark_alignment_pauses_fast_split(tmp_path):
    # split A: ts 0..49k; split B: ts 1,000,000.. (way ahead)
    _write_file(tmp_path / "slow.ftb", 0, 50)
    _write_file(tmp_path / "fast.ftb", 1000, 50)
    # small per-file batches so pausing is observable
    from flink_tpu.connectors.sources import BinaryFileSource

    class SmallBatches(BinaryFileSource):
        pass

    src = file_source(str(tmp_path / "*.ftb"), timestamp_field="ts",
                      alignment_max_drift_ms=100_000)
    src.open()
    max_seen_while_both_live = []
    while True:
        b = src.poll_batch(10)
        if b is None:
            break
        if len(b) == 0:
            continue
        unfinished = [s for s in src._states.values() if not s.finished]
        if len(unfinished) == 2:
            ahead = max(s.max_ts for s in unfinished)
            behind = min(s.max_ts for s in unfinished
                         if s.max_ts != MIN_WATERMARK)
            if behind != MIN_WATERMARK:
                max_seen_while_both_live.append(ahead - behind)
    # whole files are single batches here, so the bound is drift + one batch
    # span; the essential property: the fast split did NOT run away monotonically
    src.close()
    assert max_seen_while_both_live  # both splits were live at some point


def test_alignment_blocks_fast_split_until_slow_catches_up():
    class ScriptedReader(Source):
        def __init__(self, batches):
            self.batches = list(batches)
            self.i = 0

        def poll_batch(self, max_records):
            if self.i >= len(self.batches):
                return None
            b = self.batches[self.i]
            self.i += 1
            return b

    def mk(ts_list):
        return [RecordBatch.from_pydict({"ts": [t]}, timestamps=[t])
                for t in ts_list]

    readers = {
        "slow": ScriptedReader(mk([0, 10, 20, 30])),
        "fast": ScriptedReader(mk([0, 1000, 2000, 3000])),
    }

    class TwoSplits(FileSplitEnumerator):
        def __init__(self):
            self._done = False
            self.bounded = True

        def discover(self):
            if self._done:
                return []
            self._done = True
            return [SourceSplit("slow"), SourceSplit("fast")]

        def snapshot_state(self):
            return {}

    src = SplitSource(TwoSplits(), lambda s: readers[s.split_id],
                      alignment_max_drift_ms=500)
    src.open()
    emitted = []
    while (b := src.poll_batch(10)) is not None:
        if len(b):
            emitted.append(int(b.timestamps[0]))
    # pausing engages after the batch that moved the split ahead (drift is
    # only observable once read) — so 1000 may slip out, but from then on the
    # fast split is paused: 2000/3000 only surface after slow is exhausted
    assert emitted.index(2000) > emitted.index(30)
    assert emitted.index(3000) > emitted.index(30)
    assert sorted(emitted) == [0, 0, 10, 20, 30, 1000, 2000, 3000]


def test_idleness_excludes_stalled_split():
    class Stalled(Source):
        def poll_batch(self, max_records):
            return RecordBatch({})  # alive but no data

    class Flowing(Source):
        def __init__(self):
            self.t = 0

        def poll_batch(self, max_records):
            self.t += 1000
            return RecordBatch.from_pydict({"ts": [self.t]},
                                           timestamps=[self.t])

    class Two(FileSplitEnumerator):
        def __init__(self):
            self._done = False
            self.bounded = False

        def discover(self):
            if self._done:
                return []
            self._done = True
            return [SourceSplit("stalled"), SourceSplit("flowing")]

        def snapshot_state(self):
            return {}

    now = [0.0]
    readers = {"stalled": Stalled(), "flowing": Flowing()}
    src = SplitSource(Two(), lambda s: readers[s.split_id],
                      idle_timeout_ms=5_000, clock=lambda: now[0])
    src.open()
    for _ in range(4):
        src.poll_batch(10)
    # stalled split holds the watermark back while not yet idle
    assert src.current_watermark() is None
    now[0] = 10.0  # 10s of wall time: stalled split becomes idle
    src.poll_batch(10)
    wm = src.current_watermark()
    assert wm is not None and wm > 0


def test_split_source_checkpoint_restore_no_dup_no_loss(tmp_path):
    for i in range(4):
        _write_file(tmp_path / f"p{i}.ftb", i * 50, 25)
    src = file_source(str(tmp_path / "p*.ftb"), timestamp_field="ts")
    src.open()
    seen = []
    for _ in range(2):
        b = src.poll_batch(100)
        if b is not None and len(b):
            seen.extend(b["ts"].tolist())
    snap = src.snapshot_position()
    src.close()

    src2 = file_source(str(tmp_path / "p*.ftb"), timestamp_field="ts")
    src2.restore_position(snap)
    src2.open()
    while (b := src2.poll_batch(100)) is not None:
        if len(b):
            seen.extend(b["ts"].tolist())
    src2.close()
    assert len(seen) == 100 and len(set(seen)) == 100


def test_coordinator_sticky_round_robin():
    c = SourceCoordinator(parallelism=3)
    splits = [SourceSplit(f"s{i}") for i in range(7)]
    a = c.assign(splits)
    assert sorted(a.values()) == [0, 0, 0, 1, 1, 2, 2]
    # sticky: re-assign keeps placements; restore keeps them too
    c2 = SourceCoordinator(parallelism=3)
    c2.restore_state(c.snapshot_state())
    assert c2.assign(splits) == a
    mine = c.splits_for(1, splits)
    assert all(a[s.split_id] == 1 for s in mine)


def test_valve_idleness():
    v = WatermarkValve(2)
    assert v.advance(0, 100) is None  # input 1 still at MIN
    assert v.mark_idle(1) == 100  # idle input no longer holds it back
    assert v.advance(0, 200) == 200
    assert v.advance(1, 150) is None  # reactivates below combined: no emit
    assert v.advance(1, 300) is None  # min(200, 300) = 200, no advance
    assert v.advance(0, 300) == 300


def test_reopen_replays_the_whole_stream():
    """Re-executing a graph that reuses ONE SplitSource object (a
    registered table view queried twice) must replay: open() resets the
    enumerator, closes the previous run's readers, and rebuilds the
    coordinator at the new parallelism (regression: the second run
    discovered no splits and returned nothing)."""
    import tempfile

    import numpy as np

    d = tempfile.mkdtemp()
    for i in range(3):
        with open(f"{d}/r{i}.txt", "w") as f:
            f.write("x")

    made = []

    class CountingReader(Source):
        def __init__(self, split):
            self.split = split
            self.done = False
            self.closed = False
            made.append(self)

        def poll_batch(self, max_records):
            if self.done:
                return None
            self.done = True
            return RecordBatch.from_pydict(
                {"v": np.asarray([1])}, timestamps=np.asarray([0]))

    def close(self):
        self.closed = True

    CountingReader.close = close

    src = SplitSource(FileSplitEnumerator(f"{d}/*.txt"),
                      CountingReader)

    def drain():
        src.open(0, 1)
        n = 0
        while (b := src.poll_batch(10)) is not None:
            n += len(b)
        return n

    assert drain() == 3
    assert drain() == 3  # replay, not an empty second run
    assert len(made) == 6  # fresh readers per run
