"""Hot-key splitting (two-stage aggregation), pinned to oracles.

One dominating key is salted into sub-keys pre-aggregated on their OWN
shards as ordinary (salted-key, negative-namespace) rows; at fire and
query time the sub-rows fold back into the main row in a fixed order
(main first, then salts ascending). Everything downstream of the split
must be indistinguishable from never having split: fires and queries
bit-identical to the unsalted single-device oracle — mid-stream
registration, forced paged eviction, snapshot/restore with LIVE salted
rows, and a serving replica that still answers the split key in one
lookup. The exactness gate (float sums reassociate) and the
paged-layout requirement are pinned as errors.
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.sharded_sessions import (
    MAX_SALTS,
    MeshSessionEngine,
)
from flink_tpu.tenancy.replica import SessionReplicaAdapter
from flink_tpu.windowing.aggregates import (
    MaxAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.sessions import SessionWindower

GAP = 100
HOT = 7


def keyed_batch(keys, vals, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(vals, dtype=np.float32)},
        timestamps=np.asarray(ts, dtype=np.int64))


def _skewed_stream(num_keys=4_000, n_steps=8, per_step=2_000, seed=13,
                   hot_frac=0.5):
    """~half the records carry the one hot key. Integer-valued float32
    values: the salted sum fold stays exact, so assertions can demand
    bit-identity rather than tolerance."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        keys[rng.random(per_step) < hot_frac] = HOT
        vals = rng.integers(1, 6, per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    return steps


def _engine(agg=None, **kw):
    kw.setdefault("max_device_slots", 1024)
    return MeshSessionEngine(GAP, agg or SumAggregate("v"), make_mesh(4),
                             capacity_per_shard=1 << 14, **kw)


def _drive(engine, steps, register_at=None, salts=8):
    fired = []
    for i, (keys, vals, ts, wm) in enumerate(steps):
        if register_at is not None and i == register_at:
            got = engine.register_hot_key(HOT, salts=salts,
                                          allow_inexact=True)
            assert got == max(2, min(salts, MAX_SALTS))
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
    fired.extend(engine.on_watermark(1 << 60))
    out = {}
    for b in fired:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r[list(r)[-1]]
    return out


class TestSaltedFires:
    def test_mid_stream_split_bit_identical_to_oracle(self):
        """Salting registered at batch 2, with the hot key's session
        ALREADY live (pre-salt rows on device) and paged eviction
        forced — the fold-back must still reproduce the oracle bit for
        bit, and the split must actually have engaged (non-vacuous:
        salted records and salted fires both counted)."""
        steps = _skewed_stream(num_keys=20_000, per_step=5_000)
        eng = _engine()
        got = _drive(eng, steps, register_at=2)
        oracle = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        expected = _drive(oracle, steps)
        assert got == expected  # EXACT, not approx
        stats = eng.hot_key_stats()
        assert stats["keys"] == {HOT: 8}
        assert stats["salted_records"] > 1_000
        assert stats["salted_fires"] > 0
        assert eng.spill_counters()["pages_evicted"] > 0

    def test_max_aggregate_splits_exactly_without_flag(self):
        """min/max commute: no allow_inexact needed, still exact."""
        steps = _skewed_stream(seed=29, n_steps=5)
        eng = _engine(agg=MaxAggregate("v"))
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 1:
                eng.register_hot_key(HOT, salts=6)  # no flag needed
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        fired.extend(eng.on_watermark(1 << 60))
        oracle = SessionWindower(GAP, MaxAggregate("v"),
                                 capacity=1 << 15)
        got = {}
        for b in fired:
            for r in b.to_rows():
                got[(r[KEY_ID_FIELD], r["window_start"],
                     r["window_end"])] = r["max_v"]
        assert got == _drive(oracle, steps)
        assert eng.hot_key_stats()["salted_fires"] > 0

    def test_multi_leaf_aggregate_splits_exactly(self):
        steps = _skewed_stream(seed=41, n_steps=5)
        agg = MultiAggregate([SumAggregate("v"), MaxAggregate("v")])
        eng = _engine(agg=agg)
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 1:
                eng.register_hot_key(HOT, salts=4, allow_inexact=True)
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        fired.extend(eng.on_watermark(1 << 60))
        oracle = SessionWindower(
            GAP, MultiAggregate([SumAggregate("v"), MaxAggregate("v")]),
            capacity=1 << 15)
        ofired = []
        for keys, vals, ts, wm in steps:
            oracle.process_batch(keyed_batch(keys, vals, ts))
            ofired.extend(oracle.on_watermark(wm))
        ofired.extend(oracle.on_watermark(1 << 60))

        def rows(bs):
            return sorted(
                (r[KEY_ID_FIELD], r["window_start"], r["window_end"],
                 r["sum_v"], r["max_v"])
                for b in bs for r in b.to_rows())

        assert rows(fired) == rows(ofired)


class TestSaltedQueries:
    def test_query_batch_combines_split_rows(self):
        """One query_batch call answers the split key: the engine folds
        main + salt rows before agg.finish — same numbers the oracle's
        query path produces, salted rows invisible to the caller."""
        steps = _skewed_stream(seed=53, n_steps=4)
        eng = _engine()
        oracle = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 1:
                eng.register_hot_key(HOT, salts=8, allow_inexact=True)
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
            oracle.process_batch(keyed_batch(keys, vals, ts))
            oracle.on_watermark(wm)
        assert eng.hot_key_stats()["salted_records"] > 0
        qk = np.array([HOT, 0, 1, 2, 999, 10 ** 9], dtype=np.int64)
        assert eng.query_batch(qk) == oracle.query_sessions_batch(qk)


class TestSaltedPersistence:
    def test_snapshot_restore_with_live_salted_rows(self):
        """Crash mid-split: the snapshot carries the salted rows (they
        are ordinary table rows) AND the hot-key registry; the restored
        engine keeps salting and finishes bit-identical."""
        steps = _skewed_stream(seed=67)
        cut = 4
        eng = _engine()
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps[:cut]):
            if i == 1:
                eng.register_hot_key(HOT, salts=5, allow_inexact=True)
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        assert eng.hot_key_stats()["salted_records"] > 0
        snap = eng.snapshot(mode="savepoint")
        fresh = _engine()
        fresh.restore(snap)
        # the registry travelled: the fresh engine keeps splitting
        assert fresh.hot_key_stats()["keys"] == {HOT: 5}
        for keys, vals, ts, wm in steps[cut:]:
            fresh.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(fresh.on_watermark(wm))
        fired.extend(fresh.on_watermark(1 << 60))
        got = {}
        for b in fired:
            for r in b.to_rows():
                got[(r[KEY_ID_FIELD], r["window_start"],
                     r["window_end"])] = r["sum_v"]
        oracle = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        assert got == _drive(oracle, steps)
        assert fresh.hot_key_stats()["salted_records"] > 0

    def test_unit_snapshots_carry_the_registry(self):
        steps = _skewed_stream(seed=79, n_steps=3)
        eng = _engine()
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 1:
                eng.register_hot_key(HOT, salts=3, allow_inexact=True)
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        units = eng.snapshot_sharded(mode="savepoint")
        # every unit carries the full registry (any unit subset must be
        # able to re-arm splitting on restore)
        for u in units.values():
            assert u.get("hot_keys") == {HOT: 3}
        merged = eng.merge_unit_snapshots(list(units.values()))
        assert merged.get("hot_keys") == {HOT: 3}


class TestServingSplitKey:
    def test_replica_answers_split_key_in_one_lookup(self):
        """The split key's sub-rows never reach the published replica
        plane — its single published entry routes the lookup through
        the live combined fold, so ONE lookup_batch call still answers
        it, bit-identical to the live query."""
        steps = _skewed_stream(seed=91, n_steps=5)
        eng = _engine()
        plane = eng.arm_replica()
        for i, (keys, vals, ts, wm) in enumerate(steps):
            if i == 1:
                eng.register_hot_key(HOT, salts=8, allow_inexact=True)
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        ad = SessionReplicaAdapter(plane, eng.agg)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = [HOT, 0, 1, 2, 3]
        repl, _gen = ad.lookup_batch(qk)
        assert repl == eng.query_batch(np.asarray(qk, dtype=np.int64))
        # the hot key was served through the cold (live-fold) route
        assert plane.cold_rows_served > 0


class TestSplitGuards:
    def test_float_sum_requires_allow_inexact(self):
        eng = _engine()
        with pytest.raises(ValueError, match="allow_inexact"):
            eng.register_hot_key(HOT, salts=8)
        assert eng.hot_key_stats()["keys"] == {}

    def test_requires_paged_layout(self):
        eng = MeshSessionEngine(GAP, SumAggregate("v"), make_mesh(4),
                                capacity_per_shard=1 << 14)
        with pytest.raises(ValueError, match="paged"):
            eng.register_hot_key(HOT, salts=8, allow_inexact=True)

    def test_salt_count_clamped(self):
        eng = _engine(agg=MaxAggregate("v"))
        assert eng.register_hot_key(HOT, salts=1) == 2
        assert eng.register_hot_key(HOT, salts=10 ** 6) == MAX_SALTS
