"""Device OVER engine == host OVER engine, on randomized streams.

The host path (runtime/over_agg.py) is the oracle: it was validated
against hand-computed frames in test_over_agg.py. The device path
(runtime/over_device.py) must produce identical numbers on every frame
family it claims, across multi-fire streams with per-key context
carry-over, checkpoints, and the degrade path.
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.runtime.over_agg import OverAggOperator
from flink_tpu.runtime.over_device import (
    DeviceOverAggOperator,
    device_supported,
)

FUNCS = ["SUM", "COUNT", "AVG", "MIN", "MAX"]


def _stream(rng, n_batches=6, rows_per_batch=40, n_keys=7, ts_step=50):
    """Random batches with monotonically advancing watermarks; rows get
    timestamps strictly above the previous watermark (matching the
    operator's late-row contract)."""
    batches, wms = [], []
    wm = 0
    for b in range(n_batches):
        new_wm = wm + ts_step * 10
        ts = rng.integers(wm + 1, new_wm + ts_step * 3,
                          size=rows_per_batch)
        keys = rng.integers(0, n_keys, size=rows_per_batch)
        batches.append(RecordBatch(
            {KEY_ID_FIELD: keys.astype(np.int64),
             "k": keys.astype(np.int64),
             "x": rng.normal(size=rows_per_batch).round(3),
             TIMESTAMP_FIELD: ts.astype(np.int64)}))
        wms.append(new_wm)
        wm = new_wm
    return batches, wms


def _run(op, batches, wms):
    outs = []
    op.open(None)
    for b, wm in zip(batches, wms):
        op.process_batch(b)
        outs.extend(op.process_watermark(wm))
    outs.extend(op.close())
    return RecordBatch.concat(outs) if outs else None


def _assert_equal(host_out, dev_out, specs):
    assert (host_out is None) == (dev_out is None)
    if host_out is None:
        return
    assert len(host_out) == len(dev_out)
    # both engines emit fire-by-fire in ready-sorted order with the same
    # stable tie-breaking, so rows align positionally; aggregates compare
    # with f32 tolerance (the device kernel runs in the platform dtype —
    # float32 unless JAX_ENABLE_X64)
    np.testing.assert_array_equal(host_out[KEY_ID_FIELD],
                                  dev_out[KEY_ID_FIELD])
    np.testing.assert_array_equal(host_out.timestamps, dev_out.timestamps)
    np.testing.assert_array_equal(host_out["x"], dev_out["x"])
    for _, _, name in specs:
        np.testing.assert_allclose(
            np.asarray(dev_out[name], dtype=np.float64),
            np.asarray(host_out[name], dtype=np.float64),
            rtol=2e-4, atol=1e-5, err_msg=name)


def _specs(funcs=FUNCS):
    return [(f, None if f == "COUNT" else "x", f"__o{i}__")
            for i, f in enumerate(funcs)]


@pytest.mark.parametrize("mode,preceding,funcs", [
    ("ROWS", None, FUNCS),          # UNBOUNDED ROWS, all funcs
    ("RANGE", None, FUNCS),         # UNBOUNDED RANGE (peers), all funcs
    ("ROWS", 5, FUNCS),             # bounded ROWS incl. MIN/MAX doubling
    ("ROWS", 1, FUNCS),             # window of 2 (k=0 edge)
    ("ROWS", 0, FUNCS),             # degenerate: current row only
    ("RANGE", 300, ["SUM", "COUNT", "AVG"]),   # bounded RANGE sum-family
    ("RANGE", 1, ["SUM", "AVG"]),
])
def test_device_matches_host(mode, preceding, funcs):
    rng = np.random.default_rng(42)
    batches, wms = _stream(rng)
    specs = _specs(funcs)
    host = _run(OverAggOperator("k", specs, mode=mode,
                                preceding=preceding), batches, wms)
    dev = _run(DeviceOverAggOperator("k", specs, mode=mode,
                                     preceding=preceding), batches, wms)
    _assert_equal(host, dev, specs)


def test_device_matches_host_single_key_and_many_keys():
    for n_keys, seed in [(1, 1), (100, 2)]:
        rng = np.random.default_rng(seed)
        batches, wms = _stream(rng, n_batches=4, rows_per_batch=60,
                               n_keys=n_keys)
        specs = _specs()
        host = _run(OverAggOperator("k", specs, "ROWS", 3), batches, wms)
        dev = _run(DeviceOverAggOperator("k", specs, "ROWS", 3),
                   batches, wms)
        _assert_equal(host, dev, specs)


def test_device_matches_host_with_duplicate_timestamps():
    # RANGE peers: rows sharing (key, ts) must all take the peer-group
    # aggregate
    rng = np.random.default_rng(3)
    batches, wms = _stream(rng, ts_step=2)  # dense ts -> many duplicates
    specs = _specs(["SUM", "COUNT", "MIN"])
    host = _run(OverAggOperator("k", specs, "RANGE", None), batches, wms)
    dev = _run(DeviceOverAggOperator("k", specs, "RANGE", None),
               batches, wms)
    _assert_equal(host, dev, specs)


def test_range_unbounded_stays_on_device_across_fires():
    """RANGE UNBOUNDED carries synthetic accumulator context rows
    (ts = -2^60) into the next fire; the span guard must see only REAL
    timestamps, or the sentinel trips it after the FIRST fire and the
    frame family this engine claims silently runs on the host forever
    (ADVICE round 5, over_device.py)."""
    rng = np.random.default_rng(7)
    batches, wms = _stream(rng, n_batches=5)
    specs = _specs(["SUM", "AVG", "COUNT"])
    host = OverAggOperator("k", specs, mode="RANGE", preceding=None)
    dev = DeviceOverAggOperator("k", specs, mode="RANGE", preceding=None)
    host.open(None)
    dev.open(None)
    fires = 0
    outs_h, outs_d = [], []
    for b, wm in zip(batches, wms):
        host.process_batch(b)
        dev.process_batch(b)
        oh = host.process_watermark(wm)
        od = dev.process_watermark(wm)
        outs_h.extend(oh)
        outs_d.extend(od)
        if od:
            fires += 1
        # the accelerated path must SURVIVE each fire, not just the first
        assert not dev._fallback, f"degraded to host after fire {fires}"
    assert fires >= 2, "stream must produce at least two device fires"
    outs_h.extend(host.close())
    outs_d.extend(dev.close())
    assert not dev._fallback
    _assert_equal(RecordBatch.concat(outs_h), RecordBatch.concat(outs_d),
                  specs)


def test_device_supported_matrix():
    assert device_supported(_specs(["SUM"]), "RANGE", 10)
    assert not device_supported(_specs(["MIN"]), "RANGE", 10)
    assert device_supported(_specs(["MIN"]), "RANGE", None)
    assert device_supported(_specs(["MIN"]), "ROWS", 10)


def test_device_engine_rejects_range_min_bounded():
    with pytest.raises(ValueError, match="RANGE MIN/MAX"):
        DeviceOverAggOperator("k", _specs(["MIN"]), "RANGE", 10)


def test_checkpoint_restore_midstream_matches():
    rng = np.random.default_rng(9)
    batches, wms = _stream(rng)
    specs = _specs()
    ref = _run(DeviceOverAggOperator("k", specs, "ROWS", 4),
               batches, wms)

    op = DeviceOverAggOperator("k", specs, "ROWS", 4)
    op.open(None)
    outs = []
    for b, wm in zip(batches[:3], wms[:3]):
        op.process_batch(b)
        outs.extend(op.process_watermark(wm))
    snap = op.snapshot_state()
    op2 = DeviceOverAggOperator("k", specs, "ROWS", 4)
    op2.open(None)
    op2.restore_state(snap)
    for b, wm in zip(batches[3:], wms[3:]):
        op2.process_batch(b)
        outs.extend(op2.process_watermark(wm))
    outs.extend(op2.close())
    _assert_equal(ref, RecordBatch.concat(outs), specs)


def test_degrade_to_host_keeps_context():
    """A fire exceeding the span budget converts flat context to the
    host form and continues bit-identically."""
    rng = np.random.default_rng(5)
    batches, wms = _stream(rng, n_batches=6)
    specs = _specs(["SUM", "AVG"])
    host = _run(OverAggOperator("k", specs, "RANGE", 300), batches, wms)

    op = DeviceOverAggOperator("k", specs, "RANGE", 300)
    op.open(None)
    outs = []
    for i, (b, wm) in enumerate(zip(batches, wms)):
        if i == 3:
            op._degrade_to_host()   # simulate the span guard tripping
            assert op._fallback
        op.process_batch(b)
        outs.extend(op.process_watermark(wm))
    outs.extend(op.close())
    _assert_equal(host, RecordBatch.concat(outs), specs)


def test_degrade_unbounded_keeps_accumulators():
    rng = np.random.default_rng(6)
    batches, wms = _stream(rng)
    specs = _specs()
    host = _run(OverAggOperator("k", specs, "RANGE", None), batches, wms)

    op = DeviceOverAggOperator("k", specs, "RANGE", None)
    op.open(None)
    outs = []
    for i, (b, wm) in enumerate(zip(batches, wms)):
        if i == 2:
            op._degrade_to_host()
        op.process_batch(b)
        outs.extend(op.process_watermark(wm))
    outs.extend(op.close())
    _assert_equal(host, RecordBatch.concat(outs), specs)


def test_sql_over_engine_config():
    """table.exec.over.engine selects the operator family end-to-end
    through SQL, with identical results."""
    from flink_tpu import Configuration, StreamExecutionEnvironment
    from flink_tpu.connectors.kafka import FakeBroker
    from flink_tpu.table.environment import StreamTableEnvironment

    rng = np.random.default_rng(13)
    n = 400
    ks = rng.integers(0, 9, n).astype(np.int64)
    vs = np.round(rng.random(n), 4)
    ts = np.arange(n, dtype=np.int64) * 7
    results = {}
    for engine in ("host", "device", "auto"):
        topic = f"over_cfg_{engine}"
        broker = FakeBroker.get("default")
        broker.create_topic(topic, 1)
        broker.append(topic, 0, RecordBatch.from_pydict(
            {"key": ks, "value": vs, "ts": ts}, timestamps=ts))
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 101,
            "table.exec.over.engine": engine}))
        tenv = StreamTableEnvironment(env)
        tenv.execute_sql(
            f"CREATE TABLE {topic} (key BIGINT, value DOUBLE, ts BIGINT, "
            "WATERMARK FOR ts AS ts) "
            f"WITH ('connector'='kafka', 'topic'='{topic}')")
        rows = tenv.execute_sql(
            "SELECT key, ts, SUM(value) OVER (PARTITION BY key "
            "ORDER BY ts ROWS BETWEEN 10 PRECEDING AND CURRENT ROW) "
            f"AS r FROM {topic}").collect()
        results[engine] = sorted(
            (int(r["key"]), int(r["ts"]), float(r["r"])) for r in rows)
    # auto == host exactly (x64 off in CI -> auto stays on the host
    # engine); device matches within f32 tolerance
    assert results["auto"] == results["host"]
    assert len(results["host"]) == n == len(results["device"])
    for (hk, ht, hr), (dk, dt, dr) in zip(results["host"],
                                          results["device"]):
        assert (hk, ht) == (dk, dt)
        assert dr == pytest.approx(hr, rel=2e-4, abs=1e-5)
