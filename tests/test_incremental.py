"""Incremental (delta) checkpoints: dirty-slot tracking, tombstones, chain
materialization, chain-aware retention.

reference model: flink-statebackend-rocksdb incremental snapshots
(RocksIncrementalSnapshotStrategy: upload only new SSTs; SharedStateRegistry
keeps referenced files alive).
"""

import os

import numpy as np

from flink_tpu.checkpoint.storage import (
    apply_table_delta,
    read_checkpoint_chain,
    read_manifest,
)
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.state.slot_table import SlotTable
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def table_rows(tbl):
    return {
        (int(k), int(n)): float(v)
        for k, n, v in zip(tbl["key_id"], tbl["namespace"], tbl["leaf_0"])
    }


class TestSlotTableDelta:
    def test_delta_tracks_only_dirty_rows(self):
        agg = SumAggregate("v")
        t = SlotTable(agg, capacity=1024)
        k1 = np.array([1, 2, 3], dtype=np.int64)
        ns = np.full(3, 10, dtype=np.int64)
        slots = t.lookup_or_insert(k1, ns)
        t.scatter(slots, (np.array([1.0, 2.0, 3.0], dtype=np.float32),))
        base = t.snapshot()  # full: establishes the delta base

        # touch only key 2
        s2 = t.lookup_or_insert(np.array([2], dtype=np.int64),
                                np.array([10], dtype=np.int64))
        t.scatter(s2, (np.array([5.0], dtype=np.float32),))
        delta = t.snapshot_delta()
        assert table_rows(delta) == {(2, 10): 7.0}
        assert len(delta["freed_namespaces"]) == 0

        merged = apply_table_delta(base, delta)
        assert table_rows(merged) == {(1, 10): 1.0, (2, 10): 7.0,
                                      (3, 10): 3.0}

    def test_delta_tombstones_freed_namespaces(self):
        agg = SumAggregate("v")
        t = SlotTable(agg, capacity=1024)
        keys = np.array([1, 2], dtype=np.int64)
        t.scatter(t.lookup_or_insert(keys, np.full(2, 10, dtype=np.int64)),
                  (np.ones(2, dtype=np.float32),))
        t.scatter(t.lookup_or_insert(keys, np.full(2, 20, dtype=np.int64)),
                  (np.ones(2, dtype=np.float32),))
        base = t.snapshot()
        t.free_namespaces([10])
        delta = t.snapshot_delta()
        assert 10 in delta["freed_namespaces"].tolist()
        merged = apply_table_delta(base, delta)
        assert set(table_rows(merged)) == {(1, 20), (2, 20)}

    def test_delta_chain_equals_full(self):
        """A full snapshot + N deltas materializes to the same rows as a
        straight full snapshot of the final state."""
        agg = SumAggregate("v")
        t = SlotTable(agg, capacity=4096)
        rng = np.random.default_rng(3)
        base = None
        deltas = []
        for step in range(5):
            keys = rng.integers(0, 50, 200).astype(np.int64)
            ns = rng.integers(0, 4, 200).astype(np.int64) * 10
            vals = rng.random(200).astype(np.float32)
            t.scatter(t.lookup_or_insert(keys, ns), (vals,))
            if step == 1:
                t.free_namespaces([0])
            if step == 0:
                base = t.snapshot()
            else:
                deltas.append(t.snapshot_delta())
        materialized = base
        for d in deltas:
            materialized = apply_table_delta(materialized, d)
        # compare against a fresh full snapshot (dirty flags are clear, so
        # snapshot() reflects the same final state)
        full = t.snapshot()
        assert table_rows(materialized) == table_rows(full)


def run_windowed(tmp_path, subdir, total, extra_cfg=None, restore=None):
    cfg = {
        "execution.micro-batch.size": 256,
        "state.checkpoints.dir": str(tmp_path / subdir),
        "execution.checkpointing.every-n-source-batches": 1,
    }
    cfg.update(extra_cfg or {})
    env = StreamExecutionEnvironment(Configuration(cfg))
    sink = CollectSink()
    (env.add_source(DataGenSource(total_records=total, num_keys=30,
                                  events_per_second_of_eventtime=10_000),
                    WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key").window(TumblingEventTimeWindows.of(1000))
        .sum("value").sink_to(sink))
    r = env.execute("inc-job", restore_from=restore)
    return sink, r


class TestIncrementalE2E:
    def test_incremental_restore_matches_full(self, tmp_path):
        # totals are multiples of the 256 micro-batch so the datagen rng
        # stream splits identically across runs of different lengths (a
        # partial final batch consumes the stream differently)
        sink_full, _ = run_windowed(tmp_path, "full", 20_480)
        sink_inc, r = run_windowed(
            tmp_path, "inc", 20_480,
            {"execution.checkpointing.incremental": True,
             "execution.checkpointing.incremental.full-every": 4})
        # same results (keys AND aggregated values) while checkpointing
        # incrementally
        a = {(int(x["key"]), int(x["window_start"])): float(x["sum_value"])
             for x in sink_full.rows()}
        b = {(int(x["key"]), int(x["window_start"])): float(x["sum_value"])
             for x in sink_inc.rows()}
        assert a.keys() == b.keys()
        for kw in a:
            assert abs(a[kw] - b[kw]) < 1e-3, (kw, a[kw], b[kw])
        # delta manifests present in the chain
        root = str(tmp_path / "inc")
        manifests = [read_manifest(os.path.join(root, d))
                     for d in os.listdir(root) if d.startswith("chk-")]
        assert any(m["extra"].get("incremental") for m in manifests)

        # restore from the latest (delta) checkpoint: chain materializes,
        # the resumed segment completes the 30k-record oracle exactly
        sink_resumed, _ = run_windowed(
            tmp_path, "inc", 30_720,
            {"execution.checkpointing.incremental": True,
             "execution.checkpointing.incremental.full-every": 4},
            restore=root)
        res = {(int(x["key"]), int(x["window_start"])): float(x["sum_value"])
               for x in sink_resumed.rows()}
        assert res
        sink_oracle, _ = run_windowed(tmp_path, "oracle30", 30_720)
        oracle = {(int(x["key"]), int(x["window_start"])):
                  float(x["sum_value"]) for x in sink_oracle.rows()}
        # run1's end-of-input flush fires the final window PARTIALLY; the
        # resumed run refires it complete — res overrides b in the union,
        # which must then match the uninterrupted oracle value-for-value
        merged = {**b, **res}
        assert merged.keys() == oracle.keys()
        for kw in oracle:
            assert abs(merged[kw] - oracle[kw]) < 1e-3, \
                (kw, merged[kw], oracle[kw])

    def test_retain_keeps_chain_bases_alive(self, tmp_path):
        root = str(tmp_path / "inc2")
        run_windowed(tmp_path, "inc2", 15_000,
                     {"execution.checkpointing.incremental": True,
                      "execution.checkpointing.incremental.full-every": 50,
                      "execution.checkpointing.retained": 2})
        dirs = sorted(d for d in os.listdir(root) if d.startswith("chk-"))
        # more than `retained` dirs survive: the full base of the retained
        # deltas cannot be deleted
        latest = max(int(d[4:]) for d in dirs)
        states = read_checkpoint_chain(os.path.join(root, f"chk-{latest}"))
        assert states  # chain materializes without missing bases
        full_dirs = [d for d in dirs
                     if not read_manifest(os.path.join(root, d))
                     ["extra"].get("incremental")]
        assert full_dirs, "the full base must have survived retention"


def test_savepoint_inside_root_is_not_a_delta_base(tmp_path):
    """Restoring from a savepoint that happens to live inside the
    checkpoint root must NOT seed the delta chain: its manifest id would
    alias an unrelated sibling chk-<id>. The first post-restore checkpoint
    must be full."""
    from flink_tpu.state_processor import SavepointWriter

    root = str(tmp_path / "ck")
    run_windowed(tmp_path, "ck", 10_240,
                 {"execution.checkpointing.incremental": True,
                  "execution.checkpointing.incremental.full-every": 4})
    # savepoint written INSIDE the root, pinned at an id that collides
    # with a live sibling checkpoint
    sp = os.path.join(root, "sp-in-root")
    w = SavepointWriter.from_existing(root)
    w.checkpoint_id = max(int(d[4:]) for d in os.listdir(root)
                          if d.startswith("chk-")) - 1
    w.write(sp)
    before = {d for d in os.listdir(root) if d.startswith("chk-")}
    run_windowed(tmp_path, "ck", 20_480,
                 {"execution.checkpointing.incremental": True,
                  "execution.checkpointing.incremental.full-every": 4},
                 restore=sp)
    new_ids = sorted(int(d[4:]) for d in os.listdir(root)
                     if d.startswith("chk-") and d not in before)
    assert new_ids, "resumed run wrote checkpoints"
    first = read_manifest(os.path.join(root, f"chk-{new_ids[0]}"))
    assert not first["extra"].get("incremental"), \
        "first post-savepoint-restore checkpoint must be full"
    # and the whole new chain still materializes
    assert read_checkpoint_chain(
        os.path.join(root, f"chk-{new_ids[-1]}"))
