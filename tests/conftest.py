"""Test configuration.

Forces JAX onto the CPU backend with 8 virtual devices so that mesh/sharding
tests (the multi-chip path) run in CI without TPU hardware, mirroring how the
reference tests "multi-node" behavior in one JVM via its MiniCluster
(reference: flink-runtime/src/main/java/org/apache/flink/runtime/minicluster/MiniCluster.java).

Must run before the first ``import jax`` anywhere in the test session.
"""

import os

# Hard-override: the ambient environment may point JAX at TPU hardware
# (e.g. JAX_PLATFORMS=axon, plus a sitecustomize hook that calls
# jax.config.update("jax_platforms", "axon,cpu") at interpreter start —
# which overrides the env var). Tests always run on the virtual CPU mesh,
# so both the env var AND the config entry must be forced back to cpu
# before any backend is initialized.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep compilation fast and deterministic in CI.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: wall-clock-sensitive or long tests, excluded from the "
        "tier-1 gate (-m 'not slow' — see tools/tier1.sh)")


@pytest.fixture
def eight_device_mesh():
    import jax
    from flink_tpu.parallel.mesh import make_mesh

    n = len(jax.devices())
    assert n >= 8, f"expected >=8 virtual devices, got {n}"
    return make_mesh(8)


def assert_windows_approx_equal(got, expected, rel=1e-4, abs_tol=1e-3):
    """Per-window compare with float tolerance: the local (two-phase)
    combiner and parallel folds change f32 summation order, so sums match
    to ~1 ulp, not bit-exactly. Shared by the stage/batch/shuffle suites."""
    import pytest as _pytest

    assert set(got) == set(expected)
    for k in expected:
        assert got[k] == _pytest.approx(expected[k], rel=rel, abs=abs_tol), k
