"""Event-time temporal join (FOR SYSTEM_TIME AS OF).

reference: StreamExecTemporalJoin ->
flink-table-runtime/.../operators/join/temporal/
TemporalRowTimeJoinOperator.java — each left row joins the right VERSION
valid at its event time; version state compacts past the watermark."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.join_operators import TemporalJoinOperator
from flink_tpu.state.keygroups import hash_keys_to_i64
from flink_tpu.table.environment import StreamTableEnvironment


class _Ctx:
    max_parallelism = 128


def _kb(cols, ts):
    b = RecordBatch.from_pydict(
        cols, timestamps=np.asarray(ts, dtype=np.int64))
    return b.with_column("__key_id__", hash_keys_to_i64(b["cur"]))


class TestOperator:
    def _orders(self):
        return _kb({"cur": np.asarray([1, 1, 2, 1], dtype=np.int64),
                    "amount": np.asarray([10.0, 20.0, 30.0, 40.0])},
                   [1000, 2500, 4000, 5500])

    def _rates(self):
        return _kb({"cur": np.asarray([1, 1, 2, 1], dtype=np.int64),
                    "rate": np.asarray([1.0, 1.1, 2.0, 1.2])},
                   [0, 2000, 3000, 5000])

    def _joined(self, outs):
        rows = {}
        for b in outs:
            for r in b.to_rows():
                rows[(r["amount"], r["__ts__"])] = r["rate"]
        return rows

    def test_each_left_row_joins_the_valid_version(self):
        op = TemporalJoinOperator()
        op.open(_Ctx())
        op.process_batch(self._rates(), input_index=1)
        op.process_batch(self._orders(), input_index=0)
        got = self._joined(op.process_watermark(10_000))
        assert got == {(10.0, 1000): 1.0, (20.0, 2500): 1.1,
                       (30.0, 4000): 2.0, (40.0, 5500): 1.2}

    def test_left_rows_wait_for_the_watermark(self):
        """A left row must not join until the combined watermark covers
        its timestamp (version completeness)."""
        op = TemporalJoinOperator()
        op.open(_Ctx())
        op.process_batch(_kb({"cur": np.asarray([1]),
                              "rate": np.asarray([1.0])}, [0]),
                         input_index=1)
        op.process_batch(_kb({"cur": np.asarray([1]),
                              "amount": np.asarray([10.0])}, [2500]),
                         input_index=0)
        assert op.process_watermark(2000) == []  # not ripe yet
        # the newer version arrives BEFORE the row's watermark — it wins
        op.process_batch(_kb({"cur": np.asarray([1]),
                              "rate": np.asarray([1.5])}, [2400]),
                         input_index=1)
        got = self._joined(op.process_watermark(3000))
        assert got == {(10.0, 2500): 1.5}

    def test_no_version_drops_inner(self):
        op = TemporalJoinOperator()
        op.open(_Ctx())
        op.process_batch(_kb({"cur": np.asarray([7]),
                              "amount": np.asarray([1.0])}, [100]),
                         input_index=0)
        assert op.process_watermark(10_000) == []

    def test_version_state_compacts(self):
        op = TemporalJoinOperator()
        op.open(_Ctx())
        op.process_batch(self._rates(), input_index=1)
        op.process_watermark(10_000)
        # all versions <= watermark except the latest per key drop
        v = op._sorted_versions()
        assert len(v) == 2  # latest of cur=1 (5000) + latest of cur=2
        # and a late-arriving left row for an OLD instant is dropped
        op.process_batch(_kb({"cur": np.asarray([1]),
                              "amount": np.asarray([9.0])}, [1500]),
                         input_index=0)
        assert op.late_left_dropped == 1

    def test_snapshot_restore_key_group_filter(self):
        op = TemporalJoinOperator()
        op.open(_Ctx())
        op.process_batch(self._rates(), input_index=1)
        op.process_batch(self._orders(), input_index=0)
        snap = op.snapshot_state()
        from flink_tpu.state.keygroups import assign_key_groups

        g1 = int(assign_key_groups(np.asarray([1]), 128)[0])
        op2 = TemporalJoinOperator()
        op2.open(_Ctx())
        op2.restore_state(snap, key_group_filter={g1})
        got = self._joined(op2.process_watermark(10_000))
        # only cur=1 rows survived the filter
        assert got == {(10.0, 1000): 1.0, (20.0, 2500): 1.1,
                       (40.0, 5500): 1.2}


class TestTemporalJoinSQL:
    def _setup(self, tenv, suffix=""):
        from flink_tpu.connectors.kafka import FakeBroker

        broker = FakeBroker.get("default")
        o, r = f"ord{suffix}", f"rate{suffix}"
        broker.create_topic(o, 1)
        broker.create_topic(r, 1)
        o_ts = np.asarray([1000, 2500, 4000, 5500], dtype=np.int64)
        broker.append(o, 0, RecordBatch.from_pydict(
            {"cur": np.asarray([1, 1, 2, 1], dtype=np.int64),
             "amount": np.asarray([10.0, 20.0, 30.0, 40.0]),
             "ots": o_ts}, timestamps=o_ts))
        r_ts = np.asarray([0, 2000, 3000, 5000], dtype=np.int64)
        broker.append(r, 0, RecordBatch.from_pydict(
            {"cur": np.asarray([1, 1, 2, 1], dtype=np.int64),
             "rate": np.asarray([1.0, 1.1, 2.0, 1.2]),
             "rts": r_ts}, timestamps=r_ts))
        tenv.execute_sql(
            f"CREATE TABLE {o} (cur BIGINT, amount DOUBLE, ots BIGINT, "
            "WATERMARK FOR ots AS ots) "
            f"WITH ('connector'='kafka', 'topic'='{o}')")
        tenv.execute_sql(
            f"CREATE TABLE {r} (cur BIGINT, rate DOUBLE, rts BIGINT, "
            "WATERMARK FOR rts AS rts) "
            f"WITH ('connector'='kafka', 'topic'='{r}')")
        return o, r

    def test_for_system_time_as_of(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 2}))
        tenv = StreamTableEnvironment(env)
        o, r = self._setup(tenv, "1")
        rows = tenv.execute_sql(f"""
            SELECT o.amount, r.rate, o.ots FROM {o} AS o
            JOIN {r} FOR SYSTEM_TIME AS OF o.ots AS r
            ON o.cur = r.cur
        """).collect()
        got = {(x["amount"], x["ots"]): x["rate"] for x in rows}
        assert got == {(10.0, 1000): 1.0, (20.0, 2500): 1.1,
                       (30.0, 4000): 2.0, (40.0, 5500): 1.2}

    def test_converted_amounts(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 3}))
        tenv = StreamTableEnvironment(env)
        o, r = self._setup(tenv, "2")
        rows = tenv.execute_sql(f"""
            SELECT o.amount * r.rate AS converted FROM {o} AS o
            JOIN {r} FOR SYSTEM_TIME AS OF o.ots AS r
            ON o.cur = r.cur
        """).collect()
        assert sorted(round(x["converted"], 2) for x in rows) == \
            [10.0, 22.0, 48.0, 60.0]

    def test_as_of_must_be_left_rowtime(self):
        from flink_tpu.table.environment import PlanError

        env = StreamExecutionEnvironment(Configuration({}))
        tenv = StreamTableEnvironment(env)
        o, r = self._setup(tenv, "3")
        with pytest.raises(PlanError, match="event-time"):
            tenv.execute_sql(f"""
                SELECT o.amount FROM {o} AS o
                JOIN {r} FOR SYSTEM_TIME AS OF r.rts AS r
                ON o.cur = r.cur
            """)
