"""Jobs spanning task executors: multi-slot acquisition, reactive scale-to-
resources, and failover when any participating executor dies (reference:
SlotSharingExecutionSlotAllocator + region failover, exercised like the
reference's recovery ITCases)."""

import time

import numpy as np
import pytest

from flink_tpu import Configuration
from flink_tpu.cluster.minicluster import MiniCluster
from flink_tpu.connectors.sinks import CollectSink, JsonLinesFileSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _graph(env, sink, total=30_000, slow=False):
    class SlowGen(DataGenSource):
        def poll_batch(self, n):
            time.sleep(0.02)
            return super().poll_batch(n)

    cls = SlowGen if slow else DataGenSource
    src = cls(total_records=total, num_keys=200,
              events_per_second_of_eventtime=10_000, seed=9)
    env.from_source(src,
                    WatermarkStrategy.for_bounded_out_of_orderness(0),
                    name="gen") \
        .key_by("key").window(TumblingEventTimeWindows.of(1000)) \
        .sum("value").sink_to(sink)
    return env.get_stream_graph()


def _expected(total=30_000):
    env = StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1000}))
    sink = CollectSink()
    _graph(env, sink, total=total)
    env.execute("oracle")
    return {(r["key"], r["window_start"]): round(r["sum_value"], 3)
            for r in sink.result().to_rows()}


def _rows(path):
    return {(r["key"], r["window_start"]): round(r["sum_value"], 3)
            for r in JsonLinesFileSink.read_rows(path)}


from tests.conftest import \
    assert_windows_approx_equal as _assert_windows_equal  # noqa: E501


class TestMultiSlotJobs:
    def test_job_spans_executors(self, tmp_path):
        """stage-parallelism 3 on a 2x2-slot cluster: slots come from BOTH
        executors while the job runs."""
        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 2,
            "taskmanager.numberOfTaskSlots": 2,
            "rest.port": -1,
        }))
        try:
            out = str(tmp_path / "out.jsonl")
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 1000,
                "execution.stage-parallelism": 3,
            }))
            _graph(env, JsonLinesFileSink(out), slow=True)
            client = cluster.submit(env, "spanning")
            # while running, 3 slots must be allocated, necessarily from
            # both executors (each has only 2 slots)
            allocated = {}
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                allocated = {
                    eid: info["allocated"]
                    for eid, info in cluster.rm._executors.items()}
                if sum(allocated.values()) >= 3:
                    break
                time.sleep(0.02)
            assert sum(allocated.values()) >= 3, allocated
            assert sum(1 for v in allocated.values() if v > 0) == 2, \
                f"job must span both executors: {allocated}"
            status = client.wait(timeout=120)
            assert status["status"] == "FINISHED"
            _assert_windows_equal(_rows(out), _expected())
            # slots released after completion
            assert sum(i["allocated"]
                       for i in cluster.rm._executors.values()) == 0
        finally:
            cluster.shutdown()

    def test_scales_to_available_slots(self, tmp_path):
        """stage-parallelism 5 on a cluster with 3 slots total runs at an
        effective parallelism of 3 (reactive scale-to-resources)."""
        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 3,
            "taskmanager.numberOfTaskSlots": 1,
            "rest.port": -1,
        }))
        try:
            out = str(tmp_path / "out.jsonl")
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 1000,
                "execution.stage-parallelism": 5,
            }))
            _graph(env, JsonLinesFileSink(out))
            client = cluster.submit(env, "scaled")
            status = client.wait(timeout=120)
            assert status["status"] == "FINISHED"
            result = client.result()
            assert result.metrics["stage_parallelism"] == 3
            _assert_windows_equal(_rows(out), _expected())
        finally:
            cluster.shutdown()

    def test_participating_executor_death_fails_over(self, tmp_path):
        """Killing a NON-primary executor holding one of the job's slots
        restarts the job from the latest checkpoint on the survivors."""
        ckpt = str(tmp_path / "ckpts")
        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 3,
            "taskmanager.numberOfTaskSlots": 1,
            "heartbeat.timeout-ms": 400,
            "rest.port": -1,
        }))
        try:
            out = str(tmp_path / "out.jsonl")
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 1000,
                "execution.stage-parallelism": 3,
                "state.checkpoints.dir": ckpt,
                "execution.checkpointing.every-n-source-batches": 4,
                "restart-strategy.max-attempts": 3,
                "restart-strategy.delay-ms": 50,
            }))
            _graph(env, JsonLinesFileSink(out), total=60_000, slow=True)
            client = cluster.submit(env, "failover")
            # wait until all three slots are held, then kill a non-primary
            deadline = time.monotonic() + 20
            master = cluster.dispatcher.master(client.job_id)
            while time.monotonic() < deadline:
                if sum(i["allocated"] for i in
                       cluster.rm._executors.values()) >= 3 and \
                        master.status == "RUNNING":
                    break
                time.sleep(0.02)
            primary = master._current_executor
            victim = next(eid for eid in cluster.rm._executors
                          if eid != primary)
            time.sleep(0.3)  # let a checkpoint land
            cluster.kill_task_executor(victim)
            status = client.wait(timeout=180)
            assert status["status"] == "FINISHED"
            assert master.attempt >= 1, "job must have restarted"
            _assert_windows_equal(_rows(out), _expected(total=60_000))
        finally:
            cluster.shutdown()


class TestSlotSharingGroups:
    """reference: DataStream.slotSharingGroup — same-group subtasks share
    a slot; a distinct group forces its own slots, multiplying the job's
    slot request."""

    def _graph_with_group(self, env, sink, group=None):
        src = DataGenSource(total_records=8_000, num_keys=50,
                            events_per_second_of_eventtime=10_000, seed=9)
        ds = env.from_source(
            src, WatermarkStrategy.for_bounded_out_of_orderness(0),
            name="gen")
        agg = (ds.key_by("key")
                 .window(TumblingEventTimeWindows.of(1000)).sum("value"))
        if group is not None:
            agg = agg.slot_sharing_group(group)
        agg.sink_to(sink)
        return env.get_stream_graph()

    def test_groups_resolve_by_inheritance(self):
        env = StreamExecutionEnvironment(Configuration({}))
        sink = CollectSink()
        g = self._graph_with_group(env, sink, group="heavy")
        groups = g.distinct_slot_groups()
        assert groups == ["default", "heavy"]
        resolved = g.slot_groups()
        # the sink inherits its input's (the agg's) group
        sink_t = [t for t in g.nodes if t.kind == "sink"][0]
        assert resolved[sink_t.uid] == "heavy"
        src_t = [t for t in g.nodes if t.kind == "source"][0]
        assert resolved[src_t.uid] == "default"

    def test_extra_group_holds_an_extra_slot(self, tmp_path):
        cluster = MiniCluster(Configuration({
            "cluster.task-executors": 1,
            "taskmanager.numberOfTaskSlots": 2,
            "rest.port": -1,
        }))
        try:
            out = str(tmp_path / "out.jsonl")
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 500}))
            class Slow(DataGenSource):
                def poll_batch(self, n):
                    time.sleep(0.02)
                    return super().poll_batch(n)

            src = Slow(total_records=30_000, num_keys=50,
                       events_per_second_of_eventtime=10_000, seed=9)
            ds = env.from_source(
                src, WatermarkStrategy.for_bounded_out_of_orderness(0),
                name="gen")
            (ds.key_by("key")
               .window(TumblingEventTimeWindows.of(1000)).sum("value")
               .slot_sharing_group("isolated")
               .sink_to(JsonLinesFileSink(out)))
            client = cluster.submit(env, "grouped")
            allocated = {}
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                allocated = {
                    eid: info["allocated"]
                    for eid, info in cluster.rm._executors.items()}
                if sum(allocated.values()) >= 2:
                    break
                time.sleep(0.02)
            # two sharing groups -> two slots held while running
            assert sum(allocated.values()) >= 2, allocated
            status = client.wait(timeout=120)
            assert status["status"] == "FINISHED"
            assert sum(i["allocated"]
                       for i in cluster.rm._executors.values()) == 0
        finally:
            cluster.shutdown()
