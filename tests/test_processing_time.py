"""Processing-time windows and timers (reference:
TumblingProcessingTimeWindows + WindowOperator.onProcessingTime:497 +
ProcessingTimeService scheduled triggers)."""

import time

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import Source
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import (
    SlidingProcessingTimeWindows,
    TumblingProcessingTimeWindows,
)


class PacedSource(Source):
    """Emits `per_wave` records every `pause_s`, for `waves` waves — slow
    enough that wall-clock windows close between waves."""

    def __init__(self, waves=3, per_wave=50, pause_s=0.25, keys=5):
        self.waves = waves
        self.per_wave = per_wave
        self.pause_s = pause_s
        self.keys = keys
        self._emitted_waves = 0

    def poll_batch(self, n):
        if self._emitted_waves >= self.waves:
            return None
        if self._emitted_waves:
            time.sleep(self.pause_s)
        self._emitted_waves += 1
        k = np.arange(self.per_wave, dtype=np.int64) % self.keys
        return RecordBatch.from_pydict(
            {"key": k, "value": np.ones(self.per_wave, dtype=np.float32)},
            timestamps=np.zeros(self.per_wave, dtype=np.int64))

    def snapshot_position(self):
        return {"waves": self._emitted_waves}

    def restore_position(self, pos):
        self._emitted_waves = pos["waves"]


class TestProcessingTimeWindows:
    @pytest.mark.parametrize("stage_par", [0, 2])
    def test_tumbling_pt_windows_fire_on_wall_clock(self, stage_par):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 64,
            "execution.stage-parallelism": stage_par,
        }))
        sink = CollectSink()
        env.from_source(PacedSource(waves=3, pause_s=0.3),
                        WatermarkStrategy.no_watermarks(), name="paced") \
            .key_by("key") \
            .window(TumblingProcessingTimeWindows.of(200)) \
            .count().sink_to(sink)
        env.execute("pt")
        rows = sink.result().to_rows()
        # all 150 records counted exactly once
        assert sum(r["count"] for r in rows) == 150
        # waves arrive ~300ms apart with 200ms windows -> records must
        # land in >= 2 distinct wall-clock windows (mid-stream PT fires)
        assert len({r["window_end"] for r in rows}) >= 2
        # every emitted window's span is the configured size
        assert all(r["window_end"] - r["window_start"] == 200 for r in rows)

    def test_sliding_pt_windows_count_overlap(self):
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 64}))
        sink = CollectSink()
        env.from_source(PacedSource(waves=2, per_wave=40, pause_s=0.25),
                        WatermarkStrategy.no_watermarks(), name="paced") \
            .key_by("key") \
            .window(SlidingProcessingTimeWindows.of(400, 100)) \
            .count().sink_to(sink)
        env.execute("pt-hop")
        rows = sink.result().to_rows()
        # each record lands in size/slide = 4 overlapping windows
        assert sum(r["count"] for r in rows) == 80 * 4

    def test_end_of_input_flushes_open_pt_windows(self):
        """A fast bounded run ends before any wall-clock window closes;
        the MAX watermark at end-of-input must flush them."""
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1024}))
        sink = CollectSink()
        env.from_source(PacedSource(waves=1, per_wave=100, pause_s=0),
                        WatermarkStrategy.no_watermarks(), name="paced") \
            .key_by("key") \
            .window(TumblingProcessingTimeWindows.of(60_000)) \
            .count().sink_to(sink)
        env.execute("pt-flush")
        rows = sink.result().to_rows()
        assert sum(r["count"] for r in rows) == 100


class TestProcessingTimeTimers:
    def test_pt_timer_fires_on_idle_stream(self):
        """A processing-time timer registered by the first records fires
        from the executor's wall-clock tick even though no further data
        arrives before it is due."""
        from flink_tpu.runtime.process import KeyedProcessFunction

        fired = []

        class TimerFn(KeyedProcessFunction):
            def process_batch(self, batch, ctx):
                now = int(time.time() * 1000)
                ctx.timer_service().register_processing_time_timers(
                    np.unique(batch.key_ids), np.full(
                        len(np.unique(batch.key_ids)), now + 150,
                        dtype=np.int64))

            def on_timer(self, keys, timestamps, ctx):
                fired.extend(int(k) for k in keys)

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 64}))
        sink = CollectSink()
        env.from_source(PacedSource(waves=1, per_wave=10, keys=3,
                                    pause_s=0),
                        WatermarkStrategy.no_watermarks(), name="paced") \
            .map(lambda b: b, name="slowdown") \
            .key_by("key").process(TimerFn()).sink_to(sink)

        # keep the job alive past the timer due-time with a second slow
        # source wave
        class Tail(PacedSource):
            def poll_batch(self, n):
                b = super().poll_batch(n)
                if b is None:
                    return None
                time.sleep(0.3)
                return b

        env2 = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 64}))
        sink2 = CollectSink()
        env2.from_source(Tail(waves=2, per_wave=10, keys=3, pause_s=0.0),
                         WatermarkStrategy.no_watermarks(), name="paced") \
            .key_by("key").process(TimerFn()).sink_to(sink2)
        env2.execute("pt-timer")
        assert set(fired) >= set(), "smoke"
        assert len(fired) >= 3, f"PT timers must fire on ticks: {fired}"
