"""Mesh-sharded PAGED spill (spill_layout="pages", the default) — the
mesh port of the single-device paged session machinery (NOTES_r5 §2):
per shard, eviction moves COHORTS of the coldest rows (slot-granular
touch clocks), reloads pop whole pages and split requested rows from the
re-bundled rest, and the host indexes run registry-free. Results are
pinned to the single-device oracle under forced eviction (device slots
≪ live sessions).
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.sessions import SessionWindower

from tests.test_sessions import keyed_batch

GAP = 100


def _engine(mesh, **kw):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

    return MeshSessionEngine(gap=GAP, agg=SumAggregate("v"), mesh=mesh,
                             capacity_per_shard=1 << 14, **kw)


def _stream(num_keys=24_000, n_steps=8, per_step=6000, seed=17):
    """A live session set far beyond the 1024-slot per-shard budget:
    ~num_keys keys recur within the gap, the watermark lags a step, so
    >10k sessions stay concurrently live (>1.3k per shard) — forcing
    cohort eviction + reload-on-fire."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    steps.append((np.array([0], dtype=np.int64),
                  np.array([0.0], dtype=np.float32),
                  np.array([n_steps * 80 + 10_000], dtype=np.int64),
                  10 ** 9))
    return steps


def _run(engine, steps):
    fired = []
    for keys, vals, ts, wm in steps:
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
    return fired


def session_dict(batches):
    out = {}
    for b in batches:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r["sum_v"]
    return out


class TestMeshPagedSpill:
    def test_paged_is_default_and_registry_free(self, eight_device_mesh):
        eng = _engine(eight_device_mesh, max_device_slots=1024)
        assert eng.spill_layout == "pages"
        assert eng._paged
        for idx in eng.indexes:
            assert idx._track_ns is False
            assert idx._ns_slots == {}

    def test_forced_eviction_matches_single_device_oracle(
            self, eight_device_mesh):
        """1024 device slots/shard vs ~12k live sessions: every result
        must equal the unbounded single-device engine's, and the spill
        traffic must be PAGE-granular (cohorts of many rows per entry,
        not one entry per session)."""
        steps = _stream()
        mesh_eng = _engine(eight_device_mesh, max_device_slots=1024)
        single = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        d_mesh = session_dict(_run(mesh_eng, steps))
        d_single = session_dict(_run(single, steps))
        assert len(d_single) > 0
        assert set(d_mesh) == set(d_single)
        for k in d_single:
            assert d_mesh[k] == pytest.approx(d_single[k], rel=1e-4), k
        for idx in mesh_eng.indexes:
            assert idx.capacity <= 1024
        c = mesh_eng.spill_counters()
        assert c["pages_evicted"] > 0, "budget never became binding"
        assert c["pages_reloaded"] > 0, "fires never touched cold state"
        # page granularity: the unit of movement is a cohort — far
        # fewer spill entries than rows moved (one-entry-per-session
        # would make these equal)
        assert c["rows_evicted"] >= 8 * c["pages_evicted"]
        assert c["rows_reloaded"] >= c["pages_reloaded"]
        # amplification-free reloads: requested rows leave by index,
        # the cohort remainder stays put as lazy tombstones — NOTHING
        # re-bundles on the reload path
        assert c["rows_split_on_reload"] == 0
        # space comes back only through threshold compaction, and a
        # page is rewritten at most O(log rows) times — compaction
        # traffic stays well under the rows actually moved
        assert c["rows_compacted"] <= 2 * c["rows_reloaded"]

    def test_spilled_state_restores_cross_engine(self, eight_device_mesh):
        """Paged spilled rows are part of the logical snapshot: a
        budgeted mesh snapshot taken mid-run restores onto the
        single-device engine (and back onto a budgeted mesh engine) and
        finishes with the oracle's results."""
        steps = _stream(seed=23)
        cut = 4
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        d_ref = session_dict(_run(oracle, steps))

        a = _engine(eight_device_mesh, max_device_slots=1024)
        fired = _run(a, steps[:cut])
        assert a.spill_counters()["pages_evicted"] > 0
        snap = a.snapshot()
        # -> single-device (no budget), then back -> budgeted mesh
        single = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        single.restore(snap)
        snap2 = single.snapshot()
        b = _engine(eight_device_mesh, max_device_slots=1024)
        b.restore(snap2)
        fired.extend(_run(b, steps[cut:]))
        d_got = session_dict(fired)
        assert set(d_got) == set(d_ref)
        for k in d_ref:
            assert d_got[k] == pytest.approx(d_ref[k], rel=1e-4), k

    def test_delta_snapshot_covers_dirty_paged_rows(
            self, eight_device_mesh):
        """Rows dirty at eviction time have not been in any snapshot
        since — a delta must carry them from the page tier."""
        eng = _engine(eight_device_mesh, max_device_slots=1024)
        n = 10_000
        keys = np.arange(1, n + 1, dtype=np.int64)
        ts = np.zeros(n, dtype=np.int64)
        for a in range(0, n, 2000):
            eng.process_batch(keyed_batch(
                keys[a:a + 2000], np.full(2000, 1.0, dtype=np.float32),
                ts[:2000]))
        assert eng.spill_counters()["pages_evicted"] > 0
        delta = eng.snapshot(mode="delta")["table"]
        got = {(int(k), int(ns)) for k, ns in zip(delta["key_id"],
                                                  delta["namespace"])}
        # every session (resident or paged out) was dirty since start
        assert len(got) == n

    def test_query_sessions_reads_paged_state(self, eight_device_mesh):
        eng = _engine(eight_device_mesh, max_device_slots=1024)
        n = 10_000
        keys = np.arange(1, n + 1, dtype=np.int64)
        ts = np.zeros(n, dtype=np.int64)
        for a in range(0, n, 2000):
            eng.process_batch(keyed_batch(
                keys[a:a + 2000], np.full(2000, 2.0, dtype=np.float32),
                ts[:2000]))
        c0 = eng.spill_counters()
        assert c0["pages_evicted"] > 0
        # early keys paged out; the query must answer from the page
        # tier without changing residency
        for k in (1, 2, 1500):
            got = eng.query_sessions(k)
            assert got == {GAP: {"sum_v": pytest.approx(2.0)}}, k
        assert eng.spill_counters()["pages_reloaded"] == \
            c0["pages_reloaded"], "a query must not thrash residency"

    def test_pipelined_fires_match_oracle_in_content_and_order(
            self, eight_device_mesh):
        """Dispatch-ahead >= 2 + async fires under forced eviction must
        be invisible: every fired row equals the single-device oracle's,
        AND the fire sequence equals the synchronous mesh engine's —
        pipelining may not reorder or drop fires."""
        from flink_tpu.runtime.pending import PendingFire

        steps = _stream(seed=31)

        def run_async(engine):
            """Pipelined driver: fires dispatch async and harvest
            deferred/coalesced (out of step with dispatch), like the
            bench driver and the task loop."""
            pending, fired = [], []
            for keys, vals, ts, wm in steps:
                engine.process_batch(keyed_batch(keys, vals, ts))
                out = engine.on_watermark(wm, async_ok=True)
                assert all(isinstance(b, PendingFire) for b in out)
                pending.extend(out)
                # harvest lazily: keep up to 3 fires in flight across
                # batches so harvests genuinely coalesce
                while len(pending) > 3:
                    fired.append(pending.pop(0).harvest())
            fired.extend(p.harvest() for p in pending)
            return fired

        sync_eng = _engine(eight_device_mesh, max_device_slots=1024)
        async_eng = _engine(eight_device_mesh, max_device_slots=1024,
                            max_dispatch_ahead=3)
        assert async_eng.supports_async_fires
        d_sync = _run(sync_eng, steps)
        d_async = run_async(async_eng)
        # ORDER: the concatenated fire stream must match row for row
        def rows(batches):
            out = []
            for b in batches:
                out.extend(
                    (r[KEY_ID_FIELD], r["window_start"],
                     r["window_end"], round(float(r["sum_v"]), 4))
                    for r in b.to_rows())
            return out

        assert rows(d_async) == rows(d_sync)
        # CONTENT: and both equal the single-device oracle
        single = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        d_ref = session_dict(_run(single, steps))
        d_got = session_dict(d_async)
        assert len(d_ref) > 0 and set(d_got) == set(d_ref)
        for k in d_ref:
            assert d_got[k] == pytest.approx(d_ref[k], rel=1e-4), k
        c = async_eng.spill_counters()
        assert c["pages_evicted"] > 0, "budget never became binding"
        assert c["rows_split_on_reload"] == 0

    def test_explicit_namespaces_layout_still_works(
            self, eight_device_mesh):
        """spill_layout='namespaces' keeps the registry-driven eviction
        path functional and equal to the oracle."""
        steps = _stream(num_keys=4000, n_steps=6, per_step=1500)
        eng = _engine(eight_device_mesh, max_device_slots=1024,
                      spill_layout="namespaces")
        assert not eng._paged
        for idx in eng.indexes:
            assert idx._track_ns is True
        single = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        d_got = session_dict(_run(eng, steps))
        d_ref = session_dict(_run(single, steps))
        assert len(d_ref) > 0 and set(d_got) == set(d_ref)
        for k in d_ref:
            assert d_got[k] == pytest.approx(d_ref[k], rel=1e-4), k

    def test_unbudgeted_pages_layout_is_registry_free(
            self, eight_device_mesh):
        """Without a device budget the pages layout never spills, but
        the registry-free host bookkeeping (slot-addressed frees) still
        applies — per-batch host work independent of live sessions."""
        steps = _stream(num_keys=3000, n_steps=5, per_step=1000)
        eng = _engine(eight_device_mesh)
        single = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        d_got = session_dict(_run(eng, steps))
        d_ref = session_dict(_run(single, steps))
        assert len(d_ref) > 0 and set(d_got) == set(d_ref)
        for k in d_ref:
            assert d_got[k] == pytest.approx(d_ref[k], rel=1e-4), k
        for idx in eng.indexes:
            assert idx._ns_slots == {}
        assert eng.spill_counters() == {
            "pages_evicted": 0, "pages_reloaded": 0, "rows_evicted": 0,
            "rows_reloaded": 0, "rows_split_on_reload": 0,
            "rows_compacted": 0}
