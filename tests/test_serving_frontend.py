"""Multi-process serving tier (flink_tpu/tenancy/frontend.py + the shm
arena in native/hotcache.cpp): shared-memory attach semantics, the
frontend process pool's hit/miss/failover paths, cross-process seqlock
safety under a live writer, and DCN-aware lookup routing.

The contracts under test:

- an ATTACHED mapping is read-only BY ROLE: every table-write entry
  point refuses on an attached handle, and the owner's epoch word lets
  a frontend detect an owner restart and re-attach;
- frontend results are BIT-IDENTICAL to the owner's own lookup path
  (same tables, same miss resolution) — including across a frontend
  death mid-burst, which fails over to a live sibling;
- the seqlock read protocol holds ACROSS PROCESSES: reader processes
  probing while the owner mutates continuously never surface a torn
  row — every hit matches the deterministic value scheme of exactly
  one generation (verified against a dict-oracle formula, not
  wall-clock luck);
- lookup routing follows ``host_of_key_group`` under the LIVE
  key-group assignment, reassembling results in input order.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

from flink_tpu.native import hotcache_available

native = pytest.mark.skipif(not hotcache_available(),
                            reason="native hotcache unavailable")

JOB, OP = "job-a", "window_agg"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _shm_cache(tmp, max_entries=1 << 12):
    from flink_tpu.tenancy.hot_cache import make_hot_row_cache

    return make_hot_row_cache(max_entries=max_entries,
                              shm_dir=os.path.join(tmp, "shm"))


def _prime(cache, n=64, gen=1):
    keys = list(range(n))
    vals = [{0: {"count": float(k), "sum": float(k * 2 + gen)}}
            for k in keys]
    cache.put_many(JOB, OP, keys, gen, vals)
    return keys, vals


class _StubPlane:
    """The minimal owner the pool needs: a shm-backed hot cache plus a
    miss resolver standing in for the replica path (deterministic, so
    parity is assertable without a device mesh)."""

    def __init__(self, cache):
        self.hot_cache = cache
        self.miss_calls = []

    def lookup_batch(self, job, op, keys):
        self.miss_calls.append(list(keys))
        return [{"cold": float(k)} for k in keys]


# ------------------------------------------------------------ shm arena


@native
class TestShmArena:
    def test_frontend_client_bit_identical_to_owner_probe(self):
        from flink_tpu.tenancy.hot_cache_native import (
            FrontendCacheClient,
        )

        with tempfile.TemporaryDirectory() as tmp:
            cache = _shm_cache(tmp)
            keys, vals = _prime(cache)
            client = FrontendCacheClient(cache.shm_dir, frontend_id=0)
            try:
                hits, probe, misses = client.probe(
                    JOB, OP, np.asarray(keys, dtype=np.int64))
                assert hits == len(keys) and misses == []
                got = [probe.materialize(i) for i in range(len(keys))]
                # the owner's own probe, for bit-identity
                out = [None] * len(keys)
                m = []
                cache.get_many(JOB, OP, keys, 1, out, m, exact=False)
                assert got == out == vals
            finally:
                client.close()
                cache.close()

    def test_attached_handle_refuses_writes(self):
        from flink_tpu.native import load_hotcache

        with tempfile.TemporaryDirectory() as tmp:
            cache = _shm_cache(tmp)
            keys, vals = _prime(cache)
            lib = load_hotcache()
            tbl = cache._tables[(JOB, OP)]
            h = lib.hc_attach(tbl.shm_path.encode())
            assert h
            try:
                assert lib.hc_is_attached(h) == 1
                assert lib.hc_epoch(h) == tbl.epoch
                before = lib.hc_len(h)
                # every write entry point refuses by role (returns the
                # no-op value, mutates nothing)
                k = np.asarray([999], dtype=np.int64)
                g = np.asarray([5], dtype=np.int64)
                off = np.asarray([0, 1], dtype=np.int64)
                ns = np.asarray([0], dtype=np.int64)
                va = np.asarray([7], dtype=np.int64)
                tg = np.asarray([0], dtype=np.uint64)
                from flink_tpu.tenancy.hot_cache_native import (
                    _ptr_i64,
                    _u64p,
                )

                wrote = lib.hc_put_batch(
                    h, 1, _ptr_i64(k), _ptr_i64(g), _ptr_i64(off),
                    _ptr_i64(ns), _ptr_i64(va),
                    tg.ctypes.data_as(_u64p))
                assert wrote == 0
                assert lib.hc_len(h) == before
                lib.hc_clear(h)
                assert lib.hc_len(h) == before  # refused too
            finally:
                lib.hc_destroy(h)
                cache.close()

    def test_owner_restart_epoch_detected_and_reattached(self):
        from flink_tpu.tenancy.hot_cache_native import (
            FrontendCacheClient,
        )

        with tempfile.TemporaryDirectory() as tmp:
            cache = _shm_cache(tmp)
            _prime(cache, gen=1)
            client = FrontendCacheClient(cache.shm_dir, frontend_id=0)
            try:
                hits, probe, _ = client.probe(
                    JOB, OP, np.asarray([3], dtype=np.int64))
                assert hits == 1
                assert probe.materialize(0)[0]["sum"] == 7.0  # 3*2+1
                shm_dir = cache.shm_dir
                cache.close()  # owner "dies": manifest + arenas unlink

                from flink_tpu.tenancy.hot_cache import (
                    make_hot_row_cache,
                )

                cache = make_hot_row_cache(max_entries=1 << 12,
                                           shm_dir=shm_dir)
                _prime(cache, gen=2)  # restarted owner, NEW epoch
                hits, probe, _ = client.probe(
                    JOB, OP, np.asarray([3], dtype=np.int64))
                assert hits == 1
                # the client followed the manifest to the new arena:
                # it serves the restarted owner's values, not ghosts
                assert probe.materialize(0)[0]["sum"] == 8.0  # 3*2+2
            finally:
                client.close()
                cache.close()

    def test_manifest_lists_tables_and_cleans_up(self):
        from flink_tpu.tenancy.hot_cache_native import MANIFEST_NAME

        with tempfile.TemporaryDirectory() as tmp:
            cache = _shm_cache(tmp)
            _prime(cache)
            man = os.path.join(cache.shm_dir, MANIFEST_NAME)
            with open(man) as f:
                doc = json.load(f)
            rows = [r for r in doc["tables"]
                    if r["job"] == JOB and r["operator"] == OP]
            assert len(rows) == 1
            assert os.path.exists(rows[0]["path"])
            assert rows[0]["epoch"] != 0
            cache.close()
            assert not os.path.exists(man)
            assert not os.path.exists(rows[0]["path"])

    def test_shm_dir_without_native_plane_raises(self, monkeypatch):
        from flink_tpu.tenancy.hot_cache import make_hot_row_cache

        monkeypatch.setenv("FLINK_TPU_NATIVE_HOTCACHE", "0")
        with tempfile.TemporaryDirectory() as tmp:
            with pytest.raises(RuntimeError, match="shm_dir"):
                make_hot_row_cache(shm_dir=os.path.join(tmp, "shm"))


# -------------------------------------------------------- frontend pool


@native
class TestFrontendPool:
    def _pool(self, tmp, n=2):
        from flink_tpu.tenancy.frontend import FrontendPool

        cache = _shm_cache(tmp)
        plane = _StubPlane(cache)
        return FrontendPool(plane, n_frontends=n), plane, cache

    def test_hit_path_and_miss_crossing_bit_identical(self):
        with tempfile.TemporaryDirectory() as tmp:
            pool, plane, cache = self._pool(tmp)
            try:
                keys, vals = _prime(cache)
                # all-hit: answered in the frontend, no owner crossing
                out = pool.lookup_batch(JOB, OP, [3, 7, 11])
                assert out == [vals[3], vals[7], vals[11]]
                assert plane.miss_calls == []
                # mixed: misses cross once, merged in INPUT order
                out = pool.lookup_batch(JOB, OP,
                                        [1, 900, 2, 901, 3])
                assert out == [vals[1], {"cold": 900.0}, vals[2],
                               {"cold": 901.0}, vals[3]]
                assert plane.miss_calls == [[900, 901]]
                rows = cache.fe_stats(pool.n_frontends)
                tot = {k: sum(r[k] for r in rows) for k in rows[0]}
                assert tot["probes"] == 8 and tot["hits"] == 6
                assert tot["miss_crossings"] == 2
            finally:
                pool.close()
                cache.close()

    def test_dead_frontend_fails_over_to_sibling(self):
        with tempfile.TemporaryDirectory() as tmp:
            pool, plane, cache = self._pool(tmp)
            try:
                keys, vals = _prime(cache)
                pool._kill(pool._frontends[0])
                # pinned at the dead frontend: the request fails over
                out = pool.lookup_batch(JOB, OP, [8, 9], frontend=0)
                assert out == [vals[8], vals[9]]
                assert pool.failovers == 1
                assert pool.live_frontends() == [1]
                # owner and sibling unharmed: metrics + further lookups
                m = pool.metrics()
                assert m["frontends_live"] == 1.0
                assert pool.lookup_batch(JOB, OP, [5]) == [vals[5]]
            finally:
                pool.close()
                cache.close()

    def test_all_frontends_dead_fails_fast(self):
        with tempfile.TemporaryDirectory() as tmp:
            pool, plane, cache = self._pool(tmp)
            try:
                _prime(cache)
                for fe in pool._frontends:
                    pool._kill(fe)
                with pytest.raises(RuntimeError,
                                   match="no live frontend"):
                    pool.lookup_batch(JOB, OP, [1])
            finally:
                pool.close()
                cache.close()

    def test_pool_requires_shm_plane(self):
        from flink_tpu.tenancy.frontend import FrontendPool
        from flink_tpu.tenancy.hot_cache import HotRowCache

        with pytest.raises(RuntimeError, match="shm"):
            FrontendPool(_StubPlane(HotRowCache()), n_frontends=1)

    def test_drive_loop_reports_real_counters(self):
        with tempfile.TemporaryDirectory() as tmp:
            pool, plane, cache = self._pool(tmp, n=2)
            try:
                keys, _ = _prime(cache, n=128)
                res = pool.drive(JOB, OP, keys, batch=32, batches=20)
                assert len(res) == 2
                for r in res:
                    assert r["probes"] == 32 * 20
                    assert r["hits"] == r["probes"]  # pre-primed
                    assert r["wall_s"] > 0.0
                rows = cache.fe_stats(2)
                # the drive probes are REAL shm-header counters
                assert all(r["probes"] >= 32 * 20 for r in rows)
            finally:
                pool.close()
                cache.close()


# --------------------------------------- cross-process seqlock fuzzing

# Reader process body: attach, probe continuously, verify EVERY hit
# against the generation-deterministic value scheme v == g * 1e6 + key
# (both columns written under ONE seqlock stamp cycle — a torn read
# would surface as an inconsistent (g, v) pair). Reports JSON.
_READER_SRC = r"""
import json, os, sys, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from flink_tpu.tenancy.hot_cache_native import FrontendCacheClient

shm_dir, fe_id, seconds = sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
client = FrontendCacheClient(shm_dir, frontend_id=fe_id)
keys = np.arange(64, dtype=np.int64)
probes = hits = bad = 0
gens = set()
deadline = time.monotonic() + seconds
# under heavy box load the probe window can land after the writer's
# first generations — extend (bounded) until live mutation was seen
hard = deadline + 20.0
while (time.monotonic() < deadline
       or (len(gens) < 2 and time.monotonic() < hard)):
    n, probe, misses = client.probe("job-a", "window_agg", keys)
    probes += len(keys)
    hits += n
    if probe is None:
        continue
    for i in range(len(keys)):
        if not probe.hit[i]:
            continue
        row = probe.materialize(i)[0]
        g, v = row["g"], row["v"]
        gens.add(g)
        if v != g * 1_000_000.0 + float(keys[i]):
            bad += 1
client.close()
print(json.dumps({"probes": probes, "hits": hits, "bad": bad,
                  "gens": sorted(gens)}))
"""


@native
class TestCrossProcessSeqlock:
    def test_readers_never_see_torn_rows_under_live_writer(self):
        """Owner mutates CONTINUOUSLY (put_batch through the put_many
        wrapper — full-row rewrites under the seqlock) while two
        reader processes probe the same arena over shm. Zero torn
        reads: every hit's (g, v) pair satisfies the oracle formula of
        exactly one generation, and the readers observe MULTIPLE
        generations (the writer really was live under them)."""
        with tempfile.TemporaryDirectory() as tmp:
            cache = _shm_cache(tmp)
            try:
                keys = list(range(64))

                def write_gen(gen):
                    cache.put_many(
                        JOB, OP, keys, gen,
                        [{0: {"g": float(gen),
                              "v": gen * 1_000_000.0 + float(k)}}
                         for k in keys])

                write_gen(1)  # manifest + first rows exist up front
                env = dict(os.environ)
                env["PYTHONPATH"] = (
                    REPO + os.pathsep + env.get("PYTHONPATH", ""))
                env.setdefault("JAX_PLATFORMS", "cpu")
                seconds = 2.0
                readers = [
                    subprocess.Popen(
                        [sys.executable, "-c", _READER_SRC,
                         cache.shm_dir, str(fe), str(seconds)],
                        stdout=subprocess.PIPE,
                        stderr=subprocess.PIPE, env=env, text=True)
                    for fe in (1, 2)]
                # keep writing generations while the readers run —
                # bounded only as a hang backstop: on a loaded box the
                # readers' interpreter boot alone can outlast a tight
                # wall-clock budget, and a writer that stops early
                # turns the multi-generation guard into a flake
                gen = 1
                deadline = time.monotonic() + 60.0
                while (any(r.poll() is None for r in readers)
                       and time.monotonic() < deadline):
                    gen += 1
                    write_gen(gen)
                reports = []
                for r in readers:
                    out, err = r.communicate(timeout=30)
                    assert r.returncode == 0, err
                    reports.append(json.loads(out))
                for rep in reports:
                    assert rep["bad"] == 0, rep
                    assert rep["hits"] > 0, rep
                assert gen > 2  # the writer really wrote under them
                # at least one reader saw >1 generation: the probes
                # genuinely overlapped live mutation
                assert any(len(rep["gens"]) > 1 for rep in reports), \
                    (gen, reports)
                # torn RETRIES may legitimately occur; torn RESULTS
                # may not — and the retries are attributed per reader
                rows = cache.fe_stats(3)
                assert rows[1]["probes"] > 0 and rows[2]["probes"] > 0
            finally:
                cache.close()


# ------------------------------------------------------------- routing


class TestLookupRouter:
    def _router(self, fns=None, assignment=None):
        from flink_tpu.tenancy.frontend import LookupRouter

        return LookupRouter(
            num_hosts=4, local_devices=2, max_parallelism=128,
            local_host=0,
            lookup_fns=fns if fns is not None else {
                h: (lambda job, op, ks, h=h:
                    [{"host": h, "key": int(k)} for k in ks])
                for h in range(4)},
            assignment=assignment)

    def test_routes_by_owning_host_and_reassembles_in_order(self):
        r = self._router()
        keys = list(range(64))
        hosts = r.plan(keys)
        assert len(set(hosts.tolist())) > 1  # really fans out
        out = r.lookup_batch(JOB, OP, keys)
        for i, k in enumerate(keys):
            assert out[i] == {"host": int(hosts[i]), "key": k}
        m = r.metrics()
        assert m["router_local_keys"] + m["router_remote_keys"] == 64

    def test_follows_live_assignment(self):
        from flink_tpu.state.keygroups import KeyGroupAssignment

        # every group pinned to shard 7 -> host 7 // 2 == 3
        asg = KeyGroupAssignment(0, 8,
                                 np.full(128, 7, dtype=np.int32))
        r = self._router()
        r.set_assignment(asg)
        assert (r.plan(list(range(32))) == 3).all()
        out = r.lookup_batch(JOB, OP, list(range(8)))
        assert all(o["host"] == 3 for o in out)

    def test_plan_matches_host_of_key_group(self):
        from flink_tpu.state.keygroups import (
            assign_key_groups,
            hash_keys_to_i64,
            host_of_key_group,
        )

        r = self._router()
        keys = np.arange(100)
        want = host_of_key_group(
            assign_key_groups(hash_keys_to_i64(keys), 128),
            4, 2, 128)
        assert (r.plan(keys) == want).all()

    def test_missing_endpoint_raises(self):
        r = self._router(fns={0: lambda job, op, ks: [None] * len(ks)})
        with pytest.raises(KeyError, match="host"):
            r.lookup_batch(JOB, OP, list(range(64)))


# ------------------------------------------------------------- metrics


class _StubCoalescer:
    def __init__(self, n, b, ms):
        self._s = (n, b, list(ms))

    def stats_snapshot(self):
        return self._s


def test_aggregate_lookup_stats_folds_frontend_counters():
    from flink_tpu.tenancy.serving import aggregate_lookup_stats

    fe = [{"probes": 100, "hits": 90, "torn_retries": 1,
           "miss_crossings": 10},
          {"probes": 50, "hits": 40, "torn_retries": 0,
           "miss_crossings": 10}]
    s = aggregate_lookup_stats([_StubCoalescer(20, 2, (1.0, 2.0))],
                               frontend_stats=fe)
    assert s["frontend_probes"] == 150.0
    assert s["frontend_hits"] == 130.0
    assert s["frontend_torn_retries"] == 1.0
    assert s["frontend_miss_crossings"] == 20.0
    # frontend hits are served lookups that never reached a coalescer;
    # crossings DID reach one (already in the coalescer counters)
    assert s["lookups_total"] == 20 + 130
    # without frontend rows: the canonical dict, unchanged
    s2 = aggregate_lookup_stats([_StubCoalescer(20, 2, (1.0,))])
    assert s2["lookups_total"] == 20
    assert not any(k.startswith("frontend_") for k in s2)


@native
def test_serving_plane_metrics_include_frontend_counters():
    from flink_tpu.tenancy.serving import ServingPlane

    with tempfile.TemporaryDirectory() as tmp:
        plane = ServingPlane(workers=1,
                             shm_dir=os.path.join(tmp, "shm"))
        try:
            keys, vals = _prime(plane.hot_cache)
            from flink_tpu.tenancy.frontend import FrontendPool

            pool = FrontendPool(plane, n_frontends=1)
            try:
                assert pool.lookup_batch(JOB, OP, [3]) == [vals[3]]
                m = plane.metrics()
                assert m["frontend_probes"] >= 1.0
                assert m["frontend_hits"] >= 1.0
            finally:
                pool.close()
        finally:
            plane.shutdown_workers()
            plane.hot_cache.close()
