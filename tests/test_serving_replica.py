"""Read-replica serving plane: staleness semantics, snapshot isolation,
hot-row-cache priming, sharded-coalescer guarantees, chaos.

The staleness contract under test (the ISSUE's acceptance bar): a
lookup after watermark W sees state >= the last published boundary
<= W, BIT-IDENTICAL to a ``query_batch`` against a checkpoint taken at
that boundary — for window, session and join side tables, including
forced eviction (cold rows serve from the page tier through the
replica path, ``cold_rows_served`` counted).
"""

import threading

import numpy as np
import pytest

from flink_tpu.core.records import RecordBatch
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
from flink_tpu.parallel.sharded_windower import MeshWindowEngine
from flink_tpu.tenancy.hot_cache import HotRowCache
from flink_tpu.tenancy.replica import (
    SessionReplicaAdapter,
    WindowReplicaAdapter,
)
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import (
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
)

def _batch(keys, ts, vals):
    return RecordBatch({
        "__key_id__": np.asarray(keys, dtype=np.int64),
        "__ts__": np.asarray(ts, dtype=np.int64),
        "value": np.asarray(vals, dtype=np.float32),
    })


def _drive(engine, n_batches=6, keys=64, per=256, t0=0, step=700,
           wm_lag=600, rng=None):
    rng = rng or np.random.default_rng(7)
    t = t0
    wm = None
    for _ in range(n_batches):
        ks = rng.integers(0, keys, per)
        ts = t + rng.integers(0, 500, per)
        vs = rng.random(per).astype(np.float32)
        engine.process_batch(_batch(ks, ts, vs))
        t += step
        wm = t - wm_lag
        engine.on_watermark(wm)
    return t, wm


class TestWindowReplica:
    def _engine(self, assigner=None, **kw):
        return MeshWindowEngine(
            assigner or TumblingEventTimeWindows(5000),
            SumAggregate("value"), make_mesh(4),
            capacity_per_shard=kw.pop("capacity", 4096),
            max_parallelism=128, **kw)

    def test_boundary_equals_live_and_checkpoint(self):
        eng = self._engine()
        plane = eng.arm_replica()
        _drive(eng)
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(32))
        snap = eng.snapshot(mode="savepoint")
        live = eng.query_batch(np.asarray(qk, dtype=np.int64))
        repl, gen = ad.lookup_batch(qk)
        assert repl == live
        assert gen == plane.generation() >= 2
        # bit-identical to a query_batch against a checkpoint at the
        # boundary (the acceptance criterion, literally)
        fresh = self._engine()
        fresh.restore(snap)
        assert repl == fresh.query_batch(np.asarray(qk, dtype=np.int64))

    def test_snapshot_isolation_mid_batch(self):
        eng = self._engine()
        plane = eng.arm_replica()
        _, wm = _drive(eng)
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(16))
        before, gen = ad.lookup_batch(qk)
        # ingest WITHOUT a boundary: the sealed generation must not move
        eng.process_batch(_batch([1, 2, 3], [wm + 100] * 3,
                                 [9.0, 9.0, 9.0]))
        after, gen2 = ad.lookup_batch(qk)
        assert gen2 == gen and after == before
        assert eng.query_batch(np.asarray(qk, dtype=np.int64)) != before

    def test_sliding_windows_compose(self):
        eng = self._engine(assigner=SlidingEventTimeWindows(4000, 1000))
        plane = eng.arm_replica()
        _drive(eng)
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(24))
        assert ad.lookup_batch(qk)[0] == eng.query_batch(
            np.asarray(qk, dtype=np.int64))

    def test_forced_eviction_cold_slices_served(self):
        # many live slices (watermark held back), tight device budget:
        # namespaces evict; lookups must still be bit-identical, with
        # the cold detour exercised
        eng = self._engine(assigner=TumblingEventTimeWindows(500),
                           capacity=2048, max_device_slots=1024)
        plane = eng.arm_replica()
        rng = np.random.default_rng(3)
        t = 0
        for _ in range(8):
            ks = rng.integers(0, 700, 512)
            ts = t + rng.integers(0, 4000, 512)
            eng.process_batch(_batch(ks, ts,
                                     rng.random(512).astype(np.float32)))
            t += 4000
            eng.on_watermark(0)  # hold every window open
        assert eng.spill_counters()["rows_evicted"] > 0
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(0, 700, 3))
        snap = eng.snapshot(mode="savepoint")
        repl, _ = ad.lookup_batch(qk)
        assert repl == eng.query_batch(np.asarray(qk, dtype=np.int64))
        assert plane.cold_rows_served > 0
        fresh = self._engine(assigner=TumblingEventTimeWindows(500),
                             capacity=2048, max_device_slots=1024)
        fresh.restore(snap)
        assert repl == fresh.query_batch(np.asarray(qk, dtype=np.int64))

    def test_reshard_rebuild_republishes(self):
        eng = self._engine()
        plane = eng.arm_replica()
        _drive(eng, n_batches=3)
        eng.reshard(2)
        t0, _ = _drive(eng, n_batches=3, t0=3 * 700)
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(32))
        assert ad.lookup_batch(qk)[0] == eng.query_batch(
            np.asarray(qk, dtype=np.int64))


class TestSessionReplica:
    def _engine(self, gap=1000, **kw):
        return MeshSessionEngine(
            gap, SumAggregate("value"), make_mesh(4),
            capacity_per_shard=kw.pop("capacity", 4096),
            max_parallelism=128, **kw)

    def test_boundary_equals_live_and_checkpoint(self):
        eng = self._engine()
        plane = eng.arm_replica()
        _drive(eng)
        ad = SessionReplicaAdapter(plane, eng.agg)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(32))
        snap = eng.snapshot(mode="savepoint")
        live = eng.query_batch(np.asarray(qk, dtype=np.int64))
        repl, gen = ad.lookup_batch(qk)
        assert repl == live and gen >= 2
        fresh = self._engine()
        fresh.restore(snap)
        assert repl == fresh.query_batch(np.asarray(qk, dtype=np.int64))

    def test_snapshot_isolation_mid_batch(self):
        eng = self._engine()
        plane = eng.arm_replica()
        _, wm = _drive(eng)
        ad = SessionReplicaAdapter(plane, eng.agg)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(16))
        before, gen = ad.lookup_batch(qk)
        eng.process_batch(_batch([1, 2, 3], [wm + 100] * 3,
                                 [9.0, 9.0, 9.0]))
        after, gen2 = ad.lookup_batch(qk)
        assert gen2 == gen and after == before

    def test_forced_eviction_cold_sessions_served(self):
        # long gap: sessions never fire; tight budget: page cohorts
        # evict; replica lookups must stay bit-identical to live AND
        # to a checkpoint restored at the boundary
        eng = self._engine(gap=10 ** 6, capacity=2048,
                           max_device_slots=1024)
        plane = eng.arm_replica()
        rng = np.random.default_rng(9)
        t = 0
        for _ in range(8):
            ks = rng.integers(0, 20000, 2048)
            ts = t + rng.integers(0, 500, 2048)
            eng.process_batch(_batch(
                ks, ts, rng.random(2048).astype(np.float32)))
            t += 700
            eng.on_watermark(t - 600)
        assert eng.spill_counters()["rows_evicted"] > 0
        ad = SessionReplicaAdapter(plane, eng.agg)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(0, 20000, 37))
        snap = eng.snapshot(mode="savepoint")
        repl, _ = ad.lookup_batch(qk)
        assert repl == eng.query_batch(np.asarray(qk, dtype=np.int64))
        assert plane.cold_rows_served > 0
        fresh = self._engine(gap=10 ** 6, capacity=2048,
                             max_device_slots=1024)
        fresh.restore(snap)
        assert repl == fresh.query_batch(np.asarray(qk, dtype=np.int64))

    def test_restore_triggers_rebuild(self):
        eng = self._engine()
        _drive(eng, n_batches=3)
        snap = eng.snapshot(mode="savepoint")
        # crash-restore path: a FRESH engine (as _start builds) with an
        # armed replica restores, then republishes at its next boundary
        fresh = self._engine()
        plane = fresh.arm_replica()
        fresh.on_watermark(0)  # clears the arm-time rebuild flag
        fresh.restore(snap)    # must set it again
        fresh.on_watermark(3 * 700 - 600)
        ad = SessionReplicaAdapter(plane, fresh.agg)
        ad.cold_fetch = lambda ks: fresh.query_batch(
            np.asarray(ks, dtype=np.int64))
        qk = list(range(32))
        assert ad.lookup_batch(qk)[0] == fresh.query_batch(
            np.asarray(qk, dtype=np.int64))


class TestSessionEndMovePriming:
    """The session-priming invariant (r19): a session's result key —
    its END — moves as the session absorbs, and each publish primes
    the cached entry under the NEW end and deletes the stale-end entry
    in the SAME batched prime. A session absorbing across THREE publish
    boundaries must serve the correct end from the HIT path at every
    boundary (no device touch), with every stale end gone — on the
    native probe table AND the Python fallback, bit-identical to
    ``query_batch`` against a checkpoint at that boundary."""

    GAP = 1000

    def _engine(self):
        return MeshSessionEngine(
            self.GAP, SumAggregate("value"), make_mesh(4),
            capacity_per_shard=4096, max_parallelism=128)

    def _cache(self, kind):
        if kind == "native":
            from flink_tpu.native import hotcache_available

            if not hotcache_available():
                pytest.skip("native hotcache unavailable")
            from flink_tpu.tenancy.hot_cache_native import (
                NativeHotRowCache,
            )

            return NativeHotRowCache(max_entries=1 << 12)
        return HotRowCache(max_entries=1 << 12)

    @pytest.mark.parametrize("kind", ["native", "python"])
    def test_absorb_across_three_boundaries_hits_with_moving_end(
            self, kind):
        eng = self._engine()
        plane = eng.arm_replica()
        ad = SessionReplicaAdapter(plane, eng.agg)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        cache = self._cache(kind)
        ad.attach_cache(cache, "j", "op")
        seen_ends = []
        total = 0.0
        for b in range(3):
            t = 100 + b * 600  # within the gap: the SAME session absorbs
            total += 1.0
            eng.process_batch(_batch([5], [t], [1.0]))
            eng.on_watermark(t - 50)  # publish (session alive: wm < end)
            end = t + self.GAP
            hits0 = cache.hits
            hit, val = cache.get("j", "op", 5, plane.generation(),
                                 exact=False)
            # the HIT path serves the session at every boundary — the
            # old behavior invalidated on change, so boundary 2 and 3
            # would structurally miss here
            assert hit, f"boundary {b}: primed entry missing"
            assert cache.hits == hits0 + 1
            # the NEW end is the only result key: every stale end from
            # earlier boundaries was deleted in the same batched prime
            assert set(val.keys()) == {end}, \
                f"boundary {b}: stale ends {set(val) - {end}}"
            assert val[end]["sum_value"] == pytest.approx(total)
            seen_ends.append(end)
            # bit-identical to the live query AND to a checkpoint
            # restored at this boundary
            live = eng.query_batch(np.asarray([5], dtype=np.int64))
            assert val == live[0]
            fresh = self._engine()
            fresh.restore(eng.snapshot(mode="savepoint"))
            assert val == fresh.query_batch(
                np.asarray([5], dtype=np.int64))[0]
        assert len(set(seen_ends)) == 3  # the end genuinely moved
        if hasattr(cache, "close"):
            cache.close()

    @pytest.mark.parametrize("kind", ["native", "python"])
    def test_merge_removes_both_stale_ends(self, kind):
        # two disjoint sessions of one key merge when a bridging event
        # arrives: the merged entry must carry ONLY the merged end —
        # both pre-merge ends (including the one EQUAL to the absorbed
        # session's end) resolve correctly through remove-then-upsert
        eng = self._engine()
        plane = eng.arm_replica()
        ad = SessionReplicaAdapter(plane, eng.agg)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        cache = self._cache(kind)
        ad.attach_cache(cache, "j", "op")
        eng.process_batch(_batch([5, 5], [100, 1900], [1.0, 2.0]))
        eng.on_watermark(50)
        hit, val = cache.get("j", "op", 5, plane.generation(),
                             exact=False)
        assert hit and set(val.keys()) == {1100, 2900}
        eng.process_batch(_batch([5], [1000], [4.0]))  # bridges both
        eng.on_watermark(60)
        hit, val = cache.get("j", "op", 5, plane.generation(),
                             exact=False)
        assert hit, "merged session must stay on the hit path"
        assert set(val.keys()) == {2900}
        assert val[2900]["sum_value"] == pytest.approx(7.0)
        assert val == eng.query_batch(
            np.asarray([5], dtype=np.int64))[0]
        if hasattr(cache, "close"):
            cache.close()


class TestJoinSideReplica:
    def _engine(self, **kw):
        from flink_tpu.joins.engine import MeshIntervalJoinEngine

        return MeshIntervalJoinEngine(
            -2000, 2000, mesh=make_mesh(4),
            capacity_per_shard=kw.pop("capacity", 1024),
            max_parallelism=128, **kw)

    @staticmethod
    def _jbatch(rng, t, n=512, keys=800):
        ts = t + rng.integers(0, 500, n)
        return RecordBatch({
            "__key_id__": rng.integers(0, keys, n).astype(np.int64),
            "__ts__": ts.astype(np.int64),
            "price": rng.random(n).astype(np.float32),
            # int64 column: rides the host shadow in both modes
            "tag": (ts * 7 + 1).astype(np.int64),
        })

    def _drive(self, eng, rng, n=6, t0=0):
        t = t0
        for _ in range(n):
            t += 400
            eng.process_batch(self._jbatch(rng, t), 0)
            eng.process_batch(self._jbatch(rng, t), 1)
            eng.on_watermark(t - 300)
        return t

    def test_boundary_equals_live_and_checkpoint_with_eviction(self):
        eng = self._engine(max_device_slots=512)
        rng = np.random.default_rng(5)
        t = self._drive(eng, rng, n=1)
        ad = eng.arm_side_replica(1)
        ad.cold_fetch = lambda ks: eng.query_side_batch(
            1, np.asarray(ks, dtype=np.int64))
        t = self._drive(eng, rng, n=6, t0=t)
        assert eng.spill_counters()["rows_evicted"] > 0
        qk = list(range(0, 800, 3))
        snap = eng.snapshot(mode="savepoint")
        live = eng.query_side_batch(1, np.asarray(qk, dtype=np.int64))
        repl, gen = ad.lookup_batch(qk)
        assert repl == live and gen >= 2
        assert ad.plane.cold_rows_served > 0
        # checkpoint form: a fresh engine restored at the boundary
        # answers bit-identically
        fresh = self._engine(max_device_slots=512)
        fresh.restore(snap)
        assert repl == fresh.query_side_batch(
            1, np.asarray(qk, dtype=np.int64))

    def test_snapshot_isolation_mid_batch(self):
        eng = self._engine()
        rng = np.random.default_rng(6)
        t = self._drive(eng, rng, n=1)
        ad = eng.arm_side_replica(1)
        ad.cold_fetch = lambda ks: eng.query_side_batch(
            1, np.asarray(ks, dtype=np.int64))
        t = self._drive(eng, rng, n=3, t0=t)
        qk = list(range(0, 800, 7))
        before, gen = ad.lookup_batch(qk)
        eng.process_batch(self._jbatch(rng, t + 100, n=8), 1)
        after, gen2 = ad.lookup_batch(qk)
        assert gen2 == gen and after == before
        assert eng.query_side_batch(
            1, np.asarray(qk, dtype=np.int64)) != before


class TestHotCachePriming:
    def _armed(self):
        eng = MeshWindowEngine(
            TumblingEventTimeWindows(5000), SumAggregate("value"),
            make_mesh(4), capacity_per_shard=4096, max_parallelism=128)
        plane = eng.arm_replica()
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        cache = HotRowCache(max_entries=1 << 12)
        ad.attach_cache(cache, "j", "op")
        return eng, plane, ad, cache

    def test_prime_keeps_entries_true_across_publishes(self):
        eng, plane, ad, cache = self._armed()
        _drive(eng, n_batches=2)
        qk = list(range(16))
        # warm the cache through the miss path
        res, gen = ad.lookup_batch(qk)
        for k, r in zip(qk, res):
            cache.put("j", "op", k, gen, r)
        # more boundaries: the publish harvest must re-prime the
        # entries IN PLACE — a probe never touches the device and the
        # value equals the live boundary state
        _drive(eng, n_batches=3, t0=2 * 700)
        live = eng.query_batch(np.asarray(qk, dtype=np.int64))
        for k, want in zip(qk, live):
            hit, val = cache.get("j", "op", k, plane.generation(),
                                 exact=False)
            assert hit, f"key {k} should have been primed, not dropped"
            assert val == want
        assert cache.primes > 0

    def test_prime_removes_fired_windows(self):
        eng, plane, ad, cache = self._armed()
        _drive(eng, n_batches=2)
        qk = list(range(16))
        res, gen = ad.lookup_batch(qk)
        for k, r in zip(qk, res):
            cache.put("j", "op", k, gen, r)
        # fire everything: the freed slices must leave cached entries
        eng.on_watermark(10 ** 9)
        live = eng.query_batch(np.asarray(qk, dtype=np.int64))
        for k, want in zip(qk, live):
            hit, val = cache.get("j", "op", k, plane.generation(),
                                 exact=False)
            if hit:
                assert val == want  # i.e. shrunk to live state

    def test_rebuild_invalidates_op_entries(self):
        eng, plane, ad, cache = self._armed()
        _drive(eng, n_batches=2)
        cache.put("j", "op", 1, plane.generation(), {"x": 1})
        cache.put("other", "op", 1, 5, {"y": 2})
        eng.reshard(2)
        eng.on_watermark(10)  # publish -> rebuild -> invalidate
        hit, val = cache.get("j", "op", 1, plane.generation(),
                             exact=False)
        if hit:
            # the rebuild's full republish may re-insert the key — but
            # the STALE pre-rebuild value must be gone
            assert val != {"x": 1}
            assert val == eng.query_batch(
                np.asarray([1], dtype=np.int64))[0]
        # the OTHER job's entries survive
        assert cache.get("other", "op", 1, 5)[0]

    def test_put_never_downgrades(self):
        cache = HotRowCache()
        cache.put("j", "o", 1, 5, {"v": 5})
        cache.put("j", "o", 1, 4, {"v": 4})  # stale worker result
        assert cache.get("j", "o", 1, 5)[1] == {"v": 5}

    def test_lru_bound(self):
        cache = HotRowCache(max_entries=8)
        for k in range(20):
            cache.put("j", "o", k, 1, k)
        assert len(cache) == 8
        assert cache.evictions == 12


class TestReplicaPlaneRebuild:
    def test_rebuild_drops_ghost_index_entries(self):
        """A rebuild's republish must build its index FROM SCRATCH:
        carrying the sealed index forward would keep entries for keys
        that do not exist in the rebuilt (restored) state, whose stale
        slots could then address OTHER keys' rows."""
        from flink_tpu.tenancy.replica import ReplicaPlane

        class _Leaf:
            dtype = np.float32
            identity = 0.0

        plane = ReplicaPlane(make_mesh(2), [_Leaf()], 256)

        def shard(up, cold=(), freed=()):
            up = np.asarray(up, dtype=np.int64)
            return {"up_slots": up.astype(np.int32), "up_keys": up,
                    "up_ns": up, "up_extra": None, "cold": list(cold),
                    "freed": list(freed), "fresh": bool(len(up))}

        plane.publish(plane._accs, {0: shard([7]), 1: shard([])}, 10)
        assert 7 in plane.sealed.index
        plane.rebuild(plane.mesh, 256)
        # the restored state has only key 3 — key 7 must NOT survive
        plane.publish(plane._accs, {0: shard([3]), 1: shard([])}, 20)
        assert 3 in plane.sealed.index
        assert 7 not in plane.sealed.index

    def test_rebuild_republish_seals_even_when_empty(self):
        from flink_tpu.tenancy.replica import ReplicaPlane

        class _Leaf:
            dtype = np.float32
            identity = 0.0

        plane = ReplicaPlane(make_mesh(2), [_Leaf()], 256)

        def empty():
            return {"up_slots": np.zeros(0, np.int32),
                    "up_keys": np.zeros(0, np.int64),
                    "up_ns": np.zeros(0, np.int64),
                    "up_extra": None, "cold": [], "freed": [],
                    "fresh": False}

        up = np.asarray([5], dtype=np.int64)
        plane.publish(plane._accs, {0: {
            "up_slots": up.astype(np.int32), "up_keys": up,
            "up_ns": up, "up_extra": None, "cold": [], "freed": [],
            "fresh": True}, 1: empty()}, 10)
        gen = plane.generation()
        plane.rebuild(plane.mesh, 256)
        # restored-to-empty state: the republish must still seal (and
        # drop the ghost), not skip as a no-change boundary
        assert plane.publish(plane._accs, {0: empty(), 1: empty()}, 20)
        assert plane.generation() > gen
        assert 5 not in plane.sealed.index


class _FakeAdapter:
    """Deterministic adapter for coalescer-guarantee tests (no engine,
    no device)."""

    class _PlaneStub:
        def staleness_ms(self):
            return 0.0

        def generation(self):
            return 3

        def counters(self):
            return {}

    def __init__(self, short_by: int = 0, fail: bool = False):
        self._gen = 3
        self.short_by = short_by
        self.fail = fail
        self.calls = []
        self.plane = self._PlaneStub()

    def ready(self):
        return True

    def generation(self):
        return self._gen

    def key_id(self, key):
        return int(key)

    def shard_of(self, kid):
        return kid % 4

    def attach_cache(self, cache, job, op):
        pass

    def lookup_batch(self, keys):
        self.calls.append(list(keys))
        if self.fail:
            raise RuntimeError("flush exploded")
        out = [{"k": int(k)} for k in keys]
        if self.short_by:
            out = out[:-self.short_by]
        return out, self._gen


class TestShardedCoalescerGuarantees:
    """The PR 6 tenth-round coalescer guarantees, ported to the
    sharded-queue worker path so the rewrite cannot shed them."""

    def _plane(self, adapter):
        from flink_tpu.tenancy.serving import ServingPlane

        plane = ServingPlane(workers=2, window_ms=0.0)
        plane.bind_job("j", __import__("queue").Queue())
        plane._replicas[("j", "op")] = adapter
        plane._ensure_workers()
        return plane

    def test_short_result_raises_to_every_rider(self):
        plane = self._plane(_FakeAdapter(short_by=1))
        errs = []

        def rider(k):
            try:
                plane.lookup("j", "op", k)
            except RuntimeError as e:
                errs.append(str(e))

        ts = [threading.Thread(target=rider, args=(k,))
              for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(5)
        plane.shutdown_workers()
        assert len(errs) == 4
        assert all("results for" in e for e in errs)

    def test_flush_error_fans_out_and_counters_recorded(self):
        plane = self._plane(_FakeAdapter(fail=True))
        with pytest.raises(RuntimeError, match="flush exploded"):
            plane.lookup("j", "op", 7)
        m = plane.metrics()
        assert m["lookups_total"] >= 1
        assert m["lookup_batches_total"] >= 1
        plane.shutdown_workers()

    def test_retire_race_folds_into_retained_totals(self):
        plane = self._plane(_FakeAdapter())
        assert plane.lookup("j", "op", 5) == {"k": 5}
        before = plane.lookups_total()
        co = plane._pool.get(("j", "op"))
        plane.unbind_job("j")  # retires the coalescer
        # a lookup that raced the retire still records its counts
        co._record(n_lookups=3, batches=1)
        assert plane.lookups_total() == before + 3
        plane.shutdown_workers()

    def test_shard_queue_single_owner(self):
        # one (job, op, shard) queue is drained by exactly one worker:
        # the partition function is a pure hash — two enqueues for one
        # shard land on the same worker object
        plane = self._plane(_FakeAdapter())
        w1 = plane._pick_worker(("j", "op", 2))
        w2 = plane._pick_worker(("j", "op", 2))
        assert w1 is w2
        plane.shutdown_workers()

    def test_cache_hits_count_as_lookups(self):
        ad = _FakeAdapter()
        plane = self._plane(ad)
        assert plane.lookup("j", "op", 9) == {"k": 9}
        n_calls = len(ad.calls)
        # second lookup of the same key: cache hit, no adapter call
        assert plane.lookup("j", "op", 9) == {"k": 9}
        assert len(ad.calls) == n_calls
        assert plane.hot_cache.hits >= 1
        assert plane.lookups_total() >= 2
        plane.shutdown_workers()


class TestClusterReplicaServing:
    def _cluster_one_job(self, tmp_path, records=40_000,
                         interval_ms=0):
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.tenancy.session_cluster import SessionCluster

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 4096,
            "parallelism.default": 4,
            "serving.replica.publish-interval-ms": interval_ms,
        }))
        sink = CollectSink()
        (env.add_source(
            DataGenSource(total_records=records, num_keys=128,
                          events_per_second_of_eventtime=50_000,
                          seed=13),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
            .key_by("key")
            .window(TumblingEventTimeWindows.of(60_000))
            .sum("value").sink_to(sink))
        cluster = SessionCluster(quantum_records=4096)
        cluster.submit(env, "job-r")
        return cluster, sink

    def test_lookup_equals_live_query_at_boundaries(self, tmp_path):
        cluster, _ = self._cluster_one_job(tmp_path)
        assert ("job-r", "window_agg(SumAggregate)") \
            in cluster.serving._replicas
        op = cluster.jobs["job-r"].handle.stateful_operators()[0]
        rounds = 0
        checked = 0
        while cluster.step_round() and rounds < 6:
            rounds += 1
            if op.windower._replica.sealed is None:
                continue
            # between rounds the job is quiesced at a published
            # boundary: the replica lookup must equal the live query
            for key in (1, 5, 77):
                got = cluster.lookup("job-r",
                                     "window_agg(SumAggregate)", key)
                want = op.query_state_batch([key])[0]
                assert got == want
                checked += 1
        assert checked > 0
        assert cluster.serving.replica_generations() >= 2
        cluster.run(timeout_s=120)
        cluster.serving.shutdown_workers()

    def test_hot_cache_hits_and_slo_gauges(self, tmp_path):
        cluster, _ = self._cluster_one_job(tmp_path)
        op = cluster.jobs["job-r"].handle.stateful_operators()[0]
        rounds = 0
        while cluster.step_round() and rounds < 5:
            rounds += 1
            if op.windower._replica.sealed is None:
                continue
            for _ in range(3):
                cluster.lookup_batch("job-r",
                                     "window_agg(SumAggregate)",
                                     list(range(32)))
        assert cluster.serving.hot_row_hit_rate() > 0
        assert cluster.serving.replica_staleness_ms() >= 0.0
        # the SLO gauges are registered on the tenancy group
        names = {m.rsplit(".", 1)[-1]
                 for m in cluster.registry.snapshot()}
        assert {"lookupP99Ms", "replicaStalenessMs",
                "hotRowHitRate"} & names
        cluster.run(timeout_s=120)
        cluster.serving.shutdown_workers()

    def test_lookup_after_finish_raises_not_serving(self, tmp_path):
        cluster, _ = self._cluster_one_job(tmp_path, records=8192)
        cluster.run(timeout_s=120)
        with pytest.raises(RuntimeError, match="not serving"):
            cluster.lookup("job-r", "window_agg(SumAggregate)", 1)


class TestReplicaChaos:
    def test_crash_mid_publish_readers_keep_sealed_generation(self):
        """A crash INSIDE a publish (before the seal swap) leaves the
        sealed generation intact: readers keep serving it, and after
        the engine 'restores' (restore + republish) lookups never
        observe a torn replica."""
        from flink_tpu.chaos import injection as chaos
        from flink_tpu.chaos.injection import (
            FaultPlan,
            FaultRule,
            InjectedFault,
        )

        eng = MeshWindowEngine(
            TumblingEventTimeWindows(5000), SumAggregate("value"),
            make_mesh(4), capacity_per_shard=4096, max_parallelism=128)
        plane = eng.arm_replica()
        ad = WindowReplicaAdapter(plane, eng.agg, eng.assigner)
        ad.cold_fetch = lambda ks: eng.query_batch(
            np.asarray(ks, dtype=np.int64))
        t, _ = _drive(eng, n_batches=3)
        qk = list(range(16))
        sealed_before, gen = ad.lookup_batch(qk)
        snap = eng.snapshot(mode="savepoint")
        plan = FaultPlan(rules=[
            FaultRule(pattern="serving.replica_publish", nth=1)])
        with chaos.chaos_active(plan, seed=1):
            eng.process_batch(_batch([1, 2], [t, t], [5.0, 5.0]))
            with pytest.raises(InjectedFault):
                eng.on_watermark(t - 100)
        # the sealed generation survived the torn publish
        again, gen2 = ad.lookup_batch(qk)
        assert gen2 == gen and again == sealed_before
        # crash-restore: the restored engine republishes at its next
        # boundary; lookups see a consistent (restored) boundary
        eng.restore(snap)
        eng.on_watermark(t - 100)
        restored, gen3 = ad.lookup_batch(qk)
        assert gen3 > gen
        assert restored == eng.query_batch(
            np.asarray(qk, dtype=np.int64))
