"""LockSentinel + named_lock (flink_tpu/observe/lock_sentinel) and the
r24 thread-safety fix of the state-plane backend registry.

Covers: the acquisition-order graph (cycle raised AND recorded, clean
orders pass), reentrant re-acquisition recording no edge, the same-name
two-instance nesting hazard, hold-budget and contention accounting, the
no-sentinel fast path, and the backend registry's compare-and-restore
scope exit under a concurrent ``set_backend`` (the lost-override race
LCK01/LCK03 flagged before the fix)."""

import threading
import time

import pytest

from flink_tpu.observe.lock_sentinel import (
    LockOrderViolation,
    LockSentinel,
    NamedLock,
    current_sentinel,
    named_lock,
)


class TestNamedLock:
    def test_factory_returns_wrapper_with_name(self):
        lk = named_lock("t.basic")
        assert isinstance(lk, NamedLock)
        assert lk.name == "t.basic"
        assert not lk.reentrant

    def test_plain_lock_semantics_without_sentinel(self):
        assert current_sentinel() is None
        lk = named_lock("t.plain")
        with lk:
            assert lk.locked()
            assert not lk.acquire(blocking=False)
        assert not lk.locked()

    def test_reentrant_without_sentinel(self):
        lk = named_lock("t.re0", reentrant=True)
        with lk:
            with lk:
                assert lk.locked()
        assert not lk.locked()


class TestLockSentinel:
    def test_cycle_raises_and_is_recorded(self):
        a, b = named_lock("t.a"), named_lock("t.b")
        s = LockSentinel()
        with s:
            with a:
                with b:
                    pass
            with b:
                with pytest.raises(LockOrderViolation,
                                   match="lock order cycle"):
                    with a:
                        pass
        assert len(s.cycles) == 1
        assert set(s.cycles[0][0]) == {"t.a", "t.b"}
        with pytest.raises(LockOrderViolation):
            s.check()

    def test_consistent_order_is_clean(self):
        a, b = named_lock("t.c"), named_lock("t.d")
        s = LockSentinel()
        with s:
            for _ in range(3):
                with a:
                    with b:
                        pass
        s.check()
        assert s.cycles == []
        assert s.edges == {"t.c": {"t.d"}}

    def test_reentrant_reacquire_records_no_edge(self):
        lk = named_lock("t.re", reentrant=True)
        s = LockSentinel()
        with s:
            with lk:
                with lk:
                    pass
        s.check()
        assert s.edges == {}
        assert s.stats["t.re"].acquisitions == 1  # one real acquire

    def test_same_name_two_instances_nested_is_a_cycle(self):
        # two objects, one name: undefined intra-name order — the ABBA
        # hazard the 'staggered, never nested' discipline prevents
        l1, l2 = named_lock("t.same"), named_lock("t.same")
        s = LockSentinel()
        with s:
            with l1:
                with pytest.raises(LockOrderViolation,
                                   match="two instances"):
                    with l2:
                        pass
        assert s.cycles

    def test_hold_budget(self):
        lk = named_lock("t.hold")
        s = LockSentinel()
        with s:
            with lk:
                time.sleep(0.05)
        s.check()  # no budget: clean
        with pytest.raises(LockOrderViolation, match="hold budget"):
            s.check(hold_budget_s=0.01)
        s.check(hold_budget_s=10.0)

    def test_contention_is_counted(self):
        lk = named_lock("t.cont")
        s = LockSentinel()
        entered = threading.Event()

        def taker():
            entered.wait(5)
            with lk:
                pass

        with s:
            t = threading.Thread(target=taker, daemon=True)
            t.start()
            with lk:
                entered.set()
                time.sleep(0.05)  # taker parks on the held lock
            t.join(5)
        assert s.stats["t.cont"].acquisitions == 2
        assert s.stats["t.cont"].contended >= 1
        assert s.contended_locks() == ["t.cont"]
        assert s.stats["t.cont"].wait_s > 0

    def test_report_shape(self):
        a, b = named_lock("t.r1"), named_lock("t.r2")
        s = LockSentinel()
        with s:
            with a:
                with b:
                    pass
        rep = s.report()
        assert set(rep["locks"]) == {"t.r1", "t.r2"}
        assert rep["locks"]["t.r1"]["acquisitions"] == 1
        assert rep["cycles"] == []
        assert len(rep["edges"]) == 1
        assert rep["edges"][0][:2] == ["t.r1", "t.r2"]
        assert "t.r1@" in rep["edges"][0][2]  # witness carries the site

    def test_second_install_rejected_and_uninstall_clears(self):
        s1, s2 = LockSentinel(), LockSentinel()
        with s1:
            assert current_sentinel() is s1
            with pytest.raises(RuntimeError, match="already installed"):
                s2.install()
        assert current_sentinel() is None
        with s2:
            assert current_sentinel() is s2


class TestBackendRegistryThreadSafety:
    """The r24 satellite: set_backend/backend_scope/configure_backends
    share one module lock, and a scope exit must not clobber overrides
    it did not install."""

    def setup_method(self):
        from flink_tpu.stateplane import backends

        backends.set_backend("exchange-rank", "xla")

    teardown_method = setup_method

    def test_overlapping_scopes_leak_no_override(self):
        """Two threads' scopes overlap, exiting in ENTER order. The
        naive read/set/restore exit re-installed the second scope's
        stale 'prev' (= the first scope's override) after BOTH scopes
        closed; compare-and-restore leaves the default."""
        from flink_tpu.stateplane.backends import (
            backend_of,
            backend_scope,
        )

        t1_in, t1_go, t1_out = (threading.Event() for _ in range(3))
        t2_in, t2_go = threading.Event(), threading.Event()

        def first():
            with backend_scope("exchange-rank", "pallas"):
                t1_in.set()
                t1_go.wait(5)
            t1_out.set()

        def second():
            t1_in.wait(5)
            with backend_scope("exchange-rank", "pallas"):
                t2_in.set()
                t2_go.wait(5)

        a = threading.Thread(target=first, daemon=True)
        b = threading.Thread(target=second, daemon=True)
        a.start()
        b.start()
        t2_in.wait(5)       # both scopes open
        t1_go.set()         # first exits while second is still open
        t1_out.wait(5)
        t2_go.set()         # second exits last
        a.join(5)
        b.join(5)
        assert backend_of("exchange-rank") == "xla"

    def test_concurrent_set_backend_survives_scope_exit(self):
        """A set_backend racing a scope's exit wins: the exit re-checks
        that the installed override is still its own before restoring."""
        from flink_tpu.stateplane.backends import (
            backend_of,
            backend_scope,
            set_backend,
        )

        entered, release = threading.Event(), threading.Event()

        def scoped():
            with backend_scope("exchange-rank", "pallas"):
                entered.set()
                release.wait(5)

        t = threading.Thread(target=scoped, daemon=True)
        t.start()
        entered.wait(5)
        set_backend("exchange-rank", "xla")  # mid-scope override
        release.set()
        t.join(5)
        # the exit saw the override was no longer its own and did NOT
        # re-install its stale prev
        assert backend_of("exchange-rank") == "xla"

    def test_set_backend_churn_is_consistent(self):
        """Two threads hammer set_backend; every read must be a valid
        backend and the final state deterministic."""
        from flink_tpu.stateplane.backends import (
            backend_of,
            set_backend,
        )

        bad = []

        def churn(i):
            for _ in range(300):
                set_backend("exchange-rank",
                            "pallas" if i % 2 == 0 else "xla")
                got = backend_of("exchange-rank")
                if got not in ("xla", "pallas"):
                    bad.append(got)

        threads = [threading.Thread(target=churn, args=(i,),
                                    daemon=True) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert bad == []
        set_backend("exchange-rank", "xla")
        assert backend_of("exchange-rank") == "xla"
