"""Autoscaler: live key-group migration + DS2-style policy.

The reshard tests pin both mesh engines' mid-stream rescale (4 -> 8 ->
2, no stop-redeploy, paged spill under forced eviction) row-for-row to
the never-rescaled single-device oracle; the chaos test proves the
handoff stays exactly-once under an injected crash (restore from the
latest checkpoint, replay, re-rescale). The policy suite drives
hysteresis / cooldown / bounds / backlog thresholds / the skew guard
with an injectable clock — pure arithmetic, no devices.
"""

import numpy as np
import pytest

from flink_tpu.autoscale.controller import (
    AutoscaleController,
    SignalSample,
)
from flink_tpu.autoscale.policy import PolicyInput, ScalingPolicy
from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.assigners import TumblingEventTimeWindows
from flink_tpu.windowing.sessions import SessionWindower
from flink_tpu.windowing.windower import SliceSharedWindower

GAP = 100


def keyed_batch(keys, vals, ts):
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
         "v": np.asarray(vals, dtype=np.float32)},
        timestamps=np.asarray(ts, dtype=np.int64))


def _stream(num_keys=9_000, n_steps=8, per_step=4_000, seed=17):
    """Live state well past a 1024-slot/shard budget so eviction,
    reload and the reshard's resident/cold split are all on the path."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    return steps


def _run(engine, steps, reshards=None):
    """Drive steps; reshards = {step index -> shard count} applied
    BEFORE that step (mid-stream, state live)."""
    fired = []
    for i, (keys, vals, ts, wm) in enumerate(steps):
        if reshards and i in reshards:
            report = engine.reshard(reshards[i])
            assert report["to"] == reshards[i]
            assert engine.P == reshards[i]
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
    fired.extend(engine.on_watermark(1 << 60))
    out = {}
    for b in fired:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r["sum_v"]
    return out


def _assert_equal(got, expected):
    assert len(expected) > 0
    assert set(got) == set(expected)
    for k in expected:
        assert got[k] == pytest.approx(expected[k], rel=1e-4,
                                       abs=1e-3), k


def _session_engine(mesh, **kw):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

    return MeshSessionEngine(GAP, SumAggregate("v"), mesh,
                             capacity_per_shard=1 << 14, **kw)


def _window_engine(mesh, **kw):
    from flink_tpu.parallel.sharded_windower import MeshWindowEngine

    return MeshWindowEngine(TumblingEventTimeWindows.of(100),
                            SumAggregate("v"), mesh,
                            capacity_per_shard=1 << 14, **kw)


# ---------------------------------------------------------------------------
# live reshard: oracle equivalence
# ---------------------------------------------------------------------------


class TestLiveReshard:
    def test_session_paged_forced_eviction_up_and_down(self):
        """Paged spill, 1024 slots/shard vs ~9k live sessions: rescale
        4 -> 8 mid-stream, then 8 -> 2, results row-for-row equal to the
        never-rescaled single-device oracle."""
        steps = _stream()
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps, reshards={3: 8, 6: 2})
        _assert_equal(got, _run(oracle, steps))
        assert eng.reshards_completed == 2
        assert eng.P == 2
        # the handoff itself moved state both ways: some rows landed
        # resident, the overflow (2 shards x 1024 budget) went cold
        assert eng.last_reshard["rows_moved"] > 2048
        assert eng.last_reshard["spilled_rows"] > 0
        c = eng.spill_counters()
        assert c["pages_evicted"] > 0 and c["pages_reloaded"] > 0

    def test_session_namespace_layout_reshard(self):
        steps = _stream(seed=23)
        eng = _session_engine(make_mesh(4), max_device_slots=1024,
                              spill_layout="namespaces")
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps, reshards={4: 2})
        _assert_equal(got, _run(oracle, steps))
        assert eng.P == 2

    def test_window_engine_up_and_down(self):
        steps = _stream(seed=5)
        eng = _window_engine(make_mesh(4))
        oracle = SliceSharedWindower(TumblingEventTimeWindows.of(100),
                                     SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps, reshards={2: 8, 5: 2})
        _assert_equal(got, _run(oracle, steps))
        assert eng.reshards_completed == 2

    def test_window_engine_budgeted_scale_down(self):
        """Scale-down under a namespace-layout budget: whole namespaces
        either stay resident or land in the new shards' spill tiers —
        never split (a split namespace would double-apply on reload)."""
        steps = _stream(seed=5)
        eng = _window_engine(make_mesh(8), max_device_slots=2048)
        oracle = SliceSharedWindower(TumblingEventTimeWindows.of(100),
                                     SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps, reshards={4: 2})
        _assert_equal(got, _run(oracle, steps))
        for p in range(eng.P):
            resident_ns = {int(n) for n in eng.indexes[p].namespaces
                           if len(eng.indexes[p].slots_for_namespace(
                               int(n)))}
            spilled_ns = {int(n) for n in eng.spills[p].namespaces}
            assert not (resident_ns & spilled_ns)

    def test_reshard_preserves_dirty_rows_for_delta(self):
        """A reshard between two delta checkpoints must not lose the
        dirty rows: full + delta(s) across the reshard materializes to
        the same logical rows as a straight full snapshot."""
        from flink_tpu.checkpoint.storage import apply_table_delta

        steps = _stream(seed=31, n_steps=6)
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        for keys, vals, ts, wm in steps[:2]:
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        acc = dict(eng.snapshot()["table"])  # full base, dirty reset
        for keys, vals, ts, wm in steps[2:4]:
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        eng.reshard(8)  # dirty rows + freed tombstones must survive
        acc = apply_table_delta(acc, eng.snapshot(mode="delta")["table"])
        for keys, vals, ts, wm in steps[4:]:
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        eng.reshard(2)
        acc = apply_table_delta(acc, eng.snapshot(mode="delta")["table"])
        full = eng.snapshot(mode="savepoint")["table"]

        def rows(t):
            return {(int(k), int(n)): float(v) for k, n, v in
                    zip(t["key_id"], t["namespace"], t["leaf_0"])}

        assert rows(acc) == rows(full)

    def test_reshard_validation(self):
        eng = _window_engine(make_mesh(2), max_parallelism=8)
        with pytest.raises(ValueError, match="max_parallelism"):
            eng.reshard(16)
        with pytest.raises(ValueError):
            eng.reshard(0)
        report = eng.reshard(2)  # no-op
        assert report.get("noop")
        assert eng.reshards_completed == 0

    def test_reshard_keeps_counters_monotonic_and_reclaims_fs(
            self, tmp_path):
        """The job-lifetime spill counters must not reset when the mesh
        resizes, and the OLD tiers' fs-resident pages must be reclaimed
        (not orphaned) — every file on disk after the reshard belongs
        to a live tier."""
        import glob
        import os

        spill_dir = str(tmp_path / "spill")
        steps = _stream()
        # ~1KB host budget per shard (pages are ~20KB): every spilled
        # page overflows to the fs tier
        eng = _session_engine(make_mesh(4), max_device_slots=1024,
                              spill_dir=spill_dir,
                              spill_host_max_bytes=4096)
        for keys, vals, ts, wm in steps[:4]:
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        before = eng.spill_counters()
        assert before["pages_evicted"] > 0
        assert glob.glob(os.path.join(spill_dir, "**", "*.npz"),
                         recursive=True)
        eng.reshard(8)
        after = eng.spill_counters()
        for name, v in before.items():
            assert after[name] >= v, name  # monotonic across the move
        on_disk = {
            os.path.abspath(p) for p in glob.glob(
                os.path.join(spill_dir, "**", "*.npz"), recursive=True)}
        referenced = {
            os.path.abspath(path.split("://")[-1])
            for sp in eng.spills for path in sp._fs.values()}
        assert on_disk == referenced  # no orphans from the old tiers
        # and the engine still works against the fs tier afterwards
        oracle = SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)
        got = _run(eng, steps[4:])
        for keys, vals, ts, wm in steps[:4]:
            oracle.process_batch(keyed_batch(keys, vals, ts))
            oracle.on_watermark(wm)
        _assert_equal(got, _run(oracle, steps[4:]))

    def test_key_imbalance_matches_policy_definition(self):
        """One formula: the engine gauge IS the policy's skew guard."""
        from flink_tpu.autoscale.policy import key_imbalance

        eng = _session_engine(make_mesh(4))
        keys, vals, ts, wm = _stream()[0]
        eng.process_batch(keyed_batch(keys, vals, ts))
        assert eng.key_imbalance() == key_imbalance(
            eng.shard_resident_rows())
        assert ScalingPolicy.imbalance((10, 10)) == key_imbalance(
            (10, 10))

    def test_key_imbalance_gauge(self):
        eng = _session_engine(make_mesh(4))
        assert eng.key_imbalance() == 1.0  # empty = balanced
        keys, vals, ts, wm = _stream()[0]
        eng.process_batch(keyed_batch(keys, vals, ts))
        rows = eng.shard_resident_rows()
        assert sum(rows) > 0
        expected = max(rows) * len(rows) / sum(rows)
        assert eng.key_imbalance() == pytest.approx(expected)


# ---------------------------------------------------------------------------
# chaos: a crashed handoff stays exactly-once
# ---------------------------------------------------------------------------


class TestReshardUnderChaos:
    def test_mid_stream_rescale_with_crashes_is_exactly_once(
            self, tmp_path):
        """4 -> 8 -> 2 mid-stream with (1) a crash at the hardest
        handoff point (state lifted, new plane empty) and (2) a later
        engine crash: committed output stays bit-identical to the
        fault-free single-device oracle, and the harness replays
        through at least one LIVE handoff."""
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.chaos.injection import FaultPlan, FaultRule

        mesh = make_mesh(4)
        steps = _stream(num_keys=5_000, per_step=1_500)
        plan = FaultPlan(rules=[
            FaultRule(pattern="rescale.handoff", nth=2, kind="raise",
                      where={"stage": "commit"}),
            FaultRule(pattern="mesh.dispatch_fence", nth=8,
                      kind="raise"),
        ])

        def make_engine():
            return _session_engine(mesh, max_device_slots=1024)

        def make_oracle():
            return SessionWindower(GAP, SumAggregate("v"),
                                   capacity=1 << 15)

        report = run_crash_restore_verify(
            make_engine, make_oracle, steps, plan, seed=11,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2,
            rescales={2: 8, 6: 2})
        assert not report.diverged
        assert report.crashes == 2
        assert "rescale.handoff" in report.faults_injected
        assert report.live_handoffs >= 1
        assert report.restores >= 1

    def test_rescale_determinism(self, tmp_path):
        """Same (plan, seed, steps, rescales) -> identical signature."""
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.chaos.injection import FaultPlan, FaultRule

        mesh = make_mesh(4)
        steps = _stream(num_keys=3_000, per_step=800, n_steps=6)
        sigs = []
        for rep in range(2):
            plan = FaultPlan(rules=[
                FaultRule(pattern="rescale.handoff", nth=1,
                          kind="raise")])
            report = run_crash_restore_verify(
                lambda: _session_engine(mesh, max_device_slots=1024),
                lambda: SessionWindower(GAP, SumAggregate("v"),
                                        capacity=1 << 15),
                steps, plan, seed=3,
                ckpt_root=str(tmp_path / f"ckpt-{rep}"),
                checkpoint_every=2, rescales={3: 8})
            sigs.append(report.signature())
        assert sigs[0] == sigs[1]


class TestRebalanceUnderChaos:
    def test_mid_stream_rebalance_crash_at_commit_is_exactly_once(
            self, tmp_path):
        """A skew-driven key-group MOVE (unchanged P) crashed at the
        hardest point — commit: the hot range's rows are lifted off the
        old layout, the plane is rebuilt, nothing redistributed yet.
        Committed output stays bit-identical to the fault-free oracle:
        the assignment is runtime routing state, so the restored engine
        comes back contiguous and re-applies the move on replay."""
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.chaos.injection import FaultPlan, FaultRule

        mesh = make_mesh(4)
        steps = _stream(num_keys=5_000, per_step=1_500)
        plan = FaultPlan(rules=[
            FaultRule(pattern="rebalance.handoff", nth=1, kind="raise",
                      where={"stage": "commit"}),
        ])

        def move_first_groups(engine):
            cur = engine.key_group_assignment
            src = int(cur.table[0])
            groups = np.nonzero(cur.table == src)[0][:8] + cur.first
            return cur.move(groups, (src + 1) % engine.P)

        report = run_crash_restore_verify(
            lambda: _session_engine(mesh, max_device_slots=1024),
            lambda: SessionWindower(GAP, SumAggregate("v"),
                                    capacity=1 << 15),
            steps, plan, seed=11,
            ckpt_root=str(tmp_path / "ckpt"), checkpoint_every=2,
            rebalances={3: move_first_groups})
        assert not report.diverged
        assert report.crashes == 1
        assert report.faults_injected.get("rebalance.handoff", 0) == 1
        assert report.live_handoffs >= 1  # the re-applied move
        assert report.restores >= 1


# ---------------------------------------------------------------------------
# policy unit suite (injectable clock, no devices)
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _inp(cur=4, rate=1000.0, busy=0.7, backlog=0.0, growth=0.0,
         rows=()):
    return PolicyInput(current_shards=cur, processing_rate=rate,
                       busy_fraction=busy, backlog=backlog,
                       backlog_growth=growth, shard_resident_rows=rows)


class TestScalingPolicy:
    def test_no_signal_keeps(self):
        p = ScalingPolicy(clock=FakeClock())
        assert p.decide(_inp(rate=0.0)).reason == "no-signal"
        assert p.decide(_inp(busy=0.0)).reason == "no-signal"

    def test_steady_at_target_utilization(self):
        # busy == utilization target -> required == capacity * target
        p = ScalingPolicy(utilization_target=0.7, clock=FakeClock())
        d = p.decide(_inp(cur=4, rate=1000.0, busy=0.7))
        assert d.target == 4 and d.reason == "steady"

    def test_scale_up_when_saturated(self):
        # busy ~1.0: true rate == observed rate; required/target-rate
        # = 1/0.5 = 2x shards
        p = ScalingPolicy(utilization_target=0.5, hysteresis=0.25,
                          cooldown_s=0, clock=FakeClock())
        d = p.decide(_inp(cur=4, rate=1000.0, busy=1.0))
        assert d.target == 8 and d.reason == "scale-up"

    def test_backlog_growth_forces_scale_up(self):
        p = ScalingPolicy(utilization_target=0.8, hysteresis=0.1,
                          cooldown_s=0, clock=FakeClock())
        calm = p.decide(_inp(cur=4, rate=1000.0, busy=0.8))
        assert calm.reason == "steady"
        d = p.decide(_inp(cur=4, rate=1000.0, busy=0.8, growth=900.0))
        assert d.reason == "scale-up" and d.target > 4

    def test_standing_backlog_drains_within_horizon(self):
        p = ScalingPolicy(utilization_target=0.8, hysteresis=0.1,
                          cooldown_s=0, backlog_drain_s=10.0,
                          clock=FakeClock())
        # 20k backlog / 10 s = +2000 rec/s on top of 1000 arriving
        d = p.decide(_inp(cur=4, rate=1000.0, busy=0.8, backlog=20_000))
        assert d.reason == "scale-up" and d.target >= 8

    def test_hysteresis_dead_band(self):
        p = ScalingPolicy(utilization_target=0.7, hysteresis=0.3,
                          cooldown_s=0, clock=FakeClock())
        # target would be 5 (25% over 4): inside the 30% band -> stay
        d = p.decide(_inp(cur=4, rate=1000.0, busy=0.85))
        assert d.target == 4 and d.reason == "hysteresis"

    def test_cooldown_blocks_then_allows(self):
        clk = FakeClock()
        p = ScalingPolicy(utilization_target=0.5, hysteresis=0.1,
                          cooldown_s=30.0, clock=clk)
        saturated = _inp(cur=4, rate=1000.0, busy=1.0)
        assert p.decide(saturated).reason == "scale-up"
        p.mark_rescaled()
        clk.advance(10.0)
        assert p.decide(saturated).reason == "cooldown"
        clk.advance(25.0)  # past the 30 s cooldown
        assert p.decide(saturated).reason == "scale-up"

    def test_scale_down_when_idle(self):
        p = ScalingPolicy(utilization_target=0.7, hysteresis=0.25,
                          cooldown_s=0, clock=FakeClock())
        d = p.decide(_inp(cur=8, rate=1000.0, busy=0.2,
                          rows=(10, 10, 10, 10, 10, 10, 10, 10)))
        assert d.reason == "scale-down" and d.target < 8

    def test_imbalance_refuses_scale_down(self):
        """The hot shard explains the load: max/mean above the limit
        vetoes the scale-down the rate math asks for."""
        p = ScalingPolicy(utilization_target=0.7, hysteresis=0.25,
                          cooldown_s=0, imbalance_limit=2.0,
                          clock=FakeClock())
        skewed = (1000, 10, 10, 10, 10, 10, 10, 10)
        d = p.decide(_inp(cur=8, rate=1000.0, busy=0.2, rows=skewed))
        assert d.reason == "imbalance" and d.target == 8
        balanced = (100,) * 8
        d2 = p.decide(_inp(cur=8, rate=1000.0, busy=0.2, rows=balanced))
        assert d2.reason == "scale-down"

    def test_bounds_enforced_immediately(self):
        p = ScalingPolicy(min_shards=4, max_shards=8, cooldown_s=0,
                          clock=FakeClock())
        assert p.decide(_inp(cur=2, rate=0.0)).target == 4
        assert p.decide(_inp(cur=2, rate=0.0)).reason == "bounds"
        assert p.decide(_inp(cur=16, rate=0.0)).target == 8

    def test_target_clamped_to_max(self):
        p = ScalingPolicy(utilization_target=0.5, hysteresis=0.1,
                          cooldown_s=0, max_shards=6, clock=FakeClock())
        d = p.decide(_inp(cur=4, rate=1000.0, busy=1.0))  # raw target 8
        assert d.target == 6

    def test_rejects_bad_config(self):
        with pytest.raises(ValueError):
            ScalingPolicy(utilization_target=0.0)
        with pytest.raises(ValueError):
            ScalingPolicy(min_shards=4, max_shards=2)


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------


class _FakeEngine:
    def __init__(self, shards=2):
        self.P = shards
        self.calls = []

    def reshard(self, n):
        self.calls.append(n)
        old, self.P = self.P, n
        return {"from": old, "to": n, "rows_moved": 123,
                "seconds": 0.01}


class TestAutoscaleController:
    def test_differentiates_samples_and_rescales_live(self):
        clk = FakeClock()
        eng = _FakeEngine(shards=2)
        samples = iter([
            SignalSample(records_total=0, busy_ms_total=0),
            # +10k records over 10 s at 100% busy -> saturated
            SignalSample(records_total=10_000, busy_ms_total=10_000),
        ])
        ctl = AutoscaleController(
            ScalingPolicy(utilization_target=0.5, hysteresis=0.1,
                          cooldown_s=0, clock=clk),
            sample_fn=lambda: next(samples), engine=eng,
            interval_s=0.0, clock=clk)
        assert ctl.tick() is None  # first sample: no rate yet
        clk.advance(10.0)
        event = ctl.tick()
        assert event is not None and event.mode == "live"
        assert eng.calls == [4]  # 2 shards at 100% busy, target 0.5
        assert ctl.live_handoffs == 1
        assert event.rows_moved == 123

    def test_interval_gates_ticks(self):
        clk = FakeClock()
        calls = []

        def sample():
            calls.append(1)
            return SignalSample()

        ctl = AutoscaleController(
            ScalingPolicy(clock=clk), sample_fn=sample,
            engine=_FakeEngine(), interval_s=5.0, clock=clk)
        ctl.tick()
        clk.advance(1.0)
        ctl.tick()  # inside the interval: not even sampled
        assert len(calls) == 1
        clk.advance(5.0)
        ctl.tick()
        assert len(calls) == 2

    def test_cold_path_via_job(self):
        clk = FakeClock()

        class FakeJob:
            current_parallelism = 2

            def __init__(self):
                self.requests = []

            def request_rescale(self, n):
                self.requests.append(n)
                self.current_parallelism = n
                return True

        job = FakeJob()
        samples = iter([SignalSample(0, 0),
                        SignalSample(10_000, 10_000)])
        ctl = AutoscaleController(
            ScalingPolicy(utilization_target=0.5, hysteresis=0.1,
                          cooldown_s=0, clock=clk),
            sample_fn=lambda: next(samples), job=job,
            interval_s=0.0, clock=clk)
        ctl.tick()
        clk.advance(10.0)
        event = ctl.tick()
        assert event is not None and event.mode == "cold"
        assert job.requests == [4]

    def test_refused_cold_rescale_does_not_burn_cooldown(self):
        clk = FakeClock()

        class RefusingJob:
            current_parallelism = 2

            def request_rescale(self, n):
                return False  # e.g. no checkpointing configured

        samples = iter([SignalSample(0, 0),
                        SignalSample(10_000, 10_000)])
        policy = ScalingPolicy(utilization_target=0.5, hysteresis=0.1,
                               cooldown_s=60.0, clock=clk)
        ctl = AutoscaleController(
            policy, sample_fn=lambda: next(samples), job=RefusingJob(),
            interval_s=0.0, clock=clk)
        ctl.tick()
        clk.advance(10.0)
        assert ctl.tick() is None
        assert not policy.in_cooldown()
        assert ctl.events == []

    def test_requires_exactly_one_mechanism(self):
        with pytest.raises(ValueError):
            AutoscaleController(ScalingPolicy(),
                                sample_fn=SignalSample)
        with pytest.raises(ValueError):
            AutoscaleController(ScalingPolicy(), sample_fn=SignalSample,
                                engine=_FakeEngine(),
                                job=object())
        with pytest.raises(TypeError):
            AutoscaleController(ScalingPolicy(), sample_fn=SignalSample,
                                engine=object())

    def test_live_rescale_through_controller_matches_oracle(self):
        """End-to-end: the controller's bounds convergence drives a REAL
        mesh engine 4 -> 8 live, mid-stream, and the stream finishes
        oracle-identical."""
        clk = FakeClock()
        steps = _stream(num_keys=4_000, per_step=1_500, n_steps=6)
        eng = _session_engine(make_mesh(4), max_device_slots=1024)
        ctl = AutoscaleController(
            ScalingPolicy(min_shards=8, max_shards=8, cooldown_s=0,
                          clock=clk),
            sample_fn=lambda: SignalSample(), engine=eng,
            interval_s=0.0, clock=clk)
        fired = []
        for i, (keys, vals, ts, wm) in enumerate(steps):
            clk.advance(1.0)
            ctl.tick()
            eng.process_batch(keyed_batch(keys, vals, ts))
            fired.extend(eng.on_watermark(wm))
        fired.extend(eng.on_watermark(1 << 60))
        got = {}
        for b in fired:
            for r in b.to_rows():
                got[(r[KEY_ID_FIELD], r["window_start"],
                     r["window_end"])] = r["sum_v"]
        oracle = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        _assert_equal(got, _run(oracle, steps))
        assert eng.P == 8
        assert ctl.live_handoffs == 1  # converged once, then steady


# ---------------------------------------------------------------------------
# skew guard surface: refusal counter + gauges + rebalancer hand-off
# ---------------------------------------------------------------------------


class TestSkewGuardSurface:
    def test_policy_counts_refusals_and_records_imbalance(self):
        from flink_tpu.autoscale.policy import key_imbalance

        p = ScalingPolicy(utilization_target=0.7, hysteresis=0.25,
                          cooldown_s=0, imbalance_limit=2.0,
                          clock=FakeClock())
        skewed = (1000, 10, 10, 10, 10, 10, 10, 10)
        assert p.skew_guard_refusals == 0
        d = p.decide(_inp(cur=8, rate=1000.0, busy=0.2, rows=skewed))
        assert d.reason == "imbalance"
        assert p.skew_guard_refusals == 1
        assert p.last_imbalance == key_imbalance(skewed)
        assert p.last_imbalance > 2.0
        # a balanced decision does not bump the counter but refreshes
        # the measured imbalance gauge value
        p.decide(_inp(cur=8, rate=1000.0, busy=0.2, rows=(100,) * 8))
        assert p.skew_guard_refusals == 1
        assert p.last_imbalance == 1.0

    def test_controller_exports_skew_gauges_and_fires_hook(self):
        """The refusal count and the measured imbalance are pinned on
        the job metric tree (autoscale group), and the refusal hands
        the PolicyInput to the on_imbalance hook exactly once per
        refusing tick."""
        from flink_tpu.metrics.core import MetricRegistry

        clk = FakeClock()
        seen = []
        samples = iter([
            SignalSample(records_total=0, busy_ms_total=0),
            # +10k records / 10 s at 20% busy on 8 shards -> the rate
            # math wants a scale-down; the skewed resident rows veto it
            SignalSample(records_total=10_000, busy_ms_total=2_000,
                         shard_resident_rows=(1000, 10, 10, 10,
                                              10, 10, 10, 10)),
        ])
        ctl = AutoscaleController(
            ScalingPolicy(utilization_target=0.7, hysteresis=0.25,
                          cooldown_s=0, imbalance_limit=2.0, clock=clk),
            sample_fn=lambda: next(samples), engine=_FakeEngine(shards=8),
            interval_s=0.0, clock=clk, on_imbalance=seen.append)
        reg = MetricRegistry()
        ctl.register_metrics(reg.root_group("job"))
        assert ctl.tick() is None
        clk.advance(10.0)
        assert ctl.tick() is None  # refused: no rescale event
        assert len(seen) == 1
        assert isinstance(seen[0], PolicyInput)
        snap = reg.snapshot()
        assert snap["job.autoscale.skew_guard_refusals"] == 1
        assert snap["job.autoscale.key_imbalance"] == pytest.approx(
            1000 * 8 / 1070)
        assert snap["job.autoscale.last_decision"] == "imbalance"


# ---------------------------------------------------------------------------
# executor + minicluster integration
# ---------------------------------------------------------------------------


class TestExecutorAutoscale:
    def _run_job(self, conf_extra, total=30_000):
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 1024,
            "parallelism.default": 2,
            **conf_extra,
        }))
        sink = CollectSink()
        (env.add_source(
            DataGenSource(total_records=total, num_keys=40,
                          events_per_second_of_eventtime=20_000),
            WatermarkStrategy.for_bounded_out_of_orderness(0))
         .key_by("key").window(TumblingEventTimeWindows.of(1000))
         .count().sink_to(sink))
        result = env.execute("autoscale-job")
        return {(int(r["key"]), int(r["window_start"])): int(r["count"])
                for r in sink.rows()}, result

    def test_enabled_autoscale_converges_to_bounds_and_matches(self):
        """A job deployed at parallelism 2 with min-shards pinned to 4
        live-rescales on the first policy tick (bounds convergence, no
        stop-redeploy) and still produces the exact baseline results."""
        baseline, _ = self._run_job({})
        scaled, result = self._run_job({
            "autoscale.enabled": True,
            "autoscale.interval-ms": 0,
            "autoscale.cooldown-ms": 0,
            "autoscale.min-shards": 4,
            "autoscale.max-shards": 4,
        })
        assert len(baseline) > 0
        assert scaled == baseline
        auto = result.metrics.get("autoscale")
        assert auto is not None and auto["live_handoffs"] >= 1
        assert auto["path"][0] == (2, 4)

    def test_disabled_autoscale_adds_no_metrics(self):
        _, result = self._run_job({}, total=5_000)
        assert "autoscale" not in result.metrics

    def test_bounds_clamped_to_engine_limits(self):
        """min/max-shards far beyond the visible devices must be
        clamped at setup — a policy allowed to target 64 shards would
        crash the task loop with reshard()'s ValueError."""
        baseline, _ = self._run_job({}, total=10_000)
        scaled, result = self._run_job({
            "autoscale.enabled": True,
            "autoscale.interval-ms": 0,
            "autoscale.cooldown-ms": 0,
            "autoscale.min-shards": 64,
            "autoscale.max-shards": 64,
        }, total=10_000)
        auto = result.metrics.get("autoscale")
        assert auto is not None
        assert auto["path"][0] == (2, 8)  # clamped to the 8 devices
        assert scaled == baseline


class TestMiniclusterColdRescale:
    def test_request_rescale_redeploys_from_checkpoint(self, tmp_path):
        """The controller's cold path: request_rescale() retargets the
        stage parallelism and the adaptive supervision loop redeploys
        from the latest checkpoint without consuming restart budget."""
        import time

        from flink_tpu.cluster.minicluster import (
            FINISHED,
            RUNNING,
            MiniCluster,
        )
        from flink_tpu.connectors.sinks import JsonLinesFileSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        class SlowDataGen(DataGenSource):
            def poll_batch(self, max_records):
                b = super().poll_batch(max_records)
                if b is not None:
                    time.sleep(0.01)
                return b

        ck = str(tmp_path / "ck")
        out = str(tmp_path / "o.jsonl")
        total = 40_000
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 256,
                "jobmanager.scheduler": "adaptive",
                "state.checkpoints.dir": ck,
                "execution.checkpointing.every-n-source-batches": 2,
            }))
            (env.add_source(
                SlowDataGen(total_records=total, num_keys=5,
                            events_per_second_of_eventtime=4000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
             .key_by("key").window(TumblingEventTimeWindows.of(500))
             .count().sink_to(JsonLinesFileSink(out)))
            client = cluster.submit(env, "cold-rescale")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if client.status()["status"] == RUNNING:
                    break
                time.sleep(0.02)
            time.sleep(0.3)  # let checkpoints land
            jm = cluster.dispatcher.master(client.job_id)
            assert jm.request_rescale(2) is True
            assert jm.current_parallelism == 2
            st = client.wait(timeout=60)
            assert st["status"] == FINISHED
            assert st["attempt"] >= 1  # redeployed, budget untouched
            states = [h["state"] for h in st["state_history"]]
            assert "RESTARTING" in states
            rows = JsonLinesFileSink.read_rows(out)
            per_window = {}
            for r in rows:  # refires overwrite earlier partials
                per_window[(int(r["key"]), int(r["window_start"]))] = \
                    int(r["count"])
            assert sum(per_window.values()) == total
        finally:
            cluster.shutdown()

    def test_request_rescale_refused_without_checkpointing(self,
                                                           tmp_path):
        import time

        from flink_tpu.cluster.minicluster import RUNNING, MiniCluster
        from flink_tpu.connectors.sinks import JsonLinesFileSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        class SlowDataGen(DataGenSource):
            def poll_batch(self, max_records):
                b = super().poll_batch(max_records)
                if b is not None:
                    time.sleep(0.01)
                return b

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 256,
                "jobmanager.scheduler": "adaptive",
            }))
            (env.add_source(
                SlowDataGen(total_records=20_000, num_keys=5,
                            events_per_second_of_eventtime=4000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
             .key_by("key").window(TumblingEventTimeWindows.of(500))
             .count().sink_to(JsonLinesFileSink(
                 str(tmp_path / "o.jsonl"))))
            client = cluster.submit(env, "no-ckpt-rescale")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if client.status()["status"] == RUNNING:
                    break
                time.sleep(0.02)
            jm = cluster.dispatcher.master(client.job_id)
            # no checkpointing: a redeploy would replay from record 0
            # and double-emit — the request must be refused
            assert jm.request_rescale(2) is False
            client.wait(timeout=60)
        finally:
            cluster.shutdown()
