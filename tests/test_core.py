import numpy as np
import pytest

from flink_tpu.core.config import (
    BatchOptions,
    ConfigOption,
    Configuration,
    CoreOptions,
)
from flink_tpu.core.records import RecordBatch, TIMESTAMP_FIELD


class TestConfiguration:
    def test_defaults_and_set(self):
        c = Configuration()
        assert c.get(CoreOptions.DEFAULT_PARALLELISM) == 1
        c.set(CoreOptions.DEFAULT_PARALLELISM, 8)
        assert c.get(CoreOptions.DEFAULT_PARALLELISM) == 8

    def test_type_coercion(self):
        c = Configuration({"parallelism.default": "4"})
        assert c.get(CoreOptions.DEFAULT_PARALLELISM) == 4
        b = ConfigOption("b", default=False, type=bool)
        assert Configuration({"b": "true"}).get(b) is True
        assert Configuration({"b": "off"}).get(b) is False

    def test_fallback_keys(self):
        opt = ConfigOption("new.key", default=7, type=int,
                           fallback_keys=("old.key",))
        assert Configuration({"old.key": 3}).get(opt) == 3
        assert Configuration({"new.key": 5, "old.key": 3}).get(opt) == 5

    def test_layering(self):
        cluster = Configuration({"a": 1, "b": 2})
        job = Configuration({"b": 3})
        merged = job.with_fallback(cluster)
        assert merged.get_raw("a") == 1
        assert merged.get_raw("b") == 3
        assert merged.to_dict() == {"a": 1, "b": 3}


class TestRecordBatch:
    def test_roundtrip(self):
        b = RecordBatch.from_pydict(
            {"k": [1, 2, 3], "v": [1.0, 2.0, 3.0]}, timestamps=[10, 20, 30])
        assert len(b) == 3
        assert b.has_timestamps
        np.testing.assert_array_equal(b.timestamps, [10, 20, 30])
        rows = b.to_rows()
        assert rows[1]["v"] == 2.0

    def test_filter_take_concat(self):
        b = RecordBatch.from_pydict({"v": np.arange(10)})
        f = b.filter(b["v"] % 2 == 0)
        assert f["v"].tolist() == [0, 2, 4, 6, 8]
        t = b.take(np.array([3, 1]))
        assert t["v"].tolist() == [3, 1]
        c = RecordBatch.concat([f, t])
        assert c["v"].tolist() == [0, 2, 4, 6, 8, 3, 1]

    def test_length_mismatch_raises(self):
        with pytest.raises(ValueError):
            RecordBatch({"a": np.arange(3), "b": np.arange(4)})

    def test_empty(self):
        e = RecordBatch({})
        assert len(e) == 0
        assert RecordBatch.concat([]).num_records == 0
