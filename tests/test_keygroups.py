import numpy as np

from flink_tpu.state.keygroups import (
    assign_key_groups,
    all_ranges,
    compute_key_group_range,
    hash_keys_to_i64,
    key_group_to_operator_index,
    murmur_fmix32,
)


def test_murmur_deterministic_and_spreading():
    h = murmur_fmix32(np.arange(1000))
    h2 = murmur_fmix32(np.arange(1000))
    np.testing.assert_array_equal(h, h2)
    # avalanche: consecutive ints spread across the space
    assert len(np.unique(h % 128)) > 100


def test_assign_key_groups_in_range():
    groups = assign_key_groups(np.arange(10000, dtype=np.int64), 128)
    assert groups.min() >= 0 and groups.max() < 128
    # roughly uniform
    counts = np.bincount(groups, minlength=128)
    assert counts.min() > 0


def test_ranges_partition_all_groups():
    """Subtask ranges must partition [0, max_parallelism) exactly —
    the reference's rescale contract (KeyGroupRangeAssignment.java)."""
    for mp, p in [(128, 1), (128, 8), (128, 5), (130, 8), (7, 3)]:
        ranges = all_ranges(mp, p)
        covered = []
        for r in ranges:
            covered.extend(range(r.start, r.end + 1))
        assert covered == list(range(mp)), (mp, p)


def test_group_to_operator_consistent_with_ranges():
    mp, p = 128, 8
    groups = np.arange(mp)
    owners = key_group_to_operator_index(groups, mp, p)
    for i in range(p):
        r = compute_key_group_range(mp, p, i)
        for g in range(r.start, r.end + 1):
            assert owners[g] == i


def test_hash_keys_stable_for_strings():
    a = hash_keys_to_i64(np.array(["alpha", "beta", "alpha"], dtype=object))
    assert a[0] == a[2]
    assert a[0] != a[1]
    b = hash_keys_to_i64(np.array(["alpha", "beta", "alpha"], dtype=object))
    np.testing.assert_array_equal(a, b)


def test_hash_keys_ints_passthrough():
    k = np.array([5, -3, 5], dtype=np.int64)
    np.testing.assert_array_equal(hash_keys_to_i64(k), k)
