import numpy as np
import pytest

from flink_tpu.state.keygroups import (
    KeyGroupAssignment,
    assign_key_groups,
    all_ranges,
    compute_key_group_range,
    hash_keys_to_i64,
    host_of_key_group,
    key_group_to_operator_index,
    murmur_fmix32,
)


def test_murmur_deterministic_and_spreading():
    h = murmur_fmix32(np.arange(1000))
    h2 = murmur_fmix32(np.arange(1000))
    np.testing.assert_array_equal(h, h2)
    # avalanche: consecutive ints spread across the space
    assert len(np.unique(h % 128)) > 100


def test_assign_key_groups_in_range():
    groups = assign_key_groups(np.arange(10000, dtype=np.int64), 128)
    assert groups.min() >= 0 and groups.max() < 128
    # roughly uniform
    counts = np.bincount(groups, minlength=128)
    assert counts.min() > 0


def test_ranges_partition_all_groups():
    """Subtask ranges must partition [0, max_parallelism) exactly —
    the reference's rescale contract (KeyGroupRangeAssignment.java)."""
    for mp, p in [(128, 1), (128, 8), (128, 5), (130, 8), (7, 3)]:
        ranges = all_ranges(mp, p)
        covered = []
        for r in ranges:
            covered.extend(range(r.start, r.end + 1))
        assert covered == list(range(mp)), (mp, p)


def test_group_to_operator_consistent_with_ranges():
    mp, p = 128, 8
    groups = np.arange(mp)
    owners = key_group_to_operator_index(groups, mp, p)
    for i in range(p):
        r = compute_key_group_range(mp, p, i)
        for g in range(r.start, r.end + 1):
            assert owners[g] == i


def test_hash_keys_stable_for_strings():
    a = hash_keys_to_i64(np.array(["alpha", "beta", "alpha"], dtype=object))
    assert a[0] == a[2]
    assert a[0] != a[1]
    b = hash_keys_to_i64(np.array(["alpha", "beta", "alpha"], dtype=object))
    np.testing.assert_array_equal(a, b)


def test_hash_keys_ints_passthrough():
    k = np.array([5, -3, 5], dtype=np.int64)
    np.testing.assert_array_equal(hash_keys_to_i64(k), k)


# ---------------------------------------------------------------------------
# KeyGroupAssignment — explicit (possibly non-contiguous) routing table
# ---------------------------------------------------------------------------


def test_contiguous_assignment_matches_shard_records():
    """The default table IS the reference formula — threading an
    assignment through the data plane must be a routing no-op until a
    move happens. Bit-for-bit, full-range and sub-range."""
    from flink_tpu.parallel.shuffle import shard_records

    keys = np.arange(20_000, dtype=np.int64) * 977
    a = KeyGroupAssignment.contiguous(8, 128)
    np.testing.assert_array_equal(
        a.shard_of_keys(keys, 128), shard_records(keys, 8, 128))
    assert a.is_contiguous
    # sub-range engine (mesh x stage composition)
    sub = KeyGroupAssignment.contiguous(4, 128, (32, 63))
    groups = assign_key_groups(keys, 128)
    sel = (groups >= 32) & (groups <= 63)
    np.testing.assert_array_equal(
        sub.shard_of_keys(keys[sel], 128),
        shard_records(keys[sel], 4, 128, key_group_range=(32, 63)))


def test_move_runs_and_contiguity():
    a = KeyGroupAssignment.contiguous(4, 16)
    assert a.span == 16 and a.is_contiguous
    assert a.runs() == [(0, 3, 0), (4, 7, 1), (8, 11, 2), (12, 15, 3)]
    b = a.move([1, 2], 3)
    # immutably derived: the original is untouched
    assert a.is_contiguous and not b.is_contiguous
    assert b.runs() == [(0, 0, 0), (1, 2, 3), (3, 3, 0), (4, 7, 1),
                        (8, 11, 2), (12, 15, 3)]
    np.testing.assert_array_equal(b.groups_of_shard(3),
                                  [1, 2, 12, 13, 14, 15])
    np.testing.assert_array_equal(b.shard_of_groups([0, 1, 2, 3]),
                                  [0, 3, 3, 0])


def test_assignment_validation():
    with pytest.raises(ValueError):
        KeyGroupAssignment(0, 4, np.array([], dtype=np.int32))
    with pytest.raises(ValueError):
        KeyGroupAssignment(0, 4, np.array([0, 4], dtype=np.int32))
    with pytest.raises(ValueError):
        KeyGroupAssignment(0, 0, np.array([0], dtype=np.int32))
    a = KeyGroupAssignment.contiguous(4, 16)
    with pytest.raises(ValueError):
        a.move([16], 0)  # out of the global range


def test_host_of_key_group_follows_assignment():
    """Serving-side host routing must track the live table — a moved
    group's lookups land on the mover's host."""
    mp, hosts, local = 32, 2, 2
    groups = np.arange(mp)
    base = host_of_key_group(groups, hosts, local, mp)
    a = KeyGroupAssignment.contiguous(hosts * local, mp)
    np.testing.assert_array_equal(
        base, host_of_key_group(groups, hosts, local, mp, assignment=a))
    moved = a.move([0], hosts * local - 1)  # shard 3 -> host 1
    routed = host_of_key_group(groups, hosts, local, mp, assignment=moved)
    assert routed[0] == 1 and base[0] == 0
    np.testing.assert_array_equal(routed[1:], base[1:])
