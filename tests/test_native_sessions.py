"""Native session-metadata plane (native/sessions.cpp via
flink_tpu/windowing/session_native.py).

The acceptance discipline: the native plane and the pure-Python plane
must be BIT-IDENTICAL in everything observable — fires (values, order,
dtypes), snapshots (including row order), spill counters (residency
evolution) — under forced paged eviction; crash-restore-verify must
hold with the native plane on the engine; and snapshot/restore must
rebuild the native interval index exactly (the slotmap restore
discipline). Plus the loader's stale-.so defense: a cached ``_*.so``
is invalidated by a source-hash stamp, so editing the ``.cpp`` can
never load yesterday's binary — even when mtimes lie.
"""

import os
import shutil
import subprocess
import tempfile

import numpy as np
import pytest

from flink_tpu.native import sessions_available

needs_native = pytest.mark.skipif(
    not sessions_available(), reason="native sessions library not built")
needs_gxx = pytest.mark.skipif(
    shutil.which("g++") is None, reason="no C++ compiler")

GAP = 100


def _planes():
    from flink_tpu.windowing.session_meta import SessionIntervalSet
    from flink_tpu.windowing.session_native import (
        NativeSessionIntervalSet,
    )

    return SessionIntervalSet, NativeSessionIntervalSet


def _mesh_engine(mesh, plane: str, spill_dir=None):
    """A paged, budget-bound mesh engine with the requested metadata
    plane swapped in explicitly (both planes in ONE process — the env
    knob only selects the default)."""
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
    from flink_tpu.windowing.aggregates import SumAggregate

    py_cls, nat_cls = _planes()
    eng = MeshSessionEngine(
        GAP, SumAggregate("v"), mesh, capacity_per_shard=2048,
        max_device_slots=2048,
        spill_dir=spill_dir or tempfile.mkdtemp())
    eng.meta = (nat_cls if plane == "native" else py_cls)(GAP, 0)
    return eng


def _traffic(step, rng, n=3000, num_keys=50_000):
    from flink_tpu.core.records import (
        KEY_ID_FIELD,
        TIMESTAMP_FIELD,
        RecordBatch,
    )

    keys = rng.integers(0, num_keys, n).astype(np.int64)
    ts = (step * 70 + rng.integers(0, 200, n)).astype(np.int64)
    return RecordBatch({KEY_ID_FIELD: keys,
                        "v": np.ones(n, dtype=np.float32),
                        TIMESTAMP_FIELD: ts})


def _assert_fires_equal(fa, fb):
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        assert sorted(x.columns) == sorted(y.columns)
        for c in x.columns:
            va, vb = np.asarray(x.columns[c]), np.asarray(y.columns[c])
            assert va.dtype == vb.dtype
            np.testing.assert_array_equal(va, vb, err_msg=c)


# ------------------------------------------------------- metadata parity


@needs_native
class TestMetadataPlaneParity:
    def test_absorb_pop_fuzz_parity(self):
        """200 mixed batches at heavy key collision (exercises the
        multi-interval slow path, merges, stale records, extensions):
        sessionization, sid allocation, merge groups, pops and
        snapshots all bit-identical across planes."""
        py_cls, nat_cls = _planes()
        rng = np.random.default_rng(0)
        py, nat = py_cls(GAP, 10), nat_cls(GAP, 10)
        fired = 0
        for step in range(200):
            n = int(rng.integers(1, 400))
            keys = rng.integers(0, 50, n).astype(np.int64)
            ts = (step * 80 + rng.integers(0, 300, n)).astype(np.int64)
            rp = py.absorb_batch_ex(keys, ts)
            rn = nat.absorb_batch_ex(keys, ts)
            for name in ("sess_key", "sess_sid", "rec_to_sess", "order"):
                np.testing.assert_array_equal(
                    getattr(rp, name), getattr(rn, name), err_msg=name)
            # the native fresh set is a SUBSET (slow-path creations
            # probe conservatively — same state, never a wrong skip)
            assert np.all(~rn.fresh | rp.fresh)
            assert len(rp.groups) == len(rn.groups)
            for gp, gn in zip(rp.groups, rn.groups):
                assert gp.sids_dst == gn.sids_dst
                assert gp.sids_src == gn.sids_src
                assert gp.absorbed_sids == gn.absorbed_sids
            if step % 3 == 2:
                pp = py.pop_fired_ex(step * 80)
                pn = nat.pop_fired_ex(step * 80)
                for name in ("keys", "starts", "ends", "sids"):
                    np.testing.assert_array_equal(
                        getattr(pp, name), getattr(pn, name),
                        err_msg=name)
                fired += len(pp.keys)
            assert py._next_sid == nat._next_sid
            assert py.max_fired_watermark == nat.max_fired_watermark
        pp, pn = py.pop_fired_ex(1 << 60), nat.pop_fired_ex(1 << 60)
        for name in ("keys", "starts", "ends", "sids"):
            np.testing.assert_array_equal(getattr(pp, name),
                                          getattr(pn, name))
        assert fired + len(pp.keys) > 0
        assert py.snapshot() == nat.snapshot()

    def test_mesh_engines_bit_identical_under_forced_eviction(
            self, eight_device_mesh, tmp_path):
        """The acceptance pin: mesh engine on the native plane vs the
        Python plane vs the single-device oracle, with the live session
        set far beyond the device budget (paged eviction + reload + the
        hybrid fire genuinely on the path). Fires are bit-identical
        row-for-row, spill counters equal (identical residency
        evolution — the fold-verify path may skip probes but never
        changes hits/misses), snapshots bit-identical including row
        order."""
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.sessions import SessionWindower

        rng = np.random.default_rng(7)
        a = _mesh_engine(eight_device_mesh, "native",
                         str(tmp_path / "sp-a"))
        b = _mesh_engine(eight_device_mesh, "python",
                         str(tmp_path / "sp-b"))
        oracle = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        from flink_tpu.windowing.session_native import (
            NativeSessionIntervalSet,
        )

        assert isinstance(a.meta, NativeSessionIntervalSet)
        assert not isinstance(b.meta, NativeSessionIntervalSet)
        fa, fb, fo = [], [], []
        for step in range(20):
            batch = _traffic(step, rng, n=4000, num_keys=60_000)
            a.process_batch(batch)
            b.process_batch(batch)
            oracle.process_batch(batch)
            wm = step * 70
            fa.extend(a.on_watermark(wm))
            fb.extend(b.on_watermark(wm))
            fo.extend(oracle.on_watermark(wm))
        fa.extend(a.on_watermark(1 << 60))
        fb.extend(b.on_watermark(1 << 60))
        fo.extend(oracle.on_watermark(1 << 60))
        _assert_fires_equal(fa, fb)
        assert a.spill_counters() == b.spill_counters()
        assert a.spill_counters()["rows_evicted"] > 0  # not vacuous

        def totals(fires):
            out = {}
            for f in fires:
                cols = f.columns
                names = sorted(cols)
                for i in range(len(f)):
                    row = tuple(np.asarray(cols[n])[i].item()
                                for n in names if n != "sum_v")
                    out[row] = out.get(row, 0.0) + float(
                        np.asarray(cols["sum_v"])[i])
            return out

        assert totals(fa) == totals(fo)  # oracle equivalence
        sa, sb = a.snapshot(), b.snapshot()
        assert sa["sessions"] == sb["sessions"]
        assert sa["next_sid"] == sb["next_sid"]
        assert sorted(sa["table"]) == sorted(sb["table"])
        for k in sa["table"]:
            np.testing.assert_array_equal(
                np.asarray(sa["table"][k]), np.asarray(sb["table"][k]),
                err_msg=k)

    def test_restore_rebuilds_native_index_exactly(
            self, eight_device_mesh, tmp_path):
        """The slotmap restore discipline applied to the metadata
        plane: snapshot a live native engine mid-stream, restore into a
        FRESH native engine and a fresh Python-plane engine, continue
        the stream on both — fires and final snapshots stay
        bit-identical, proving the native interval index (singles
        store, multi membership, fire candidates) was rebuilt
        exactly."""
        rng = np.random.default_rng(11)
        src = _mesh_engine(eight_device_mesh, "native",
                           str(tmp_path / "src"))
        for step in range(8):
            src.process_batch(_traffic(step, rng))
            src.on_watermark(step * 70)
        snap = src.snapshot()
        nat = _mesh_engine(eight_device_mesh, "native",
                           str(tmp_path / "nat"))
        py = _mesh_engine(eight_device_mesh, "python",
                          str(tmp_path / "py"))
        nat.restore(snap)
        py.restore(snap)
        assert nat.meta.snapshot() == py.meta.snapshot()
        rng2 = np.random.default_rng(12)
        fa, fb = [], []
        for step in range(8, 16):
            batch = _traffic(step, rng2)
            nat.process_batch(batch)
            py.process_batch(batch)
            fa.extend(nat.on_watermark(step * 70))
            fb.extend(py.on_watermark(step * 70))
        fa.extend(nat.on_watermark(1 << 60))
        fb.extend(py.on_watermark(1 << 60))
        _assert_fires_equal(fa, fb)
        assert nat.snapshot()["sessions"] == py.snapshot()["sessions"]

    def test_fold_verification_rejects_stale_hints(self):
        """A folded slot is a pure cache: verification against the
        state index's own metadata takes a hint iff the index maps
        exactly that pair at that slot — absent, reused and
        out-of-range hints all fall back to -1 (the probe path)."""
        from flink_tpu.state.slot_table import (
            make_slot_index,
            verify_slot_hints,
        )

        idx = make_slot_index(1024)
        keys = np.array([5, 6, 7], dtype=np.int64)
        nss = np.array([50, 60, 70], dtype=np.int64)
        slots = idx.lookup_or_insert(keys, nss)
        ok = verify_slot_hints(idx, keys, nss, slots)
        np.testing.assert_array_equal(ok, slots)
        # free one pair: its hint must now fail verification
        idx.free_slots(slots[1:2], keys=keys[1:2], nss=nss[1:2])
        after = verify_slot_hints(idx, keys, nss, slots)
        assert after[0] == slots[0] and after[2] == slots[2]
        assert after[1] == -1
        # wrong-pair and out-of-range hints fail; -1 passes through
        bogus = np.array([int(slots[2]), 1 << 20, -1], dtype=np.int32)
        out = verify_slot_hints(idx, keys, nss, bogus)
        assert list(out) == [-1, -1, -1]

    def test_env_knob_selects_python_plane(self, monkeypatch):
        from flink_tpu.windowing.session_meta import (
            SessionIntervalSet,
            make_session_meta,
        )
        from flink_tpu.windowing.session_native import (
            NativeSessionIntervalSet,
        )

        assert isinstance(make_session_meta(GAP),
                          NativeSessionIntervalSet)
        monkeypatch.setenv("FLINK_TPU_NATIVE_SESSIONS", "0")
        meta = make_session_meta(GAP)
        assert isinstance(meta, SessionIntervalSet)
        assert not isinstance(meta, NativeSessionIntervalSet)

    def test_single_device_windower_parity(self):
        """SessionWindower (the single-device engine) drives the same
        absorb -> stage -> fire flow through the plane: fires and
        snapshots bit-identical across planes with a bounded paged
        table (hints exercised on resolve AND fire)."""
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.sessions import SessionWindower

        py_cls, nat_cls = _planes()

        def make(plane):
            w = SessionWindower(
                GAP, SumAggregate("v"), capacity=2048,
                spill={"max_device_slots": 2048,
                       "spill_dir": tempfile.mkdtemp()})
            w.meta = (nat_cls if plane == "native" else py_cls)(GAP, 0)
            return w

        a, b = make("native"), make("python")
        rng = np.random.default_rng(3)
        fa, fb = [], []
        for step in range(15):
            batch = _traffic(step, rng, n=1500, num_keys=20_000)
            a.process_batch(batch)
            b.process_batch(batch)
            fa.extend(a.on_watermark(step * 70))
            fb.extend(b.on_watermark(step * 70))
        fa.extend(a.on_watermark(1 << 60))
        fb.extend(b.on_watermark(1 << 60))
        _assert_fires_equal(fa, fb)
        assert a.spill_counters() == b.spill_counters()
        assert a.spill_counters()["rows_evicted"] > 0


# ------------------------------------------------------ chaos coverage


@needs_native
class TestNativePlaneChaos:
    def test_crash_restore_verify_on_native_plane(
            self, eight_device_mesh, tmp_path):
        """Crash-restore-verify with the NATIVE metadata plane driving
        the engine (the default when compiled): crashes at a session
        fire and inside a page reload, restore from the latest complete
        checkpoint, replay — committed output equals the fault-free
        oracle exactly and the run is seed-deterministic. The restore
        path rebuilds the native interval index from the snapshot
        (mirroring the slotmap restore discipline) — a divergence here
        is exactly a mis-rebuilt index."""
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.chaos.injection import FaultPlan, FaultRule
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.aggregates import SumAggregate
        from flink_tpu.windowing.session_native import (
            NativeSessionIntervalSet,
        )
        from flink_tpu.windowing.sessions import SessionWindower

        def make_engine():
            eng = MeshSessionEngine(
                GAP, SumAggregate("v"), eight_device_mesh,
                capacity_per_shard=1 << 14, max_device_slots=1024)
            # the native plane must actually be on the engine — a
            # compiler-less environment would silently test the
            # Python plane (needs_native guards, this asserts)
            assert isinstance(eng.meta, NativeSessionIntervalSet)
            return eng

        def make_oracle():
            return SessionWindower(GAP, SumAggregate("v"),
                                   capacity=1 << 15)

        rng = np.random.default_rng(17)
        steps = []
        for s in range(8):
            keys = rng.integers(0, 6000, 1500).astype(np.int64)
            vals = rng.random(1500).astype(np.float32)
            ts = rng.integers(s * 80, s * 80 + 60, 1500).astype(np.int64)
            steps.append((keys, vals, ts, (s - 1) * 80))
        plan = FaultPlan(rules=[
            FaultRule(pattern="mesh.session_fire", nth=4),
            FaultRule(pattern="spill.page_reload", nth=5),
        ])

        def run(tag):
            return run_crash_restore_verify(
                make_engine, make_oracle, steps, plan, seed=23,
                ckpt_root=str(tmp_path / f"ckpt-{tag}"),
                checkpoint_every=2)

        r1 = run("a")
        assert not r1.diverged and r1.windows > 0
        assert r1.crashes >= 1 and r1.restores >= 1
        r2 = run("b")
        assert r2.signature() == r1.signature()


# ---------------------------------------------------- stale-.so defense


@needs_gxx
class TestSourceHashStamp:
    SRC_V1 = 'extern "C" { long probe_value() { return 111; } }\n'
    SRC_V2 = 'extern "C" { long probe_value() { return 222; } }\n'

    def test_source_hash_invalidates_cached_so(self, tmp_path,
                                               monkeypatch):
        """Editing the .cpp can never load yesterday's binary: the
        cached artifact is stamped with the source sha256, and a
        mismatch rebuilds EVEN WHEN the mtimes are identical (git
        checkouts and copies routinely produce exactly that lie)."""
        import ctypes

        import flink_tpu.native as native

        root = tmp_path
        (root / "native").mkdir()
        monkeypatch.setattr(native, "_REPO_ROOT", str(root))
        monkeypatch.setattr(native, "_BUILD_DIR",
                            str(root / "native" / "build"))
        src = root / "native" / "probe.cpp"
        src.write_text(self.SRC_V1)
        lib = native.load_native("probe.cpp", "_probe.so")
        assert lib is not None
        lib.probe_value.restype = ctypes.c_long
        lib.probe_value.argtypes = []
        assert lib.probe_value() == 111
        so = root / "native" / "build" / "_probe.so"
        stamp = root / "native" / "build" / "_probe.so.srchash"
        assert so.exists() and stamp.exists()
        stamp_v1 = stamp.read_text()
        old_stat = src.stat()
        # rewrite the source, then FORGE the old timestamps — an
        # mtime-based check would serve the stale binary
        src.write_text(self.SRC_V2)
        os.utime(src, ns=(old_stat.st_atime_ns, old_stat.st_mtime_ns))
        # drop the v1 handle: dlopen dedupes same-path libraries while
        # a handle is alive (the stamp's job is cross-PROCESS
        # staleness; within one process the loaders cache anyway)
        import _ctypes

        handle = lib._handle
        del lib
        _ctypes.dlclose(handle)
        lib2 = native.load_native("probe.cpp", "_probe.so")
        assert stamp.read_text() != stamp_v1  # rebuilt, not served stale
        lib2.probe_value.restype = ctypes.c_long
        lib2.probe_value.argtypes = []
        assert lib2.probe_value() == 222
        # and a missing stamp (stampless artifact of unknown
        # provenance) also forces a rebuild rather than trusting it
        stamp.unlink()
        assert native.load_native("probe.cpp", "_probe.so") is not None
        assert stamp.exists()

    def test_disabled_env_returns_none(self, tmp_path, monkeypatch):
        import flink_tpu.native as native

        monkeypatch.setenv("FLINK_TPU_NATIVE", "0")
        assert native.load_native("slotmap.cpp", "_slotmap.so") is None
        monkeypatch.delenv("FLINK_TPU_NATIVE")
        monkeypatch.setenv("FLINK_TPU_NO_NATIVE", "1")
        assert native.load_native("slotmap.cpp", "_slotmap.so") is None
