"""Standalone deployment: JobManager + remote TaskExecutors over gRPC
(flink_tpu/cluster/standalone.py).

reference parity: StandaloneSessionClusterEntrypoint + TaskManagerRunner —
workers register with a ResourceManager they reach over the network, jobs
deploy to whichever worker offers a slot, heartbeats ride the same RPC.

The first tests run JM and TEs in ONE test process but on SEPARATE
RpcServices/ports (every interaction crosses real gRPC); the last test
boots a TaskExecutor in a genuinely separate OS process.
"""

import json
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
from flink_tpu.cluster.standalone import TaskExecutorRunner, remote_submit
from flink_tpu.connectors.sinks import JsonLinesFileSink
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def _pipeline(env, out_path, total=20_000):
    (env.add_source(
        DataGenSource(total_records=total, num_keys=50,
                      events_per_second_of_eventtime=10_000),
        WatermarkStrategy.for_bounded_out_of_orderness(0))
     .key_by("key").window(TumblingEventTimeWindows.of(2000))
     .sum("value").sink_to(JsonLinesFileSink(str(out_path))))


def _wait(dispatcher, job_id, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = dispatcher.job_status(job_id)
        if st["status"] in ("FINISHED", "FAILED", "CANCELED"):
            return st
        time.sleep(0.2)
    raise TimeoutError(dispatcher.job_status(job_id))


class TestStandaloneCluster:
    def test_job_runs_on_remote_taskexecutor(self, tmp_path):
        jm = MiniCluster(Configuration({"cluster.task-executors": 0}))
        te = None
        try:
            # no workers yet: the RM has nothing to offer
            assert jm.rm_gateway().executor_registry() == {}
            te = TaskExecutorRunner(
                jm.service.address,
                Configuration({"heartbeat.interval-ms": 100})).start()
            reg = jm.rm_gateway().executor_registry()
            assert te.executor_id in reg
            assert reg[te.executor_id]["address"] == te.address
            assert te.address != jm.service.address  # separate server

            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 2048}))
            out = tmp_path / "out.jsonl"
            _pipeline(env, out)
            job_id, dispatcher = remote_submit(jm.service.address, env,
                                               "standalone-job")
            st = _wait(dispatcher, job_id)
            assert st["status"] == FINISHED, st
            rows = JsonLinesFileSink.read_rows(str(out))
            assert sum(1 for _ in rows) > 0
            # heartbeats flowed to the remote worker
            time.sleep(0.5)
            reg = jm.rm_gateway().executor_registry()
            assert reg[te.executor_id]["heartbeat_age_s"] < 5
        finally:
            if te is not None:
                te.stop()
            jm.shutdown()

    def test_rest_lists_remote_executor(self, tmp_path):
        jm = MiniCluster(Configuration({"cluster.task-executors": 0}))
        te = None
        try:
            te = TaskExecutorRunner(jm.service.address).start()
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{jm.rest_port}/taskexecutors").read())
            ids = [e.get("executor_id") for e in body["executors"]]
            assert te.executor_id in ids
            entry = [e for e in body["executors"]
                     if e.get("executor_id") == te.executor_id][0]
            assert entry["address"] == te.address
        finally:
            if te is not None:
                te.stop()
            jm.shutdown()

    def test_worker_death_detected_and_job_fails_over(self, tmp_path):
        """Kill the remote worker mid-job: the JobMaster must detect the
        dead executor and redeploy on a surviving one from the latest
        checkpoint."""
        jm = MiniCluster(Configuration({
            "cluster.task-executors": 0,
            "heartbeat.interval-ms": 100,
            "heartbeat.timeout-ms": 1000,
        }))
        te1 = te2 = None
        try:
            te1 = TaskExecutorRunner(
                jm.service.address,
                Configuration({"heartbeat.interval-ms": 100})).start()
            te2 = TaskExecutorRunner(
                jm.service.address,
                Configuration({"heartbeat.interval-ms": 100})).start()
            env = StreamExecutionEnvironment(Configuration({
                "execution.micro-batch.size": 256,
                "execution.checkpointing.every-n-source-batches": 4,
                "state.checkpoints.dir": str(tmp_path / "ckpt"),
                "restart-strategy.fixed-delay.attempts": 3,
                "restart-strategy.fixed-delay.delay-ms": 100,
            }))
            out = tmp_path / "out.jsonl"
            _pipeline(env, out, total=200_000)
            job_id, dispatcher = remote_submit(jm.service.address, env,
                                               "failover-job")
            # wait until the job lands on a worker, then kill that worker
            deadline = time.time() + 30
            victim = None
            while time.time() < deadline and victim is None:
                for runner in (te1, te2):
                    if runner.endpoint._tasks:
                        victim = runner
                        break
                time.sleep(0.05)
            assert victim is not None, "job never deployed"
            victim.service.stop()  # hard kill: no dead-mark courtesy call
            st = _wait(dispatcher, job_id, timeout=120)
            assert st["status"] == FINISHED, st
            assert st["attempt"] >= 1  # it really failed over
        finally:
            for runner in (te1, te2):
                if runner is not None:
                    try:
                        runner.stop()
                    except Exception:
                        pass
            jm.shutdown()


class TestTrueMultiProcess:
    def test_taskexecutor_subprocess(self, tmp_path):
        jm = MiniCluster(Configuration({"cluster.task-executors": 0}))
        proc = None
        try:
            code = (
                "import os\n"
                "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
                "import jax\n"
                "jax.config.update('jax_platforms', 'cpu')\n"
                "from flink_tpu.cluster.standalone import "
                "TaskExecutorRunner\n"
                f"r = TaskExecutorRunner({jm.service.address!r})\n"
                "r.start()\n"  # registered BEFORE announcing readiness
                "print('READY', r.address, flush=True)\n"
                "import time\n"
                "while True: time.sleep(3600)\n"
            )
            proc = subprocess.Popen(
                [sys.executable, "-c", code], stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True)
            line = proc.stdout.readline()
            assert line.startswith("READY"), (line, proc.stderr.read())

            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 2048}))
            out = tmp_path / "out.jsonl"
            _pipeline(env, out, total=10_000)
            job_id, dispatcher = remote_submit(jm.service.address, env,
                                               "xproc-job")
            # generous deadline: the worker subprocess cold-imports jax
            # and may compile under full-suite load
            st = _wait(dispatcher, job_id, timeout=240)
            assert st["status"] == FINISHED, st
            assert sum(1 for _ in
                       JsonLinesFileSink.read_rows(str(out))) > 0
        finally:
            if proc is not None:
                proc.terminate()
            jm.shutdown()
