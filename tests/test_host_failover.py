"""Host-granular failure domains: the watchdog's HOST escalation level,
multi-shard evacuation (``lose_shards``), and the end-to-end host-loss
failover — a lost HOST (one process's contiguous slice of shards) means
"lose k shard units, restore k units, replay one contiguous range",
bit-identical to the fault-free oracle and seed-deterministic.

Runs single-process on virtual topologies (2x2 / 2x4 over CPU devices);
tools/multiproc_smoke.py drives the same protocol across REAL process
boundaries (kill 1 of 2 processes) — these tests keep the escalation
ladder and the evacuation/restore/replay machinery in plain tier-1.
"""

import numpy as np
import pytest

from flink_tpu.chaos.harness import run_shard_loss_verify
from flink_tpu.chaos.injection import FaultPlan, FaultRule
from flink_tpu.parallel.mesh import HostTopology, make_mesh
from flink_tpu.runtime.watchdog import (
    DeviceWatchdog,
    HostFailedError,
    MeshStalledError,
    ShardFailedError,
)
from flink_tpu.windowing.aggregates import SumAggregate

GAP = 100


def _steps(n_steps=8, per_step=800, num_keys=3000, seed=17):
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.random(per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        out.append((keys, vals, ts, (s - 1) * 80))
    return out


def _mk_session_engine(shards=8, slots=1024, topology=HostTopology(2, 4)):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

    return MeshSessionEngine(
        GAP, SumAggregate("v"), make_mesh(shards),
        capacity_per_shard=1 << 14, max_device_slots=slots,
        max_dispatch_ahead=2, host_topology=topology)


def _mk_session_oracle():
    from flink_tpu.windowing.sessions import SessionWindower

    return SessionWindower(GAP, SumAggregate("v"), capacity=1 << 15)


def _host_loss_plan(host=1, nth=6):
    return FaultPlan(rules=[
        FaultRule(pattern="host.lost", nth=nth, where={"host": host})])


# ----------------------------------------------------- watchdog ladder


class TestHostEscalation:
    def _wd(self, hosts=2, local=2, **kw):
        wd = DeviceWatchdog(hosts * local, **kw)
        wd.set_topology(HostTopology(hosts, local))
        return wd

    def test_uniform_one_host_streak_declares_the_host(self):
        t = [0.0]
        wd = self._wd(deadline_ms=10, max_misses=2,
                      clock=lambda: t[0])
        # per-shard sections: ONLY host 1's shards (2, 3) miss
        for _ in range(2):
            for p in (2, 3):
                with wd.section("op", shard=p):
                    t[0] += 0.05
            for p in (0, 1):
                with wd.section("op", shard=p):
                    t[0] += 0.001
        with pytest.raises(HostFailedError) as ei:
            wd.boundary_probe()
        assert ei.value.host == 1
        assert ei.value.shards == (2, 3)
        assert wd.quarantined == {2, 3}
        assert wd.hosts_declared_dead == 1

    def test_partial_host_streak_stays_shard_granular(self):
        t = [0.0]
        wd = self._wd(deadline_ms=10, max_misses=2,
                      clock=lambda: t[0])
        # only ONE of host 1's shards misses — a wedged chip, not a
        # lost process: the shard, not the host, is declared
        for _ in range(2):
            with wd.section("op", shard=3):
                t[0] += 0.05
            for p in (0, 1, 2):
                with wd.section("op", shard=p):
                    t[0] += 0.001
        with pytest.raises(ShardFailedError) as ei:
            wd.boundary_probe()
        assert not isinstance(ei.value, HostFailedError)
        assert ei.value.shard == 3
        assert wd.quarantined == {3}

    def test_streak_spilling_outside_one_host_stays_shard_granular(
            self):
        # shards 0, 1 AND 2 miss (host 0 fully + half of host 1):
        # mixed attribution contradicts the lost-process signature —
        # no host is declared, the first offender shard is
        t = [0.0]
        wd = self._wd(deadline_ms=10, max_misses=2,
                      clock=lambda: t[0])
        for _ in range(2):
            for p in (0, 1, 2):
                with wd.section("op", shard=p):
                    t[0] += 0.05
            with wd.section("op", shard=3):
                t[0] += 0.001
        with pytest.raises(ShardFailedError) as ei:
            wd.boundary_probe()
        assert not isinstance(ei.value, HostFailedError)
        assert wd.hosts_declared_dead == 0
        assert wd.quarantined == {ei.value.shard}

    def test_whole_mesh_streak_is_still_a_stall(self):
        # EVERY live shard misses: no host attribution either — the
        # honest escalation stays the whole-job MeshStalledError
        t = [0.0]
        wd = self._wd(deadline_ms=10, max_misses=2,
                      clock=lambda: t[0])
        for _ in range(2):
            with wd.section("op"):  # whole-mesh SPMD section
                t[0] += 0.05
        with pytest.raises(MeshStalledError):
            wd.boundary_probe()
        assert not wd.quarantined

    def test_rebind_to_new_size_clears_stale_topology(self):
        wd = self._wd()
        wd.rebind(3)  # survivors after a loss: 2x2 no longer applies
        assert wd._topology is None

    def test_set_topology_validates_coverage(self):
        wd = DeviceWatchdog(4)
        with pytest.raises(ValueError, match="does not cover"):
            wd.set_topology(HostTopology(2, 4))


# ------------------------------------------------------- evacuation


class TestLoseShards:
    def test_contiguity_enforced(self):
        eng = _mk_session_engine()
        with pytest.raises(ValueError, match="contiguous"):
            eng.lose_shards([1, 3])

    def test_whole_mesh_loss_refused(self):
        eng = _mk_session_engine()
        with pytest.raises(ValueError, match="whole mesh"):
            eng.lose_shards(list(range(8)))

    def test_host_slice_evacuates_in_one_pass(self):
        from tests.test_sessions import keyed_batch

        eng = _mk_session_engine()
        steps = _steps(n_steps=3)
        for keys, vals, ts, wm in steps:
            eng.process_batch(keyed_batch(keys, vals, ts))
            eng.on_watermark(wm)
        topo = eng.host_topology
        dead = list(topo.shards_of_host(1))
        ranges = eng.shard_key_groups()
        want = (ranges[dead[0]][0], ranges[dead[-1]][1])
        g0, g1 = eng.lose_shards(dead)
        assert (g0, g1) == want
        assert eng.P == 4
        # the stale 2x4 factorization dropped with the dead host
        assert eng.host_topology is None
        info = eng.last_shard_loss
        assert info["dead_shards"] == dead
        assert info["survivor_rows"] > 0


# ------------------------------------------------- end-to-end failover


class TestHostLossVerify:
    def test_session_engine_host_loss_oracle_identical(self, tmp_path):
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(),
            _host_loss_plan(), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.hosts_lost == 1
        assert report.shards_lost == 4  # the whole host's slice
        assert report.shard_restores == 1
        # bounded replay: HALF the key space (one of two hosts), only
        # since its units' checkpoint position — never the whole stream
        assert 0 < report.records_replayed <= report.events // 2
        assert report.shard_loss_recovery_ms > 0

    def test_forced_eviction_stays_on_the_path(self, tmp_path):
        holder = {}

        def mk():
            holder["eng"] = _mk_session_engine(slots=1024)
            return holder["eng"]

        report = run_shard_loss_verify(
            mk, _mk_session_oracle,
            _steps(num_keys=9000, per_step=2000),
            _host_loss_plan(), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=2)
        assert not report.diverged
        assert report.hosts_lost == 1
        assert holder["eng"].spill_counters()["rows_evicted"] > 0

    def test_seed_deterministic_signature(self, tmp_path):
        sigs = []
        for i in range(2):
            r = run_shard_loss_verify(
                _mk_session_engine, _mk_session_oracle, _steps(),
                _host_loss_plan(), seed=7,
                ckpt_root=str(tmp_path / f"c{i}"), checkpoint_every=2)
            sigs.append(r.signature())
        assert sigs[0] == sigs[1]
        assert sigs[0]["hosts_lost"] == 1

    def test_window_engine_host_loss(self, tmp_path):
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )
        from flink_tpu.windowing.windower import SliceSharedWindower

        def mk_engine():
            return MeshWindowEngine(
                TumblingEventTimeWindows.of(50), SumAggregate("v"),
                make_mesh(8), capacity_per_shard=1 << 14,
                host_topology=HostTopology(2, 4))

        def mk_oracle():
            return SliceSharedWindower(
                TumblingEventTimeWindows.of(50), SumAggregate("v"),
                capacity=1 << 15)

        report = run_shard_loss_verify(
            mk_engine, mk_oracle, _steps(), _host_loss_plan(),
            seed=11, ckpt_root=str(tmp_path / "c"),
            checkpoint_every=2)
        assert not report.diverged
        assert report.hosts_lost == 1
        assert report.shards_lost == 4

    def test_host_loss_before_first_checkpoint_replays_cold(
            self, tmp_path):
        report = run_shard_loss_verify(
            _mk_session_engine, _mk_session_oracle, _steps(),
            _host_loss_plan(nth=2), seed=7,
            ckpt_root=str(tmp_path / "c"), checkpoint_every=4)
        assert not report.diverged
        assert report.hosts_lost == 1
