"""Skew detection + rebalance planning — pure host-side arithmetic.

ShardLoadAccountant turns routed key columns into EWMA per-key-group
load estimates (plus a Misra-Gries hot-key sketch); RebalancePolicy
scores greedy group moves against them with hysteresis and cooldown.
No devices anywhere in this file — everything runs on an injectable
clock, the same idiom as the scaling-policy suite.
"""

import numpy as np
import pytest

from flink_tpu.autoscale import RebalancePolicy
from flink_tpu.parallel.load import ShardLoadAccountant, busy_from_flight
from flink_tpu.state.keygroups import (
    KeyGroupAssignment,
    assign_key_groups,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _keys_for_group(group, max_parallelism, n, start=0):
    """n distinct key ids that murmur into ``group``."""
    out = []
    k = start
    while len(out) < n:
        if int(assign_key_groups(np.array([k]), max_parallelism)[0]) == group:
            out.append(k)
        k += 1
    return np.array(out, dtype=np.int64)


class TestShardLoadAccountant:
    def test_group_counts_then_ewma_rates(self):
        clk = FakeClock()
        acc = ShardLoadAccountant(4, 16, ewma_alpha=0.5, clock=clk)
        hot = _keys_for_group(3, 16, 5)
        acc.note_batch(np.repeat(hot, 20))  # 100 records into group 3
        # before any differentiating tick: raw counts
        assert acc.group_load()[3] == 100
        acc.tick()
        clk.advance(10.0)
        acc.note_batch(np.repeat(hot, 20))
        acc.tick()
        # first differentiated rate: 100 / 10 s
        assert acc.group_load()[3] == pytest.approx(10.0)
        clk.advance(10.0)
        acc.tick()  # nothing arrived: EWMA halves toward zero
        assert acc.group_load()[3] == pytest.approx(5.0)
        assert acc.hottest_group() == 3
        assert acc.ticks == 3 and acc.records_seen == 200

    def test_imbalance_through_proposed_assignment(self):
        """The point of the accountant: score a move BEFORE applying
        it. Piling load onto shard 0's groups shows imbalance under
        the contiguous layout and ~balance under the fixed table."""
        acc = ShardLoadAccountant(4, 16, clock=FakeClock())
        for g, n in [(0, 300), (1, 300), (4, 100), (8, 100), (12, 100)]:
            acc.note_batch(np.repeat(_keys_for_group(g, 16, 1), n))
        cur = KeyGroupAssignment.contiguous(4, 16)
        before = acc.imbalance(cur)
        assert before == pytest.approx(600 * 4 / 900)
        fixed = cur.move([1], 3)  # hot group 1 off the hot shard
        assert acc.imbalance(fixed) < before
        np.testing.assert_allclose(
            acc.shard_load(fixed), [300, 100, 100, 400])

    def test_hot_key_sketch_flags_dominant_key(self):
        acc = ShardLoadAccountant(4, 16, top_k=4, clock=FakeClock())
        hot = _keys_for_group(5, 16, 1)[0]
        cold = np.arange(1000, 1200, dtype=np.int64)
        acc.note_batch(np.concatenate([np.full(800, hot, dtype=np.int64),
                                       cold]))
        cands = acc.hot_key_candidates()
        assert cands and cands[0][0] == int(hot)
        assert cands[0][1] == 5
        assert cands[0][2] > 0.9  # the key IS its group's load

    def test_register_metrics_skew_group(self):
        from flink_tpu.metrics.core import MetricRegistry

        acc = ShardLoadAccountant(4, 16, clock=FakeClock())
        acc.note_batch(np.repeat(_keys_for_group(0, 16, 1), 50))
        reg = MetricRegistry()
        acc.register_metrics(reg.root_group("job"))
        snap = reg.snapshot()
        assert snap["job.skew.records_seen"] == 50
        assert snap["job.skew.hottest_group"] == 0
        assert snap["job.skew.hottest_shard"] == 0
        assert snap["job.skew.imbalance"] == pytest.approx(4.0)
        assert snap["job.skew.hot_key_count"] == 1

    def test_rejects_bad_alpha(self):
        with pytest.raises(ValueError):
            ShardLoadAccountant(4, 16, ewma_alpha=0.0)
        with pytest.raises(ValueError):
            ShardLoadAccountant(4, 16, ewma_alpha=1.5)

    def test_busy_from_flight_sums_shard_spans(self):
        class Rec:
            def __init__(self, kind, shard, duration_s):
                self.kind = kind
                self.shard = shard
                self.duration_s = duration_s

        class Recorder:
            def snapshot(self):
                return [Rec("fire.shard", 0, 0.25),
                        Rec("fire.shard", 0, 0.25),
                        Rec("fire.shard", 2, 0.10),
                        Rec("batch", 1, 9.0),       # wrong kind
                        Rec("fire.shard", 7, 1.0)]  # out of range

        np.testing.assert_allclose(busy_from_flight(Recorder(), 3),
                                   [0.5, 0.0, 0.1])


class TestRebalancePolicy:
    def _loaded(self, loads, P=4, mp=16):
        """Accountant whose group_load() equals ``loads`` exactly."""
        acc = ShardLoadAccountant(P, mp, clock=FakeClock())
        for g, n in enumerate(loads):
            if n:
                acc.note_batch(np.repeat(_keys_for_group(g, mp, 1), n))
        return acc

    def test_balanced_load_plans_nothing(self):
        acc = self._loaded([10] * 16)
        pol = RebalancePolicy(imbalance_trigger=1.5, clock=FakeClock())
        plan = pol.plan(acc, KeyGroupAssignment.contiguous(4, 16))
        assert plan.assignment is None and plan.reason == "balanced"

    def test_moves_hot_groups_and_improves_imbalance(self):
        loads = [0] * 16
        loads[0], loads[1] = 300, 300  # both on shard 0
        for g in (4, 8, 12):
            loads[g] = 100
        acc = self._loaded(loads)
        pol = RebalancePolicy(imbalance_trigger=1.2, hysteresis=0.1,
                              cooldown_s=0.0, clock=FakeClock())
        plan = pol.plan(acc, KeyGroupAssignment.contiguous(4, 16))
        assert plan.reason == "rebalance" and plan.assignment is not None
        assert plan.imbalance_after < plan.imbalance_before
        # the first move lifts one of the hot groups off the hot shard
        g0, src0, dst0 = plan.moves[0]
        assert g0 in (0, 1) and src0 == 0 and dst0 != 0
        assert not plan.assignment.is_contiguous

    def test_hysteresis_discards_marginal_plans(self):
        loads = [0] * 16
        loads[0], loads[4], loads[8], loads[12] = 110, 100, 100, 100
        acc = self._loaded(loads)
        pol = RebalancePolicy(imbalance_trigger=1.0, hysteresis=0.9,
                              cooldown_s=0.0, clock=FakeClock())
        plan = pol.plan(acc, KeyGroupAssignment.contiguous(4, 16))
        assert plan.assignment is None
        assert plan.reason in ("hysteresis", "no-improving-move")

    def test_cooldown_blocks_then_allows(self):
        loads = [0] * 16
        loads[0], loads[1] = 300, 300
        for g in (4, 8, 12):
            loads[g] = 100
        acc = self._loaded(loads)
        clk = FakeClock()
        pol = RebalancePolicy(imbalance_trigger=1.2, hysteresis=0.05,
                              cooldown_s=30.0, clock=clk)
        cur = KeyGroupAssignment.contiguous(4, 16)
        assert pol.plan(acc, cur).reason == "rebalance"
        pol.mark_rebalanced()
        clk.advance(10.0)
        assert pol.plan(acc, cur).reason == "cooldown"
        clk.advance(25.0)
        assert pol.plan(acc, cur).reason == "rebalance"

    def test_dominant_key_reported_as_split_candidate(self):
        """One key carrying its whole group: moves cannot help (the
        group is atomic) — the policy must say SPLIT."""
        P, mp = 4, 16
        acc = ShardLoadAccountant(P, mp, clock=FakeClock())
        hot = _keys_for_group(0, mp, 1)[0]
        acc.note_batch(np.full(900, hot, dtype=np.int64))
        for g in (4, 8, 12):
            acc.note_batch(np.repeat(_keys_for_group(g, mp, 1), 50))
        pol = RebalancePolicy(imbalance_trigger=1.2, dominance_share=0.5,
                              cooldown_s=0.0, clock=FakeClock())
        plan = pol.plan(acc, KeyGroupAssignment.contiguous(P, mp))
        assert int(hot) in plan.split_candidates
        # and no move can fix it: shard 0 owns ONE loaded group
        assert plan.reason in ("no-improving-move", "rebalance")

    def test_one_group_shard_is_never_drained_into_a_swap(self):
        """max_moves=8 on a 2-shard layout with one hot group: the
        planner must not bounce the hot group back and forth."""
        loads = [0] * 8
        loads[0] = 100
        acc = self._loaded(loads, P=2, mp=8)
        pol = RebalancePolicy(imbalance_trigger=1.1, hysteresis=0.0,
                              cooldown_s=0.0, max_moves=8,
                              clock=FakeClock())
        plan = pol.plan(acc, KeyGroupAssignment.contiguous(2, 8))
        # moving the only loaded group just relocates the hot spot
        assert plan.assignment is None
        assert plan.reason == "no-improving-move"

    def test_rejects_bad_trigger(self):
        with pytest.raises(ValueError):
            RebalancePolicy(imbalance_trigger=0.5)
