"""Device-native streaming joins (flink_tpu/joins/): the interval and
temporal join engines over dual keyed slot tables.

The contract under test, in order of importance:

1. BIT-IDENTITY: the device engine (both shuffle modes) equals the
   host-numpy oracle backend row for row — same values, same emission
   order — including under forced paged eviction and a mid-stream live
   ``reshard()``. The oracle shares every metadata decision; the value
   path is pure movement, so equality is exact, not approximate.
2. CHECKPOINTS: snapshot -> restore -> snapshot round-trips bit-exactly;
   ``key_group_filter`` restores exactly one range;
   ``snapshot_sharded`` units union back to the full snapshot through
   ``merge_unit_snapshots``.
3. SEMANTICS: out-of-order and late rows behave exactly like the host
   operators (``runtime/join_operators.py`` — the reference-derived
   IntervalJoinOperator / TemporalJoinOperator), pinned as pair-set
   equality over identical streams.
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, TIMESTAMP_FIELD, RecordBatch
from flink_tpu.joins import (
    MeshIntervalJoinEngine,
    MeshTemporalJoinEngine,
    pair_lower_bound,
)
from flink_tpu.parallel.mesh import make_mesh
from flink_tpu.state.keygroups import assign_key_groups


def kb(keys, vals, ts, name="v", dtype=np.float32):
    return RecordBatch({
        KEY_ID_FIELD: np.asarray(keys, dtype=np.int64),
        name: np.asarray(vals, dtype=dtype),
        TIMESTAMP_FIELD: np.asarray(ts, dtype=np.int64),
    })


def assert_batches_equal(got, want):
    assert len(got) == len(want), (len(got), len(want))
    for a, b in zip(got, want):
        assert sorted(a.names()) == sorted(b.names())
        assert len(a) == len(b), (len(a), len(b))
        for n in a.names():
            np.testing.assert_array_equal(a[n], b[n], err_msg=n)


def interval_stream(steps=6, n=400, keys=500, span=100, seed=0):
    """(side, keys, vals, ts, watermark) steps, deterministically out
    of order within each batch."""
    rng = np.random.default_rng(seed)
    out = []
    for step in range(steps):
        for side in (0, 1):
            ks = rng.integers(0, keys, n)
            ts = step * span + rng.integers(0, span, n)
            vs = rng.random(n).astype(np.float32)
            out.append((side, ks, vs, ts, step * span - 2 * span))
    return out


def drive_interval(eng, stream):
    out = []
    for side, ks, vs, ts, wm in stream:
        out += eng.process_batch(
            kb(ks, vs, ts, name="v" if side == 0 else "w"), side)
        eng.on_watermark(wm)
    return out


def pairs_of(batches):
    """Canonical (sorted) tuple set of joined rows — the order-free
    comparison for semantics pinning."""
    rows = set()
    for b in batches:
        for r in b.to_rows():
            rows.add(tuple(sorted(
                (k, round(float(v), 6) if isinstance(
                    v, (float, np.floating)) else int(v))
                for k, v in r.items())))
    return rows


class TestPairLowerBound:
    def test_matches_reference_lexicographic_search(self):
        rng = np.random.default_rng(3)
        k = np.sort(rng.integers(0, 20, 200))
        t = rng.integers(0, 50, 200)
        order = np.lexsort((t, k))
        k, t = k[order], t[order]
        qk = rng.integers(-1, 22, 64)
        qt = rng.integers(-5, 55, 64)
        got = pair_lower_bound(k, t, qk, qt)
        pairs = list(zip(k.tolist(), t.tolist()))
        for i in range(64):
            want = sum(1 for p in pairs if p < (qk[i], qt[i]))
            assert got[i] == want, (qk[i], qt[i])

    def test_empty_inputs(self):
        e = np.empty(0, dtype=np.int64)
        assert len(pair_lower_bound(e, e, e, e)) == 0
        assert pair_lower_bound(e, e, np.array([1]),
                                np.array([2]))[0] == 0


class TestIntervalOracle:
    def _host(self, **kw):
        return MeshIntervalJoinEngine(-30, 40, backend="host",
                                      num_shards=4, **kw)

    def _device(self, shuffle_mode="device", **kw):
        return MeshIntervalJoinEngine(-30, 40, mesh=make_mesh(4),
                                      shuffle_mode=shuffle_mode, **kw)

    @pytest.mark.parametrize("shuffle_mode", ["device", "host"])
    def test_device_matches_host_oracle_bitwise(self, shuffle_mode):
        stream = interval_stream()
        got = drive_interval(self._device(shuffle_mode=shuffle_mode),
                             stream)
        want = drive_interval(self._host(), stream)
        assert sum(len(b) for b in want) > 0
        assert_batches_equal(got, want)

    def test_bit_identity_under_forced_paged_eviction(self):
        # key space >> slots, watermark far behind: the plane thrashes
        stream = interval_stream(steps=8, n=700, keys=20_000, span=40)
        dev = self._device(capacity_per_shard=256,
                           max_device_slots=256)
        host = self._host(capacity_per_shard=256,
                          max_device_slots=256)
        got = drive_interval(dev, stream)
        want = drive_interval(host, stream)
        assert_batches_equal(got, want)
        sc = dev.spill_counters()
        assert sc["rows_evicted"] > 0, "spill never engaged — vacuous"
        assert sc["cold_rows_served"] > 0, \
            "no probe ever hit the page tier — vacuous"
        # the oracle's spill bookkeeping is the same code
        assert sc == host.spill_counters()

    def test_bit_identity_across_midstream_reshard(self):
        stream = interval_stream(steps=8, n=500, keys=8_000, span=40)
        dev = self._device(capacity_per_shard=256,
                           max_device_slots=256)
        host = self._host(capacity_per_shard=256,
                          max_device_slots=256)
        got, want = [], []
        for i, (side, ks, vs, ts, wm) in enumerate(stream):
            if i == 7:
                r1 = dev.reshard(2)
                r2 = host.reshard(2)
                assert r1["rows_moved"] == r2["rows_moved"] > 0
            if i == 12:
                dev.reshard(4)
                host.reshard(4)
            name = "v" if side == 0 else "w"
            got += dev.process_batch(kb(ks, vs, ts, name=name), side)
            want += host.process_batch(kb(ks, vs, ts, name=name), side)
            dev.on_watermark(wm)
            host.on_watermark(wm)
        assert sum(len(b) for b in want) > 0
        assert_batches_equal(got, want)

    def test_int64_columns_ride_the_host_shadow_bitwise(self):
        # int64 cannot ride the x32 device plane — the shadow store
        # carries it in BOTH modes, so 2^53+ values stay exact
        big = (1 << 60) + 7
        dev = self._device()
        host = self._host()
        got, want = [], []
        for eng, sink in ((dev, got), (host, want)):
            sink += eng.process_batch(
                kb([1, 2], [big, big + 1], [0, 10], name="snowflake",
                   dtype=np.int64), 0)
            sink += eng.process_batch(
                kb([1, 2], [5, 6], [5, 15], name="w"), 1)
        assert_batches_equal(got, want)
        assert got[0]["snowflake"].dtype == np.int64
        # emission is shard-major — compare as a set
        assert set(got[0]["snowflake"].tolist()) == {big, big + 1}

    def test_shared_key_routing_makes_probes_shard_local(self):
        # both sides of one key land on the same shard: a pair whose
        # sides were co-partitioned differently could never match
        eng = self._device()
        out = eng.process_batch(kb([123], [1.0], [0]), 0)
        out += eng.process_batch(kb([123], [2.0], [5]), 1)
        assert sum(len(b) for b in out) == 1

    def test_invalid_modes_rejected(self):
        with pytest.raises(ValueError):
            MeshIntervalJoinEngine(-1, 1, backend="gpu")
        with pytest.raises(ValueError):
            MeshIntervalJoinEngine(-1, 1, backend="host",
                                   shuffle_mode="magic")
        with pytest.raises(ValueError):
            MeshIntervalJoinEngine(5, 4, backend="host")


class TestTemporalOracle:
    def _drive(self, eng, steps=8, seed=1):
        rng = np.random.default_rng(seed)
        out = []
        for step in range(steps):
            n = 300
            ks = rng.integers(0, 150, n)
            ts = step * 100 + rng.integers(0, 100, n)
            out += eng.process_batch(
                kb(ks, rng.random(n).astype(np.float32), ts), 0)
            vk = rng.integers(0, 150, 60)
            vt = step * 100 + rng.integers(0, 100, 60)
            out += eng.process_batch(
                kb(vk, rng.random(60).astype(np.float32), vt,
                   name="rate"), 1)
            out += eng.on_watermark(step * 100 - 50)
        out += eng.on_watermark(1 << 40)
        return out

    def test_device_matches_host_oracle_bitwise(self):
        got = self._drive(MeshTemporalJoinEngine(mesh=make_mesh(4)))
        want = self._drive(MeshTemporalJoinEngine(backend="host",
                                                  num_shards=4))
        assert sum(len(b) for b in want) > 0
        assert_batches_equal(got, want)

    def test_versioned_plane_under_forced_eviction(self):
        dev = MeshTemporalJoinEngine(mesh=make_mesh(4),
                                     capacity_per_shard=256,
                                     max_device_slots=256)
        host = MeshTemporalJoinEngine(backend="host", num_shards=4,
                                      capacity_per_shard=256,
                                      max_device_slots=256)
        rng = np.random.default_rng(5)
        got, want = [], []
        for step in range(6):
            vk = rng.integers(0, 30_000, 900)
            vt = step * 50 + rng.integers(0, 50, 900)
            vv = rng.random(900).astype(np.float32)
            lk = rng.integers(0, 30_000, 400)
            lt = step * 50 + rng.integers(0, 50, 400)
            lv = rng.random(400).astype(np.float32)
            for eng, sink in ((dev, got), (host, want)):
                sink += eng.process_batch(
                    kb(vk, vv, vt, name="rate"), 1)
                sink += eng.process_batch(kb(lk, lv, lt), 0)
                # watermark far behind: versions pile up and spill
                sink += eng.on_watermark(step * 50 - 500)
        for eng, sink in ((dev, got), (host, want)):
            sink += eng.on_watermark(1 << 40)
        assert_batches_equal(got, want)
        sc = dev.spill_counters()
        assert sc["rows_evicted"] > 0 and sc["cold_rows_served"] > 0
        assert sc == host.spill_counters()

    def test_late_left_rows_drop_with_counter(self):
        eng = MeshTemporalJoinEngine(backend="host", num_shards=2)
        eng.process_batch(kb([1], [9.0], [100], name="rate"), 1)
        eng.on_watermark(200)
        out = eng.process_batch(kb([1, 1], [1.0, 2.0], [150, 300]), 0)
        assert out == []
        assert eng.late_left_dropped == 1  # ts=150 <= watermark 200
        out = eng.on_watermark(400)
        assert sum(len(b) for b in out) == 1  # the ts=300 row joined


class TestSemanticsVsHostOperators:
    """Out-of-order / late-row semantics pinned against the
    reference-derived host operators over identical streams."""

    def test_interval_pairs_equal_interval_join_operator(self):
        from flink_tpu.runtime.join_operators import (
            IntervalJoinOperator,
        )

        stream = interval_stream(steps=6, n=250, keys=60, span=80,
                                 seed=9)
        eng = MeshIntervalJoinEngine(-30, 40, backend="host",
                                     num_shards=4)
        op = IntervalJoinOperator(-30, 40)
        got, want = [], []
        for side, ks, vs, ts, wm in stream:
            name = "v" if side == 0 else "w"
            got += eng.process_batch(kb(ks, vs, ts, name=name), side)
            want += op.process_batch(kb(ks, vs, ts, name=name), side)
            eng.on_watermark(wm)
            op.process_watermark(wm)
        assert pairs_of(got) == pairs_of(want)
        assert len(pairs_of(got)) > 0

    def test_pruned_rows_never_match_like_host_operator(self):
        from flink_tpu.runtime.join_operators import (
            IntervalJoinOperator,
        )

        eng = MeshIntervalJoinEngine(0, 10, backend="host",
                                     num_shards=2)
        op = IntervalJoinOperator(0, 10)
        for o in (eng, op):
            o.process_batch(kb([7], [1.0], [100]), 0)
        # watermark passes 100 + upper: the left row is dead in both
        eng.on_watermark(200)
        op.process_watermark(200)
        got = eng.process_batch(kb([7], [2.0], [105], name="w"), 1)
        want = op.process_batch(kb([7], [2.0], [105], name="w"), 1)
        assert pairs_of(got) == pairs_of(want) == set()

    def test_temporal_pairs_equal_temporal_join_operator(self):
        from flink_tpu.runtime.join_operators import (
            TemporalJoinOperator,
        )

        rng = np.random.default_rng(11)
        eng = MeshTemporalJoinEngine(backend="host", num_shards=4)
        op = TemporalJoinOperator()
        got, want = [], []
        for step in range(6):
            ks = rng.integers(0, 40, 200)
            ts = step * 100 + rng.integers(0, 100, 200)
            vs = rng.random(200).astype(np.float32)
            vk = rng.integers(0, 40, 40)
            vt = step * 100 + rng.integers(0, 100, 40)
            vv = rng.random(40).astype(np.float32)
            for o, sink in ((eng, got), (op, want)):
                pb = o.process_batch
                sink += pb(kb(ks, vs, ts), 0)
                sink += pb(kb(vk, vv, vt, name="rate"), 1)
                wm = step * 100 - 30
                sink += (o.on_watermark(wm) if o is eng
                         else o.process_watermark(wm))
        got += eng.on_watermark(1 << 40)
        want += op.process_watermark(1 << 40)
        assert pairs_of(got) == pairs_of(want)
        assert len(pairs_of(got)) > 0
        assert eng.late_left_dropped == op.late_left_dropped


class TestCheckpoints:
    def _spilling_engine(self, backend="device"):
        kw = dict(capacity_per_shard=256, max_device_slots=256)
        if backend == "device":
            return MeshIntervalJoinEngine(-30, 40, mesh=make_mesh(4),
                                          **kw)
        return MeshIntervalJoinEngine(-30, 40, backend="host",
                                      num_shards=4, **kw)

    def _loaded(self, backend="device"):
        eng = self._spilling_engine(backend)
        drive_interval(eng, interval_stream(steps=4, n=600,
                                            keys=20_000, span=40))
        return eng

    def test_snapshot_restore_snapshot_roundtrip_bitwise(self):
        eng = self._loaded()
        s1 = eng.snapshot()
        fresh = self._spilling_engine()
        fresh.restore(s1)
        s2 = fresh.snapshot()
        assert s2["next_rid"] == s1["next_rid"]
        for side in ("left", "right"):
            t1, t2 = s1[side]["table"], s2[side]["table"]
            assert set(t1) == set(t2)
            for k in t1:
                if k == "dirty":
                    continue  # restored rows are the checkpoint's: clean
                np.testing.assert_array_equal(
                    np.asarray(t1[k]), np.asarray(t2[k]),
                    err_msg=f"{side}/{k}")

    def test_restored_engine_continues_bit_identical(self):
        stream = interval_stream(steps=8, n=500, keys=20_000, span=40)
        ref = self._spilling_engine()
        cut = self._spilling_engine()
        got, want = [], []
        for i, (side, ks, vs, ts, wm) in enumerate(stream):
            if i == 8:
                snap = cut.snapshot()
                cut = self._spilling_engine()
                cut.restore(snap)
            name = "v" if side == 0 else "w"
            want += ref.process_batch(kb(ks, vs, ts, name=name), side)
            got += cut.process_batch(kb(ks, vs, ts, name=name), side)
            ref.on_watermark(wm)
            cut.on_watermark(wm)
        assert_batches_equal(got, want)

    def test_key_group_filter_restores_exactly_one_range(self):
        eng = self._loaded()
        snap = eng.snapshot()
        g0, g1 = eng.shard_key_groups()[1]
        fresh = self._spilling_engine()
        fresh.restore(snap, key_group_filter=range(g0, g1 + 1))
        s2 = fresh.snapshot()
        for side in ("left", "right"):
            full = snap[side]["table"]
            kept = s2[side]["table"]
            kg_full = np.asarray(full["key_group"])
            in_range = (kg_full >= g0) & (kg_full <= g1)
            assert len(kept["key_id"]) == int(in_range.sum()) > 0
            np.testing.assert_array_equal(
                np.asarray(kept["namespace"]),
                np.asarray(full["namespace"])[in_range])

    def test_sharded_units_union_to_full_snapshot(self):
        eng = self._loaded()
        full = eng.snapshot()
        units = eng.snapshot_sharded()
        assert set(units) == set(
            (g0, g1) for g0, g1 in eng.shard_key_groups())
        # disjoint cover: every row lands in exactly one unit
        merged = eng.merge_unit_snapshots(list(units.values()))
        for side in ("left", "right"):
            t_full, t_merged = (full[side]["table"],
                                merged[side]["table"])
            for k in t_full:
                np.testing.assert_array_equal(
                    np.asarray(t_full[k]), np.asarray(t_merged[k]),
                    err_msg=f"{side}/{k}")
        fresh = self._spilling_engine()
        fresh.restore(merged)
        s2 = fresh.snapshot()
        for side in ("left", "right"):
            np.testing.assert_array_equal(
                np.asarray(s2[side]["table"]["namespace"]),
                np.asarray(full[side]["table"]["namespace"]))

    def test_restore_grows_past_base_capacity_without_spill(self):
        # an engine with no spill tier grows its plane during the run;
        # a fresh engine at BASE capacity must restore that snapshot by
        # growing exactly like ingest does (a recovery path must never
        # be narrower than the run that produced the checkpoint)
        eng = MeshIntervalJoinEngine(-30, 40, backend="host",
                                     num_shards=2,
                                     capacity_per_shard=256)
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 100_000, 2000)
        eng.process_batch(kb(keys, np.ones(2000, np.float32),
                             np.arange(2000)), 0)
        snap = eng.snapshot()
        assert len(snap["left"]["table"]["key_id"]) == 2000
        fresh = MeshIntervalJoinEngine(-30, 40, backend="host",
                                       num_shards=2,
                                       capacity_per_shard=256)
        fresh.restore(snap)
        s2 = fresh.snapshot()
        np.testing.assert_array_equal(
            np.asarray(s2["left"]["table"]["namespace"]),
            np.asarray(snap["left"]["table"]["namespace"]))

    def test_temporal_snapshot_carries_pending_and_watermark(self):
        eng = MeshTemporalJoinEngine(backend="host", num_shards=2)
        eng.process_batch(kb([1, 2], [1.0, 2.0], [50, 60],
                             name="rate"), 1)
        eng.process_batch(kb([1, 2], [3.0, 4.0], [80, 90]), 0)
        eng.on_watermark(70)
        snap = eng.snapshot()
        assert snap["pending"] is not None
        fresh = MeshTemporalJoinEngine(backend="host", num_shards=2)
        fresh.restore(snap)
        assert fresh._emitted_wm == eng._emitted_wm
        out = fresh.on_watermark(1 << 40)
        want = eng.on_watermark(1 << 40)
        assert_batches_equal(out, want)

    def test_temporal_sharded_units_split_pending_by_range(self):
        eng = MeshTemporalJoinEngine(backend="host", num_shards=2)
        keys = np.arange(64, dtype=np.int64)
        eng.process_batch(kb(keys, np.ones(64), keys * 0 + 100), 0)
        units = eng.snapshot_sharded()
        tot = 0
        for (gg0, gg1), u in units.items():
            pend = u["pending"]
            kg = assign_key_groups(
                np.asarray(pend[KEY_ID_FIELD], dtype=np.int64),
                eng.max_parallelism)
            assert ((kg >= gg0) & (kg <= gg1)).all()
            tot += len(pend[KEY_ID_FIELD])
        assert tot == 64
        merged = eng.merge_unit_snapshots(list(units.values()))
        assert len(merged["pending"][KEY_ID_FIELD]) == 64


class TestWatchdogAndOperators:
    def test_watchdog_sections_wrap_device_interactions(self):
        from flink_tpu.runtime.watchdog import DeviceWatchdog

        eng = MeshIntervalJoinEngine(-30, 40, mesh=make_mesh(2))
        wd = DeviceWatchdog(2, deadline_ms=0.0)
        eng.attach_watchdog(wd)
        stream = interval_stream(steps=2, n=100, keys=30)
        drive_interval(eng, stream)
        assert wd.heartbeat_age_s() < 60

    def test_device_interval_join_operator_end_to_end(self):
        from flink_tpu.joins.operators import (
            DeviceIntervalJoinOperator,
        )
        from flink_tpu.runtime.operators import OperatorContext

        op = DeviceIntervalJoinOperator(-30, 40, capacity=2048)
        op.open(OperatorContext(parallelism=2))
        out = op.process_batch(kb([1, 2], [1.0, 2.0], [0, 10]), 0)
        out += op.process_batch(kb([1, 2], [5.0, 6.0], [5, 15],
                                   name="w"), 1)
        assert sum(len(b) for b in out) == 2
        snap = op.snapshot_state()
        op2 = DeviceIntervalJoinOperator(-30, 40, capacity=2048)
        op2.open(OperatorContext(parallelism=2))
        op2.restore_state(snap)
        assert op2.engine.snapshot()["next_rid"] == \
            op.engine.snapshot()["next_rid"]
        assert op.supports_live_rescale()
        op.reshard(1)
        assert op.engine.P == 1

    def test_device_temporal_join_operator_end_to_end(self):
        from flink_tpu.joins.operators import (
            DeviceTemporalJoinOperator,
        )
        from flink_tpu.runtime.operators import OperatorContext

        op = DeviceTemporalJoinOperator(capacity=2048)
        op.open(OperatorContext(parallelism=2))
        op.process_batch(kb([1], [9.5], [100], name="rate"), 1)
        op.process_batch(kb([1], [1.0], [150]), 0)
        out = op.process_watermark(200)
        assert sum(len(b) for b in out) == 1
        row = out[0].to_rows()[0]
        assert row["rate"] == pytest.approx(9.5)

    def test_datastream_join_mode_device_matches_host(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import Source

        class SideSource(Source):
            def __init__(self, seed, col):
                self.seed, self.col, self.done = seed, col, False

            def poll_batch(self, max_records):
                if self.done:
                    return None
                self.done = True
                rng = np.random.default_rng(self.seed)
                n = 600
                ks = rng.integers(0, 40, n).astype(np.int64)
                ts = np.sort(rng.integers(0, 2000, n).astype(np.int64))
                return RecordBatch.from_pydict(
                    {"k": ks,
                     self.col: rng.random(n).astype(np.float32)},
                    timestamps=ts)

        def run(mode):
            env = StreamExecutionEnvironment(Configuration({
                "join.mode": mode,
                "execution.micro-batch.size": 128}))
            sink = CollectSink()
            left = env.add_source(SideSource(1, "price")).key_by("k")
            right = env.add_source(SideSource(2, "rate")).key_by("k")
            left.interval_join(right).between(-100, 100).sink_to(sink)
            env.execute("ij-" + mode)
            return pairs_of(sink.batches)

        host = run("host")
        device = run("device")
        assert host == device
        assert len(host) > 0
