"""Two-phase commit sinks: end-to-end exactly once across failover.

reference test model: Sink V2 committer tests + exactly-once FileSink
ITCases with fault injection.
"""

import os
import time

import numpy as np
import pytest

from flink_tpu.connectors.two_phase import (
    ExactlyOnceFileSink,
    TwoPhaseSinkOperator,
)
from flink_tpu.connectors.sources import DataGenSource
from flink_tpu.core.config import Configuration
from flink_tpu.core.records import RecordBatch
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


def batch(values):
    return RecordBatch.from_pydict({"v": np.asarray(values)})


class TestProtocol:
    def test_commit_is_idempotent_and_publishes_atomically(self, tmp_path):
        d = str(tmp_path / "out")
        sink = ExactlyOnceFileSink(d)
        sink.open()
        sink.write(batch([1, 2]))
        assert ExactlyOnceFileSink.read_committed_rows(d) == []  # invisible
        committables = sink.prepare_commit()
        assert ExactlyOnceFileSink.read_committed_rows(d) == []  # sealed only
        sink.commit(committables)
        rows = ExactlyOnceFileSink.read_committed_rows(d)
        assert [r["v"] for r in rows] == [1, 2]
        sink.commit(committables)  # re-commit after "failover": no-op
        assert len(ExactlyOnceFileSink.read_committed_rows(d)) == 2

    def test_lost_committable_fails_loudly(self, tmp_path):
        d = str(tmp_path / "out")
        sink = ExactlyOnceFileSink(d)
        sink.open()
        sink.write(batch([1]))
        committables = sink.prepare_commit()
        os.remove(committables[0]["pending"])
        with pytest.raises(IOError, match="committable lost"):
            sink.commit(committables)

    def test_restore_recommits_and_discards_unsealed(self, tmp_path):
        d = str(tmp_path / "out")
        sink = ExactlyOnceFileSink(d)
        op = TwoPhaseSinkOperator(sink)
        op.open(type("C", (), {"operator_index": 0})())
        op.process_batch(batch([1, 2]))
        state = op.snapshot_state()  # sealed, checkpoint taken
        op.process_batch(batch([3, 4]))  # post-checkpoint, never sealed
        # crash here: neither commit nor another checkpoint happened
        sink2 = ExactlyOnceFileSink(d)
        op2 = TwoPhaseSinkOperator(sink2)
        op2.open(type("C", (), {"operator_index": 0})())
        op2.restore_state(state)
        rows = ExactlyOnceFileSink.read_committed_rows(d)
        assert sorted(r["v"] for r in rows) == [1, 2]  # 3,4 discarded
        assert not [n for n in os.listdir(d) if n.endswith(".inprogress")]

    def test_dispose_aborts_instead_of_committing(self, tmp_path):
        """Failure-path dispose must NOT publish the uncommitted
        transaction (reference: TwoPhaseCommitSinkFunction.close aborts);
        publishing there would double-commit after restore."""
        d = str(tmp_path / "out")
        sink = ExactlyOnceFileSink(d)
        op = TwoPhaseSinkOperator(sink)
        op.open(type("C", (), {"operator_index": 0})())
        op.process_batch(batch([1, 2]))
        state = op.snapshot_state()
        op.notify_checkpoint_complete(1)
        op.process_batch(batch([3, 4]))  # post-checkpoint, uncommitted
        op.dispose()  # crash path
        rows = ExactlyOnceFileSink.read_committed_rows(d)
        assert sorted(r["v"] for r in rows) == [1, 2]  # 3,4 NOT published
        # the leftovers stay .inprogress for restore-time cleanup
        assert [n for n in os.listdir(d) if n.endswith(".inprogress")]
        sink2 = ExactlyOnceFileSink(d)
        op2 = TwoPhaseSinkOperator(sink2)
        op2.open(type("C", (), {"operator_index": 0})())
        op2.restore_state(state)
        assert not [n for n in os.listdir(d) if n.endswith(".inprogress")]
        rows = ExactlyOnceFileSink.read_committed_rows(d)
        assert sorted(r["v"] for r in rows) == [1, 2]

    def test_savepoint_then_checkpoint_commits_all_sealed(self, tmp_path):
        """A savepoint seals a transaction without a commit following; the
        next checkpoint-complete must still publish it."""
        d = str(tmp_path / "out")
        op = TwoPhaseSinkOperator(ExactlyOnceFileSink(d))
        op.open(type("C", (), {"operator_index": 0})())
        op.process_batch(batch([1]))
        op.snapshot_state()  # savepoint: sealed, NOT committed
        op.process_batch(batch([2]))
        op.snapshot_state()  # checkpoint
        op.notify_checkpoint_complete(1)
        rows = ExactlyOnceFileSink.read_committed_rows(d)
        assert sorted(r["v"] for r in rows) == [1, 2]


class TestExactlyOnceE2E:
    def test_failover_exactly_once_totals(self, tmp_path):
        """Fault mid-job, restart from checkpoint: committed output holds
        every window exactly once (the JsonLines sink would double-emit
        here; the 2PC sink must not)."""
        out = str(tmp_path / "out")
        ck = str(tmp_path / "ck")
        flag = str(tmp_path / "crashed.flag")
        total = 20_000

        from flink_tpu.cluster.minicluster import FINISHED, MiniCluster

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2,
            "restart-strategy.max-attempts": 3,
            "restart-strategy.delay-ms": 10,
        }))

        def poison_once(b, flag=flag):
            import os as _os
            ts = b.timestamps
            if len(ts) and ts.max() > 900 and not _os.path.exists(flag):
                open(flag, "w").write("x")
                raise RuntimeError("injected fault")
            return b

        (env.add_source(DataGenSource(total_records=total, num_keys=10,
                                      events_per_second_of_eventtime=10_000),
                        WatermarkStrategy.for_bounded_out_of_orderness(0))
            .map(poison_once, name="poison")
            .key_by("key")
            .window(TumblingEventTimeWindows.of(500))
            .count()
            .sink_to(ExactlyOnceFileSink(out)))

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            client = cluster.submit(env, "2pc-job")
            st = client.wait(timeout=60)
            assert st["status"] == FINISHED
            assert st["attempt"] >= 1  # the fault really fired
        finally:
            cluster.shutdown()
        rows = ExactlyOnceFileSink.read_committed_rows(out)
        per_window = {}
        for r in rows:
            k = (int(r["key"]), int(r["window_start"]))
            # exactly-once: no window may be committed twice
            assert k not in per_window, f"duplicate committed window {k}"
            per_window[k] = int(r["count"])
        assert sum(per_window.values()) == total
