"""Nexmark Q5/Q7 correctness against oracles (small scale)."""

import numpy as np

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.benchmarks.nexmark import (
    BidSource,
    build_q5,
    build_q7,
    oracle_q5,
    oracle_q7,
)


def make_env():
    return StreamExecutionEnvironment(Configuration({
        "execution.micro-batch.size": 1024}))


def drain_source(src, batch_size=1024):
    """Drain with the SAME batch size as the pipeline run — the generator's
    random stream depends on the draw sizes."""
    src.open()
    rows = []
    while True:
        b = src.poll_batch(batch_size)
        if b is None:
            break
        rows.extend(b.to_rows())
    return rows


class TestQ5:
    def test_q5_matches_oracle(self):
        n = 20_000
        env = make_env()
        result = build_q5(
            env, BidSource(n, num_auctions=50, seed=1),
            size_ms=1000, slide_ms=500).execute_and_collect()

        bid_rows = drain_source(BidSource(n, num_auctions=50, seed=1))
        oracle = oracle_q5([(r["auction"], r["__ts__"]) for r in bid_rows],
                           1000, 500)

        got = {}
        for r in result.to_rows():
            w = r["window_end"]
            got.setdefault(w, (r["count"], set()))
            assert r["count"] == got[w][0], "mixed counts in one window"
            got[w][1].add(r["auction"])
        assert set(got) == set(oracle)
        for w in oracle:
            assert got[w][0] == oracle[w][0], f"window {w} max count"
            assert got[w][1] == oracle[w][1], f"window {w} winner set"


class TestQ7:
    def test_q7_matches_oracle(self):
        n = 20_000
        env = make_env()
        result = build_q7(
            env, BidSource(n, num_auctions=100, seed=2),
            size_ms=1000).execute_and_collect()

        bid_rows = drain_source(BidSource(n, num_auctions=100, seed=2))
        oracle = oracle_q7(
            [(r["auction"], r["bidder"], r["price"], r["__ts__"])
             for r in bid_rows], 1000)

        got = {}
        for r in result.to_rows():
            got.setdefault(r["window_end"], []).append(
                (r["auction"], r["bidder"], r["price"]))
        assert set(got) == set(oracle)
        for w, rows in got.items():
            mx, winners = oracle[w]
            for a, b, p in rows:
                assert p == mx
            assert sorted((a, b) for a, b, _ in rows) == sorted(winners)
