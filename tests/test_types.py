"""Type system + serializer snapshots/compatibility.

Mirrors the reference's serializer upgrade/migration tests
(flink-tests/.../typeserializerupgrade/) in the columnar model.
"""

import numpy as np
import pytest

from flink_tpu.core.records import RecordBatch
from flink_tpu.core.serializers import (
    Compatibility,
    NumericArraySerializer,
    PickleArraySerializer,
    RowBatchSerializer,
    SerializerSnapshot,
    StringArraySerializer,
)
from flink_tpu.core.types import (
    DOUBLE_TYPE_INFO,
    LONG_TYPE_INFO,
    RowTypeInfo,
    STRING_TYPE_INFO,
    TypeInformation,
)


def test_type_extraction():
    assert TypeInformation.of(np.array([1, 2])).dtype == "<i8"
    assert TypeInformation.of(np.float32).kind == "numeric"
    assert TypeInformation.of("hello").kind == "string"
    assert TypeInformation.of(np.array(["a"], dtype=object)).kind == "object"
    assert TypeInformation.of(3.5) == DOUBLE_TYPE_INFO
    rt = RowTypeInfo.from_batch(
        RecordBatch.from_pydict({"a": [1], "b": [1.5]}))
    assert rt.field_type("a") == LONG_TYPE_INFO
    assert rt.field_type("b") == DOUBLE_TYPE_INFO


@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32,
                                   np.float64, np.bool_, np.uint16])
def test_numeric_roundtrip(dtype):
    ser = NumericArraySerializer(dtype)
    arr = np.arange(17).astype(dtype)
    out = ser.deserialize(ser.serialize(arr))
    assert out.dtype == np.dtype(dtype) and out.tolist() == arr.tolist()


def test_string_roundtrip():
    ser = StringArraySerializer()
    arr = np.array(["", "héllo", "a" * 1000, "☃"], dtype=object)
    assert ser.deserialize(ser.serialize(arr)).tolist() == arr.tolist()


def test_pickle_roundtrip():
    ser = PickleArraySerializer()
    arr = np.empty(2, dtype=object)
    arr[0] = {"nested": [1, 2]}
    arr[1] = ("t", 1)
    assert ser.deserialize(ser.serialize(arr)).tolist() == arr.tolist()


def test_numeric_compatibility_widening_and_narrowing():
    old = NumericArraySerializer(np.int32)
    snap = old.snapshot()
    assert snap.resolve_compatibility(NumericArraySerializer(np.int32)) \
        is Compatibility.COMPATIBLE_AS_IS
    wide = NumericArraySerializer(np.int64)
    assert snap.resolve_compatibility(wide) \
        is Compatibility.COMPATIBLE_AFTER_MIGRATION
    narrow_snap = NumericArraySerializer(np.int64).snapshot()
    assert narrow_snap.resolve_compatibility(NumericArraySerializer(np.int32)) \
        is Compatibility.INCOMPATIBLE
    # migration actually reads old bytes into the new dtype
    data = old.serialize(np.array([1, 2, 3], dtype=np.int32))
    migrated = wide.migrate(data, snap)
    assert migrated.dtype == np.int64 and migrated.tolist() == [1, 2, 3]


def test_snapshot_json_roundtrip_restores_serializer():
    snap = NumericArraySerializer(np.float32).snapshot()
    snap2 = SerializerSnapshot.from_json(snap.to_json())
    ser = snap2.restore_serializer()
    arr = np.array([1.5, 2.5], dtype=np.float32)
    assert ser.deserialize(ser.serialize(arr)).tolist() == [1.5, 2.5]


def _batch():
    return RecordBatch.from_pydict(
        {"k": np.array([1, 2, 3], dtype=np.int64),
         "v": np.array([1.0, 2.0, 3.0], dtype=np.float32),
         "s": np.array(["x", "y", "z"], dtype=object)})


def test_row_batch_roundtrip():
    rt = RowTypeInfo.of(k=np.int64, v=np.float32, s=STRING_TYPE_INFO)
    ser = RowBatchSerializer(rt)
    out = ser.deserialize(ser.serialize(_batch()))
    assert out["k"].tolist() == [1, 2, 3]
    assert out["v"].dtype == np.float32
    assert out["s"].tolist() == ["x", "y", "z"]


def test_row_schema_evolution_add_remove_widen():
    old_rt = RowTypeInfo.of(k=np.int32, v=np.float32, gone=np.int64)
    old_ser = RowBatchSerializer(old_rt)
    data = old_ser.serialize(RecordBatch.from_pydict(
        {"k": np.array([1, 2], dtype=np.int32),
         "v": np.array([0.5, 1.5], dtype=np.float32),
         "gone": np.array([9, 9], dtype=np.int64)}))
    snap = SerializerSnapshot.from_json(old_ser.snapshot().to_json())

    # new schema: k widened, 'gone' dropped, 'fresh' added
    new_rt = RowTypeInfo.of(k=np.int64, v=np.float32, fresh=np.float64)
    new_ser = RowBatchSerializer(new_rt)
    assert snap.resolve_compatibility(new_ser) \
        is Compatibility.COMPATIBLE_AFTER_MIGRATION
    out = new_ser.migrate(data, snap)
    assert out["k"].dtype == np.int64 and out["k"].tolist() == [1, 2]
    assert out["fresh"].tolist() == [0.0, 0.0]
    assert "gone" not in out.columns

    # identical schema is AS_IS; string->numeric is incompatible
    assert snap.resolve_compatibility(RowBatchSerializer(old_rt)) \
        is Compatibility.COMPATIBLE_AS_IS
    bad = RowTypeInfo.of(k=STRING_TYPE_INFO, v=np.float32)
    assert snap.resolve_compatibility(RowBatchSerializer(bad)) \
        is Compatibility.INCOMPATIBLE


def test_row_batch_rejects_garbage():
    rt = RowTypeInfo.of(k=np.int64)
    with pytest.raises(ValueError):
        RowBatchSerializer(rt).deserialize(b"not a batch at all")


def test_binary_file_sink_source_roundtrip_and_evolution(tmp_path):
    from flink_tpu.connectors.sinks import BinaryFileSink
    from flink_tpu.connectors.sources import BinaryFileSource

    path = str(tmp_path / "data.ftb")
    sink = BinaryFileSink(path)
    sink.open()
    sink.write(RecordBatch.from_pydict(
        {"k": np.array([1, 2], dtype=np.int32),
         "v": np.array([0.5, 1.5], dtype=np.float32)}))
    sink.write(RecordBatch.from_pydict(
        {"k": np.array([3], dtype=np.int32),
         "v": np.array([2.5], dtype=np.float32)}))
    sink.close()

    # plain read: schema restored from the embedded snapshot
    src = BinaryFileSource(path)
    src.open()
    b1, b2, end = src.poll_batch(100), src.poll_batch(100), src.poll_batch(100)
    assert b1["k"].tolist() == [1, 2] and b2["v"].tolist() == [2.5]
    assert end is None
    src.close()

    # evolved read: k widened to int64, new column filled with defaults
    rt = RowTypeInfo.of(k=np.int64, v=np.float32, extra=np.float64)
    src = BinaryFileSource(path, row_type=rt)
    src.open()
    b = src.poll_batch(100)
    assert b["k"].dtype == np.int64 and b["extra"].tolist() == [0.0, 0.0]
    src.close()

    # checkpointed position restore skips already-read batches
    src = BinaryFileSource(path)
    src.open()
    src.poll_batch(100)
    pos = src.snapshot_position()
    src.close()
    src2 = BinaryFileSource(path)
    src2.restore_position(pos)
    src2.open()
    assert src2.poll_batch(100)["k"].tolist() == [3]
    src2.close()
