"""The on-device keyBy shuffle (shuffle.mode=device, the default).

A batch goes host->device ONCE as flat padded columns and a single
compiled program (``build_exchange_scatter``) segment-sorts records
into per-destination buckets, exchanges them with ``all_to_all`` over
the mesh axis, and feeds the aggregate scatter — keyBy -> window ->
aggregate as ONE XLA program. These tests pin the contract the fused
path must honor:

- staging shapes walk the ``pad_bucket_size`` tiers (bounded program
  shapes — the recompile smoke gates the runtime signal),
- output BIT-IDENTICAL to the explicit host fallback
  (``bucket_by_shard`` + sharded device_put) and to the single-device
  oracle, under forced paged eviction,
- a live ``reshard()`` mid-stream in device mode stays
  oracle-identical,
- the fence/dispatch-ahead discipline holds against the one-hop ingest
  (pooled staging buffers are generation-rotated exactly like the host
  blocks).
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.ops.segment_ops import pad_bucket_size
from flink_tpu.parallel.shuffle import (
    ShuffleBufferPool,
    bucket_by_shard,
    exchange_chunk_size,
    stage_device_exchange,
)
from flink_tpu.windowing.aggregates import SumAggregate
from flink_tpu.windowing.sessions import SessionWindower

from tests.test_sessions import keyed_batch

GAP = 100


def _session_engine(mesh, mode, **kw):
    from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

    return MeshSessionEngine(gap=GAP, agg=SumAggregate("v"), mesh=mesh,
                             capacity_per_shard=1 << 14,
                             shuffle_mode=mode, **kw)


def _window_engine(mesh, mode, **kw):
    from flink_tpu.parallel.sharded_windower import MeshWindowEngine
    from flink_tpu.windowing.assigners import TumblingEventTimeWindows

    return MeshWindowEngine(TumblingEventTimeWindows.of(50),
                            SumAggregate("v"), mesh,
                            capacity_per_shard=1 << 14,
                            shuffle_mode=mode, **kw)


def _stream(num_keys=24_000, n_steps=8, per_step=6000, seed=17):
    """Live set far beyond a 1024-slot/shard budget — forced paged
    eviction, cold fires, reloads (same shape as test_mesh_paged_spill).
    Values are small integers so float sums are EXACT and bit-identity
    across data planes is meaningful."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.integers(0, 1000, per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    steps.append((np.array([0], dtype=np.int64),
                  np.array([0.0], dtype=np.float32),
                  np.array([n_steps * 80 + 10_000], dtype=np.int64),
                  10 ** 9))
    return steps


def _run(engine, steps, reshard_at=None, reshard_to=None):
    fired = []
    for i, (keys, vals, ts, wm) in enumerate(steps):
        if reshard_at is not None and i == reshard_at:
            engine.reshard(reshard_to)
        engine.process_batch(keyed_batch(keys, vals, ts))
        fired.extend(engine.on_watermark(wm))
    return fired


def _sessions_dict(batches):
    out = {}
    for b in batches:
        for r in b.to_rows():
            out[(r[KEY_ID_FIELD], r["window_start"],
                 r["window_end"])] = r["sum_v"]
    return out


class TestStaging:
    def test_chunk_size_walks_pad_tiers(self):
        assert exchange_chunk_size(0, 8) == 256
        assert exchange_chunk_size(8 * 256, 8) == 256
        assert exchange_chunk_size(8 * 256 + 1, 8) == 512
        assert exchange_chunk_size(65536, 8) == \
            pad_bucket_size(65536 // 8)

    def test_flat_layout_and_padding_sentinel(self):
        rng = np.random.default_rng(1)
        n, P = 1000, 4
        shards = rng.integers(0, P, n).astype(np.int64)
        slots = rng.integers(1, 500, n).astype(np.int32)
        vals = rng.random(n).astype(np.float32)
        dst, (s_col, v_col), width = stage_device_exchange(
            shards, P, [slots, vals], fills=[0, 0.0])
        C = exchange_chunk_size(n, P)
        assert len(dst) == P * C == len(s_col) == len(v_col)
        np.testing.assert_array_equal(dst[:n], shards)
        # padding lanes carry the out-of-range destination and fills
        assert (dst[n:] == P).all()
        assert (s_col[n:] == 0).all() and (v_col[n:] == 0.0).all()
        np.testing.assert_array_equal(s_col[:n], slots)
        # bucket width: a pad tier of the densest (chunk, dest) pair,
        # never wider than the chunk itself
        assert width <= C
        chunk = np.arange(n) // C
        pair_max = int(np.bincount(chunk * P + shards,
                                   minlength=P * P).max())
        assert width == min(pad_bucket_size(pair_max), C)

    def test_pool_buffers_rotate_by_generation(self):
        pool = ShuffleBufferPool(generations=2)
        shards = np.zeros(10, dtype=np.int64)
        cols = [np.arange(10, dtype=np.int32)]
        pool.flip()
        d1, (c1,), _ = stage_device_exchange(shards, 2, cols, [0],
                                             pool=pool)
        pool.flip()
        d2, (c2,), _ = stage_device_exchange(shards, 2, cols, [0],
                                             pool=pool)
        pool.flip()
        d3, (c3,), _ = stage_device_exchange(shards, 2, cols, [0],
                                             pool=pool)
        # generation rotation: gen0's buffers are reused on the third
        # flip, a different generation's never aliased
        assert d1 is d3 and c1 is c3
        assert d1 is not d2 and c1 is not c2


class TestFusedExchangeProgram:
    def test_matches_host_bucket_scatter(self, eight_device_mesh):
        """The fused program's scatter result equals the host
        bucket_by_shard + scatter_step path bit-for-bit."""
        import jax
        import jax.numpy as jnp

        from flink_tpu.parallel.mesh import KEY_AXIS
        from flink_tpu.parallel.shuffle import build_exchange_scatter
        from flink_tpu.parallel.sharded_windower import build_mesh_steps
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = eight_device_mesh
        agg = SumAggregate("v")
        sharding = NamedSharding(mesh, P(KEY_AXIS))
        cap = 4096
        rng = np.random.default_rng(3)
        n = 5000
        shards = rng.integers(0, 8, n).astype(np.int64)
        slots = rng.integers(1, cap, n).astype(np.int32)
        vals = rng.integers(0, 100, n).astype(np.float32)

        def fresh_accs():
            return tuple(
                jax.device_put(jnp.full((8, cap), l.identity,
                                        dtype=l.dtype), sharding)
                for l in agg.leaves)

        xstep = build_exchange_scatter(mesh, agg, valued=False)
        dst, staged, width = stage_device_exchange(
            shards, 8, [slots, vals], fills=[0, 0.0])
        put = jax.device_put((dst, *staged), sharding)
        dev = jax.device_get(list(xstep(
            fresh_accs(), put[0], put[1], tuple(put[2:]), width)))

        scatter = build_mesh_steps(mesh, agg)[0]
        counts, blocked = bucket_by_shard(shards, 8, [slots, vals],
                                          fills=[0, 0.0])
        host = jax.device_get(list(scatter(
            fresh_accs(), jax.device_put(blocked[0], sharding),
            (jax.device_put(blocked[1], sharding),))))
        for d, h in zip(dev, host):
            np.testing.assert_array_equal(np.asarray(d), np.asarray(h))

    def test_invalid_mode_rejected(self, eight_device_mesh):
        with pytest.raises(ValueError, match="shuffle_mode"):
            _session_engine(eight_device_mesh, "netty")


class TestDeviceModeEngines:
    def test_sessions_bit_identical_to_host_mode_under_eviction(
            self, eight_device_mesh):
        steps = _stream()
        dev = _session_engine(eight_device_mesh, "device",
                              max_device_slots=1024)
        host = _session_engine(eight_device_mesh, "host",
                               max_device_slots=1024)
        d_dev = _sessions_dict(_run(dev, steps))
        d_host = _sessions_dict(_run(host, steps))
        assert len(d_dev) > 0 and set(d_dev) == set(d_host)
        diff = [k for k in d_dev if d_dev[k] != d_host[k]]
        assert not diff, f"{len(diff)} windows differ, e.g. {diff[:3]}"
        # the run genuinely thrashed the budget (cold fires, reloads)
        c = dev.spill_counters()
        assert c["pages_evicted"] > 0 and c["rows_reloaded"] > 0

    def test_sessions_match_single_device_oracle(self,
                                                 eight_device_mesh):
        steps = _stream(seed=23)
        dev = _session_engine(eight_device_mesh, "device",
                              max_device_slots=1024)
        single = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        d_dev = _sessions_dict(_run(dev, steps))
        d_ref = _sessions_dict(_run(single, steps))
        assert len(d_ref) > 0 and set(d_dev) == set(d_ref)
        for k in d_ref:
            assert d_dev[k] == pytest.approx(d_ref[k], rel=1e-4), k

    def test_windows_bit_identical_to_host_mode_under_eviction(
            self, eight_device_mesh):
        steps = _stream(seed=29)
        dev = _window_engine(eight_device_mesh, "device",
                             max_device_slots=4096)
        host = _window_engine(eight_device_mesh, "host",
                              max_device_slots=4096)
        d_dev = _sessions_dict(_run(dev, steps))
        d_host = _sessions_dict(_run(host, steps))
        assert len(d_dev) > 0 and set(d_dev) == set(d_host)
        diff = [k for k in d_dev if d_dev[k] != d_host[k]]
        assert not diff, f"{len(diff)} windows differ, e.g. {diff[:3]}"

    def test_two_phase_partial_batches_use_valued_exchange(
            self, eight_device_mesh):
        """Locally pre-aggregated (two-phase) batches route through the
        VALUED exchange variant and stay equal to the host path."""
        from flink_tpu.runtime.local_agg import PARTIAL_LEAF_PREFIX

        rng = np.random.default_rng(7)
        n = 4000
        keys = rng.integers(0, 800, n).astype(np.int64)
        vals = rng.integers(0, 50, n).astype(np.float32)
        ts = rng.integers(0, 40, n).astype(np.int64)

        def partial_batch():
            b = keyed_batch(keys, vals, ts)
            return b.with_column(PARTIAL_LEAF_PREFIX + "0", vals)

        out = {}
        for mode in ("device", "host"):
            eng = _window_engine(eight_device_mesh, mode)
            eng.process_batch(partial_batch())
            out[mode] = _sessions_dict(eng.on_watermark(10 ** 9))
        assert len(out["device"]) > 0
        assert out["device"] == out["host"]

    def test_live_reshard_mid_stream_in_device_mode(
            self, eight_device_mesh):
        """A live reshard() (8 -> 4 shards) mid-stream with the device
        data plane active stays oracle-identical — the rebuilt mesh
        plane rebuilds its exchange programs with it."""
        steps = _stream(seed=31)
        dev = _session_engine(eight_device_mesh, "device",
                              max_device_slots=1024)
        single = SessionWindower(GAP, SumAggregate("v"),
                                 capacity=1 << 15)
        fired = _run(dev, steps, reshard_at=4, reshard_to=4)
        assert dev.P == 4 and dev.shuffle_mode == "device"
        d_dev = _sessions_dict(fired)
        d_ref = _sessions_dict(_run(single, steps))
        assert len(d_ref) > 0 and set(d_dev) == set(d_ref)
        for k in d_ref:
            assert d_dev[k] == pytest.approx(d_ref[k], rel=1e-4), k

    def test_operator_wires_ctx_shuffle_mode(self, eight_device_mesh):
        """The operator layer hands OperatorContext.shuffle_mode (the
        shuffle.mode config) through to the mesh engine."""
        import jax

        from flink_tpu.runtime.operators import (
            OperatorContext,
            SessionWindowAggOperator,
        )

        for mode in ("host", "device"):
            op = SessionWindowAggOperator(gap=GAP, agg=SumAggregate("v"),
                                          key_field="k")
            op.open(OperatorContext(
                parallelism=min(8, len(jax.devices())),
                shuffle_mode=mode))
            assert op.windower.shuffle_mode == mode
