"""LEFT (outer) interval joins.

reference: IntervalJoinOperator outer semantics — an expired unmatched
left row null-extends exactly once, when the watermark proves no match
can still arrive."""

import numpy as np
import pytest

from flink_tpu import Configuration, StreamExecutionEnvironment
from flink_tpu.core.records import RecordBatch
from flink_tpu.runtime.join_operators import IntervalJoinOperator
from flink_tpu.state.keygroups import hash_keys_to_i64
from flink_tpu.table.environment import StreamTableEnvironment


class _Ctx:
    max_parallelism = 128


def _kb(cols, ts):
    b = RecordBatch.from_pydict(
        cols, timestamps=np.asarray(ts, dtype=np.int64))
    return b.with_column("__key_id__", hash_keys_to_i64(b["k"]))


class TestOperator:
    def _op(self):
        op = IntervalJoinOperator(-100, 100, left_outer=True,
                                  right_columns=["k", "vb"])
        op.open(_Ctx())
        return op

    def test_unmatched_left_pads_after_expiry(self):
        op = self._op()
        op.process_batch(_kb({"k": np.asarray([1, 2]),
                              "va": np.asarray([10.0, 20.0])},
                             [1000, 1000]), input_index=0)
        op.process_batch(_kb({"k": np.asarray([1]),
                              "vb": np.asarray([1.5])}, [1050]),
                         input_index=1)
        # before expiry: nothing pads
        assert op.process_watermark(1050) == []
        outs = op.process_watermark(5000)
        assert len(outs) == 1
        rows = outs[0].to_rows()
        assert len(rows) == 1
        assert rows[0]["va"] == 20.0 and np.isnan(rows[0]["vb"])

    def test_matched_left_never_pads(self):
        op = self._op()
        op.process_batch(_kb({"k": np.asarray([1]),
                              "va": np.asarray([10.0])}, [1000]),
                         input_index=0)
        op.process_batch(_kb({"k": np.asarray([1]),
                              "vb": np.asarray([1.5])}, [1050]),
                         input_index=1)
        assert op.process_watermark(10_000) == []

    def test_close_flushes_remaining_unmatched(self):
        op = self._op()
        op.process_batch(_kb({"k": np.asarray([9]),
                              "va": np.asarray([1.0])}, [100]),
                         input_index=0)
        outs = op.close()
        assert len(outs) == 1 and np.isnan(outs[0].to_rows()[0]["vb"])

    def test_declared_right_schema_mismatch_raises(self):
        """Advisor r4 (low): a right batch whose columns drift from the
        declared right_columns must raise, not silently give matched
        and padded batches different schemas."""
        op = self._op()  # declares ["k", "vb"]
        op.process_batch(_kb({"k": np.asarray([1]),
                              "va": np.asarray([1.0])}, [100]),
                         input_index=0)
        with pytest.raises(RuntimeError, match="declared right columns"):
            op.process_batch(_kb({"k": np.asarray([1]),
                                  "OTHER": np.asarray([1.5])}, [100]),
                             input_index=1)

    def test_padded_and_matched_share_dtype_for_int_and_str(self):
        """Integer right columns carry float64 in BOTH matched and
        padded emissions (SQL NULL needs a representation); string
        right columns pad with None, not float NaN."""
        op = IntervalJoinOperator(-100, 100, left_outer=True,
                                  right_columns=["k", "cnt", "tag"])
        op.open(_Ctx())
        op.process_batch(_kb({"k": np.asarray([1, 2]),
                              "va": np.asarray([10.0, 20.0])},
                             [1000, 1000]), input_index=0)
        out = op.process_batch(
            _kb({"k": np.asarray([1]),
                 "cnt": np.asarray([7], dtype=np.int64),
                 "tag": np.asarray(["x"])}, [1050]),
            input_index=1)
        matched = out[0]
        assert matched["cnt"].dtype == np.float64
        assert matched["cnt"][0] == 7.0
        padded = op.process_watermark(5000)[0]
        assert padded["cnt"].dtype == matched["cnt"].dtype
        assert np.isnan(padded["cnt"][0])
        assert padded["tag"].dtype == matched["tag"].dtype == object
        assert padded["tag"][0] is None and matched["tag"][0] == "x"

    def test_restore_with_key_group_filter_after_merge(self):
        """Regression: a right-side match merges the per-batch flag
        arrays into one — restore with a key-group filter must stay
        aligned (and not crash) with multiple buffered left batches."""
        op = self._op()
        op.process_batch(_kb({"k": np.asarray([1, 2]),
                              "va": np.asarray([10.0, 20.0])},
                             [1000, 1000]), input_index=0)
        op.process_batch(_kb({"k": np.asarray([3]),
                              "va": np.asarray([30.0])}, [1100]),
                         input_index=0)
        op.process_batch(_kb({"k": np.asarray([1]),
                              "vb": np.asarray([1.5])}, [1050]),
                         input_index=1)
        snap = op.snapshot_state()
        from flink_tpu.state.keygroups import assign_key_groups

        kids = hash_keys_to_i64(np.asarray([1, 2, 3]))
        groups = assign_key_groups(kids, 128)
        keep = {int(g) for g in groups}  # all groups: full restore
        op2 = self._op()
        op2.restore_state(snap, key_group_filter=keep)
        outs = op2.process_watermark(10_000)
        vas = sorted(r["va"] for b in outs for r in b.to_rows())
        assert vas == [20.0, 30.0]  # key 1 stayed matched

    def test_matched_flags_survive_snapshot_restore(self):
        op = self._op()
        op.process_batch(_kb({"k": np.asarray([1, 2]),
                              "va": np.asarray([10.0, 20.0])},
                             [1000, 1000]), input_index=0)
        op.process_batch(_kb({"k": np.asarray([1]),
                              "vb": np.asarray([1.5])}, [1050]),
                         input_index=1)
        snap = op.snapshot_state()
        op2 = self._op()
        op2.restore_state(snap)
        outs = op2.process_watermark(10_000)
        rows = [r for b in outs for r in b.to_rows()]
        # only key 2 pads — key 1's match was remembered in the snapshot
        assert [r["va"] for r in rows] == [20.0]


class TestLeftJoinSQL:
    def _setup(self, suffix):
        from flink_tpu.connectors.kafka import FakeBroker

        broker = FakeBroker.get("default")
        a, b = f"lja{suffix}", f"ljb{suffix}"
        broker.create_topic(a, 1)
        broker.create_topic(b, 1)
        a_ts = np.asarray([1000, 2000, 3000, 4000], dtype=np.int64)
        broker.append(a, 0, RecordBatch.from_pydict(
            {"k": np.asarray([1, 2, 3, 1], dtype=np.int64),
             "va": np.asarray([10.0, 20.0, 30.0, 40.0]),
             "ats": a_ts}, timestamps=a_ts))
        b_ts = np.asarray([1050, 3100], dtype=np.int64)
        broker.append(b, 0, RecordBatch.from_pydict(
            {"k": np.asarray([1, 3], dtype=np.int64),
             "vb": np.asarray([1.5, 3.5]), "bts": b_ts},
            timestamps=b_ts))
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 2}))
        tenv = StreamTableEnvironment(env)
        for name, cols in ((a, "k BIGINT, va DOUBLE, ats BIGINT, "
                            "WATERMARK FOR ats AS ats"),
                           (b, "k BIGINT, vb DOUBLE, bts BIGINT, "
                            "WATERMARK FOR bts AS bts")):
            tenv.execute_sql(
                f"CREATE TABLE {name} ({cols}) "
                f"WITH ('connector'='kafka', 'topic'='{name}')")
        return tenv, a, b

    def test_left_interval_join(self):
        tenv, a, b = self._setup("1")
        rows = tenv.execute_sql(f"""
            SELECT x.va, y.vb FROM {a} AS x
            LEFT JOIN {b} AS y ON x.k = y.k
            AND y.bts BETWEEN x.ats - INTERVAL '0.2' SECOND
                          AND x.ats + INTERVAL '0.2' SECOND
        """).collect()
        got = {r["va"]: r["vb"] for r in rows}
        assert got[10.0] == 1.5 and got[30.0] == 3.5
        assert np.isnan(got[20.0]) and np.isnan(got[40.0])
        assert len(rows) == 4

    def test_left_join_without_time_bounds_rejected(self):
        from flink_tpu.table.environment import PlanError

        tenv, a, b = self._setup("2")
        with pytest.raises(PlanError, match="event-time bounds"):
            tenv.execute_sql(
                f"SELECT x.va FROM {a} AS x LEFT JOIN {b} AS y "
                "ON x.k = y.k")

    def test_left_join_with_residual_rejected(self):
        from flink_tpu.table.environment import PlanError

        tenv, a, b = self._setup("3")
        with pytest.raises(PlanError, match="LEFT JOIN"):
            tenv.execute_sql(f"""
                SELECT x.va FROM {a} AS x
                LEFT JOIN {b} AS y ON x.k = y.k AND x.va > y.vb
                AND y.bts BETWEEN x.ats - INTERVAL '0.2' SECOND
                              AND x.ats + INTERVAL '0.2' SECOND
            """)
