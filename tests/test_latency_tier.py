"""The fire-latency tier (ROADMAP item 1): incremental pane
pre-aggregation, fire-deadline-aware micro-batching, overlapped fire
harvests, and the fire-latency autoscale signal.

Pins:

- the pane layout's DELTA fire (per-window running partials combined at
  absorb, one closing ring row gathered per fire) is bit-identical to
  the full-window harvest AND to the slot-layout oracle — values and
  emission order — on integer-valued data (float sums refold in record
  order, exact there), across restore-rebuild and late re-firing;
- the mesh window engine's async fires (PendingFire) equal its sync
  fires exactly;
- the mesh session engine's fused delta-fire program family lives in
  the shared PROGRAM_CACHE (kind "delta-fire");
- a fire-deadline-split run (latency.fire-deadline-ms) is output-
  identical to the unsplit run — values AND order — including under
  forced paged eviction, and matches the single-device oracle's values;
- crash-restore-verify over a ``harvest.pending_fire`` chaos fault on
  the async delta-harvest path (forced eviction; with and without a
  mid-stream reshard) stays oracle-identical and seed-deterministic;
- the autoscale policy's fire-latency signal: sustained deadline
  breaches scale up, an active breach vetoes scale-down, cooldown
  holds;
- the ``window`` metric group exposes fire-latency p50/p99 gauges fed
  from the operator reservoir.
"""

import numpy as np
import pytest

from flink_tpu.core.records import KEY_ID_FIELD, RecordBatch
from flink_tpu.windowing.aggregates import (
    CountAggregate,
    MultiAggregate,
    SumAggregate,
)
from flink_tpu.windowing.assigners import SlidingEventTimeWindows
from flink_tpu.windowing.windower import PaneWindower, SliceSharedWindower


def _int_events(n=4000, keys=150, seed=5, rate=1000):
    """Integer-valued float32 payloads: exact under any fold order, so
    delta-vs-full comparisons can demand BITWISE equality."""
    rng = np.random.default_rng(seed)
    ks = rng.integers(0, keys, n).astype(np.int64)
    ts = (np.arange(n, dtype=np.int64) * 1000) // rate
    vs = rng.integers(0, 16, n).astype(np.float32)
    return RecordBatch.from_pydict(
        {KEY_ID_FIELD: ks, "v": vs}, timestamps=ts)


AGG = lambda: MultiAggregate(  # noqa: E731
    [SumAggregate("v", output="s"), CountAggregate(output="n")])


def _drive_rows(w, batch, step=800, wm_lag=500, async_ok=False,
                flush=True):
    """Feed in chunks with advancing watermarks; returns the emitted
    rows IN EMISSION ORDER (the order pin) as (key, ws, we, s, n)."""
    rows = []

    def collect(fired):
        for b in fired:
            if b is None:
                continue
            if hasattr(b, "harvest"):
                b = b.harvest()
                if b is None:
                    continue
            for r in b.to_rows():
                rows.append((r[KEY_ID_FIELD], r["window_start"],
                             r["window_end"], float(r["s"]), int(r["n"])))

    n = len(batch)
    for i in range(0, n, step):
        chunk = batch.slice(i, min(i + step, n))
        w.process_batch(chunk)
        kw = {"async_ok": True} if async_ok else {}
        collect(w.on_watermark(
            int(chunk.timestamps.max()) - wm_lag, **kw))
    if flush:
        kw = {"async_ok": True} if async_ok else {}
        collect(w.on_watermark(1 << 60, **kw))
    return rows


class TestPaneDeltaFire:
    def test_delta_bit_identical_to_full_harvest_and_slot_oracle(self):
        batch = _int_events()
        assigner = lambda: SlidingEventTimeWindows.of(2000, 500)  # noqa
        delta = _drive_rows(PaneWindower(assigner(), AGG(),
                                         capacity=2048, preagg=True),
                            batch)
        full = _drive_rows(PaneWindower(assigner(), AGG(),
                                        capacity=2048, preagg=False),
                           batch)
        slot = _drive_rows(SliceSharedWindower(assigner(), AGG(),
                                               capacity=2048), batch)
        # values AND emission order, bitwise (integer-valued sums)
        assert delta == full and len(delta) > 100
        # vs the slot-layout oracle: same windows/keys/values bitwise;
        # within-window key order differs between LAYOUTS by design
        # (the slot fire sorts keys, the pane fire emits column order)
        assert sorted(delta) == sorted(slot)

    def test_fires_gather_one_partial_row(self):
        batch = _int_events(n=1500)
        w = PaneWindower(SlidingEventTimeWindows.of(2000, 500), AGG(),
                         capacity=1024, preagg=True)
        w.process_batch(batch)
        # partial rows are maintained for the pending windows
        assert len(w.table.window_row) > 0
        pending = set(w.book.pending_windows())
        assert set(w.table.window_row).issubset(pending)
        _drive_rows(w, batch.slice(0, 0))  # final watermark only
        # fired windows release their partial rows
        assert len(w.table.window_row) == 0

    def test_async_delta_equals_sync(self):
        batch = _int_events(seed=11)
        assigner = lambda: SlidingEventTimeWindows.of(2000, 500)  # noqa
        sync = _drive_rows(PaneWindower(assigner(), AGG(),
                                        capacity=2048), batch)
        asyn = _drive_rows(PaneWindower(assigner(), AGG(),
                                        capacity=2048), batch,
                           async_ok=True)
        assert sync == asyn and len(sync) > 50

    def test_restore_rebuilds_partials(self):
        batch = _int_events(n=3000, seed=7)
        half_a, half_b = batch.slice(0, 1500), batch.slice(1500, 3000)
        assigner = lambda: SlidingEventTimeWindows.of(2000, 500)  # noqa
        one = PaneWindower(assigner(), AGG(), capacity=2048, preagg=True)
        rows = _drive_rows(one, half_a, wm_lag=900, flush=False)
        snap = one.snapshot()
        two = PaneWindower(assigner(), AGG(), capacity=2048, preagg=True)
        two.restore(snap)
        # partial rows were refolded from the authoritative panes
        assert set(two.table.window_row) == set(one.table.window_row)
        rows += _drive_rows(two, half_b, wm_lag=900)
        oracle = _drive_rows(
            PaneWindower(assigner(), AGG(), capacity=2048,
                         preagg=False), batch, wm_lag=900)
        assert rows == oracle and len(rows) > 50

    def test_late_refire_refolds_from_panes(self):
        """allowed_lateness > 0: a late record re-registers an
        already-fired window; the delta path must refold that window's
        partial from the retained panes and re-fire identically to the
        full harvest."""
        assigner = lambda: SlidingEventTimeWindows.of(1000, 500)  # noqa

        def run(preagg):
            w = PaneWindower(assigner(), AGG(), capacity=1024,
                             allowed_lateness=2000, preagg=preagg)
            rows = []

            def go(ks, vs, ts, wm):
                w.process_batch(RecordBatch.from_pydict(
                    {KEY_ID_FIELD: np.asarray(ks, dtype=np.int64),
                     "v": np.asarray(vs, dtype=np.float32)},
                    timestamps=ts))
                for b in w.on_watermark(wm):
                    for r in b.to_rows():
                        rows.append((r[KEY_ID_FIELD], r["window_start"],
                                     r["window_end"], float(r["s"]),
                                     int(r["n"])))

            go([1, 2], [3, 5], [100, 600], 1100)   # fires w<=1000
            go([1], [7], [300], 1200)              # LATE: re-fires 1000
            go([2], [2], [1400], 1 << 60)          # flush
            return rows

        assert run(True) == run(False)
        # the late re-firing actually happened (window 1000 emitted twice)
        fired_1000 = [r for r in run(True) if r[2] == 1000]
        assert len(fired_1000) >= 2

    def test_preagg_config_reaches_operator(self):
        from flink_tpu.runtime.operators import (
            OperatorContext,
            WindowAggOperator,
        )

        op = WindowAggOperator(SlidingEventTimeWindows.of(2000, 500),
                               AGG(), key_field="k",
                               window_layout="panes")
        op.open(OperatorContext(parallelism=1, pane_preagg=False))
        assert op.windower._preagg is False
        op2 = WindowAggOperator(SlidingEventTimeWindows.of(2000, 500),
                                AGG(), key_field="k",
                                window_layout="panes")
        op2.open(OperatorContext(parallelism=1))
        assert op2.windower._preagg is True


class TestMeshWindowAsyncFires:
    def _drive(self, mesh, async_ok):
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine

        eng = MeshWindowEngine(SlidingEventTimeWindows.of(2000, 500),
                               AGG(), mesh, capacity_per_shard=2048)
        assert eng.supports_async_fires
        batch = _int_events(n=3000, seed=3)
        rows = []
        n = len(batch)
        for i in range(0, n, 1000):
            chunk = batch.slice(i, min(i + 1000, n))
            eng.process_batch(chunk)
            fired = eng.on_watermark(int(chunk.timestamps.max()) - 600,
                                     async_ok=async_ok)
            for b in fired:
                if hasattr(b, "harvest"):
                    b = b.harvest()
                if b is None:
                    continue
                for r in b.to_rows():
                    rows.append((r[KEY_ID_FIELD], r["window_end"],
                                 float(r["s"]), int(r["n"])))
        for b in eng.on_watermark(1 << 60, async_ok=async_ok):
            if hasattr(b, "harvest"):
                b = b.harvest()
            if b is None:
                continue
            for r in b.to_rows():
                rows.append((r[KEY_ID_FIELD], r["window_end"],
                             float(r["s"]), int(r["n"])))
        return rows

    def test_async_equals_sync(self, eight_device_mesh):
        sync = self._drive(eight_device_mesh, async_ok=False)
        asyn = self._drive(eight_device_mesh, async_ok=True)
        assert sync == asyn and len(sync) > 100


class TestDeltaFireProgramFamily:
    def test_registered_in_shared_cache(self, eight_device_mesh):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.tenancy.program_cache import PROGRAM_CACHE

        eng = MeshSessionEngine(40, SumAggregate("v"),
                                eight_device_mesh,
                                capacity_per_shard=2048)
        eng.process_batch(RecordBatch.from_pydict(
            {KEY_ID_FIELD: np.asarray([1, 2, 3], dtype=np.int64),
             "v": np.ones(3, dtype=np.float32)},
            timestamps=[0, 10, 20]))
        fired = eng.on_watermark(1 << 40)
        assert sum(len(b) for b in fired) == 3
        kinds = {k for (k, _) in PROGRAM_CACHE.programs}
        assert "delta-fire" in kinds


class TestDeadlineSplitExecutor:
    def _run(self, parallelism, deadline_ms, async_fires,
             spill_slots=0, batch=512, data=None, gap=400):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.windowing.assigners import EventTimeSessionWindows

        if data is None:
            rng = np.random.default_rng(23)
            data = []
            t = 0
            for _ in range(4000):
                t += int(rng.integers(1, 50))
                data.append({"key": int(rng.integers(900)),
                             "v": float(rng.integers(1, 8)), "t": t})
        conf = {
            "execution.micro-batch.size": batch,
            "parallelism.default": parallelism,
            "latency.fire-deadline-ms": deadline_ms,
            "execution.window.async-fires": async_fires,
        }
        if spill_slots:
            conf["state.slot-table.capacity"] = spill_slots
            conf["state.slot-table.max-device-slots"] = spill_slots
        env = StreamExecutionEnvironment(Configuration(conf))
        sink = CollectSink()
        (env.from_collection(data, timestamp_field="t")
            .key_by("key")
            .window(EventTimeSessionWindows.with_gap(gap))
            .sum("v").sink_to(sink))
        result = env.execute("latency-tier")
        out = [(r["key"], r["window_start"], r["window_end"],
                float(r["sum_v"]))
               for r in sink.rows()]
        return out, result

    @staticmethod
    def _thrash_data():
        """Live-session set well beyond a 1024-slot/shard budget at
        parallelism 2 (paged eviction genuinely on the path): huge key
        space (sessions ~singletons), slow event time, 700 ms gap."""
        rng = np.random.default_rng(41)
        data = []
        t = 0
        for i in range(12000):
            t += int(rng.integers(0, 2)) if i % 2 else 0
            data.append({"key": int(rng.integers(1_000_000)),
                         "v": float(rng.integers(1, 8)), "t": t})
        return data

    @staticmethod
    def _per_key(rows):
        from collections import defaultdict

        seq = defaultdict(list)
        for k, ws, we, s in rows:
            seq[k].append((ws, we, s))
        return dict(seq)

    def test_split_single_device_bit_identical(self):
        """At parallelism 1 the emission order is fully defined (pop in
        session-end order), so a deadline-split run with mid-stream
        fires must be BIT-IDENTICAL — values and emission order — to
        the synchronous unsplit run."""
        oracle, _ = self._run(parallelism=1, deadline_ms=0,
                              async_fires=False)
        split, _ = self._run(parallelism=1, deadline_ms=2,
                             async_fires=True)
        assert split == oracle and len(split) > 500

    def test_split_mesh_identical(self):
        """On the mesh, emission within ONE watermark advance is shard-
        ordered, so splitting an advance legitimately interleaves shards
        differently — the pins are per-key emission order (session-end
        order both ways) and exact values vs both the whole-batch run
        and the synchronous single-device oracle."""
        oracle, _ = self._run(parallelism=1, deadline_ms=0,
                              async_fires=False)
        split, _ = self._run(parallelism=8, deadline_ms=2,
                             async_fires=True)
        whole, _ = self._run(parallelism=8, deadline_ms=0,
                             async_fires=True)
        assert len(split) > 500
        assert self._per_key(split) == self._per_key(whole) \
            == self._per_key(oracle)

    def test_split_mesh_forced_eviction(self):
        """Same pins at a shape whose live-session set EXCEEDS the
        device budget — the deadline-split delta fires run against the
        paged spill tier, and the test fails as vacuous if eviction
        never engaged."""
        data = self._thrash_data()
        oracle, _ = self._run(parallelism=1, deadline_ms=0,
                              async_fires=False, data=data, gap=700)
        split, res = self._run(parallelism=2, deadline_ms=2,
                               async_fires=True, spill_slots=1024,
                               data=data, gap=700)
        whole, _ = self._run(parallelism=2, deadline_ms=0,
                             async_fires=True, spill_slots=1024,
                             data=data, gap=700)
        snap = res.registry.snapshot()
        evicted = [v for k, v in snap.items()
                   if k.endswith("state.rows_evicted")]
        assert evicted and max(evicted) > 0, "vacuous: no eviction"
        assert len(split) > 500
        assert self._per_key(split) == self._per_key(whole) \
            == self._per_key(oracle)

    def test_deadline_rate_ema_settles(self):
        from flink_tpu.cluster.local_executor import LocalExecutor

        ex = LocalExecutor()
        ex._fire_deadline_ms = 10
        ex._deadline_rate = 0.0
        ex._deadline_observe(1000, 0.01)  # 100k rec/s
        assert ex._deadline_rate == pytest.approx(100_000)
        ex._deadline_observe(1000, 0.02)  # 50k rec/s folds in
        assert 50_000 < ex._deadline_rate < 100_000


class TestChaosDeltaHarvest:
    def test_pending_fire_crash_restore_on_delta_path(
            self, eight_device_mesh, tmp_path):
        """The satellite scenario: a ``harvest.pending_fire`` fault
        kills the job between a delta fire's dispatch and its harvest
        (forced paged eviction on the path); restore + replay must be
        oracle-identical and seed-deterministic."""
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.chaos.injection import FaultPlan, FaultRule
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.sessions import SessionWindower

        GAP = 25
        rng = np.random.default_rng(31)
        steps = []
        for s in range(8):
            keys = rng.integers(0, 6000, 1500).astype(np.int64)
            vals = rng.random(1500).astype(np.float32)
            ts = rng.integers(s * 80, s * 80 + 60, 1500).astype(np.int64)
            steps.append((keys, vals, ts, (s - 1) * 80))
        plan = FaultPlan(rules=[
            FaultRule(pattern="harvest.pending_fire", nth=3)])

        def run(tag, rescales=None):
            return run_crash_restore_verify(
                lambda: MeshSessionEngine(
                    GAP, SumAggregate("v"), eight_device_mesh,
                    capacity_per_shard=1 << 14, max_device_slots=1024),
                lambda: SessionWindower(GAP, SumAggregate("v"),
                                        capacity=1 << 15),
                steps, plan, seed=13,
                ckpt_root=str(tmp_path / f"ckpt-{tag}"),
                checkpoint_every=2, async_fires=True,
                rescales=rescales)

        r1 = run("a")
        assert not r1.diverged and r1.windows > 0
        assert r1.crashes >= 1 and r1.restores >= 1
        assert r1.faults_injected.get("harvest.pending_fire", 0) >= 1
        r2 = run("b")
        assert r2.signature() == r1.signature()

    def test_pending_fire_crash_with_midstream_reshard(
            self, eight_device_mesh, tmp_path):
        from flink_tpu.chaos.harness import run_crash_restore_verify
        from flink_tpu.chaos.injection import FaultPlan, FaultRule
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine
        from flink_tpu.windowing.sessions import SessionWindower

        GAP = 25
        rng = np.random.default_rng(37)
        steps = []
        for s in range(8):
            keys = rng.integers(0, 6000, 1500).astype(np.int64)
            vals = rng.random(1500).astype(np.float32)
            ts = rng.integers(s * 80, s * 80 + 60, 1500).astype(np.int64)
            steps.append((keys, vals, ts, (s - 1) * 80))
        plan = FaultPlan(rules=[
            FaultRule(pattern="harvest.pending_fire", nth=4)])
        r = run_crash_restore_verify(
            lambda: MeshSessionEngine(
                GAP, SumAggregate("v"), eight_device_mesh,
                capacity_per_shard=1 << 14, max_device_slots=1024),
            lambda: SessionWindower(GAP, SumAggregate("v"),
                                    capacity=1 << 15),
            steps, plan, seed=19,
            ckpt_root=str(tmp_path / "ckpt"),
            checkpoint_every=2, async_fires=True,
            rescales={3: 4})
        assert not r.diverged and r.windows > 0
        assert r.faults_injected.get("harvest.pending_fire", 0) >= 1


class TestFireLatencyAutoscaleSignal:
    def _policy(self, **kw):
        from flink_tpu.autoscale.policy import ScalingPolicy

        base = dict(cooldown_s=0.0, fire_deadline_ms=100.0,
                    fire_breach_ticks=3, max_shards=16)
        base.update(kw)
        return ScalingPolicy(**base)

    def _inp(self, shards=4, p99=0.0, rate=0.0, busy=0.0, **kw):
        from flink_tpu.autoscale.policy import PolicyInput

        return PolicyInput(current_shards=shards, processing_rate=rate,
                           busy_fraction=busy, fire_latency_p99_ms=p99,
                           **kw)

    def test_sustained_breach_scales_up(self):
        p = self._policy()
        # two breaches: not yet (a single slow harvest is noise)
        assert p.decide(self._inp(p99=250.0), now=1.0).target == 4
        assert p.decide(self._inp(p99=250.0), now=2.0).target == 4
        d = p.decide(self._inp(p99=250.0), now=3.0)
        assert d.target == 6 and d.reason == "fire-latency" and d.rescale

    def test_recovery_resets_streak(self):
        p = self._policy()
        p.decide(self._inp(p99=250.0), now=1.0)
        p.decide(self._inp(p99=250.0), now=2.0)
        p.decide(self._inp(p99=50.0), now=3.0)   # back under deadline
        d = p.decide(self._inp(p99=250.0), now=4.0)
        assert d.target == 4  # streak restarted

    def test_breach_vetoes_scale_down(self):
        p = self._policy(hysteresis=0.0)
        # rate signal says "half the shards would do", but fires are
        # missing their deadline — hold
        inp = self._inp(shards=4, p99=250.0, rate=100.0, busy=0.25)
        d = p.decide(inp, now=1.0)
        assert d.target == 4 and d.reason == "fire-latency-hold"
        assert not d.rescale

    def test_cooldown_holds_breach_scaleup(self):
        p = self._policy(cooldown_s=60.0)
        p.mark_rescaled(now=0.0)
        for t in (1.0, 2.0, 3.0, 4.0):
            d = p.decide(self._inp(p99=250.0), now=t)
        assert d.target == 4 and d.reason == "cooldown"

    def test_no_deadline_no_signal(self):
        p = self._policy(fire_deadline_ms=0.0)
        for t in (1.0, 2.0, 3.0, 4.0):
            d = p.decide(self._inp(p99=9999.0), now=t)
        assert d.target == 4 and d.reason == "no-signal"

    def test_controller_passes_signal_through(self):
        from flink_tpu.autoscale.controller import (
            AutoscaleController,
            SignalSample,
        )

        seen = []
        policy = self._policy(fire_breach_ticks=1)
        orig = policy.decide

        def spy(inp, now=None):
            seen.append(inp.fire_latency_p99_ms)
            return orig(inp, now=now)

        policy.decide = spy
        clock_t = [0.0]
        ctl = AutoscaleController(
            policy,
            sample_fn=lambda: SignalSample(records_total=100.0,
                                           busy_ms_total=10.0,
                                           fire_latency_p99_ms=321.0),
            apply_fn=lambda n: {"seconds": 0.0},
            current_shards_fn=lambda: 4,
            interval_s=0.0, clock=lambda: clock_t[0])
        ctl.tick()
        clock_t[0] = 1.0
        ctl.tick()
        assert seen and seen[-1] == 321.0


class TestWindowMetricGroup:
    def test_known_group_and_gauges(self):
        from flink_tpu.metrics import KNOWN_METRIC_GROUPS

        assert "window" in KNOWN_METRIC_GROUPS

    def test_fire_latency_gauges_registered(self):
        from flink_tpu import Configuration, StreamExecutionEnvironment
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 64}))
        sink = CollectSink()
        rows = [{"k": i % 5, "v": 1, "ts": i * 50} for i in range(500)]
        (env.from_collection(rows, timestamp_field="ts")
            .key_by("k").window(TumblingEventTimeWindows.of(1000))
            .sum("v").sink_to(sink))
        result = env.execute("window-metrics")
        snap = result.registry.snapshot()
        p99 = [k for k in snap if k.endswith("window.fireLatencyP99Ms")]
        p50 = [k for k in snap if k.endswith("window.fireLatencyP50Ms")]
        cnt = [k for k in snap if k.endswith("window.fireCount")]
        assert p99 and p50 and cnt
        assert any(snap[k] > 0 for k in cnt)
