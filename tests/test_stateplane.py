"""The shared state-plane kernel library (flink_tpu/stateplane).

Three contracts:

- **One library, one cache entry per (family, key)**: every engine's
  device programs come from the ``families`` builders, keyed on WHAT
  they compute — two owners with the same plane layout share the
  executable object (the multi-tenant zero-recompile contract, now
  enforced at the library boundary).
- **Backend hook honesty**: ``stateplane.backend.<family>`` resolves
  per family, rejects unknown families/backends, and refuses a pallas
  request for a family with no pallas implementation (a config typo
  must not vacuously pass an A/B experiment).
- **Golden bit identity**: the Pallas exchange-rank kernel equals the
  XLA one-hot-cumsum EXACTLY on random shapes (ranks AND the
  downstream fold/scatter order), and ported engines driven through
  forced paged eviction plus a live mid-stream reshard pin their
  fires (including emission order), snapshots (including row order),
  deltas and spill counters — run-to-run and against the host data
  plane.
"""

import numpy as np
import pytest

from flink_tpu.stateplane import (
    KNOWN_PROGRAM_FAMILIES,
    backend_of,
    backend_scope,
    build_exchange_rank,
    configure_backends,
    exchange_rank_flat,
    flat_fence,
    flat_gather,
    flat_put,
    flat_scatter_combine,
    flat_segment_fire,
    pallas_available,
    set_backend,
    xla_rank,
)
from flink_tpu.windowing.aggregates import AvgAggregate, SumAggregate

needs_pallas = pytest.mark.skipif(
    not pallas_available(),
    reason="pallas kernel unavailable on this host")


# ------------------------------------------------------------- families


class TestProgramFamilies:
    def test_registry_is_duplicate_free(self):
        assert len(KNOWN_PROGRAM_FAMILIES) == \
            len(set(KNOWN_PROGRAM_FAMILIES))

    def test_builders_key_on_what_not_who(self):
        """Two aggregate INSTANCES with the same plane layout share
        every program object — the library keys on (methods, dtypes),
        never on an owner identity."""
        a, b = SumAggregate("v"), SumAggregate("w")
        assert flat_scatter_combine(a.leaves) is \
            flat_scatter_combine(b.leaves)
        assert flat_gather(a.leaves) is flat_gather(b.leaves)
        assert flat_put(a.leaves) is flat_put(b.leaves)
        # fire keys on agg.cache_key() (finish parameters count);
        # equal-keyed instances share, distinct fields do not alias
        assert flat_segment_fire(SumAggregate("v")) is \
            flat_segment_fire(SumAggregate("v"))
        assert flat_fence("<f4") is flat_fence("<f4")

    def test_distinct_layouts_do_not_collide(self):
        assert flat_scatter_combine(SumAggregate("v").leaves) is not \
            flat_scatter_combine(AvgAggregate("v").leaves)

    def test_registry_matches_source_literal(self):
        """flint's REG04 parses the tuple statically; the import path
        must agree with the literal (same pin as KNOWN_FAULT_POINTS)."""
        import ast
        from pathlib import Path

        src = (Path(__file__).resolve().parents[1]
               / "flink_tpu/stateplane/families.py").read_text()
        for node in ast.parse(src).body:
            if isinstance(node, ast.Assign) and any(
                    getattr(t, "id", None) == "KNOWN_PROGRAM_FAMILIES"
                    for t in node.targets):
                parsed = tuple(e.value for e in node.value.elts)
                assert parsed == KNOWN_PROGRAM_FAMILIES
                return
        pytest.fail("KNOWN_PROGRAM_FAMILIES literal not found")


# -------------------------------------------------------------- backends


class TestBackendHook:
    def test_default_is_xla(self):
        assert backend_of("exchange-rank") == "xla"
        assert backend_of("gather") == "xla"

    def test_scope_restores(self):
        with backend_scope("exchange-rank", "pallas"):
            assert backend_of("exchange-rank") == "pallas"
        assert backend_of("exchange-rank") == "xla"

    def test_unknown_family_rejected(self):
        with pytest.raises(ValueError, match="unknown program family"):
            set_backend("exchange-rnak", "pallas")

    def test_unknown_backend_rejected(self):
        with pytest.raises(ValueError, match="unknown backend"):
            set_backend("exchange-rank", "triton")

    def test_pallas_for_incapable_family_rejected(self):
        """No silent xla fallback: a family without a pallas
        implementation refuses the override outright."""
        with pytest.raises(ValueError, match="no pallas implementation"):
            set_backend("gather", "pallas")

    def test_config_hook_applies_and_restores(self):
        from flink_tpu.core.config import Configuration

        conf = Configuration(
            {"stateplane.backend.exchange-rank": "pallas"})
        try:
            applied = configure_backends(conf)
            assert applied == {"exchange-rank": "pallas"}
            assert backend_of("exchange-rank") == "pallas"
        finally:
            set_backend("exchange-rank", "xla")

    def test_config_hook_rejects_typo_family(self):
        from flink_tpu.core.config import Configuration

        conf = Configuration({"stateplane.backend.gather": "pallas"})
        with pytest.raises(ValueError):
            configure_backends(conf)

    def test_config_hook_scans_keys_not_known_names(self):
        """A typo'd FAMILY in the config key must raise, not be
        silently skipped — the hook scans the key space for the
        prefix (including fallback layers)."""
        from flink_tpu.core.config import Configuration

        conf = Configuration({"stateplane.backend.gahter": "xla"})
        with pytest.raises(ValueError, match="unknown program family"):
            configure_backends(conf)
        layered = Configuration({"unrelated.key": 1}).with_fallback(
            Configuration({"stateplane.backend.exchange-rnak": "xla"}))
        with pytest.raises(ValueError, match="unknown program family"):
            configure_backends(layered)

    def test_executor_applies_backend_config_at_submit(self):
        """A job Configuration's stateplane.backend.* keys take effect
        through the executor — and an invalid one fails the job at
        SUBMIT, before any batch runs."""
        from flink_tpu.connectors.sinks import CollectSink
        from flink_tpu.connectors.sources import DataGenSource
        from flink_tpu.core.config import Configuration
        from flink_tpu.datastream.environment import (
            StreamExecutionEnvironment,
        )
        from flink_tpu.runtime.watermarks import WatermarkStrategy
        from flink_tpu.windowing.assigners import (
            TumblingEventTimeWindows,
        )

        def job(conf):
            env = StreamExecutionEnvironment(conf)
            sink = CollectSink()
            (env.add_source(
                DataGenSource(total_records=2000, num_keys=16,
                              events_per_second_of_eventtime=2000),
                WatermarkStrategy.for_bounded_out_of_orderness(0))
             .key_by("key")
             .window(TumblingEventTimeWindows.of(1000))
             .count()
             .sink_to(sink))
            env.execute()
            return sink

        try:
            sink = job(Configuration(
                {"stateplane.backend.exchange-rank": "xla"}))
            assert len(sink.rows()) > 0
            assert backend_of("exchange-rank") == "xla"
            with pytest.raises(ValueError, match="unknown program"):
                job(Configuration(
                    {"stateplane.backend.gahter": "xla"}))
        finally:
            set_backend("exchange-rank", "xla")


# ---------------------------------------------------- rank kernel parity


@needs_pallas
class TestPallasRankParity:
    def test_random_shapes_bit_identical(self):
        """Property test: over random (num_dests, length, width) the
        Pallas counting sort equals the XLA one-hot-cumsum EXACTLY —
        ranks and the flattened (dest, rank) scatter positions,
        including the out-of-range destinations staging pads with and
        bucket-overflow lanes."""
        from flink_tpu.stateplane.rank import pallas_rank

        rng = np.random.default_rng(19)
        for _ in range(25):
            D = int(rng.integers(1, 17))
            n = int(rng.integers(1, 500))
            W = int(rng.integers(1, 64))
            d = rng.integers(-2, D + 3, size=n).astype(np.int32)
            np.testing.assert_array_equal(
                np.asarray(pallas_rank(d, D)),
                np.asarray(xla_rank(d, D)))
            np.testing.assert_array_equal(
                np.asarray(exchange_rank_flat(d, D, W, "pallas")),
                np.asarray(exchange_rank_flat(d, D, W, "xla")))

    def test_cached_program_parity_and_distinct_keys(self):
        """The cached exchange-rank programs agree across backends and
        occupy DISTINCT cache entries (cache-key honesty: a backend
        swap is a new key, never a silent retrace)."""
        d = np.asarray([3, 0, 1, 0, 7, 3, 3, -1, 0], dtype=np.int32)
        px = build_exchange_rank(8, "xla")
        pp = build_exchange_rank(8, "pallas")
        assert px is not pp
        np.testing.assert_array_equal(
            np.asarray(px(d, 4)), np.asarray(pp(d, 4)))

    def test_downstream_fold_order_identical(self, eight_device_mesh):
        """The full fused exchange+scatter program under the pallas
        rank backend equals the xla-backed one bit-for-bit — same
        bucket positions means same scatter order means identical
        state planes (the fold-order half of the A/B gate)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from flink_tpu.parallel.mesh import KEY_AXIS
        from flink_tpu.parallel.shuffle import (
            build_exchange_scatter,
            stage_device_exchange,
        )

        mesh = eight_device_mesh
        agg = SumAggregate("v")
        sharding = NamedSharding(mesh, P(KEY_AXIS))
        cap = 2048
        rng = np.random.default_rng(5)
        n = 4000
        shards = rng.integers(0, 8, n).astype(np.int64)
        slots = rng.integers(1, cap, n).astype(np.int32)
        vals = rng.integers(0, 100, n).astype(np.float32)
        dst, staged, width = stage_device_exchange(
            shards, 8, [slots, vals], fills=[0, 0.0])
        put = jax.device_put((dst, *staged), sharding)

        def run():
            accs = tuple(
                jax.device_put(jnp.full((8, cap), l.identity,
                                        dtype=l.dtype), sharding)
                for l in agg.leaves)
            step = build_exchange_scatter(mesh, agg, valued=False)
            return jax.device_get(list(step(
                accs, put[0], put[1], tuple(put[2:]), width)))

        base = run()
        with backend_scope("exchange-rank", "pallas"):
            swapped = run()
        for b, s in zip(base, swapped):
            np.testing.assert_array_equal(np.asarray(b),
                                          np.asarray(s))


# ------------------------------------------------------- golden identity


GAP = 100


def _stream(num_keys=20_000, n_steps=6, per_step=5000, seed=41):
    """Live set far beyond the device budget — forced paged eviction
    with integer-valued float sums so bit-identity is meaningful."""
    rng = np.random.default_rng(seed)
    steps = []
    for s in range(n_steps):
        keys = rng.integers(0, num_keys, per_step).astype(np.int64)
        vals = rng.integers(0, 1000, per_step).astype(np.float32)
        ts = rng.integers(s * 80, s * 80 + 60, per_step).astype(np.int64)
        steps.append((keys, vals, ts, (s - 1) * 80))
    return steps


def _drive(engine, steps, reshard_at=None, reshard_to=None,
           delta_at=None):
    """Run the stream; returns (fires, deltas) where fires preserve
    emission order and deltas are the engine's mid-stream incremental
    snapshots (mode="delta") taken at ``delta_at`` boundaries."""
    from tests.test_sessions import keyed_batch

    fires, deltas = [], []
    for i, (keys, vals, ts, wm) in enumerate(steps):
        if reshard_at is not None and i == reshard_at:
            engine.reshard(reshard_to)
        engine.process_batch(keyed_batch(keys, vals, ts))
        fires.extend(engine.on_watermark(wm))
        if delta_at is not None and i in delta_at:
            deltas.append(engine.snapshot(mode="delta"))
    return fires, deltas


def _fire_rows(batches):
    """Order-PRESERVING flatten: a reordered emission diverges even
    when the value multiset matches."""
    rows = []
    for b in batches:
        for r, t in zip(b.to_rows(),
                        np.asarray(b.timestamps).tolist()):
            rows.append((t, tuple(sorted(r.items()))))
    return rows


def _assert_deep_equal(a, b, path=""):
    """Bit-exact structural equality — dict key ORDER and array row
    ORDER both count (the snapshot's row order is part of the golden
    contract: a nondeterministic harvest would reorder it)."""
    assert type(a) is type(b), f"{path}: {type(a)} vs {type(b)}"
    if isinstance(a, dict):
        assert list(a.keys()) == list(b.keys()), path
        for k in a:
            _assert_deep_equal(a[k], b[k], f"{path}/{k}")
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_deep_equal(x, y, f"{path}[{i}]")
    elif isinstance(a, np.ndarray):
        np.testing.assert_array_equal(a, b, err_msg=path)
    else:
        assert a == b, path


class TestGoldenBitIdentity:
    """Ported engines under forced eviction + live reshard: every
    observable — fires (order included), snapshots (row order
    included), deltas, spill counters — is pinned bit-identical
    run-to-run, and fires are pinned against the host data plane."""

    def _window_engine(self, mesh, mode="device"):
        from flink_tpu.parallel.sharded_windower import MeshWindowEngine
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        return MeshWindowEngine(TumblingEventTimeWindows.of(50),
                                SumAggregate("v"), mesh,
                                capacity_per_shard=1 << 14,
                                shuffle_mode=mode,
                                max_device_slots=2048)

    def _session_engine(self, mesh, mode="device"):
        from flink_tpu.parallel.sharded_sessions import MeshSessionEngine

        return MeshSessionEngine(gap=GAP, agg=SumAggregate("v"),
                                 mesh=mesh,
                                 capacity_per_shard=1 << 14,
                                 shuffle_mode=mode,
                                 max_device_slots=1024)

    def test_window_engine_golden_replay(self, eight_device_mesh):
        steps = _stream(seed=43)
        runs = []
        for _ in range(2):
            eng = self._window_engine(eight_device_mesh)
            fires, deltas = _drive(eng, steps, reshard_at=3,
                                   reshard_to=4, delta_at={2, 4})
            runs.append((_fire_rows(fires), deltas,
                         eng.snapshot(mode="full"),
                         eng.spill_counters()))
        (f1, d1, s1, c1), (f2, d2, s2, c2) = runs
        assert len(f1) > 0, "vacuous run: no fires"
        assert f1 == f2, "fires (or their order) diverge run-to-run"
        _assert_deep_equal(d1, d2, "delta")
        _assert_deep_equal(s1, s2, "snapshot")
        assert c1 == c2, f"spill counters diverge: {c1} vs {c2}"
        assert c1["pages_evicted"] > 0, \
            "vacuous run: eviction never engaged"

    def test_session_engine_golden_replay(self, eight_device_mesh):
        steps = _stream(seed=47)
        runs = []
        for _ in range(2):
            eng = self._session_engine(eight_device_mesh)
            fires, deltas = _drive(eng, steps, reshard_at=3,
                                   reshard_to=4, delta_at={4})
            runs.append((_fire_rows(fires), deltas,
                         eng.snapshot(mode="full"),
                         eng.spill_counters()))
        (f1, d1, s1, c1), (f2, d2, s2, c2) = runs
        assert len(f1) > 0, "vacuous run: no fires"
        assert f1 == f2
        _assert_deep_equal(d1, d2, "delta")
        _assert_deep_equal(s1, s2, "snapshot")
        assert c1 == c2
        assert c1["pages_evicted"] > 0 and c1["rows_reloaded"] > 0

    def test_device_fires_match_host_plane_under_eviction(
            self, eight_device_mesh):
        """The ported device exchange path vs the host bucketing path:
        the fired VALUES must agree per (key, window) even though
        emission grouping differs across data planes."""
        from flink_tpu.core.records import KEY_ID_FIELD

        def vals_of(batches):
            out = {}
            for b in batches:
                for r in b.to_rows():
                    out[(r[KEY_ID_FIELD], r["window_start"],
                         r["window_end"])] = r["sum_v"]
            return out

        steps = _stream(seed=53)
        dev, _ = _drive(self._window_engine(eight_device_mesh,
                                            "device"), steps)
        host, _ = _drive(self._window_engine(eight_device_mesh,
                                             "host"), steps)
        v_dev, v_host = vals_of(dev), vals_of(host)
        assert len(v_dev) > 0 and v_dev == v_host

    @needs_pallas
    def test_session_fires_identical_under_pallas_rank(
            self, eight_device_mesh):
        """The engine-level half of the Pallas A/B gate: a device-mode
        session run with the pallas exchange-rank backend emits
        bit-identical fires IN ORDER vs the xla backend."""
        steps = _stream(seed=59, n_steps=4)
        base, _ = _drive(self._session_engine(eight_device_mesh),
                         steps)
        with backend_scope("exchange-rank", "pallas"):
            swapped, _ = _drive(
                self._session_engine(eight_device_mesh), steps)
        assert len(_fire_rows(base)) > 0
        assert _fire_rows(base) == _fire_rows(swapped)
