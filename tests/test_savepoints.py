"""Savepoints, claim modes, stop-with-savepoint, State Processor API,
rescale-on-restore.

reference test model: savepoint ITCases (flink-tests/.../checkpointing/
SavepointITCase), state-processor tests
(flink-libraries/flink-state-processing-api/src/test), rescaling ITCases.
"""

import os
import time

import numpy as np
import pytest

from flink_tpu.checkpoint.savepoint import (
    RestoreMode,
    is_savepoint,
    prepare_restore,
    write_savepoint,
)
from flink_tpu.checkpoint.storage import resolve_snapshot_dir
from flink_tpu.cluster.minicluster import FINISHED, MiniCluster
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.connectors.sources import DataGenSource, Source
from flink_tpu.core.config import Configuration
from flink_tpu.core.records import RecordBatch
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.state_processor import (
    KeyedStateBootstrap,
    SavepointReader,
    SavepointWriter,
)
from flink_tpu.windowing.assigners import TumblingEventTimeWindows


class SlowDataGen(DataGenSource):
    """DataGen that sleeps per poll so a client can savepoint mid-flight."""

    def __init__(self, *args, sleep_s=0.01, **kwargs):
        super().__init__(*args, **kwargs)
        self._sleep_s = sleep_s

    def poll_batch(self, max_records):
        b = super().poll_batch(max_records)
        if b is not None:
            time.sleep(self._sleep_s)
        return b


def build_count_pipeline(env, total, num_keys=40, rate=10_000,
                         source_cls=DataGenSource, sink=None, **src_kw):
    if sink is None:
        sink = CollectSink()
    src = source_cls(total_records=total, num_keys=num_keys,
                     events_per_second_of_eventtime=rate, **src_kw)
    (env.add_source(src, WatermarkStrategy.for_bounded_out_of_orderness(0))
        .key_by("key")
        .window(TumblingEventTimeWindows.of(1000))
        .count()
        .sink_to(sink))
    return sink


def counts_by_key_window(rows):
    return {(int(r["key"]), int(r["window_start"])): int(r["count"])
            for r in rows}


class TestSavepointTrigger:
    def test_trigger_savepoint_while_running(self, tmp_path):
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512}))
            build_count_pipeline(env, total=40_000, source_cls=SlowDataGen)
            client = cluster.submit(env, "sp-job")
            sp_path = str(tmp_path / "sp1")
            # wait for RUNNING then savepoint mid-flight
            deadline = time.monotonic() + 10
            path = None
            while time.monotonic() < deadline:
                try:
                    path = client.trigger_savepoint(sp_path)
                    break
                except RuntimeError:
                    time.sleep(0.02)
            assert path == sp_path
            assert is_savepoint(sp_path)
            reader = SavepointReader.load(sp_path)
            assert reader.operators()  # source position + window state
            # job keeps running to completion after the savepoint
            assert client.wait(timeout=30)["status"] == FINISHED
        finally:
            cluster.shutdown()

    def test_stop_with_savepoint_and_resume_is_exactly_once(self, tmp_path):
        # uninterrupted oracle run
        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        oracle_sink = build_count_pipeline(env, total=20_000)
        env.execute("oracle")
        oracle = counts_by_key_window(oracle_sink.rows())

        # run 1: stop-with-savepoint mid-flight. The graph is serialized to
        # the worker, so results must come back through the filesystem.
        from flink_tpu.connectors.sinks import JsonLinesFileSink

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        sp_path = str(tmp_path / "sp-stop")
        out1 = str(tmp_path / "part1.jsonl")
        try:
            env1 = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512}))
            build_count_pipeline(env1, total=20_000, source_cls=SlowDataGen,
                                 sink=JsonLinesFileSink(out1))
            client = cluster.submit(env1, "stop-job")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    client.stop_with_savepoint(sp_path)
                    break
                except RuntimeError:
                    time.sleep(0.02)
            assert client.wait(timeout=30)["status"] == FINISHED
        finally:
            cluster.shutdown()
        import json as _json

        with open(out1) as f:
            part1 = counts_by_key_window(
                [_json.loads(line) for line in f if line.strip()])
        assert len(part1) < len(oracle)  # genuinely stopped mid-flight

        # run 2: resume from the savepoint, same pipeline shape (same source
        # class — operator identity is part of the stable uid)
        env2 = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        sink2 = build_count_pipeline(env2, total=20_000,
                                     source_cls=SlowDataGen, sleep_s=0)
        env2.execute("resume", restore_from=sp_path)
        part2 = counts_by_key_window(sink2.rows())

        # no window fired twice, union equals the oracle exactly
        assert not (set(part1) & set(part2))
        merged = {**part1, **part2}
        assert merged == oracle

    def test_stop_with_savepoint_drain_flushes_windows(self, tmp_path):
        from flink_tpu.connectors.sinks import JsonLinesFileSink

        cluster = MiniCluster(Configuration({"rest.port": -1}))
        sp_path = str(tmp_path / "sp-drain")
        out = str(tmp_path / "drained.jsonl")
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512}))
            build_count_pipeline(env, total=30_000, source_cls=SlowDataGen,
                                 sink=JsonLinesFileSink(out))
            client = cluster.submit(env, "drain-job")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                try:
                    client.stop_with_savepoint(sp_path, drain=True)
                    break
                except RuntimeError:
                    time.sleep(0.02)
            assert client.wait(timeout=30)["status"] == FINISHED
        finally:
            cluster.shutdown()
        import json as _json

        with open(out) as f:
            rows = [_json.loads(line) for line in f if line.strip()]
        # drained: every record seen so far was flushed into a fired window
        total_counted = sum(int(r["count"]) for r in rows)
        reader = SavepointReader.load(sp_path)
        emitted_counts = [reader.read_source_position(u)["emitted"]
                          for u in reader.operators()
                          if "source" in reader.read_state(u)]
        assert emitted_counts and total_counted == emitted_counts[0]


class TestRestoreModes:
    def _make_savepoint(self, tmp_path, total=5_000):
        env = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        build_count_pipeline(env, total=total)
        # produce a savepoint via the state processor (fastest offline path):
        # run with checkpoints, copy latest into a savepoint
        ck = str(tmp_path / "ck-src")
        env2 = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 3,
        }))
        build_count_pipeline(env2, total=total)
        env2.execute("ck-job")
        sp = str(tmp_path / "the-savepoint")
        SavepointWriter.from_existing(ck).write(sp)
        return sp

    def test_no_claim_leaves_savepoint_intact(self, tmp_path):
        sp = self._make_savepoint(tmp_path)
        ck2 = str(tmp_path / "ck-new")
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck2,
            "execution.checkpointing.every-n-source-batches": 2,
        }))
        build_count_pipeline(env, total=20_000)
        env.execute("resume-nc", restore_from=sp, restore_mode="no-claim")
        assert os.path.exists(os.path.join(sp, "manifest.json"))

    def test_claim_deletes_savepoint_once_subsumed(self, tmp_path):
        sp = self._make_savepoint(tmp_path)
        ck2 = str(tmp_path / "ck-new")
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck2,
            "execution.checkpointing.every-n-source-batches": 2,
        }))
        build_count_pipeline(env, total=20_000)
        env.execute("resume-c", restore_from=sp, restore_mode="claim")
        assert not os.path.exists(sp)  # claimed + subsumed -> deleted

    def test_claim_never_deletes_own_chain(self, tmp_path):
        ck = str(tmp_path / "ck-own")
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 3,
        }))
        build_count_pipeline(env, total=5_000)
        env.execute("first")
        env2 = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 3,
        }))
        build_count_pipeline(env2, total=10_000)
        env2.execute("second", restore_from=ck, restore_mode="claim")
        # chain continued, retention policy governs deletions — the claimed
        # sibling was not force-deleted by claim handling
        assert resolve_snapshot_dir(ck)


class TestStateProcessor:
    def test_read_keyed_state(self, tmp_path):
        ck = str(tmp_path / "ck")
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2,
        }))
        build_count_pipeline(env, total=8_000, num_keys=16)
        env.execute("sp-read")
        reader = SavepointReader.load(ck)
        keyed_uids = [u for u in reader.operators()
                      if reader.has_keyed_state(u)]
        assert keyed_uids
        batch = reader.read_keyed_state(keyed_uids[0])
        assert "key_id" in batch.columns and "key_group" in batch.columns
        # key groups follow the contract (0 <= g < max_parallelism)
        assert batch["key_group"].min() >= 0
        assert batch["key_group"].max() < 128

    def test_bootstrap_and_restore(self, tmp_path):
        """Write a savepoint from raw data, then start a job from it —
        pre-seeded counts add to streamed ones."""
        sp = str(tmp_path / "boot")
        # discover the pipeline's stable uids + state schema via a probe run
        probe_env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": str(tmp_path / "probe-ck"),
            "execution.checkpointing.every-n-source-batches": 1,
        }))
        build_count_pipeline(probe_env, total=4_000, num_keys=4, rate=4_000)
        probe_env.execute("probe")
        reader = SavepointReader.load(str(tmp_path / "probe-ck"))
        window_uid = [u for u in reader.operators()
                      if reader.has_keyed_state(u)][0]
        source_uid = [u for u in reader.operators()
                      if "source" in reader.read_state(u)][0]
        probe_state = reader.read_state(window_uid)

        # bootstrap: key 0, the very first window [0, 1000) (slice end
        # 1000), pre-count 1000. The operator nests its windower state;
        # reuse the probe's schema with fresh bookkeeping.
        boot = KeyedStateBootstrap(
            key_ids=[0], namespaces=[1000], leaves=[np.array([1000])])
        state = {
            k: v for k, v in probe_state.items() if k != "windower"}
        state["windower"] = {
            "table": boot.table,
            "pending": [1000],
            "slice_last_window": {1000: 1000},
        }
        writer = SavepointWriter.new_savepoint("boot-job")
        writer.with_operator(window_uid, state)
        fresh = DataGenSource(total_records=4_000, num_keys=4,
                              events_per_second_of_eventtime=4_000)
        fresh.open()
        writer.with_operator(source_uid, {
            "source": fresh.snapshot_position()})
        writer.write(sp)

        env_plain = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        sink_plain = build_count_pipeline(env_plain, total=4_000, num_keys=4,
                                          rate=4_000)
        env_plain.execute("plain")
        env3 = StreamExecutionEnvironment(Configuration(
            {"execution.micro-batch.size": 512}))
        sink3 = build_count_pipeline(env3, total=4_000, num_keys=4,
                                     rate=4_000)
        env3.execute("from-boot", restore_from=sp)
        plain = counts_by_key_window(sink_plain.rows())
        seeded = counts_by_key_window(sink3.rows())
        boosted = (0, 0)
        for kw in plain:
            expect = plain[kw] + (1000 if kw == boosted else 0)
            assert seeded[kw] == expect, (kw, seeded[kw], expect)

    def test_remove_operator_and_transform(self, tmp_path):
        ck = str(tmp_path / "ck")
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 512,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 2,
        }))
        build_count_pipeline(env, total=8_000)
        env.execute("sp2")
        w = SavepointWriter.from_existing(ck)
        uid = w._states and list(w._states)[0]
        w.remove_operator(uid)
        out = str(tmp_path / "derived")
        w.write(out)
        assert uid not in SavepointReader.load(out).operators()

        # transform: double every count leaf
        w2 = SavepointWriter.from_existing(ck)
        rd = SavepointReader.load(ck)
        keyed = [u for u in rd.operators() if rd.has_keyed_state(u)]

        def double(state):
            t = dict(state["windower"]["table"])
            t["leaf_0"] = np.asarray(t["leaf_0"]) * 2
            return {**state,
                    "windower": {**state["windower"], "table": t}}

        w2.transform_operator(keyed[0], double)
        out2 = str(tmp_path / "doubled")
        w2.write(out2)
        a = SavepointReader.load(ck).read_keyed_state(keyed[0])
        b = SavepointReader.load(out2).read_keyed_state(keyed[0])
        np.testing.assert_array_equal(np.asarray(a["leaf_0"]) * 2,
                                      b["leaf_0"])

    def test_writer_refuses_overwrite(self, tmp_path):
        sp = str(tmp_path / "x")
        SavepointWriter.new_savepoint().with_operator(
            "op", {"table": {"key_id": np.array([1]),
                             "namespace": np.array([1]),
                             "key_group": np.array([0])}}).write(sp)
        with pytest.raises(FileExistsError):
            SavepointWriter.new_savepoint().with_operator(
                "op", {"k": np.array([1])}).write(sp)


class TestRescaleRestore:
    def test_slot_table_snapshot_rescales_by_key_group(self):
        """A snapshot taken at one parallelism restores at another: each new
        subtask filters its own key-group range; the union is exact
        (reference: KeyGroupRangeAssignment rescale contract)."""
        from flink_tpu.state.keygroups import compute_key_group_range
        from flink_tpu.state.slot_table import SlotTable
        from flink_tpu.windowing.aggregates import SumAggregate

        agg = SumAggregate("v")
        t = SlotTable(agg, capacity=4096, max_parallelism=128)
        rng = np.random.default_rng(7)
        keys = rng.integers(0, 500, 2000).astype(np.int64)
        ns = np.full(2000, 42, dtype=np.int64)
        vals = rng.random(2000).astype(np.float32)
        slots = t.lookup_or_insert(keys, ns)
        t.scatter(slots, (vals,))
        snap = t.snapshot()

        # restore across 4 subtasks, verify the union reproduces all sums
        merged = {}
        for idx in range(4):
            kg = compute_key_group_range(128, 4, idx)
            part = SlotTable(agg, capacity=4096, max_parallelism=128)
            part.restore(snap, key_group_filter=kg)
            s = part.slots_for_namespace(42)
            res = part.fire(s[:, None])
            for k, v in zip(part.keys_of_slots(s).tolist(),
                            res["sum_v"].tolist()):
                assert k not in merged, "key restored to two subtasks"
                merged[k] = v
        expect = {}
        for k, v in zip(keys.tolist(), vals.tolist()):
            expect[k] = expect.get(k, 0.0) + v
        assert set(merged) == set(expect)
        for k in expect:
            assert abs(merged[k] - expect[k]) < 1e-3


class TestSavepointSafety:
    def test_savepoint_never_overwrites_user_directory(self, tmp_path):
        """A savepoint targeting an existing non-empty directory must fail
        fast and leave it untouched — and a stop-with-savepoint must leave
        the job RUNNING (reference: failed savepoint never stops the job)."""
        victim = tmp_path / "precious"
        victim.mkdir()
        (victim / "data.txt").write_text("do not delete")
        cluster = MiniCluster(Configuration({"rest.port": -1}))
        try:
            env = StreamExecutionEnvironment(Configuration(
                {"execution.micro-batch.size": 512}))
            build_count_pipeline(env, total=40_000, source_cls=SlowDataGen)
            client = cluster.submit(env, "safety-job")
            deadline = time.monotonic() + 10
            saw_exists_error = False
            while time.monotonic() < deadline:
                try:
                    client.stop_with_savepoint(str(victim))
                    break
                except FileExistsError:
                    saw_exists_error = True
                    break
                except RuntimeError:
                    time.sleep(0.02)
            assert saw_exists_error
            assert (victim / "data.txt").read_text() == "do not delete"
            # job survived the failed stop and runs to completion
            assert client.wait(timeout=30)["status"] == FINISHED
        finally:
            cluster.shutdown()

    def test_restore_older_savepoint_keeps_checkpoint_ids_monotonic(
            self, tmp_path):
        """Restoring an older savepoint into a root holding newer stale
        checkpoints must not let retain() delete the live chain."""
        ck = str(tmp_path / "ck")
        env = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 1,
        }))
        build_count_pipeline(env, total=5_000)
        env.execute("first")  # leaves chk-N for some N > 1
        import os as _os

        stale_max = max(int(n[4:]) for n in _os.listdir(ck)
                        if n.startswith("chk-"))
        # savepoint pinned at an old id
        sp = str(tmp_path / "old-sp")
        w = SavepointWriter.from_existing(ck)
        w.checkpoint_id = 1
        w.write(sp)
        env2 = StreamExecutionEnvironment(Configuration({
            "execution.micro-batch.size": 256,
            "state.checkpoints.dir": ck,
            "execution.checkpointing.every-n-source-batches": 1,
        }))
        # larger total: the restored source position leaves work to do
        build_count_pipeline(env2, total=10_000)
        r = env2.execute("resumed", restore_from=sp)
        # new checkpoints got ids ABOVE the stale ones
        assert r.metrics["checkpoints"] > stale_max
        latest = resolve_snapshot_dir(ck)
        assert int(latest.rsplit("chk-", 1)[1]) > stale_max
