"""ProcessFunction family: timers, keyed state, side outputs, connect/
broadcast, async I/O.

Mirrors the reference's harness-style tests (KeyedProcessOperatorTest,
SideOutputITCase, CoProcessFunction tests, AsyncWaitOperatorTest).
"""

import numpy as np
import pytest

from flink_tpu import (
    AsyncDataStream,
    BroadcastProcessFunction,
    Configuration,
    CoProcessFunction,
    KeyedProcessFunction,
    ListStateDescriptor,
    MapStateDescriptor,
    OutputTag,
    ProcessFunction,
    RecordBatch,
    ReducingStateDescriptor,
    StreamExecutionEnvironment,
    ValueStateDescriptor,
)
from flink_tpu.connectors.sinks import CollectSink
from flink_tpu.runtime.process import (
    ProcessContext,
    ProcessOperator,
    TimerService,
)
from flink_tpu.runtime.operators import OperatorContext


def _env(**conf):
    base = {"execution.micro-batch.size": 4}
    base.update(conf)
    return StreamExecutionEnvironment(Configuration(base))


def _rows(n, key_mod=2):
    return [{"k": i % key_mod, "v": float(i), "ts": i * 1000}
            for i in range(n)]


# --------------------------------------------------------------- side output


class SplitEvenOdd(ProcessFunction):
    LATE = OutputTag("odd")

    def process_batch(self, batch, ctx):
        even = batch["v"].astype(np.int64) % 2 == 0
        ctx.collect(batch.filter(even))
        ctx.output(self.LATE, batch.filter(~even))


def test_side_output_routing():
    env = _env()
    s = env.from_collection(_rows(10), timestamp_field="ts")
    main = s.process(SplitEvenOdd())
    side_sink = CollectSink()
    main.get_side_output(SplitEvenOdd.LATE).sink_to(side_sink)
    main_sink = CollectSink()
    main.sink_to(main_sink)
    env.execute()
    assert sorted(main_sink.result()["v"].tolist()) == [0, 2, 4, 6, 8]
    assert sorted(side_sink.result()["v"].tolist()) == [1, 3, 5, 7, 9]


# ------------------------------------------------------- keyed state + timer


class CountThenFlushAtTimer(KeyedProcessFunction):
    """Counts per key; registers an event-time timer at the next 5 s boundary
    and emits (key, count) when it fires — the canonical KeyedProcessFunction
    example from the reference docs."""

    COUNT = ReducingStateDescriptor("count", np.add, np.int64, 0)

    def process_batch(self, batch, ctx):
        kid = batch.key_ids
        ctx.state(self.COUNT).add(kid, np.ones(len(batch), dtype=np.int64))
        fire_at = (batch.timestamps // 5000 + 1) * 5000 - 1
        ctx.timer_service().register_event_time_timers(kid, fire_at)

    def on_timer(self, key_ids, timestamps, ctx):
        counts = ctx.state(self.COUNT).get(key_ids)
        ctx.collect(RecordBatch.from_pydict(
            {"key": key_ids, "count": counts}, timestamps=timestamps))


def test_keyed_process_with_timers():
    env = _env()
    s = env.from_collection(_rows(10, key_mod=2), timestamp_field="ts")
    out = s.key_by("k").process(CountThenFlushAtTimer()).execute_and_collect()
    # ts 0..9000; timers at 4999 (records 0-4) and 9999 (all 10)
    rows = sorted(zip(out["__ts__"].tolist(), out["key"].tolist(),
                      out["count"].tolist()))
    by_ts = {}
    for ts, k, c in rows:
        by_ts.setdefault(ts, []).append(c)
    # timer 4999 fires when the watermark passes it — after the micro-batch
    # reaching ts 7000 was processed, so both keys have counted 4 records
    # (identical to the reference with coarse watermark granularity)
    assert sorted(by_ts[4999]) == [4, 4]
    assert sorted(by_ts[9999]) == [5, 5]


def test_timer_dedup_and_delete():
    ts = TimerService()
    ts.register_event_time_timers([1, 1, 2], [100, 100, 200])
    ts.delete_event_time_timers([2], [200])
    keys, tss = ts.advance_watermark(1000)
    assert keys.tolist() == [1] and tss.tolist() == [100]


def test_processing_time_timers_fire_with_injected_clock():
    now = [0]
    op = ProcessOperator(CountThenFlushAtTimer(), keyed=True,
                         clock=lambda: now[0])

    class _Fn(ProcessFunction):
        def process_batch(self, batch, ctx):
            ctx.timer_service().register_processing_time_timers(
                batch.key_ids, batch.timestamps + 10)

        def on_timer(self, key_ids, timestamps, ctx):
            ctx.collect(RecordBatch.from_pydict({"key": key_ids},
                                                timestamps=timestamps))

    op = ProcessOperator(_Fn(), keyed=True, clock=lambda: now[0])
    op.open(OperatorContext())
    b = RecordBatch.from_pydict(
        {"__key_id__": np.array([7], dtype=np.int64)},
        timestamps=np.array([100], dtype=np.int64))
    assert op.process_batch(b) == []
    now[0] = 200
    outs = op.process_watermark(0)
    assert len(outs) == 1 and outs[0]["key"].tolist() == [7]


def test_value_and_map_and_list_state():
    from flink_tpu.state.keyed_state import KeyedStateStore

    store = KeyedStateStore(capacity=1024)
    vs = store.get_state(ValueStateDescriptor("v", np.float64, -1.0))
    kid = np.array([10, 20, 10], dtype=np.int64)
    assert vs.get(kid).tolist() == [-1.0, -1.0, -1.0]
    vs.put(kid, np.array([1.0, 2.0, 3.0]))
    assert vs.get(np.array([10, 20])).tolist() == [3.0, 2.0]

    ls = store.get_state(ListStateDescriptor("l"))
    ls.add(kid, np.array([1, 2, 3]))
    assert ls.get(10) == [1, 3] and ls.get(20) == [2]

    ms = store.get_state(MapStateDescriptor("m"))
    ms.put(10, "a", 1)
    assert ms.get(10, "a") == 1 and not ms.contains(20, "a")

    # snapshot -> fresh store -> restore (descriptors re-registered lazily)
    snap = store.snapshot()
    store2 = KeyedStateStore(capacity=1024)
    store2.restore(snap)
    vs2 = store2.get_state(ValueStateDescriptor("v", np.float64, -1.0))
    assert vs2.get(np.array([10, 20])).tolist() == [3.0, 2.0]
    assert store2.get_state(ListStateDescriptor("l")).get(10) == [1, 3]
    assert store2.get_state(MapStateDescriptor("m")).get(10, "a") == 1


def test_process_operator_snapshot_restore():
    fn = CountThenFlushAtTimer()
    op = ProcessOperator(fn, keyed=True)
    op.open(OperatorContext())
    b = RecordBatch.from_pydict(
        {"__key_id__": np.array([1, 1, 2], dtype=np.int64)},
        timestamps=np.array([100, 200, 300], dtype=np.int64))
    op.process_batch(b)
    snap = op.snapshot_state()

    op2 = ProcessOperator(CountThenFlushAtTimer(), keyed=True)
    op2.open(OperatorContext())
    op2.restore_state(snap)
    outs = op2.process_watermark(10_000)
    assert len(outs) == 1
    got = dict(zip(outs[0]["key"].tolist(), outs[0]["count"].tolist()))
    assert got == {1: 2, 2: 1}


# ------------------------------------------------------------------- connect


class Zipper(CoProcessFunction):
    def process_batch1(self, batch, ctx):
        ctx.collect(batch.with_column("side", np.full(len(batch), 1)))

    def process_batch2(self, batch, ctx):
        ctx.collect(batch.with_column("side", np.full(len(batch), 2)))


def test_connected_streams_co_process():
    env = _env()
    a = env.from_collection([{"v": 1.0, "ts": 0}], timestamp_field="ts")
    b = env.from_collection([{"v": 2.0, "ts": 0}], timestamp_field="ts")
    out = a.connect(b).process(Zipper()).execute_and_collect()
    assert sorted(zip(out["v"].tolist(), out["side"].tolist())) == [
        (1.0, 1), (2.0, 2)]


class FilterByBroadcastRule(BroadcastProcessFunction):
    def process_batch(self, batch, ctx, bstate):
        allowed = bstate.get("allowed", set())
        mask = np.array([k in allowed for k in batch["k"].tolist()])
        ctx.collect(batch.filter(mask))

    def process_broadcast(self, batch, ctx, bstate):
        s = bstate.setdefault("allowed", set())
        s.update(batch["allow"].tolist())


def test_broadcast_state_pattern():
    env = _env()
    rules = env.from_collection([{"allow": 1, "ts": 0}], timestamp_field="ts")
    data = env.from_collection(_rows(8, key_mod=3), timestamp_field="ts")
    out = (data.connect(rules.broadcast())
           .process(FilterByBroadcastRule())
           .execute_and_collect())
    assert len(out) and set(out["k"].tolist()) == {1}


# ------------------------------------------------------------------ async IO


def test_async_unordered_and_ordered():
    import time

    def slow_enrich(batch):
        # later batches finish faster — exercises reordering
        time.sleep(0.02 if batch["v"][0] < 4 else 0.001)
        return batch.with_column("r", batch["v"] * 10)

    for ordered in (True, False):
        env = _env()
        s = env.from_collection(_rows(8, key_mod=8), timestamp_field="ts")
        wait = (AsyncDataStream.ordered_wait if ordered
                else AsyncDataStream.unordered_wait)
        out = wait(s, slow_enrich, timeout_ms=5_000, capacity=2
                   ).execute_and_collect()
        assert sorted(out["r"].tolist()) == [v * 10.0 for v in range(8)]
        if ordered:
            assert out["v"].tolist() == [float(v) for v in range(8)]


def test_async_timeout_fallback():
    import time

    from flink_tpu.runtime.async_operator import AsyncFunction

    class Flaky(AsyncFunction):
        def invoke(self, batch):
            time.sleep(10)
            return batch

        def timeout(self, batch):
            return batch.with_column("r", np.full(len(batch), -1.0))

    env = _env(**{"execution.micro-batch.size": 100})
    s = env.from_collection(_rows(3, key_mod=3), timestamp_field="ts")
    out = AsyncDataStream.ordered_wait(
        s, Flaky(), timeout_ms=50, capacity=2).execute_and_collect()
    assert out["r"].tolist() == [-1.0, -1.0, -1.0]


# ------------------------------------------------- keyed running aggregates


def test_keyed_stream_running_sum():
    env = _env(**{"execution.micro-batch.size": 100})
    s = env.from_collection(_rows(6, key_mod=2), timestamp_field="ts")
    out = s.key_by("k").sum("v").execute_and_collect()
    # single micro-batch -> one upsert per key with the final sum
    got = dict(zip(out["k"].tolist(), out["sum_v"].tolist()))
    assert got == {0: 0.0 + 2 + 4, 1: 1.0 + 3 + 5}
